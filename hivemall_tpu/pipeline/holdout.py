"""Drift-aware rolling holdout for the continuous-training eval gate.

The gate needs labeled data the trainer has NEVER seen, drawn from the
stream's CURRENT concept. Both properties come from one mechanism: every
``every``-th observed batch is routed here instead of to the trainer
(a deterministic 1/``every`` holdout split of the live stream), and the
reservoir is a bounded ring in rows — old-concept batches age out as the
stream drifts, so the gate always scores candidates against roughly the
last ``capacity_rows`` worth of held-out traffic.

Thread-safety: the pipeline worker appends while benches/tests snapshot
concurrently; one lock guards the ring, and snapshot() copies references
out under it (the arrays themselves are never mutated after append).

# graftcheck: serving-module
"""

from __future__ import annotations

import threading
from collections import deque
from typing import List, Optional, Tuple

import numpy as np


class RollingHoldout:
    """Bounded ring of held-out ``(indices, values, labels)`` batches."""

    def __init__(self, capacity_rows: int = 4096, every: int = 8) -> None:
        if every < 2:
            raise ValueError(f"every must be >= 2 (every={every} would "
                             "starve the trainer)")
        self.capacity_rows = int(capacity_rows)
        self.every = int(every)
        self._batches: deque = deque()
        self._rows = 0
        self._lock = threading.Lock()

    def routes_here(self, batch_index: int) -> bool:
        """True when observed batch ``batch_index`` is holdout, not
        training data. Offset 1 so batch 0 (and the first batch after a
        resume at a multiple of ``every``) trains — a cold start should
        learn before it evaluates."""
        return batch_index % self.every == 1

    def add(self, indices: np.ndarray, values: np.ndarray,
            labels: np.ndarray) -> None:
        with self._lock:
            self._batches.append((indices, values, labels))
            self._rows += len(labels)
            while self._rows > self.capacity_rows and len(self._batches) > 1:
                old = self._batches.popleft()
                self._rows -= len(old[2])

    @property
    def rows(self) -> int:
        with self._lock:
            return self._rows

    def snapshot(self) -> Optional[Tuple[List[np.ndarray], List[np.ndarray],
                                         np.ndarray]]:
        """Current reservoir as a pre-parsed request the serving engines
        score directly: ``(idx_rows, val_rows, labels)`` with labels in
        {-1,+1}. None while empty."""
        with self._lock:
            batches = list(self._batches)
        if not batches:
            return None
        idx_rows: List[np.ndarray] = []
        val_rows: List[np.ndarray] = []
        labels: List[np.ndarray] = []
        for idx, val, lab in batches:
            # per-row arrays, int64 indices — the models.base._stage_rows
            # pre-parsed convention the engines accept verbatim
            idx_rows.extend(np.asarray(idx, np.int64))
            val_rows.extend(np.asarray(val, np.float32))
            labels.append(np.asarray(lab, np.float32))
        return idx_rows, val_rows, np.concatenate(labels)
