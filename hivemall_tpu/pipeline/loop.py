"""Continuous training: stream -> freeze -> eval gate -> hot-swap.

This module closes the train->serve loop (ROADMAP: Hivemall's
mapper->MIX->ensemble cycle reborn as one system; the ads-infra paper's
model-freshness-under-continuous-traffic claim): a `ContinuousPipeline`
owns a publisher state machine running over a drifting event stream —

    TRAIN ──cadence──> FREEZE ──> GATE ──pass──> PUBLISH (atomic hot-swap)
      ^                            │ fail                │
      │<── revert-on-refuse ───────┘      ROLLBACK <─────┘ (health check)

- **TRAIN**: an online linear learner (core/engine.make_train_step,
  minibatch mode) consumes observed event batches; every ``holdout_every``-th
  batch is routed to the rolling holdout instead (pipeline/holdout.py) so
  the gate always has unseen, current-concept data. The loop checkpoints
  through io/checkpoint.save_elastic on an event cadence, so PR 8 fault
  plans (crash_mid_write / corrupt / transient) fire through the SAME seams
  training uses — and recovery resumes from the last valid checkpoint
  (loud ``.prev`` fallback) and replays the deterministic stream from the
  checkpoint's ``block_step``.
- **FREEZE**: on an event cadence the live state freezes into an immutable
  versioned artifact (serving/artifact.freeze, optionally straight to
  bf16/int8). The ``artifact_frozen`` hook mirrors io/checkpoint's chaos
  seams: tests rot the artifact there and the gate must refuse it.
- **GATE**: the candidate is loaded back sha256-VERIFIED and scored through
  the serving path next to the live version (pipeline/gate.EvalGate) — a
  regression, an unmeasurable candidate, or a corrupt artifact refuses
  publication and the old version keeps serving. ``revert_on_refuse``
  additionally restores the trainer to the last-published state, so a
  bad-data window is quarantined instead of poisoning every later
  candidate.
- **PUBLISH**: serving/server.ModelRegistry.deploy — warm off to the side,
  one-assignment swap, old batcher drains; zero failed in-flight requests
  (the PR 3 pin). The deploy carries version lineage (gate decisions) that
  /models surfaces.
- **ROLLBACK**: each cycle starts with a health check — if the LIVE
  version's holdout logloss degrades past ``rollback_tol_logloss`` vs the
  previously-published version on the CURRENT holdout, the previous
  artifact is redeployed (lineage records the rollback).

**Freshness** is the pipeline's headline metric: for every observed event
batch the loop records "event observed -> the first model version
published after the pipeline processed it is serving" latency into the
``pipeline.<name>.freshness_seconds`` histogram on /metrics (and keeps
raw samples for exact bench percentiles). "Processed" is deliberate:
a revert-on-refuse quarantine means the publishing model judged a bad
window and DISCARDED it — the pipeline's response to those events, not
incorporation of them (``trained_through_event`` on decisions is likewise
the observed-through watermark). Events covered by a REFUSED candidate
stay pending — their freshness keeps growing until a later version ships
them, so gate refusals show up in the p99 instead of vanishing.

Every stage runs under a PR 5 span (``pipeline.cycle`` > ``pipeline.freeze``
/ ``pipeline.gate`` / ``pipeline.publish`` / ``pipeline.revert``), so a slow
publish is attributable from the trace ring (docs/observability.md).

Thread model: one worker thread (``start()``/``stop()``) owns the trainer
state, the stream cursor and the freshness ledger; everything shared with
other threads (decisions, published versions, counters, freshness samples)
goes through ``self._lock`` — and nothing blocking ever runs under it
(graftcheck G012-G016 pin this module; analysis/config.py scopes it).

# graftcheck: serving-module
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field as dc_field
from typing import Callable, List, Optional

import numpy as np

from ..core.batch import pad_to_bucket
from ..core.engine import Rule, make_train_step
from ..core.state import init_linear_state
from ..io.checkpoint import (PREV_SUFFIX, load_elastic, pack_linear_state,
                             save_elastic, unpack_linear_state)
from ..models.base import TrainedLinearModel
from ..runtime import faults
from ..runtime.metrics import REGISTRY
from ..runtime.tracing import TRACER
from ..serving import artifact as serving_artifact
from ..serving.engine import ServingEngine
from .gate import EvalGate, GateDecision, score_metrics
from .holdout import RollingHoldout

FAMILY = "pipeline_linear"

# freshness is seconds-scale (train cadence + gate + warm + swap), not the
# serving latency scale — buckets to 300s so a stuck publisher is visible
FRESHNESS_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0,
                     120.0, 300.0)


def artifact_frozen(path: str) -> None:
    """No-op hook fired after freeze() lands a candidate artifact — the
    chaos seam mirroring io/checkpoint.checkpoint_written: tests patch it
    to rot the artifact, and the gate's verified reload must refuse to
    publish it (tests/test_pipeline.py)."""


@dataclass
class PipelineConfig:
    """Knobs of one continuous-training loop. ``artifact_root`` holds the
    versioned artifact dirs and (by default) the elastic checkpoint."""

    artifact_root: str
    dims: int
    rule: Rule
    hyper: dict = dc_field(default_factory=dict)
    name: str = "ctr"
    width: int = 8  # stream row nnz (engine width bucket floor)
    freeze_every_events: int = 512
    checkpoint_every_events: int = 256
    holdout_every: int = 8
    holdout_capacity_rows: int = 4096
    regression_tol_logloss: float = 0.005
    min_holdout_rows: int = 64
    rollback_tol_logloss: float = 0.05
    revert_on_refuse: bool = True
    health_check: bool = True
    quantize: Optional[str] = None  # freeze straight to "bf16" / "int8"
    amplify_x: int = 1  # ftvec/amplify multi-epoch substitute
    amplify_buffers: int = 4
    max_restarts: int = 8
    # linear backoff between recoverable restarts (sleep = backoff * n,
    # capped at 1 s): a persistently failing step must not spin the
    # restart path at CPU speed (graftcheck G031)
    restart_backoff_s: float = 0.02
    checkpoint_path: Optional[str] = None
    # the gate's candidate engines (scoring only, never deployed)
    gate_engine_kwargs: dict = dc_field(
        default_factory=lambda: {"max_batch": 256, "max_width": 32})

    def __post_init__(self):
        if self.checkpoint_path is None:
            # name-scoped: artifacts are already namespaced {name}-v{N},
            # which invites sharing one artifact_root between pipelines —
            # a shared checkpoint file would silently cross-resume them
            self.checkpoint_path = os.path.join(
                self.artifact_root, f"{self.name}_pipeline_ckpt.npz")


class ContinuousPipeline:
    """The publisher state machine over (registry, stream).

    ``stream_fn(i)`` returns observed batch ``i`` as ``(indices [B,K]
    int32, values [B,K] float32, labels [B] float32 in {-1,+1})`` and must
    be a pure function of ``i`` (dataset/lr_datagen.DriftStream.block is
    the reference implementation) — determinism is what makes crash
    recovery a REPLAY instead of data loss.

    ``holdout_stream_fn`` (optional, same contract) supplies the batches
    routed to the gate's holdout ring instead of ``stream_fn`` — the
    "trusted delayed ground truth" pattern: when evaluation labels come
    from a cleaner source than the training log (e.g. settled conversions
    vs the live click stream), a corrupted training window cannot bias
    the gate's ground truth toward the model that learned the corruption.
    Default None: the ring holds the observed stream as-is (label noise
    included — the honest default)."""

    RECOVERABLE = (faults.CrashMidWrite, faults.TransientStepError,
                   faults.WorkerLost)

    def __init__(self, registry, stream_fn: Callable[[int], tuple],
                 config: PipelineConfig,
                 holdout_stream_fn: Optional[Callable[[int], tuple]] = None
                 ) -> None:
        self.registry = registry
        self.stream_fn = stream_fn
        self.holdout_stream_fn = holdout_stream_fn
        self.cfg = config
        self.gate = EvalGate(config.regression_tol_logloss,
                             config.min_holdout_rows)
        self.holdout = RollingHoldout(config.holdout_capacity_rows,
                                      config.holdout_every)
        self._step = make_train_step(config.rule, dict(config.hyper),
                                     mode="minibatch")
        os.makedirs(config.artifact_root, exist_ok=True)
        self._freshness_hist = REGISTRY.histogram(
            f"pipeline.{config.name}.freshness_seconds", FRESHNESS_BUCKETS)
        self._publishes = REGISTRY.counter("pipeline",
                                           f"{config.name}.publishes")
        self._refusals = REGISTRY.counter("pipeline",
                                          f"{config.name}.refusals")
        self._rollbacks = REGISTRY.counter("pipeline",
                                           f"{config.name}.rollbacks")
        # --- shared surface (any thread), guarded by _lock ---------------
        self._lock = threading.Lock()
        # bounded: a long-lived pipeline must not grow host memory per
        # cycle/batch — /metrics histograms and counters are the
        # unbounded-horizon views; these rings feed status()/lineage()
        # and exact recent-window percentiles
        self._decisions: deque = deque(maxlen=512)
        self._published: List[dict] = []  # oldest..newest; [-1] is live
        self._freshness_samples: deque = deque(maxlen=65536)  # (n, secs)
        self._stats = {"batches": 0, "events": 0, "trained_rows": 0,
                       "replayed_batches": 0,
                       "publishes": 0, "refusals": 0, "rollbacks": 0,
                       "restarts": 0, "restart_causes": [],
                       "checkpoints_written": 0,
                       "freshness_samples": 0, "freshness_events": 0,
                       "running": False, "done": False, "fatal": None}
        # --- worker-confined state (the run() thread only) ---------------
        # bounded: under a persistent gate-refusal pathology nothing
        # drains the ledger — overflow drops the OLDEST pending batches'
        # samples (their freshness was unbounded anyway) instead of
        # growing host memory per batch forever
        self._ledger: deque = deque(maxlen=1 << 17)  # (last_ev, ts, n)
        self._observed_through = -1  # newest event ever ledgered
        self._published_through = -1  # newest event a published model covers
        self._holdout_through = -1  # newest batch index already held out
        self._next_version = 1
        self._events_consumed = 0
        self._last_freeze_events = 0
        self._last_ckpt_events = 0
        self._publish_snapshot: Optional[dict] = None  # host state pack
        self._prev_engine: Optional[tuple] = None  # (version, art, engine)
        self._batch_high = 0  # high-water batch cursor (replay detection)
        self._condemned: set = set()  # versions a rollback has condemned
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------------

    def start(self, n_batches: int) -> None:
        """Run the loop on a worker thread (the bench/serving deployment
        shape: traffic threads share the process)."""
        t = threading.Thread(target=self._run_guarded, args=(n_batches,),
                             daemon=True,
                             name=f"pipeline-{self.cfg.name}")
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                raise RuntimeError("pipeline is already running")
            self._thread = t
        self._stop.clear()  # graftcheck: disable=G012 (threading.Event is its own synchronization)
        t.start()

    def _run_guarded(self, n_batches: int) -> None:
        try:
            self.run(n_batches)
        except Exception as e:  # surfaced via status(), not a dead thread
            with self._lock:
                self._stats["fatal"] = f"{type(e).__name__}: {e}"
                self._stats["running"] = False

    def stop(self, timeout: float = 120.0) -> None:
        """Request a clean stop (the in-flight batch finishes, a final
        checkpoint lands) and wait for the worker. A stop() while nothing
        is running is a no-op — it must not leak into the NEXT run and
        silently truncate it to zero batches."""
        with self._lock:
            running = self._stats["running"]
            t = self._thread
        if running or (t is not None and t.is_alive()):
            self._stop.set()
        if t is not None:
            t.join(timeout)

    def join(self, timeout: Optional[float] = None) -> bool:
        with self._lock:
            t = self._thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    # -- the loop -------------------------------------------------------------

    def run(self, n_batches: int) -> dict:
        """Drive the loop to ``n_batches`` (or stop()), self-healing from
        recoverable faults: each restart reloads the last VALID checkpoint
        (`.prev` fallback on rot) and replays the stream from its
        block_step. Returns status()."""
        # a DIRECT run() (no worker thread) must shed any stale stop flag
        # a racing stop() left behind after the previous run's finally;
        # when run() executes ON the worker thread, start() already
        # cleared it and clearing again would lose a stop() issued
        # between start() and this line
        with self._lock:
            t = self._thread
        if t is None or not t.is_alive():
            self._stop.clear()  # graftcheck: disable=G012 (threading.Event is its own synchronization)
        with self._lock:
            self._stats["running"] = True
            self._stats["done"] = False
        try:
            with TRACER.span("pipeline.run",
                             args={"name": self.cfg.name,
                                   "n_batches": int(n_batches)}):
                while True:
                    state, start = self._resume()
                    self._ensure_serving()
                    try:
                        self._loop(state, start, n_batches)
                        break
                    except self.RECOVERABLE as e:
                        with self._lock:
                            self._stats["restarts"] += 1
                            self._stats["restart_causes"].append(
                                type(e).__name__)
                            restarts = self._stats["restarts"]
                        TRACER.instant("pipeline.restart",
                                       args={"cause": type(e).__name__})
                        if restarts > self.cfg.max_restarts:
                            # supervisor give-up: leave the postmortem
                            # artifact (flight recorder) next to the
                            # versioned artifacts BEFORE re-raising —
                            # write_crash_bundle never raises, so the
                            # original exception stays the signal
                            from ..runtime.debug_bundle import \
                                write_crash_bundle

                            write_crash_bundle(
                                os.path.join(
                                    self.cfg.artifact_root,
                                    f"{self.cfg.name}_crash_bundle.json"),
                                reason=(f"pipeline {self.cfg.name!r} gave "
                                        f"up after {restarts} restarts "
                                        f"(last cause: "
                                        f"{type(e).__name__}: {e})"),
                                registry=self.registry)
                            raise
                        time.sleep(min(
                            self.cfg.restart_backoff_s * restarts, 1.0))
        finally:
            with self._lock:
                self._stats["running"] = False
                self._stats["done"] = True
            # a stop() racing completion must not wedge a later run
            self._stop.clear()  # graftcheck: disable=G012 (threading.Event is its own synchronization)
        return self.status()

    def _resume(self):
        """(state, start_batch) from the newest valid checkpoint — loud
        ``.prev`` fallback via io/checkpoint.load_elastic — or a cold
        zeroed state. Publisher bookkeeping (version counter, published
        lineage, covered-events watermark) restores from the manifest so a
        FRESH process continues the version sequence instead of restarting
        at v1."""
        cfg = self.cfg
        path = cfg.checkpoint_path
        if not (os.path.exists(path) or os.path.exists(path + PREV_SUFFIX)):
            # true cold start — including a restart after a crash on the
            # very first checkpoint write: the stream replays from 0, so
            # the consumption cursors reset with it (the freshness ledger
            # does NOT — first-observation timestamps are the metric)
            self._events_consumed = 0
            self._last_freeze_events = 0
            self._last_ckpt_events = 0
            state = init_linear_state(
                cfg.dims, use_covariance=cfg.rule.use_covariance,
                slot_names=cfg.rule.slot_names,
                global_names=cfg.rule.global_names)
            return state, 0
        with TRACER.span("pipeline.resume", args={"path": path}):
            arrays, manifest = load_elastic(path)
            if manifest.get("family") != FAMILY:
                raise ValueError(
                    f"checkpoint {path} holds a "
                    f"{manifest.get('family')!r} model; cannot resume it "
                    f"as a {FAMILY} pipeline")
            if int(manifest.get("dims", cfg.dims)) != cfg.dims:
                raise ValueError(
                    f"checkpoint {path} was trained at dims "
                    f"{manifest['dims']} != configured {cfg.dims}")
            state = unpack_linear_state(arrays)
            start = int(manifest.get("block_step", 0))
            self._events_consumed = int(manifest.get("events", 0))
            # the freeze clock persists: resetting it to the checkpoint
            # cadence would slip every later publish by up to a full
            # freeze window after each recovery (and a recurring fault
            # could starve publishes entirely)
            self._last_freeze_events = int(
                manifest.get("last_freeze_events", self._events_consumed))
            self._last_ckpt_events = self._events_consumed
            self._published_through = int(
                manifest.get("published_through", self._published_through))
            self._next_version = max(self._next_version,
                                     int(manifest.get("next_version", 1)))
            self._condemned |= set(manifest.get("condemned", ()))
            with self._lock:
                if not self._published and manifest.get("published"):
                    self._published = list(manifest["published"])
        return state, start

    def _ensure_serving(self) -> None:
        """Cold-start republish: a fresh process resuming a pipeline whose
        registry lost its entries redeploys the last published version, so
        traffic is served from the first batch on."""
        with self._lock:
            last = self._published[-1] if self._published else None
        if last is None or self.registry.get(self.cfg.name) is not None:
            return
        try:
            art = serving_artifact.load(last["path"], verify=True)
        except Exception as e:
            # rotted artifact on disk: keep training, the next gated
            # publish re-establishes serving
            TRACER.instant("pipeline.republish_failed",
                           args={"version": last["version"],
                                 "error": type(e).__name__})
            return
        d = GateDecision(str(last["version"]), True, "resume_republish")
        self._record_decision(d)
        self.registry.deploy(self.cfg.name, art,
                             version=str(last["version"]),
                             lineage=self.lineage())

    def _loop(self, state, start: int, n_batches: int) -> None:
        cfg = self.cfg
        next_batch = start  # the batch a resume would process next
        for i in range(start, n_batches):
            if self._stop.is_set():
                break
            faults.step_hook(i)
            idx, val, lab = self.stream_fn(i)
            b = len(lab)
            last_ev = self._events_consumed + b - 1
            # first-observation timestamps survive replays: a restarted
            # loop re-trains these events but their freshness clock keeps
            # running from when they were FIRST seen
            if last_ev > self._observed_through:
                self._ledger.append((last_ev, time.monotonic(), b))
                self._observed_through = last_ev
            if self.holdout.routes_here(i):
                # a crash-replay re-observes batches the holdout already
                # holds — re-adding would double-weight those rows in
                # every later gate decision (training replays by design;
                # the holdout ring must not)
                if i > self._holdout_through:
                    if self.holdout_stream_fn is not None:
                        hidx, hval, hlab = self.holdout_stream_fn(i)
                        self.holdout.add(hidx, hval, hlab)
                    else:
                        self.holdout.add(idx, val, lab)
                    self._holdout_through = i
            else:
                state = self._train(state, i, idx, val, lab)
            self._events_consumed += b
            ev_now = self._events_consumed  # worker-confined; the locked
            next_batch = i + 1              # surface gets a plain copy
            replayed = i + 1 <= self._batch_high
            self._batch_high = max(self._batch_high, i + 1)
            with self._lock:
                # batches/events report the STREAM CURSOR (they rewind on
                # a restart and re-grow); replays are counted separately
                self._stats["batches"] = i + 1
                self._stats["events"] = ev_now
                if replayed:
                    self._stats["replayed_batches"] += 1
            if ev_now - self._last_freeze_events >= cfg.freeze_every_events:
                state = self._cycle(state, trained_through=last_ev)
                self._last_freeze_events = ev_now
            if (ev_now - self._last_ckpt_events
                    >= cfg.checkpoint_every_events):
                self._checkpoint(state, i + 1)
                self._last_ckpt_events = ev_now
        # final checkpoint: the stream cursor lands exactly where a later
        # run should pick up (stop() mid-run included)
        self._checkpoint(state, next_batch)

    def _train(self, state, i: int, idx, val, lab):
        """One (possibly amplified) training application of batch ``i``.
        ``amplify_x > 1`` replays the batch's rows through ftvec/amplify's
        seeded reservoir shuffle in x same-shape sub-blocks — Hivemall's
        multi-epoch substitute, deterministic per batch index."""
        cfg = self.cfg
        b = len(lab)
        with TRACER.span("pipeline.train", args={"batch": i, "rows": b}):
            if cfg.amplify_x <= 1:
                state, _loss = self._step(state, idx, val, lab)
                trained = b
            else:
                from ..ftvec.amplify import rand_amplify

                order = np.fromiter(
                    rand_amplify(cfg.amplify_x, cfg.amplify_buffers,
                                 range(b), seed=(i * 9_176 + 11) % (2**31)),
                    dtype=np.int64)
                for s in range(0, len(order), b):
                    sel = order[s:s + b]
                    if len(sel) < b:  # reservoir tail: same-shape pad by
                        sel = np.concatenate([sel, sel[:b - len(sel)]])
                    state, _loss = self._step(state, idx[sel], val[sel],
                                              lab[sel])
                trained = cfg.amplify_x * b
        with self._lock:
            self._stats["trained_rows"] += trained
        return state

    # -- freeze -> gate -> publish -> (rollback) ------------------------------

    def _cycle(self, state, trained_through: int):
        cfg = self.cfg
        with TRACER.span("pipeline.cycle",
                         args={"trained_through": int(trained_through)}):
            snapshot = self.holdout.snapshot()
            # the health check scores the live engine on this snapshot;
            # its numbers double as the gate's incumbent metrics below —
            # one predict pass per cycle, not two
            live_metrics = self._maybe_rollback(snapshot) \
                if cfg.health_check else None
            while True:
                version = str(self._next_version)
                self._next_version += 1  # never reused, refused or not
                path = os.path.join(cfg.artifact_root,
                                    f"{cfg.name}-v{version}")
                if not os.path.exists(
                        os.path.join(path, serving_artifact.MANIFEST_FILE)):
                    break
                # a crash between freeze vN and the next checkpoint left
                # vN frozen on disk but the resumed manifest still says
                # next_version=N — artifacts are immutable, so the replay
                # burns the number instead of dying on FileExistsError
                TRACER.instant("pipeline.version_burned",
                               args={"version": version})
            with TRACER.span("pipeline.freeze", args={"version": version}):
                model = TrainedLinearModel(
                    state=state, rule=cfg.rule, dims=cfg.dims,
                    block_width=pad_to_bucket(cfg.width))
                serving_artifact.freeze(model, path, name=cfg.name,
                                        version=version,
                                        quantize=cfg.quantize)
                artifact_frozen(path)
            incumbent = self.registry.get(cfg.name)
            art = None
            with TRACER.span("pipeline.gate", args={"version": version}):
                try:
                    # sha256-verified reload THROUGH the serving path: what
                    # the gate scores is exactly what production would run,
                    # and a rotted artifact refuses here — never published
                    art = serving_artifact.load(path, verify=True)
                    cand = ServingEngine(art, name=f"{cfg.name}-candidate",
                                         **cfg.gate_engine_kwargs)
                except Exception as e:
                    decision = GateDecision(
                        version, False, "artifact_corrupt",
                        extra={"error": f"{type(e).__name__}: {e}"})
                else:
                    try:
                        decision = self.gate.evaluate(
                            version, cand,
                            incumbent.engine if incumbent else None,
                            snapshot,
                            incumbent_version=incumbent.version
                            if incumbent else None,
                            incumbent_metrics=live_metrics)
                    except Exception as e:
                        # a scoring failure (incumbent predict hiccup,
                        # holdout shape error) is NOT artifact rot — name
                        # it honestly; never publish unmeasured
                        decision = GateDecision(
                            version, False, "gate_error",
                            extra={"error": f"{type(e).__name__}: {e}"})
                decision.trained_through_event = int(trained_through)
                TRACER.instant("pipeline.gate.decision",
                               args={"version": version,
                                     "published": decision.published,
                                     "reason": decision.reason})
            self._record_decision(decision)
            if decision.published:
                with TRACER.span("pipeline.publish",
                                 args={"version": version}):
                    self.registry.deploy(cfg.name, art, version=version,
                                         lineage=self.lineage())
                publish_ts = time.monotonic()
                info = {"version": version, "path": path,
                        "trained_through": int(trained_through),
                        "gate_logloss": decision.candidate_logloss}
                with self._lock:
                    self._published.append(info)
                    self._stats["publishes"] += 1
                self._publishes.increment()
                self._observe_freshness(int(trained_through), publish_ts)
                # host snapshot of the state that passed the gate — the
                # revert-on-refuse target
                self._publish_snapshot = pack_linear_state(state)
            else:
                with self._lock:
                    self._stats["refusals"] += 1
                self._refusals.increment()
                # quarantine ONLY on a measured regression — the one
                # reason that is evidence the recent TRAINING hurt. An
                # unmeasurable candidate (corrupt artifact, starved
                # holdout, scoring hiccup) says nothing about the update,
                # and discarding a window of good training for it would
                # be pure loss
                if cfg.revert_on_refuse and decision.reason == "regression" \
                        and self._publish_snapshot is not None:
                    with TRACER.span("pipeline.revert",
                                     args={"refused_version": version}):
                        state = unpack_linear_state(self._publish_snapshot)
        return state

    def _maybe_rollback(self, snapshot) -> Optional[dict]:
        """Post-publish health: if the LIVE version now regresses past
        ``rollback_tol_logloss`` against the previously-published version
        on the CURRENT holdout, redeploy the previous version (the gate's
        discipline applied retroactively — drift or a bad publish the gate
        missed is bounded by one cycle).

        Returns the score_metrics() of whatever version is live AFTER the
        check (None when nothing was scored) — the same cycle's gate
        reuses it as the incumbent's metrics instead of re-scoring the
        same engine on the same snapshot."""
        cfg = self.cfg
        live = self.registry.get(cfg.name)
        if live is None or snapshot is None \
                or len(snapshot[2]) < cfg.min_holdout_rows:
            return None
        with self._lock:
            if len(self._published) < 2 \
                    or self._published[-1]["version"] != live.version:
                return None
            prior = [dict(p) for p in self._published[:-1]]
        # the nearest prior version that is neither the live one nor one a
        # rollback already condemned — after [v1, v2, rollback-to-v1] the
        # candidate must not be v2, or two versions would ping-pong
        # gate-free forever
        prev = next((p for p in reversed(prior)
                     if p["version"] != live.version
                     and p["version"] not in self._condemned), None)
        if prev is None:
            return None
        idx_rows, val_rows, labels = snapshot
        try:
            # artifacts are immutable: the verified reload + engine build
            # for the previous version is cached by version, so the
            # almost-always-healthy cycle pays scoring only, not a full
            # table read + sha256 + engine construction every time
            if self._prev_engine is not None \
                    and self._prev_engine[0] == prev["version"]:
                prev_art, prev_engine = self._prev_engine[1:]
            else:
                prev_art = serving_artifact.load(prev["path"], verify=True)
                prev_engine = ServingEngine(prev_art,
                                            name=f"{cfg.name}-candidate",
                                            **cfg.gate_engine_kwargs)
                self._prev_engine = (prev["version"], prev_art, prev_engine)
            live_m = score_metrics(live.engine, idx_rows, val_rows, labels)
            prev_m = score_metrics(prev_engine, idx_rows, val_rows, labels)
        except Exception as e:  # unscoreable previous artifact: no rollback
            TRACER.instant("pipeline.rollback_skipped",
                           args={"error": type(e).__name__})
            return None
        if live_m["logloss"] <= prev_m["logloss"] + cfg.rollback_tol_logloss:
            return live_m
        d = GateDecision(
            str(prev["version"]), True, "rollback",
            holdout_rows=len(labels),
            candidate_logloss=prev_m["logloss"],
            incumbent_logloss=live_m["logloss"],
            incumbent_version=live.version,
            extra={"rolled_back_version": live.version})
        self._record_decision(d)
        with TRACER.span("pipeline.rollback",
                         args={"from": live.version,
                               "to": str(prev["version"])}):
            self.registry.deploy(cfg.name, prev_art,
                                 version=str(prev["version"]),
                                 lineage=self.lineage())
        with self._lock:
            self._published.append(prev)
            self._stats["rollbacks"] += 1
        self._rollbacks.increment()
        self._condemned.add(live.version)
        # the revert-on-refuse target held the state the rollback just
        # condemned — drop it (the artifact lacks optimizer slots, so the
        # previous version's TRAINER state is unrecoverable; refusals
        # fall back to continuing the live trainer until the next publish
        # re-establishes a known-good snapshot)
        self._publish_snapshot = None
        # the rolled-back-to version is live now; its metrics stand as
        # the incumbent's for this cycle's gate
        return prev_m

    # -- freshness ------------------------------------------------------------

    def _observe_freshness(self, through_event: int,
                           publish_ts: float) -> None:
        """Events up to ``through_event`` are now covered by a SERVING
        model: close their ledger entries as end-to-end freshness samples
        (event observed -> the first post-processing publish serving;
        a quarantined window counts as processed-by-discard, see the
        module docstring). Entries already covered by an earlier publish
        are skipped; entries covered only by a REFUSED candidate stayed
        open — their latency kept accruing, which is the honest cost of
        the refusal."""
        while self._ledger and self._ledger[0][0] <= through_event:
            last_ev, ts, n = self._ledger.popleft()
            if last_ev <= self._published_through:
                continue
            f = max(0.0, publish_ts - ts)
            self._freshness_hist.observe(f)
            with self._lock:
                self._freshness_samples.append((n, f))
                self._stats["freshness_samples"] += 1
                self._stats["freshness_events"] += n
        self._published_through = max(self._published_through,
                                      through_event)

    def freshness_percentiles(self, qs=(0.5, 0.99)) -> dict:
        """Event-weighted exact percentiles over the raw-sample ring (the
        last ~65k batch samples — benches fit entirely; for longer
        horizons the /metrics histogram is the always-on view)."""
        with self._lock:
            samples = list(self._freshness_samples)
        if not samples:
            return {f"p{int(q * 100)}": None for q in qs}
        vals = np.asarray([s for _, s in samples], np.float32)
        weights = np.asarray([n for n, _ in samples], np.float32)
        order = np.argsort(vals)
        vals, weights = vals[order], weights[order]
        cum = np.cumsum(weights)
        out = {}
        for q in qs:
            rank = q * cum[-1]
            out[f"p{int(q * 100)}"] = float(vals[np.searchsorted(cum, rank)])
        return out

    # -- bookkeeping ----------------------------------------------------------

    def _checkpoint(self, state, block_step: int) -> None:
        arrays = pack_linear_state(state)
        with self._lock:
            published = [dict(p) for p in self._published]
        manifest = {
            "family": FAMILY, "dims": int(self.cfg.dims),
            "rule": self.cfg.rule.name,
            "block_step": int(block_step),
            "events": int(self._events_consumed),
            "last_freeze_events": int(self._last_freeze_events),
            "published_through": int(self._published_through),
            "next_version": int(self._next_version),
            "published": published,
            # rollback-condemned versions: without persisting these, a
            # restart would forget the ping-pong guard and could redeploy
            # a condemned version gate-free
            "condemned": sorted(self._condemned),
            "step": int(arrays["step"]),
        }
        with TRACER.span("pipeline.checkpoint",
                         args={"block_step": int(block_step)}):
            save_elastic(self.cfg.checkpoint_path, arrays, manifest)
        with self._lock:
            self._stats["checkpoints_written"] += 1

    def _record_decision(self, decision: GateDecision) -> None:
        with self._lock:
            self._decisions.append(decision.as_record())

    def lineage(self, n: int = 20) -> List[dict]:
        """The last ``n`` gate decisions — what deploy() hands /models."""
        with self._lock:
            return [dict(d) for d in list(self._decisions)[-n:]]

    def status(self) -> dict:
        with self._lock:
            st = dict(self._stats)
            st["restart_causes"] = list(st["restart_causes"])
            st["decisions"] = [dict(d) for d in self._decisions]
            st["published_versions"] = [p["version"]
                                        for p in self._published]
        st["holdout_rows"] = self.holdout.rows
        st["freshness"] = self.freshness_percentiles()
        return st
