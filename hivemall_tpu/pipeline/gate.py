"""The evaluation gate: refuse to publish a model that regresses.

The gate is the pipeline's only quality authority: a frozen candidate
artifact is scored on the rolling holdout next to the CURRENTLY-SERVED
version, and publication happens only when the candidate's holdout logloss
does not regress past ``regression_tol_logloss``. Decisions are explicit
records (`GateDecision`) — the bench publishes them and /models carries
them as version lineage.

Semantics (tests/test_pipeline.py pins each):

- **no incumbent** — first publish: a finite candidate metric suffices
  (there is nothing to regress against; serving something beats serving
  nothing);
- **insufficient holdout** — with an incumbent serving, a candidate that
  cannot be measured (< ``min_holdout_rows`` held-out rows) is refused:
  never swap blind;
- **regression** — candidate logloss > incumbent logloss + tolerance:
  refused, the old version keeps serving;
- scoring happens through the SERVING path (a ServingEngine over the
  verified artifact), so what the gate measures is what production would
  run — manifest dtype pins, quantized tables and all.

# graftcheck: serving-module
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..evaluation.metrics import auc, logloss
from ..tools.math import sigmoid


def score_metrics(engine, idx_rows, val_rows, labels) -> dict:
    """Holdout logloss/AUC of one engine. Margins are std-calibrated
    before the sigmoid (the bench.py AdaBatch-sweep discipline): linear
    margin scores are uncalibrated, and without the normalization a
    confidently-wrong tail row saturates the 1e-15 clip and dominates the
    mean — the gate would compare score SCALES, not ranking quality.
    Labels in {-1,+1} or {0,1} (evaluation.metrics treats >0 as
    positive)."""
    margins = np.asarray(engine.predict((idx_rows, val_rows)), np.float32)
    z = margins / max(float(np.std(margins)), 1e-9)
    return {"logloss": logloss(sigmoid(z), labels),
            "auc": auc(margins, labels)}


@dataclass
class GateDecision:
    """One gate verdict, the unit of lineage."""

    version: str
    published: bool
    reason: str  # first_publish | improved_or_equal | regression |
    #              insufficient_holdout | artifact_corrupt | rollback
    holdout_rows: int = 0
    candidate_logloss: Optional[float] = None
    candidate_auc: Optional[float] = None
    incumbent_logloss: Optional[float] = None
    incumbent_version: Optional[str] = None
    trained_through_event: Optional[int] = None
    extra: dict = field(default_factory=dict)

    def as_record(self) -> dict:
        r = {k: v for k, v in self.__dict__.items()
             if k != "extra" and v is not None}
        r.update(self.extra)
        return r


class EvalGate:
    """Stateless decision function over (candidate, incumbent, holdout)."""

    def __init__(self, regression_tol_logloss: float = 0.005,
                 min_holdout_rows: int = 64) -> None:
        self.regression_tol_logloss = float(regression_tol_logloss)
        self.min_holdout_rows = int(min_holdout_rows)

    def evaluate(self, version: str, candidate_engine, incumbent_engine,
                 holdout_snapshot,
                 incumbent_version: Optional[str] = None,
                 incumbent_metrics: Optional[dict] = None) -> GateDecision:
        """Score both sides on the SAME holdout and decide.

        ``holdout_snapshot`` is RollingHoldout.snapshot() output (or
        None); ``incumbent_engine`` None means no version is serving.
        ``incumbent_metrics`` (a score_metrics() result) skips rescoring
        the incumbent when the caller already scored it on this exact
        snapshot — the pipeline's health check runs first in the same
        cycle and hands its numbers over."""
        n = 0 if holdout_snapshot is None else len(holdout_snapshot[2])
        if incumbent_engine is None:
            d = GateDecision(version, True, "first_publish", holdout_rows=n)
            if n:
                idx_rows, val_rows, labels = holdout_snapshot
                m = score_metrics(candidate_engine, idx_rows, val_rows,
                                  labels)
                d.candidate_logloss, d.candidate_auc = (m["logloss"],
                                                        m["auc"])
                if not math.isfinite(d.candidate_logloss):
                    d.published = False
                    d.reason = "candidate_metric_not_finite"
            return d
        if n < self.min_holdout_rows:
            return GateDecision(
                version, False, "insufficient_holdout", holdout_rows=n,
                incumbent_version=incumbent_version,
                extra={"min_holdout_rows": self.min_holdout_rows})
        idx_rows, val_rows, labels = holdout_snapshot
        cand = score_metrics(candidate_engine, idx_rows, val_rows, labels)
        inc = incumbent_metrics if incumbent_metrics is not None \
            else score_metrics(incumbent_engine, idx_rows, val_rows, labels)
        regressed = (not math.isfinite(cand["logloss"])
                     or cand["logloss"] > inc["logloss"]
                     + self.regression_tol_logloss)
        return GateDecision(
            version, not regressed,
            "regression" if regressed else "improved_or_equal",
            holdout_rows=n,
            candidate_logloss=cand["logloss"], candidate_auc=cand["auc"],
            incumbent_logloss=inc["logloss"],
            incumbent_version=incumbent_version)
