"""Continuous-training pipeline: stream -> freeze -> eval gate -> hot-swap.

The first subsystem that owns a control loop across both halves of the
codebase (docs/continuous_training.md): an online trainer consumes a
drifting event stream, periodically freezes immutable artifacts
(serving/artifact), runs an evaluation gate on a rolling holdout
(evaluation/metrics: refuse to publish on regression), and atomically
hot-swaps passing versions into a live serving/server.ModelRegistry while
traffic flows — reporting end-to-end "event observed -> model serving it"
freshness as a first-class metric.

# graftcheck: serving-module
"""

from .gate import EvalGate, GateDecision, score_metrics
from .holdout import RollingHoldout
from .loop import (FAMILY, FRESHNESS_BUCKETS, ContinuousPipeline,
                   PipelineConfig, artifact_frozen)

__all__ = [
    "ContinuousPipeline", "PipelineConfig", "EvalGate", "GateDecision",
    "RollingHoldout", "score_metrics", "artifact_frozen", "FAMILY",
    "FRESHNESS_BUCKETS",
]
