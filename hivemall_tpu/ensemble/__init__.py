"""Ensemble/merge UDAFs (ref: hivemall/ensemble/*.java, SURVEY.md §2.12) —
the offline model-merge counterparts of the MIX reductions."""

from __future__ import annotations

from collections import Counter, defaultdict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np


def voted_avg(values: Iterable[float]) -> float:
    """Average of the majority-sign values (ref: ensemble/bagging/VotedAvgUDAF.java:26):
    if positives outnumber negatives, average the positives; else the negatives."""
    pos = [v for v in values if v > 0]
    neg = [v for v in values if v <= 0]
    if len(pos) > len(neg):
        return float(np.mean(pos)) if pos else 0.0
    return float(np.mean(neg)) if neg else 0.0


def weight_voted_avg(values: Iterable[float]) -> float:
    """Weighted variant: side with larger absolute weight sum wins
    (ref: ensemble/bagging/WeightVotedAvgUDAF.java:29)."""
    pos = [v for v in values if v > 0]
    neg = [v for v in values if v <= 0]
    if sum(pos) > -sum(neg):
        return float(np.mean(pos)) if pos else 0.0
    return float(np.mean(neg)) if neg else 0.0


def max_label(score_label_pairs: Iterable[Tuple[float, object]]):
    """Label with the maximum score (ref: ensemble/MaxValueLabelUDAF.java:28)."""
    best = None
    for score, label in score_label_pairs:
        if best is None or score > best[0]:
            best = (score, label)
    return best[1] if best is not None else None


def maxrow(rows: Iterable[Sequence], compare_index: int = 0) -> Optional[Sequence]:
    """The whole row holding the max compare column (ref: ensemble/MaxRowUDAF.java:59)."""
    best = None
    for row in rows:
        if best is None or row[compare_index] > best[compare_index]:
            best = row
    return best


def argmin_kld(mean_covar_pairs: Iterable[Tuple[float, float]]) -> float:
    """Precision-weighted mean (1/sum(1/covar)) * sum(mean/covar)
    (ref: ensemble/ArgminKLDistanceUDAF.java:28-90) — the offline counterpart
    of the MIX argminKLD operator (parallel/mix.py)."""
    sum_mean_div_covar = 0.0
    sum_inv_covar = 0.0
    n = 0
    for mean, covar in mean_covar_pairs:
        if mean is None or covar is None:
            continue
        sum_mean_div_covar += mean / covar
        sum_inv_covar += 1.0 / covar
        n += 1
    if n == 0:
        return 0.0
    return float(sum_mean_div_covar / sum_inv_covar)


def rf_ensemble(votes: Iterable[int]) -> Tuple[int, float, List[float]]:
    """Random-forest majority vote -> (label, probability, posterior probs)
    (ref: smile/tools/RandomForestEnsembleUDAF.java:34)."""
    counts = Counter(int(v) for v in votes)
    if not counts:
        return -1, 0.0, []
    total = sum(counts.values())
    k = max(counts) + 1
    posteriori = [counts.get(i, 0) / total for i in range(k)]
    label, cnt = counts.most_common(1)[0]
    return label, cnt / total, posteriori
