"""Evaluation UDAFs (ref: hivemall/evaluation/*.java, SURVEY.md §2.11).

Each metric exists in two forms:
- a streaming aggregator class with iterate/merge/terminate — the UDAF
  lifecycle (PARTIAL1/PARTIAL2/FINAL) that makes the metric map/combine/
  reduce-safe exactly like the reference (e.g. NDCGUDAF.java:113-196);
- a one-shot vectorized function over arrays (the convenient API).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np


class _PartialSum:
    def __init__(self) -> None:
        self.sum = 0.0
        self.count = 0

    def iterate(self, v: float) -> None:
        self.sum += float(v)
        self.count += 1

    def merge(self, other: "_PartialSum") -> None:
        self.sum += other.sum
        self.count += other.count


class MAE(_PartialSum):
    """mean absolute error (ref: evaluation/MeanAbsoluteErrorUDAF.java)."""

    def iterate(self, predicted: float, actual: float) -> None:  # type: ignore[override]
        super().iterate(abs(float(predicted) - float(actual)))

    def terminate(self) -> float:
        return self.sum / self.count if self.count else 0.0


class MSE(_PartialSum):
    """mean squared error (ref: evaluation/MeanSquaredErrorUDAF.java)."""

    def iterate(self, predicted: float, actual: float) -> None:  # type: ignore[override]
        d = float(predicted) - float(actual)
        super().iterate(d * d)

    def terminate(self) -> float:
        return self.sum / self.count if self.count else 0.0


class RMSE(MSE):
    """root mean squared error (ref: evaluation/RootMeanSquaredErrorUDAF.java)."""

    def terminate(self) -> float:
        return math.sqrt(super().terminate())


class LogLossAggregator(_PartialSum):
    """logloss(predicted, actual) UDAF (ref: evaluation/LogarithmicLossUDAF.java:28-100):
    actual in {0,1} (or {-1,1}), predicted a probability."""

    EPS = 1e-15

    def iterate(self, predicted: float, actual: float) -> None:  # type: ignore[override]
        p = min(max(float(predicted), self.EPS), 1.0 - self.EPS)
        y = 1.0 if float(actual) > 0 else 0.0
        super().iterate(-(y * math.log(p) + (1.0 - y) * math.log(1.0 - p)))

    def terminate(self) -> float:
        return self.sum / self.count if self.count else 0.0


class R2:
    """R^2 coefficient of determination (ref: evaluation/R2UDAF.java:33)."""

    def __init__(self) -> None:
        self.n = 0
        self.sum_sq_err = 0.0
        self.sum_actual = 0.0
        self.sum_sq_actual = 0.0

    def iterate(self, predicted: float, actual: float) -> None:
        a, p = float(actual), float(predicted)
        self.n += 1
        self.sum_sq_err += (a - p) ** 2
        self.sum_actual += a
        self.sum_sq_actual += a * a

    def merge(self, o: "R2") -> None:
        self.n += o.n
        self.sum_sq_err += o.sum_sq_err
        self.sum_actual += o.sum_actual
        self.sum_sq_actual += o.sum_sq_actual

    def terminate(self) -> float:
        if self.n == 0:
            return 0.0
        mean = self.sum_actual / self.n
        ss_tot = self.sum_sq_actual - self.n * mean * mean
        if ss_tot == 0.0:
            return 1.0 if self.sum_sq_err == 0.0 else 0.0
        return 1.0 - self.sum_sq_err / ss_tot


class F1Score:
    """f1score(actual_list, predicted_list) micro-F1 over multi-label rows
    (ref: evaluation/FMeasureUDAF.java:33)."""

    def __init__(self) -> None:
        self.tp = 0
        self.total_actual = 0
        self.total_predicted = 0

    def iterate(self, actual: Sequence, predicted: Sequence) -> None:
        sa, sp = set(actual), set(predicted)
        self.tp += len(sa & sp)
        self.total_actual += len(sa)
        self.total_predicted += len(sp)

    def merge(self, o: "F1Score") -> None:
        self.tp += o.tp
        self.total_actual += o.total_actual
        self.total_predicted += o.total_predicted

    def terminate(self) -> float:
        prec = self.tp / self.total_predicted if self.total_predicted else 0.0
        rec = self.tp / self.total_actual if self.total_actual else 0.0
        if prec + rec == 0.0:
            return 0.0
        return 2.0 * prec * rec / (prec + rec)


class NDCG:
    """ndcg(rank_items, true_items[, k]) UDAF with full partial lifecycle
    (ref: evaluation/NDCGUDAF.java:51-196)."""

    def __init__(self, k: Optional[int] = None) -> None:
        self.k = k
        self.sum = 0.0
        self.count = 0

    def iterate(self, ranked: Sequence, truth: Sequence) -> None:
        self.sum += ndcg(ranked, truth, self.k)
        self.count += 1

    def merge(self, o: "NDCG") -> None:
        self.sum += o.sum
        self.count += o.count

    def terminate(self) -> float:
        return self.sum / self.count if self.count else 0.0


class AUC:
    """Streaming ROC AUC over (score, label) pairs."""

    def __init__(self) -> None:
        self.scores: list = []
        self.labels: list = []

    def iterate(self, score: float, label: float) -> None:
        self.scores.append(float(score))
        self.labels.append(1.0 if float(label) > 0 else 0.0)

    def merge(self, o: "AUC") -> None:
        self.scores.extend(o.scores)
        self.labels.extend(o.labels)

    def terminate(self) -> float:
        return auc(np.asarray(self.scores), np.asarray(self.labels))


# ---------------- one-shot vectorized forms ----------------

def mae(predicted, actual) -> float:
    p, a = np.asarray(predicted, float), np.asarray(actual, float)
    return float(np.mean(np.abs(p - a)))


def mse(predicted, actual) -> float:
    p, a = np.asarray(predicted, float), np.asarray(actual, float)
    return float(np.mean((p - a) ** 2))


def rmse(predicted, actual) -> float:
    return float(math.sqrt(mse(predicted, actual)))


def r2(predicted, actual) -> float:
    agg = R2()
    for p, a in zip(np.asarray(predicted, float), np.asarray(actual, float)):
        agg.iterate(p, a)
    return agg.terminate()


def logloss(predicted, actual) -> float:
    p = np.clip(np.asarray(predicted, float), 1e-15, 1 - 1e-15)
    y = (np.asarray(actual, float) > 0).astype(float)
    return float(np.mean(-(y * np.log(p) + (1 - y) * np.log(1 - p))))


def f1score(actual_rows, predicted_rows) -> float:
    agg = F1Score()
    for a, p in zip(actual_rows, predicted_rows):
        agg.iterate(a, p)
    return agg.terminate()


def ndcg(ranked: Sequence, truth: Sequence, k: Optional[int] = None) -> float:
    """Binary-relevance NDCG@k (ref: evaluation/BinaryResponsesMeasures.java nDCG)."""
    truth_set = set(truth)
    if not truth_set:
        return 0.0
    items = list(ranked)[: k if k is not None else len(ranked)]
    dcg = sum(1.0 / math.log2(i + 2) for i, it in enumerate(items) if it in truth_set)
    ideal_n = min(len(truth_set), len(items)) if items else 0
    idcg = sum(1.0 / math.log2(i + 2) for i in range(ideal_n))
    return dcg / idcg if idcg > 0 else 0.0


def auc(scores, labels) -> float:
    """ROC AUC via rank statistic (ties averaged)."""
    s = np.asarray(scores, float)
    y = (np.asarray(labels, float) > 0).astype(float)
    n_pos = float(y.sum())
    n_neg = float(len(y) - y.sum())
    if n_pos == 0 or n_neg == 0:
        return 0.5
    order = np.argsort(s, kind="mergesort")
    ranks = np.empty(len(s), float)
    sorted_s = s[order]
    i = 0
    r = 1.0
    while i < len(s):
        j = i
        while j + 1 < len(s) and sorted_s[j + 1] == sorted_s[i]:
            j += 1
        avg = (r + r + (j - i)) / 2.0
        ranks[order[i : j + 1]] = avg
        r += j - i + 1
        i = j + 1
    sum_pos_ranks = float(np.sum(ranks[y == 1]))
    return (sum_pos_ranks - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg)
