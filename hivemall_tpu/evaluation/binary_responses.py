"""Binary-relevance ranking measures (ref: evaluation/BinaryResponsesMeasures.java)."""

from __future__ import annotations

from typing import Optional, Sequence


def precision_at(ranked: Sequence, truth: Sequence, k: Optional[int] = None) -> float:
    items = list(ranked)[: k if k is not None else len(ranked)]
    if not items:
        return 0.0
    ts = set(truth)
    return sum(1 for it in items if it in ts) / len(items)


def recall_at(ranked: Sequence, truth: Sequence, k: Optional[int] = None) -> float:
    ts = set(truth)
    if not ts:
        return 0.0
    items = list(ranked)[: k if k is not None else len(ranked)]
    return sum(1 for it in items if it in ts) / len(ts)


def hitrate(ranked: Sequence, truth: Sequence, k: Optional[int] = None) -> float:
    ts = set(truth)
    items = list(ranked)[: k if k is not None else len(ranked)]
    return 1.0 if any(it in ts for it in items) else 0.0


def mrr(ranked: Sequence, truth: Sequence, k: Optional[int] = None) -> float:
    ts = set(truth)
    items = list(ranked)[: k if k is not None else len(ranked)]
    for i, it in enumerate(items):
        if it in ts:
            return 1.0 / (i + 1)
    return 0.0


def average_precision(ranked: Sequence, truth: Sequence,
                      k: Optional[int] = None) -> float:
    ts = set(truth)
    if not ts:
        return 0.0
    items = list(ranked)[: k if k is not None else len(ranked)]
    hits = 0
    s = 0.0
    for i, it in enumerate(items):
        if it in ts:
            hits += 1
            s += hits / (i + 1)
    denom = min(len(ts), len(items)) if items else 1
    return s / denom if denom else 0.0
