"""Feature parsing — the host-side front door of the framework.

Mirrors the reference's two feature grammars:

- linear learners: ``"name"`` or ``"name:value"`` — split at the FIRST colon,
  value defaults to 1.0, name may be an int index or arbitrary string
  (ref: core/.../model/FeatureValue.java:74-93).
- FM/FFM: ``"idx:value"`` (int feature) or ``"field:idx:value"``
  (ref: core/.../fm/Feature.java:76-170).

String names are folded into the hashed feature space with bit-identical
MurmurHash3 (see utils/hashing.py), which is the reference's own default
canonicalization (ref: ftvec/hashing/FeatureHashingUDF.java:172).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple, Union

import numpy as np

from .hashing import DEFAULT_NUM_FEATURES, mhash, murmurhash3_bytes_batch

FeatureLike = Union[str, Tuple[int, float], Tuple[str, float]]


@dataclass
class FeatureValue:
    """Parsed (feature, value) pair (ref: model/FeatureValue.java:26)."""

    feature: Union[int, str]
    value: float = 1.0

    @staticmethod
    def parse(s: str) -> "FeatureValue":
        if not s:
            raise ValueError("feature string is empty")
        pos = s.find(":")
        if pos == 0:
            raise ValueError(f"invalid feature {s!r}")
        if pos < 0:
            name: Union[int, str] = s
            value = 1.0
        else:
            name = s[:pos]
            vs = s[pos + 1 :]
            if not vs:
                raise ValueError(f"invalid feature value {s!r}")
            value = float(vs)
        try:
            name = int(name)
        except (TypeError, ValueError):
            pass
        return FeatureValue(name, value)


def parse_feature(s: str) -> Tuple[Union[int, str], float]:
    fv = FeatureValue.parse(s)
    return fv.feature, fv.value


def hash_feature_name(name: Union[int, str], num_features: int) -> int:
    """Int names index directly (mod space); strings are murmur-hashed."""
    if isinstance(name, (int, np.integer)):
        return int(name) % num_features
    return mhash(str(name), num_features)


def parse_features_batch(
    rows: Sequence[Sequence[FeatureLike]],
    num_features: int = DEFAULT_NUM_FEATURES,
) -> Tuple[List[np.ndarray], List[np.ndarray]]:
    """Parse many rows of features into (indices, values) numpy arrays.

    Accepts per-row lists of "name[:value]" strings or (name, value) tuples.
    String names are bulk murmur-hashed; int names index the space directly,
    matching the reference's dense-model int-feature path
    (ref: LearnerBaseUDTF.java:164-196 dense vs sparse model selection).
    """
    from .. import native

    # C fast path: one pass over a concatenated token buffer (parse + hash +
    # mod in native code). Falls back below for tuple features, exotic
    # numeric literals, or malformed tokens (identical error behavior).
    fast = native.parse_features_bulk(rows, num_features)
    if fast is not None:
        return fast

    idx_rows: List[np.ndarray] = []
    val_rows: List[np.ndarray] = []
    # Collect string names for one vectorized hash pass.
    str_names: List[str] = []
    str_slots: List[Tuple[int, int]] = []  # (row, k) positions to backfill
    for r, row in enumerate(rows):
        idxs = np.empty(len(row), dtype=np.int64)
        vals = np.empty(len(row), dtype=np.float32)
        for k, f in enumerate(row):
            if isinstance(f, str):
                name, value = parse_feature(f)
            else:
                name, value = f
            vals[k] = value
            if isinstance(name, (int, np.integer)):
                idxs[k] = int(name) % num_features
            else:
                idxs[k] = -1
                str_slots.append((r, k))
                str_names.append(str(name))
        idx_rows.append(idxs)
        val_rows.append(vals)
    if str_names:
        hashed = murmurhash3_bytes_batch(str_names, num_features)
        for (r, k), h in zip(str_slots, hashed):
            idx_rows[r][k] = h
    return idx_rows, val_rows


@dataclass
class FMFeature:
    """FM/FFM feature: (field, index, value) (ref: fm/Feature.java:32)."""

    index: int
    value: float
    field: int = -1  # -1 when not field-aware

    @staticmethod
    def parse(s: str, as_int: bool = True, num_features: int = DEFAULT_NUM_FEATURES,
              num_fields: int = 1024) -> "FMFeature":
        parts = s.split(":")
        if len(parts) == 2:
            idx_s, val_s = parts
            field = -1
        elif len(parts) == 3:
            field_s, idx_s, val_s = parts
            try:
                field = int(field_s)
            except ValueError:
                field = mhash(field_s, num_fields)
        else:
            raise ValueError(f"invalid FM feature {s!r}")
        try:
            idx = int(idx_s)
            if idx < 0:
                raise ValueError(f"index must be non-negative: {s!r}")
        except ValueError:
            if not as_int:
                raise
            idx = mhash(idx_s, num_features)
        return FMFeature(idx, float(val_s), field)


def add_bias(features: Sequence[str], bias_name: str = "0") -> List[str]:
    """`add_bias(features)` appends the constant bias feature
    (ref: ftvec/AddBiasUDF.java, HivemallConstants.java:25)."""
    return list(features) + [f"{bias_name}:1.0"]


def extract_feature(fv: str) -> str:
    """`extract_feature("name:value") -> name` (ref: ftvec/ExtractFeatureUDF.java:31)."""
    pos = fv.find(":")
    return fv if pos < 0 else fv[:pos]


def extract_weight(fv: str) -> float:
    """`extract_weight("name:value") -> value` (ref: ftvec/ExtractWeightUDF.java)."""
    pos = fv.find(":")
    return 1.0 if pos < 0 else float(fv[pos + 1 :])


def feature(name: Union[str, int], value: float) -> str:
    """`feature(name, value) -> "name:value"` (ref: ftvec/FeatureUDF.java)."""
    return f"{name}:{value}"

def feature_index(fv: str) -> Union[int, str]:
    """`feature_index("idx:value") -> idx` (ref: ftvec/FeatureIndexUDF.java)."""
    name = extract_feature(fv)
    try:
        return int(name)
    except ValueError:
        return name


def sort_by_feature(features: Sequence[str]) -> List[str]:
    """`sort_by_feature(features)` (ref: ftvec/SortByFeatureUDF.java)."""
    return sorted(features, key=lambda s: str(feature_index(s)))
