"""Host-side collection substrate.

The reference ships ~4k LoC of hand-written open-addressing maps and helper
structures (ref: SURVEY.md §2.17: OpenHashMap, Int2FloatOpenHashTable,
BoundedPriorityQueue, LRUMap, IndexedSet, SparseIntArray...). On the TPU build
the *hot* lookups became feature-hashed dense arrays + segment ops; what
remains host-side maps to Python/numpy. These classes keep the same API
surface for the places that still want them (top-k, vocab interning, caching).
"""

from __future__ import annotations

import heapq
import itertools
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, Generic, Iterable, Iterator, List, \
    Optional, Tuple, TypeVar

import numpy as np

T = TypeVar("T")


class BoundedPriorityQueue(Generic[T]):
    """Keep the k largest items (ref: utils/collections/BoundedPriorityQueue.java,
    used by each_top_k, tools/EachTopKUDTF.java:48-57)."""

    def __init__(self, k: int):
        if k <= 0:
            raise ValueError("k must be positive")
        self.k = k
        self._heap: List = []
        self._counter = itertools.count()

    def offer(self, priority: float, item: T = None) -> bool:
        entry = (priority, next(self._counter), item)
        if len(self._heap) < self.k:
            heapq.heappush(self._heap, entry)
            return True
        if entry[0] > self._heap[0][0]:
            heapq.heappushpop(self._heap, entry)
            return True
        return False

    def drain_descending(self) -> List:
        out = sorted(self._heap, key=lambda e: (e[0], e[1]), reverse=True)
        self._heap = []
        return [(p, item) for p, _, item in out]

    def __len__(self) -> int:
        return len(self._heap)


class LRUMap(OrderedDict):
    """Fixed-capacity LRU (ref: utils/collections/LRUMap.java).

    ``on_evict(key, value)`` is the cost-aware eviction hook: it fires for
    every entry the map drops to stay within ``capacity`` (and from
    explicit ``evict_oldest()`` calls), AFTER the entry is removed — a
    byte-budgeted wrapper (serving/cache.py) keeps its resident-cost
    accounting exact by decrementing in the hook, so capacity eviction and
    budget eviction share one accounting path. ``capacity <= 0`` is the
    degenerate "holds nothing" map: every insert is immediately evicted
    through the hook (a cache configured with a zero budget stays
    consistent instead of raising from an empty-iterator pop).

    NOT thread-safe: reads rotate the recency list, so even ``m[k]`` is a
    write (``dict.get`` stays a C-level peek and does NOT rotate — the
    documented escape hatch for lock-free inspection). Share across
    threads via `SynchronizedLRUMap`, or hold your own lock when map ops
    must be atomic with surrounding accounting (what serving/cache.py
    does).
    """

    def __init__(self, capacity: int,
                 on_evict: Optional[Callable[[Any, Any], None]] = None):
        super().__init__()
        self.capacity = capacity
        self.on_evict = on_evict

    def evict_oldest(self) -> Optional[Tuple[Any, Any]]:
        """Drop the least-recently-used entry, firing ``on_evict``;
        returns the ``(key, value)`` pair or None when empty. The value
        read bypasses the overridden ``__getitem__`` so eviction never
        rotates recency (and never trips the popitem re-entrancy below)."""
        if not self:
            return None
        oldest = next(iter(self))
        value = OrderedDict.__getitem__(self, oldest)
        super().__delitem__(oldest)
        if self.on_evict is not None:
            self.on_evict(oldest, value)
        return oldest, value

    def __setitem__(self, key, value):
        if key in self:
            # replacement: remove silently (no on_evict — the entry is not
            # leaving the map, it is being refreshed) then re-insert at MRU
            super().__delitem__(key)
        elif len(self) >= self.capacity:
            # not popitem(): the C implementation re-enters the overridden
            # __getitem__ after unlinking the node, and its move_to_end
            # then KeyErrors on the half-removed key
            self.evict_oldest()
        super().__setitem__(key, value)
        if self.capacity <= 0:
            self.evict_oldest()

    def __getitem__(self, key):
        value = super().__getitem__(key)
        self.move_to_end(key)
        return value

    def popitem(self, last: bool = True):
        # the C implementation re-enters the overridden __getitem__ after
        # unlinking the node, and its move_to_end then KeyErrors on the
        # half-removed key (the PR 2 eviction bug) — pop through the
        # non-rotating reads instead
        if not self:
            raise KeyError("popitem(): map is empty")
        key = next(reversed(self)) if last else next(iter(self))
        value = OrderedDict.__getitem__(self, key)
        super().__delitem__(key)
        return key, value


class SynchronizedLRUMap(LRUMap):
    """Thread-guarded LRUMap: item access, insertion, deletion, get/pop/
    popitem/setdefault/update/clear and eviction — reads included, since
    a hit rotates the recency order — run under one RLock (reentrant:
    ``__setitem__`` calls ``evict_oldest`` with the lock already held).
    Iteration and the keys/values/items views are NOT guarded: snapshot
    under your own coordination if the map is being mutated concurrently.

    This makes individual map operations safe to share across threads; it
    does NOT make compound check-then-act sequences atomic. A caller whose
    lookup, insert and side accounting must commit together (the serving
    score cache's byte budget + hit counters) still needs its own outer
    lock around a plain `LRUMap` — pinned in tests/test_collections.py.
    """

    def __init__(self, capacity: int,
                 on_evict: Optional[Callable[[Any, Any], None]] = None):
        super().__init__(capacity, on_evict)
        self._lock = threading.RLock()

    def evict_oldest(self):
        with self._lock:
            return super().evict_oldest()

    def __setitem__(self, key, value):
        with self._lock:
            super().__setitem__(key, value)

    def __getitem__(self, key):
        with self._lock:
            return super().__getitem__(key)

    def __delitem__(self, key):
        with self._lock:
            super().__delitem__(key)

    def __contains__(self, key):
        with self._lock:
            return super().__contains__(key)

    def __len__(self):
        with self._lock:
            return super().__len__()

    def get(self, key, default=None):
        with self._lock:
            return super().get(key, default)

    def pop(self, key, *default):
        with self._lock:
            return super().pop(key, *default)

    def popitem(self, last: bool = True):
        with self._lock:
            return super().popitem(last)

    def setdefault(self, key, default=None):
        with self._lock:
            return super().setdefault(key, default)

    def update(self, *args, **kwargs):
        with self._lock:
            super().update(*args, **kwargs)

    def clear(self):
        with self._lock:
            super().clear()


class IndexedSet(Generic[T]):
    """Intern values to dense int ids (ref: utils/collections/IndexedSet.java) —
    the string-vocabulary front end of the hashed feature space."""

    def __init__(self) -> None:
        self._map: Dict[T, int] = {}
        self._items: List[T] = []

    def add(self, item: T) -> int:
        idx = self._map.get(item)
        if idx is None:
            idx = len(self._items)
            self._map[item] = idx
            self._items.append(item)
        return idx

    def index_of(self, item: T) -> int:
        return self._map.get(item, -1)

    def get(self, idx: int) -> T:
        return self._items[idx]

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self) -> Iterator[T]:
        return iter(self._items)


class OpenHashMap(dict):
    """API-parity alias: Python dicts are already open-addressed hash maps
    (ref: utils/collections/OpenHashMap.java)."""


class SparseIntArray:
    """Sparse int->int array with dense export
    (ref: utils/collections/SparseIntArray.java)."""

    def __init__(self) -> None:
        self._map: Dict[int, int] = {}

    def put(self, idx: int, value: int) -> None:
        self._map[idx] = value

    def get(self, idx: int, default: int = 0) -> int:
        return self._map.get(idx, default)

    def increment(self, idx: int, by: int = 1) -> None:
        self._map[idx] = self._map.get(idx, 0) + by

    def to_dense(self, size: Optional[int] = None) -> np.ndarray:
        n = size if size is not None else (max(self._map) + 1 if self._map else 0)
        out = np.zeros(n, dtype=np.int64)
        for k, v in self._map.items():
            if k < n:
                out[k] = v
        return out


class ReservoirSampler(Generic[T]):
    """Uniform k-sample over a stream (ref: common/ReservoirSampler.java:32)."""

    def __init__(self, k: int, seed: int = 31):
        self.k = k
        self._rng = np.random.RandomState(seed)
        self._samples: List[T] = []
        self._seen = 0

    def add(self, item: T) -> None:
        self._seen += 1
        if len(self._samples) < self.k:
            self._samples.append(item)
        else:
            j = self._rng.randint(0, self._seen)
            if j < self.k:
                self._samples[j] = item

    @property
    def samples(self) -> List[T]:
        return list(self._samples)
