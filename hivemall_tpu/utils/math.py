"""Math substrate (ref: utils/math/{MathUtils,Primes,StatsUtils}.java)."""

from __future__ import annotations

import math
from typing import List


def bits_required(x: int) -> int:
    """Number of bits to represent x (ref: MathUtils.bitsRequired)."""
    return max(1, int(x).bit_length())


def modulo_power_of_two(x: int, power_of_two: int) -> int:
    """x & (2^k - 1) with two's-complement semantics for negative x
    (ref: MathUtils.moduloPowerOfTwo)."""
    return x & (power_of_two - 1)


def is_power_of_two(x: int) -> bool:
    return x > 0 and (x & (x - 1)) == 0


def next_power_of_two(x: int) -> int:
    if x <= 1:
        return 1
    return 1 << (x - 1).bit_length()


def is_prime(n: int) -> bool:
    if n < 2:
        return False
    if n % 2 == 0:
        return n == 2
    f = 3
    while f * f <= n:
        if n % f == 0:
            return False
        f += 2
    return True


def next_prime(n: int) -> int:
    """Smallest prime >= n (ref: utils/math/Primes.java, used to size hash
    tables)."""
    if n <= 2:
        return 2
    c = n if n % 2 else n + 1
    while not is_prime(c):
        c += 2
    return c


def inverse_erf(x: float) -> float:
    """erf^-1 via the Giles series refinement (ref: MathUtils.inverseErf)."""
    a = 0.147
    ln1mx2 = math.log(max(1e-300, 1.0 - x * x))
    t1 = 2.0 / (math.pi * a) + ln1mx2 / 2.0
    v = math.copysign(math.sqrt(math.sqrt(t1 * t1 - ln1mx2 / a) - t1), x)
    # two Newton refinements: f(v) = erf(v) - x
    for _ in range(2):
        err = math.erf(v) - x
        v -= err * math.sqrt(math.pi) / 2.0 * math.exp(v * v)
    return v


def probit(p: float, bound: float = 5.0) -> float:
    """probit(p) = sqrt(2) erfinv(2p - 1), clamped (ref: StatsUtils.java:35-60)."""
    if p < 0 or p > 1:
        raise ValueError("p must be in [0,1]")
    if p == 0:
        return -bound
    if p == 1:
        return bound
    v = math.sqrt(2.0) * inverse_erf(2.0 * p - 1.0)
    return max(-bound, min(bound, v))


def sigmoid(x: float) -> float:
    return 1.0 / (1.0 + math.exp(-x))


def close_to_zero(x: float, eps: float = 1e-9) -> bool:
    return abs(x) <= eps
