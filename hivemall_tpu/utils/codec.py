"""Binary codecs for model compression.

Mirrors the reference codec substrate (ref: utils/codec/{ZigZagLEB128Codec,
VariableByteCodec,DeflateCodec,Base91}.java and utils/lang/HalfFloat.java:34-80):
these compress FFM prediction models and serialized trees
(ref: fm/FFMPredictionModel.java:149-200, DecisionTree.predictSerCodegen:927).

Half-float: the reference's 10KB-lookup-table fp16 codec is IEEE 754 binary16
— numpy float16 is the same format (numpy rounds-to-nearest where the table
truncates; values differ by at most 1 ulp). On TPU, bf16 storage supersedes
this for in-HBM compression; the codec remains for model-table interchange.
"""

from __future__ import annotations

import struct
import zlib
from typing import Iterable, List, Sequence, Tuple

import numpy as np


# ---------------------------------------------------------------- half float

def float_to_half(x) -> np.ndarray:
    return np.asarray(x, dtype=np.float32).astype(np.float16)


def half_to_float(h) -> np.ndarray:
    return np.asarray(h, dtype=np.float16).astype(np.float32)


def half_float_bits(x: float) -> int:
    """float -> uint16 bit pattern (HalfFloat.floatToHalfFloat analog)."""
    return int(np.float16(x).view(np.uint16))


def bits_to_half_float(bits: int) -> float:
    return float(np.uint16(bits).view(np.float16))


# ---------------------------------------------------------------- zigzag

def zigzag_encode(v: int) -> int:
    """Signed -> unsigned zigzag (ref: ZigZagLEB128Codec.java). The Java
    codec's (v << 1) ^ (v >> 63) form assumes 64-bit wrap; on unbounded
    Python ints the equivalent is the closed form below."""
    return (-v << 1) - 1 if v < 0 else v << 1


def zigzag_decode(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


# ---------------------------------------------------------------- LEB128

def leb128_encode(value: int, out: bytearray) -> None:
    """Unsigned LEB128 append."""
    if value < 0:
        raise ValueError("leb128 encodes unsigned values; zigzag first")
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def leb128_decode(buf: bytes, pos: int = 0) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7


def zigzag_leb128_encode_array(values: Iterable[int]) -> bytes:
    vals = values if isinstance(values, np.ndarray) else list(values)
    arr = None
    if not (isinstance(vals, np.ndarray)
            and not np.can_cast(vals.dtype, np.int64, "safe")):
        try:
            arr = np.asarray(vals, np.int64)
        except (OverflowError, ValueError):  # >64-bit: Python path only
            arr = None
    if arr is not None and arr.size:
        from .. import native

        encoded = native.zigzag_leb128_encode(arr)
        if encoded is not None:
            return encoded
    out = bytearray()
    for v in vals:
        leb128_encode(zigzag_encode(int(v)), out)
    return bytes(out)


def zigzag_leb128_decode_array(buf: bytes, n: int) -> List[int]:
    from .. import native

    if n:
        try:
            decoded = native.zigzag_leb128_decode(buf, n)
        except ValueError:  # >64-bit values: only the Python path handles them
            decoded = None
        if decoded is not None:
            return decoded.tolist()
    out = []
    pos = 0
    for _ in range(n):
        v, pos = leb128_decode(buf, pos)
        out.append(zigzag_decode(v))
    return out


# ---------------------------------------------------------------- varbyte

def vbyte_encode(values: Iterable[int]) -> bytes:
    """Variable-byte codec for non-negative ints (ref: VariableByteCodec.java)."""
    out = bytearray()
    for v in values:
        leb128_encode(int(v), out)
    return bytes(out)


def vbyte_decode(buf: bytes, n: int) -> List[int]:
    out = []
    pos = 0
    for _ in range(n):
        v, pos = leb128_decode(buf, pos)
        out.append(v)
    return out


# ------------------------------------------------------- model blob helpers

def compress_model_blob(payload: bytes, level: int = 6) -> bytes:
    """deflate a serialized model blob (DeflateCodec analog)."""
    return zlib.compress(payload, level)


def decompress_model_blob(blob: bytes) -> bytes:
    return zlib.decompress(blob)


def encode_sparse_model(feats: np.ndarray, weights: np.ndarray,
                        half_float: bool = True) -> bytes:
    """Compress (feature, weight) model rows: delta+zigzag-LEB128 indices +
    fp16 weights + deflate — the FFMPredictionModel.writeExternal recipe
    (ref: FFMPredictionModel.java:149-200)."""
    feats = np.asarray(feats, np.int64)
    order = np.argsort(feats)
    feats = feats[order]
    weights = np.asarray(weights, np.float32)[order]
    deltas = np.diff(feats, prepend=0)
    idx_bytes = zigzag_leb128_encode_array(deltas)
    if half_float:
        w_bytes = float_to_half(weights).tobytes()
    else:
        w_bytes = weights.tobytes()
    header = struct.pack("<qB", len(feats), 1 if half_float else 0)
    return compress_model_blob(header + struct.pack("<q", len(idx_bytes))
                               + idx_bytes + w_bytes)


def decode_sparse_model(blob: bytes) -> Tuple[np.ndarray, np.ndarray]:
    payload = decompress_model_blob(blob)
    n, hf = struct.unpack_from("<qB", payload, 0)
    off = 9
    (idx_len,) = struct.unpack_from("<q", payload, off)
    off += 8
    deltas = zigzag_leb128_decode_array(payload[off : off + idx_len], n)
    off += idx_len
    feats = np.cumsum(np.asarray(deltas, np.int64))
    if hf:
        weights = half_to_float(np.frombuffer(payload, np.float16, count=n,
                                              offset=off))
    else:
        weights = np.frombuffer(payload, np.float32, count=n, offset=off).copy()
    return feats, np.asarray(weights, np.float32)
