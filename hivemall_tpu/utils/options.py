"""Option-string parsing for trainer/function options.

Every reference trainer takes a commons-cli style option string, e.g.
``train_arow(features, label, '-r 0.1 -mix host1,host2')``
(ref: core/.../UDTFWithOptions.java:90-124). This module reproduces that
surface: each learner declares `Option`s, user passes one string, `-help`
raises with an auto-generated usage message (ref: UDTFWithOptions.java:99-118).
"""

from __future__ import annotations

import shlex
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


class HelpRequested(Exception):
    """Raised when the option string contains -help; message is the usage text."""


class OptionError(ValueError):
    pass


@dataclass
class Option:
    name: str
    long_name: Optional[str] = None
    has_arg: bool = False
    help: str = ""
    default: Any = None
    type: Callable[[str], Any] = str


@dataclass
class Options:
    """A minimal commons-cli Options/CommandLine equivalent."""

    opts: List[Option] = field(default_factory=list)

    def add(
        self,
        name: str,
        long_name: Optional[str] = None,
        has_arg: bool = False,
        help: str = "",
        default: Any = None,
        type: Callable[[str], Any] = str,
    ) -> "Options":
        self.opts.append(Option(name, long_name, has_arg, help, default, type))
        return self

    def usage(self, func_name: str = "") -> str:
        lines = [f"usage: {func_name} [options]"]
        for o in self.opts:
            names = f"-{o.name}" + (f",--{o.long_name}" if o.long_name else "")
            arg = " <arg>" if o.has_arg else ""
            lines.append(f"  {names}{arg}  {o.help}")
        return "\n".join(lines)

    def parse(self, option_string: Optional[str], func_name: str = "") -> "CommandLine":
        by_name: Dict[str, Option] = {}
        for o in self.opts:
            by_name[o.name] = o
            if o.long_name:
                by_name[o.long_name] = o
        values: Dict[str, Any] = {}
        tokens = shlex.split(option_string) if option_string else []
        i = 0
        while i < len(tokens):
            tok = tokens[i]
            if tok in ("-help", "--help", "-h"):
                raise HelpRequested(self.usage(func_name))
            if not tok.startswith("-"):
                raise OptionError(f"unexpected token {tok!r} in options {option_string!r}")
            key = tok.lstrip("-")
            opt = by_name.get(key)
            if opt is None:
                raise OptionError(f"unknown option {tok!r}\n{self.usage(func_name)}")
            if opt.has_arg:
                i += 1
                if i >= len(tokens):
                    raise OptionError(f"option {tok!r} requires an argument")
                values[opt.name] = opt.type(tokens[i])
            else:
                values[opt.name] = True
            i += 1
        return CommandLine(values, {o.name: o for o in self.opts})


@dataclass
class CommandLine:
    values: Dict[str, Any]
    specs: Dict[str, Option]

    def has(self, name: str) -> bool:
        return name in self.values

    def get(self, name: str, default: Any = None) -> Any:
        if name in self.values:
            return self.values[name]
        spec = self.specs.get(name)
        if default is not None:
            return default
        return spec.default if spec is not None else None

    def get_float(self, name: str, default: Optional[float] = None) -> Optional[float]:
        v = self.get(name, default)
        return None if v is None else float(v)

    def get_int(self, name: str, default: Optional[int] = None) -> Optional[int]:
        v = self.get(name, default)
        return None if v is None else int(v)
