"""MurmurHash3 x86_32 — bit-identical to the reference implementation.

The reference hashes feature-name strings (UTF-8) with seed 0x9747b28c and maps
them into a 2^24 feature space with Java signed floor-mod semantics
(ref: core/.../utils/hashing/MurmurHash3.java:26-35, ftvec/hashing/MurmurHash3UDF.java:31).

Bit-compatibility matters: feature spaces must match between any host
preprocessing (including existing Hivemall-produced models) and our TPU
kernels, so the same string must land in the same slot.

A vectorized numpy path (`murmurhash3_bytes_batch`) handles bulk host-side
hashing; `hivemall_tpu.native` provides a C++ version of the same loop that is
used transparently when the shared library has been built.
"""

from __future__ import annotations

from typing import Iterable, List, Sequence

import numpy as np

DEFAULT_NUM_FEATURES = 1 << 24  # 2^24 (ref: MurmurHash3.java:27)
DEFAULT_SEED = 0x9747B28C

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_M32 = 0xFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    x &= _M32
    return ((x << r) | (x >> (32 - r))) & _M32


def murmurhash3_x86_32(data: bytes | str, seed: int = DEFAULT_SEED) -> int:
    """MurmurHash3_x86_32 over UTF-8 bytes. Returns a signed 32-bit int,
    matching Java's return type (ref: MurmurHash3.java:57-144)."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    n = len(data)
    h1 = seed & _M32
    nblocks = n >> 2
    for i in range(nblocks):
        k1 = int.from_bytes(data[i * 4 : i * 4 + 4], "little")
        k1 = (k1 * _C1) & _M32
        k1 = _rotl32(k1, 15)
        k1 = (k1 * _C2) & _M32
        h1 ^= k1
        h1 = _rotl32(h1, 13)
        h1 = (h1 * 5 + 0xE6546B64) & _M32
    # tail
    tail = data[nblocks * 4 :]
    k1 = 0
    for i, b in enumerate(tail):
        k1 |= b << (8 * i)
    if tail:
        k1 = (k1 * _C1) & _M32
        k1 = _rotl32(k1, 15)
        k1 = (k1 * _C2) & _M32
        h1 ^= k1
    # finalization
    h1 ^= n
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & _M32
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & _M32
    h1 ^= h1 >> 16
    # to Java signed int
    return h1 - (1 << 32) if h1 >= (1 << 31) else h1


def mhash(data: bytes | str, num_features: int = DEFAULT_NUM_FEATURES) -> int:
    """The `mhash(word)` SQL function: murmur3 folded into [0, num_features)
    with Java `%`-then-fixup semantics, which equals Python floor-mod on the
    *signed* hash (ref: MurmurHash3.java:32-46)."""
    return murmurhash3_x86_32(data) % num_features


def murmurhash3_bytes_batch(
    strings: Sequence[bytes | str],
    num_features: int = DEFAULT_NUM_FEATURES,
    seed: int = DEFAULT_SEED,
) -> np.ndarray:
    """Hash many strings; numpy-vectorized across the block loop.

    All inputs are processed in lockstep over their 4-byte blocks (padded with
    a done-mask), which vectorizes the hot path for bulk feature hashing.
    Returns int64 indices in [0, num_features).
    """
    bss: List[bytes] = [s.encode("utf-8") if isinstance(s, str) else s for s in strings]
    if not bss:
        return np.zeros((0,), dtype=np.int64)
    if seed == DEFAULT_SEED:
        from .. import native

        out = native.murmur3_bulk(bss, num_features)
        if out is not None:
            return out
    lens = np.array([len(b) for b in bss], dtype=np.int64)
    maxlen = int(lens.max())
    padded = int(-(-max(maxlen, 1) // 4) * 4)
    buf = np.zeros((len(bss), padded), dtype=np.uint8)
    for i, b in enumerate(bss):
        buf[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
    words = buf.view("<u4").astype(np.uint64)  # [N, padded//4]

    h1 = np.full(len(bss), seed, dtype=np.uint64)
    nblocks = lens >> 2
    for j in range(words.shape[1]):
        active = nblocks > j
        k1 = words[:, j]
        k1 = (k1 * _C1) & _M32
        k1 = ((k1 << 15) | (k1 >> 17)) & _M32
        k1 = (k1 * _C2) & _M32
        h1x = h1 ^ k1
        h1x = ((h1x << 13) | (h1x >> 19)) & _M32
        h1x = (h1x * 5 + 0xE6546B64) & _M32
        h1 = np.where(active, h1x, h1)
    # tails: k1 = remaining bytes little-endian
    tail_len = lens & 3
    tail_start = (nblocks * 4).astype(np.int64)
    k1 = np.zeros(len(bss), dtype=np.uint64)
    for i in range(3):
        has = tail_len > i
        idx = np.minimum(tail_start + i, padded - 1)
        byte = buf[np.arange(len(bss)), idx].astype(np.uint64)
        k1 = np.where(has, k1 | (byte << np.uint64(8 * i)), k1)
    has_tail = tail_len > 0
    k1 = (k1 * _C1) & _M32
    k1 = ((k1 << 15) | (k1 >> 17)) & _M32
    k1 = (k1 * _C2) & _M32
    h1 = np.where(has_tail, h1 ^ k1, h1)
    # finalization
    h1 ^= lens.astype(np.uint64)
    h1 &= _M32
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & _M32
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & _M32
    h1 ^= h1 >> 16
    signed = h1.astype(np.int64)
    signed = np.where(signed >= (1 << 31), signed - (1 << 32), signed)
    return np.mod(signed, num_features)


def sha1_hash(data: bytes | str, num_features: int = DEFAULT_NUM_FEATURES) -> int:
    """The `sha1(word)` SQL function analog (ref: ftvec/hashing/Sha1UDF.java):
    first 4 bytes of SHA-1 as a big-endian signed int, floor-mod folded."""
    import hashlib

    if isinstance(data, str):
        data = data.encode("utf-8")
    digest = hashlib.sha1(data).digest()
    h = int.from_bytes(digest[:4], "big", signed=True)
    return h % num_features


def array_hash_values(
    values: Iterable[str],
    prefix: str | None = None,
    num_features: int = DEFAULT_NUM_FEATURES,
    use_indexed_prefix: bool = False,
) -> List[int]:
    """`array_hash_values` / `prefixed_hash_values` SQL functions
    (ref: ftvec/hashing/ArrayHashValuesUDF.java, ArrayPrefixedHashValuesUDF.java)."""
    out = []
    for i, v in enumerate(values):
        key = v if prefix is None else (f"{prefix}{i}:{v}" if use_indexed_prefix else prefix + v)
        out.append(mhash(key, num_features))
    return out
