"""ctypes bindings for the native host ops (native/hivemall_native.cpp).

The C++ library accelerates the host-side input pipeline: bulk murmur3 feature
hashing and padded-CSR block packing (the [native-equiv] substrate pieces from
SURVEY.md §2.17). Python/numpy fallbacks are used automatically when the .so
hasn't been built (scripts/build_native.sh)."""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

_LIB_PATH = os.path.join(os.path.dirname(__file__), "libhivemall_native.so")
_lib: Optional[ctypes.CDLL] = None


_load_error: Optional[str] = None

# HIVEMALL_TPU_NATIVE_SANITIZE selects a sanitizer-instrumented .so variant
# built by `scripts/build_native.sh --sanitize=...` (suffixed so the
# build-stamp machinery never confuses it with the optimized build):
#   ""     -> libhivemall_native.so       (the optimized default)
#   "asan" -> libhivemall_native.asan.so  (ASan+UBSan, halt_on_error gate)
#   "tsan" -> libhivemall_native.tsan.so  (TSan — armed for the threaded
#                                          native apply)
# Sanitizer runtimes are not linked into a -shared .so: the test harness
# LD_PRELOADs libasan/libubsan (scripts/test.sh gate 11).
_SANITIZE_ENV = "HIVEMALL_TPU_NATIVE_SANITIZE"
_SANITIZE_SUFFIX = {"": "", "asan": ".asan", "tsan": ".tsan"}


def _so_path() -> Optional[str]:
    """The .so variant selected by the sanitizer env var, or None (with
    ``_load_error`` recorded) for an unknown value — a typo'd sanitizer
    name must refuse loudly, never silently load the uninstrumented .so."""
    global _load_error
    variant = os.environ.get(_SANITIZE_ENV, "").strip().lower()
    suffix = _SANITIZE_SUFFIX.get(variant)
    if suffix is None:
        _load_error = (f"unknown {_SANITIZE_ENV}={variant!r} "
                       f"(expected one of: "
                       f"{', '.join(repr(k) for k in _SANITIZE_SUFFIX)})")
        import warnings

        warnings.warn(f"hivemall_tpu.native: {_load_error}; native "
                      f"backend disabled, using Python fallbacks")
        return None
    if not suffix:
        return _LIB_PATH
    base, ext = os.path.splitext(_LIB_PATH)
    return base + suffix + ext


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_error
    if _lib is not None:
        return _lib
    if _load_error is not None:
        return None
    path = _so_path()
    if path is None or not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
        _bind_core(lib)
    except (OSError, AttributeError) as e:
        # a built .so that cannot load on THIS host (toolchain/libstdc++
        # mismatch — OSError) or that predates a core symbol
        # (AttributeError from the prototype binding, including a stale
        # build without hm_plan_abi_version) is the same situation as an
        # unbuilt one: fall back to the Python implementations
        # (identical semantics), once, loudly
        _load_error = str(e)
        import warnings

        warnings.warn(f"hivemall_tpu.native: {path} failed to load "
                      f"({e}); using Python fallbacks — rebuild with "
                      f"scripts/build_native.sh")
        return None
    # runtime half of the frozen-ABI contract (G025 is the static half):
    # a .so compiled against a different plan layout must never serve
    from ..ops.scatter import PLAN_ABI_VERSION

    native_ver = int(lib.hm_plan_abi_version())
    if native_ver != PLAN_ABI_VERSION:
        _load_error = (f"plan ABI version mismatch: .so compiled with "
                       f"{native_ver}, Python expects {PLAN_ABI_VERSION}")
        import warnings

        warnings.warn(f"hivemall_tpu.native: {path} failed to load "
                      f"({_load_error}); using Python fallbacks — rebuild "
                      f"with scripts/build_native.sh")
        return None
    _bind_optional(lib)
    _lib = lib
    return lib


def _bind_core(lib: ctypes.CDLL) -> None:
    # the ABI handshake symbol: absent => stale pre-v16 build, and the
    # AttributeError here routes through _load's loud-fallback path
    lib.hm_plan_abi_version.restype = ctypes.c_int64
    lib.hm_plan_abi_version.argtypes = []
    lib.hm_murmur3_x86_32.restype = ctypes.c_int32
    lib.hm_murmur3_x86_32.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                      ctypes.c_uint32]
    lib.hm_murmur3_bulk.restype = None
    lib.hm_murmur3_bulk.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint32,
        ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.hm_pack_block.restype = None
    lib.hm_pack_block.argtypes = [ctypes.c_void_p] * 3 + [
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.hm_decode_records.restype = ctypes.c_int64
    lib.hm_decode_records.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.hm_encode_records_bound.restype = ctypes.c_int64
    lib.hm_encode_records_bound.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.hm_encode_records.restype = ctypes.c_int64
    lib.hm_encode_records.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
    ]
    lib.hm_zigzag_leb128_encode.restype = ctypes.c_int64
    lib.hm_zigzag_leb128_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
    ]
    lib.hm_zigzag_leb128_decode.restype = ctypes.c_int64
    lib.hm_zigzag_leb128_decode.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.hm_forest_eval.restype = ctypes.c_int64
    lib.hm_forest_eval.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p,
    ]


def _bind_optional(lib: ctypes.CDLL) -> None:
    """Per-symbol guards: these entry points may be absent from older .so
    builds without invalidating the core library. hasattr probes (not
    try/except around the whole block) so every PRESENT symbol gets its
    full prototype declared at load time — no call ever runs on ctypes'
    guessed signature (graftcheck G024's contract)."""
    if hasattr(lib, "hm_lattice_tokenize_bulk"):
        lib.hm_lattice_tokenize_bulk.restype = ctypes.c_int64
        lib.hm_lattice_tokenize_bulk.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
    if hasattr(lib, "hm_arow_reference_rowloop"):
        lib.hm_arow_reference_rowloop.restype = ctypes.c_int64
        lib.hm_arow_reference_rowloop.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_float,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
    if hasattr(lib, "hm_fm_reference_rowloop"):
        lib.hm_fm_reference_rowloop.restype = ctypes.c_int64
        lib.hm_fm_reference_rowloop.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p,
        ]
    if hasattr(lib, "hm_batch_apply_block"):
        lib.hm_batch_apply_block.restype = ctypes.c_int64
        lib.hm_batch_apply_block.argtypes = [
            ctypes.c_int32, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int32, ctypes.c_void_p,
        ]
    if hasattr(lib, "hm_parse_features_batch"):
        lib.hm_parse_features_batch.restype = ctypes.c_int64
        lib.hm_parse_features_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p,
        ]


def available() -> bool:
    return _load() is not None


def load_error() -> Optional[str]:
    """The recorded load failure for a PRESENT-but-unloadable .so (toolchain
    mismatch — the PR 11 GLIBCXX pathology), or None. Callers that refuse or
    fall back on unavailability report this so the mismatch is named, never
    swallowed (scripts/build_native.sh --if-stale rebuilds it away)."""
    _load()
    return _load_error


def has_batch_apply() -> bool:
    """True when the loaded .so exports the batched-apply entry point
    (hm_batch_apply_block) — the -native_apply execution backend's probe."""
    lib = _load()
    return lib is not None and hasattr(lib, "hm_batch_apply_block")


# rule-family ids of hm_batch_apply_block's native closed forms — the ABI's
# rule enum, mirrored (native/hivemall_native.cpp HM_BATCH_RULE_*)
BATCH_APPLY_RULES = {"perceptron": 0, "cw": 1, "arow": 2, "arowh": 3}
# hyperparameters each native form REQUIRES: a missing one must raise like
# the XLA rule's hyper["..."] KeyError would, never default to a silently
# degenerate 0.0 (phi=0 freezes CW entirely)
_BATCH_APPLY_REQUIRED_HYPER = {"perceptron": (), "cw": ("phi",),
                               "arow": ("r",), "arowh": ("r", "c")}


def batch_apply_block(rule_name: str, hyper: dict, values: np.ndarray,
                      labels: np.ndarray, main_plan, tail_plan, dims: int,
                      weights: np.ndarray, covars: Optional[np.ndarray],
                      touched: Optional[np.ndarray],
                      mini_batch_average: bool = True) -> Optional[float]:
    """Apply one staged block through hm_batch_apply_block: the whole
    gather -> batch closed form -> segment-reduce -> scatter-back pass in
    one native call, mutating the host-resident f32 tables in place.

    `main_plan` is the block's stacked StagedDedupPlan ([nb, ...] leading
    axis, core/batch_update.py::BlockPlans.main) or None; `tail_plan` the
    remainder chunk's plan or None. Plans must satisfy the frozen ctypes
    ABI (ops/scatter.py::plan_abi_arrays — int32, C-contiguous); values
    [n_rows, width] f32, labels [n_rows] f32. Returns the block's loss sum,
    or None when the library (or the symbol) is unavailable. Raises on a
    rule outside BATCH_APPLY_RULES or malformed plan/table arguments."""
    lib = _load()
    if lib is None or not hasattr(lib, "hm_batch_apply_block"):
        return None
    if rule_name not in BATCH_APPLY_RULES:
        raise ValueError(f"no native batch closed form for rule "
                         f"{rule_name!r} (supported: "
                         f"{sorted(BATCH_APPLY_RULES)})")
    missing = [h for h in _BATCH_APPLY_REQUIRED_HYPER[rule_name]
               if h not in hyper]
    if missing:
        raise KeyError(f"rule {rule_name!r} requires hyperparameter(s) "
                       f"{missing} — same contract as the XLA rule's "
                       f"hyper[...] access")
    from ..ops.scatter import plan_abi_arrays

    values = np.ascontiguousarray(values, np.float32)
    labels = np.ascontiguousarray(labels, np.float32)
    n_rows, width = values.shape
    if labels.shape != (n_rows,):
        raise ValueError(f"labels shape {labels.shape} != ({n_rows},) for "
                         f"values {values.shape}")
    as_p = lambda a: (a.ctypes.data_as(ctypes.c_void_p)  # noqa: E731
                      if a is not None else None)
    nb = bsz = slots_u = 0
    mo = mls = mrep = mst = men = None
    if main_plan is not None:
        mo, mls, mrep, mst, men = plan_abi_arrays(main_plan, stacked=True)
        nb, lanes = mo.shape
        slots_u = mrep.shape[1]
        bsz = lanes // width
    tail_rows = tail_u = 0
    to = tls = trep = tst = ten = None
    if tail_plan is not None:
        to, tls, trep, tst, ten = plan_abi_arrays(tail_plan)
        tail_rows = to.shape[0] // width
        tail_u = trep.shape[0]
    for name, t, dt in (("weights", weights, np.float32),
                        ("covars", covars, np.float32),
                        ("touched", touched, np.int8)):
        if t is None:
            continue
        if t.dtype != dt or not t.flags["C_CONTIGUOUS"]:
            raise ValueError(f"native batch apply needs C-contiguous "
                             f"{np.dtype(dt).name} {name} table, got "
                             f"{t.dtype}")
        if t.shape[0] < dims:
            # the C pass writes any rp < dims: a short table would be
            # heap corruption, not a drop — fail at the boundary
            raise ValueError(f"{name} table has {t.shape[0]} rows < dims "
                             f"{dims}")
    loss = ctypes.c_double(0.0)
    rc = lib.hm_batch_apply_block(
        BATCH_APPLY_RULES[rule_name],
        ctypes.c_float(float(hyper.get("r", 0.0))),
        ctypes.c_float(float(hyper.get("c", 0.0))),
        ctypes.c_float(float(hyper.get("phi", 0.0))),
        as_p(values), as_p(labels), n_rows, width,
        nb, bsz, slots_u, as_p(mo), as_p(mls), as_p(mrep), as_p(mst),
        as_p(men), tail_rows, tail_u, as_p(to), as_p(tls), as_p(trep),
        as_p(tst), as_p(ten), dims, as_p(weights), as_p(covars),
        as_p(touched), 1 if mini_batch_average else 0,
        ctypes.byref(loss))
    if rc != 0:
        raise ValueError("hm_batch_apply_block rejected its arguments "
                         f"(rc={rc}): rule/plan/table mismatch")
    return float(loss.value)


def murmur3(data: bytes, seed: int = 0x9747B28C) -> Optional[int]:
    lib = _load()
    if lib is None:
        return None
    return int(lib.hm_murmur3_x86_32(data, len(data), seed))


def _pack_bytes(items: Sequence[bytes]):
    """Concatenate byte strings into (ctypes buffer, int64 offsets[n+1]) —
    the marshalling shape every bulk string entry point shares."""
    n = len(items)
    offsets = np.zeros(n + 1, dtype=np.int64)
    for i, s in enumerate(items):
        offsets[i + 1] = offsets[i] + len(s)
    buf = b"".join(items)
    return ctypes.create_string_buffer(buf, len(buf) or 1), offsets


def murmur3_bulk(strings: Sequence[bytes], num_features: int,
                 seed: int = 0x9747B28C) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    n = len(strings)
    cbuf, offsets = _pack_bytes(strings)
    out = np.empty(n, dtype=np.int64)
    lib.hm_murmur3_bulk(
        ctypes.cast(cbuf, ctypes.c_void_p),
        offsets.ctypes.data_as(ctypes.c_void_p), n, seed, num_features,
        out.ctypes.data_as(ctypes.c_void_p))
    return out


def decode_records(body: bytes, n_rows: int):
    """Decode a HMTR1 shard body -> (row_offsets, indices, values, labels),
    or None without the library."""
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(body, dtype=np.uint8)
    total = lib.hm_decode_records(buf.ctypes.data_as(ctypes.c_void_p), len(body),
                                  n_rows, None, None, None, None)
    if total < 0:
        raise ValueError("corrupt record shard")
    offsets = np.empty(n_rows + 1, np.int64)
    indices = np.empty(total, np.int64)
    values = np.empty(total, np.float32)
    labels = np.empty(n_rows, np.float32)
    out = lib.hm_decode_records(
        buf.ctypes.data_as(ctypes.c_void_p), len(body), n_rows,
        offsets.ctypes.data_as(ctypes.c_void_p),
        indices.ctypes.data_as(ctypes.c_void_p),
        values.ctypes.data_as(ctypes.c_void_p),
        labels.ctypes.data_as(ctypes.c_void_p))
    if out != total:
        raise ValueError("corrupt record shard")
    return offsets, indices, values, labels


def encode_records(idx_rows: Sequence[np.ndarray],
                   val_rows: Sequence[np.ndarray],
                   labels: np.ndarray) -> Optional[bytes]:
    """Encode rows to an HMTR1 shard body (sorting each row by feature id),
    or None without the library. Raises on nnz > 255 / negative ids."""
    lib = _load()
    if lib is None:
        return None
    n = len(idx_rows)
    if len(val_rows) != n or len(labels) != n:
        raise ValueError("idx_rows/val_rows/labels length mismatch")
    offsets = np.zeros(n + 1, dtype=np.int64)
    for i, r in enumerate(idx_rows):
        if len(val_rows[i]) != len(r):
            raise ValueError(f"row {i}: {len(r)} indices vs "
                             f"{len(val_rows[i])} values")
        offsets[i + 1] = offsets[i] + len(r)
    indices = (np.ascontiguousarray(
        np.concatenate(idx_rows).astype(np.int64, copy=False)) if n else
        np.zeros(0, np.int64))
    values = (np.ascontiguousarray(
        np.concatenate(val_rows).astype(np.float32, copy=False)) if n else
        np.zeros(0, np.float32))
    labs = np.ascontiguousarray(labels, dtype=np.float32)
    cap = int(lib.hm_encode_records_bound(
        offsets.ctypes.data_as(ctypes.c_void_p), n))
    out = np.empty(max(cap, 1), dtype=np.uint8)
    written = lib.hm_encode_records(
        indices.ctypes.data_as(ctypes.c_void_p),
        values.ctypes.data_as(ctypes.c_void_p),
        offsets.ctypes.data_as(ctypes.c_void_p),
        labs.ctypes.data_as(ctypes.c_void_p), n,
        out.ctypes.data_as(ctypes.c_void_p), cap)
    if written < 0:
        raise ValueError("row nnz > 255 or negative feature id")
    return out[:written].tobytes()


def zigzag_leb128_encode(values: np.ndarray) -> Optional[bytes]:
    lib = _load()
    if lib is None:
        return None
    vals = np.ascontiguousarray(values, dtype=np.int64)
    cap = 10 * len(vals)
    out = np.empty(max(cap, 1), dtype=np.uint8)
    written = lib.hm_zigzag_leb128_encode(
        vals.ctypes.data_as(ctypes.c_void_p), len(vals),
        out.ctypes.data_as(ctypes.c_void_p), cap)
    if written < 0:
        raise ValueError("zigzag-leb128 encode overflow")
    return out[:written].tobytes()


def zigzag_leb128_decode(buf: bytes, n: int) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    data = np.frombuffer(buf, dtype=np.uint8)
    out = np.empty(max(n, 1), dtype=np.int64)
    consumed = lib.hm_zigzag_leb128_decode(
        data.ctypes.data_as(ctypes.c_void_p), len(data), n,
        out.ctypes.data_as(ctypes.c_void_p))
    if consumed < 0:
        raise ValueError("corrupt zigzag-leb128 stream")
    return out[:n]


def pack_block(idx_rows: Sequence[np.ndarray], val_rows: Sequence[np.ndarray],
               width: int, dims: int
               ) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    lib = _load()
    if lib is None:
        return None
    n = len(idx_rows)
    offsets = np.zeros(n + 1, dtype=np.int64)
    for i, r in enumerate(idx_rows):
        offsets[i + 1] = offsets[i] + len(r)
    indices = (np.concatenate(idx_rows).astype(np.int64) if n else
               np.zeros(0, np.int64))
    values = (np.concatenate(val_rows).astype(np.float32) if n else
              np.zeros(0, np.float32))
    out_idx = np.empty((n, width), dtype=np.int32)
    out_val = np.empty((n, width), dtype=np.float32)
    out_nnz = np.empty(n, dtype=np.int32)
    lib.hm_pack_block(
        indices.ctypes.data_as(ctypes.c_void_p),
        values.ctypes.data_as(ctypes.c_void_p),
        offsets.ctypes.data_as(ctypes.c_void_p), n, width, dims,
        out_idx.ctypes.data_as(ctypes.c_void_p),
        out_val.ctypes.data_as(ctypes.c_void_p),
        out_nnz.ctypes.data_as(ctypes.c_void_p))
    return out_idx, out_val, out_nnz


def forest_eval(programs: Sequence[Tuple[np.ndarray, np.ndarray, np.ndarray]],
                X: np.ndarray) -> Optional[np.ndarray]:
    """Evaluate T compiled opcode programs (vm.compile_script_arrays output)
    over X [N, F] raw rows -> [T, N] leaf values, or None without the
    library. Raises on a malformed program."""
    lib = _load()
    if lib is None:
        return None
    T = len(programs)
    X = np.ascontiguousarray(X, dtype=np.float64)
    N, F = X.shape
    offsets = np.zeros(T + 1, np.int64)
    for t, (ops, _, _) in enumerate(programs):
        offsets[t + 1] = offsets[t] + len(ops)
    ops = np.ascontiguousarray(np.concatenate([p[0] for p in programs]),
                               dtype=np.int8)
    argi = np.ascontiguousarray(np.concatenate([p[1] for p in programs]),
                                dtype=np.int32)
    argf = np.ascontiguousarray(np.concatenate([p[2] for p in programs]),
                                dtype=np.float64)
    out = np.empty((T, N), np.float64)
    rc = lib.hm_forest_eval(
        ops.ctypes.data_as(ctypes.c_void_p), argi.ctypes.data_as(ctypes.c_void_p),
        argf.ctypes.data_as(ctypes.c_void_p),
        offsets.ctypes.data_as(ctypes.c_void_p), T,
        X.ctypes.data_as(ctypes.c_void_p), N, F,
        out.ctypes.data_as(ctypes.c_void_p))
    if rc != 0:
        raise ValueError("malformed opcode program")
    return out


def parse_features_bulk(rows: Sequence[Sequence[str]], num_features: int
                        ) -> Optional[Tuple[List[np.ndarray], List[np.ndarray]]]:
    """Bulk-parse rows of "name[:value]" tokens through the C parser
    (hm_parse_features_batch): one concatenated buffer in, flat idx/val
    arrays out, re-split per row. Returns None when the .so is absent or a
    token falls outside the canonical grammar (caller uses the Python
    parser, keeping error behavior and exotic-literal handling identical)."""
    lib = _load()
    if lib is None or not hasattr(lib, "hm_parse_features_batch"):
        return None
    toks: List[bytes] = []
    row_lens = np.empty(len(rows), dtype=np.int64)
    for r, row in enumerate(rows):
        row_lens[r] = len(row)
        for t in row:
            if type(t) is not str:
                return None  # (name, value) tuples etc. -> Python path
            if not t.isascii():
                # the C scan can't see Unicode-NUMERIC names that Python's
                # int() would direct-index (e.g. Arabic-Indic digits, nbsp
                # + digits); decline those precisely — ordinary non-ASCII
                # names (no decimals/whitespace) stay on the fast path
                name = t.split(":", 1)[0]
                if any(ch.isdecimal() or ch.isspace() for ch in name):
                    return None
            toks.append(t.encode("utf-8"))
    n = len(toks)
    cbuf, offsets = _pack_bytes(toks)
    out_idx = np.empty(n, dtype=np.int64)
    out_val = np.empty(n, dtype=np.float32)
    rc = lib.hm_parse_features_batch(
        ctypes.cast(cbuf, ctypes.c_void_p),
        offsets.ctypes.data_as(ctypes.c_void_p), n, num_features,
        out_idx.ctypes.data_as(ctypes.c_void_p),
        out_val.ctypes.data_as(ctypes.c_void_p))
    if rc != 0:
        return None
    bounds = np.zeros(len(rows) + 1, dtype=np.int64)
    np.cumsum(row_lens, out=bounds[1:])
    idx_rows = [out_idx[bounds[r]:bounds[r + 1]] for r in range(len(rows))]
    val_rows = [out_val[bounds[r]:bounds[r + 1]] for r in range(len(rows))]
    return idx_rows, val_rows


def arow_reference_rowloop(idx: np.ndarray, val: np.ndarray,
                           labels: np.ndarray, dims: int, r: float = 0.1,
                           state: Optional[dict] = None,
                           track_touched: bool = False) -> Optional[int]:
    """Run the reference's per-row AROW hot loop (C transliteration of
    AROWClassifierUDTF.java:99-150 + DenseModel.java:193-201 set
    bookkeeping) over [n_rows, width] gathered blocks. This is the MEASURED
    anchor for vs_baseline (VERDICT r3 missing #2): one sequential mapper's
    row loop with the JVM's parse/boxing costs excluded (flattering the
    reference). Mutates/allocates flat model arrays in `state` (reused
    across calls when passed); returns margin-violation count, or None
    without the library.

    `track_touched`: maintain a monotone uint8 `state["touch"]` was-ever-
    set flag per feature — the -native_scan backend's model-emission mask
    (clocks/deltas wrap like the reference's short/byte counters and can
    NOT serve as touched). Anchor measurements leave it off so the timed
    loop stays the pure reference transliteration."""
    lib = _load()
    if lib is None or not hasattr(lib, "hm_arow_reference_rowloop"):
        return None
    n_rows, width = idx.shape
    if state is None:
        state = {}
    if "w" not in state:
        state["w"] = np.zeros(dims, np.float32)
        state["cov"] = np.ones(dims, np.float32)
        state["clocks"] = np.zeros(dims, np.int16)
        state["deltas"] = np.zeros(dims, np.int8)
    if track_touched and "touch" not in state:
        state["touch"] = np.zeros(dims, np.uint8)
    idx = np.ascontiguousarray(idx, np.int32)
    val = np.ascontiguousarray(val, np.float32)
    labels = np.ascontiguousarray(labels, np.float32)
    as_p = lambda a: a.ctypes.data_as(ctypes.c_void_p)  # noqa: E731
    return int(lib.hm_arow_reference_rowloop(
        as_p(idx), as_p(val), as_p(labels), n_rows, width,
        ctypes.c_float(r), as_p(state["w"]), as_p(state["cov"]),
        as_p(state["clocks"]), as_p(state["deltas"]),
        as_p(state["touch"]) if track_touched else None))


def fm_reference_rowloop(idx: np.ndarray, val: np.ndarray,
                         labels: np.ndarray, dims: int, k: int = 5,
                         eta: float = 0.05, lam: float = 0.01,
                         state: Optional[dict] = None,
                         track_touched: bool = False) -> Optional[int]:
    """Run the reference's per-row train_fm (classification) hot loop (C
    transliteration of FactorizationMachineUDTF.java:369-393 trainTheta;
    fixed eta, defaults eta0=0.05 lambda=0.01 per FMHyperParameters.java:
    30-70) — the measured train_fm anchor, and (with `track_touched`) the
    -native_scan FM backend body. Returns sign-error count, or None
    without the library."""
    lib = _load()
    if lib is None or not hasattr(lib, "hm_fm_reference_rowloop"):
        return None
    n_rows, width = idx.shape
    if state is None:
        state = {}
    if "w" not in state:
        rng = np.random.RandomState(42)
        state["w0"] = np.zeros(1, np.float32)
        state["w"] = np.zeros(dims, np.float32)
        # sigma=0.1 gaussian rankinit like the reference default
        state["V"] = (0.1 * rng.randn(dims, k)).astype(np.float32)
    if track_touched and "touch" not in state:
        state["touch"] = np.zeros(dims, np.uint8)
    idx = np.ascontiguousarray(idx, np.int32)
    val = np.ascontiguousarray(val, np.float32)
    labels = np.ascontiguousarray(labels, np.float32)
    as_p = lambda a: a.ctypes.data_as(ctypes.c_void_p)  # noqa: E731
    rc = int(lib.hm_fm_reference_rowloop(
        as_p(idx), as_p(val), as_p(labels), n_rows, width, k,
        ctypes.c_float(eta), ctypes.c_float(lam),
        as_p(state["w0"]), as_p(state["w"]), as_p(state["V"]),
        as_p(state["touch"]) if track_touched else None))
    if rc < 0:
        raise ValueError("fm reference rowloop: k > 64 unsupported")
    return rc


def lattice_tokenize_bulk(cps: np.ndarray, classes: np.ndarray,
                          text_offsets: np.ndarray,
                          surf_buf: np.ndarray, surf_offsets: np.ndarray,
                          entry_offsets: np.ndarray, entry_pos: np.ndarray,
                          entry_cost: np.ndarray, max_word: int,
                          conn: np.ndarray,
                          unk_base: np.ndarray, unk_per: np.ndarray,
                          unk_pos: np.ndarray):
    """Bulk lattice Viterbi (hm_lattice_tokenize_bulk); all marshalling is
    done by the caller (nlp/lattice.py, which owns the lexicon encoding).
    Returns (starts, lens, pos_ids, counts) or None when unavailable."""
    lib = _load()
    if lib is None or not hasattr(lib, "hm_lattice_tokenize_bulk"):
        return None
    # pin every caller-marshalled buffer to the ABI dtype + C order: the
    # native pass reads these at fixed widths, so a strided or
    # wrong-width array here is silent corruption, not an exception
    cps = np.ascontiguousarray(cps, np.uint32)
    classes = np.ascontiguousarray(classes, np.uint8)
    text_offsets = np.ascontiguousarray(text_offsets, np.int64)
    surf_buf = np.ascontiguousarray(surf_buf, np.uint32)
    surf_offsets = np.ascontiguousarray(surf_offsets, np.int64)
    entry_offsets = np.ascontiguousarray(entry_offsets, np.int64)
    entry_pos = np.ascontiguousarray(entry_pos, np.int16)
    entry_cost = np.ascontiguousarray(entry_cost, np.int32)
    conn = np.ascontiguousarray(conn, np.int32)
    unk_base = np.ascontiguousarray(unk_base, np.int32)
    unk_per = np.ascontiguousarray(unk_per, np.int32)
    unk_pos = np.ascontiguousarray(unk_pos, np.int16)
    n_texts = len(text_offsets) - 1
    total_chars = int(text_offsets[-1])
    out_start = np.empty(max(total_chars, 1), np.int32)
    out_len = np.empty(max(total_chars, 1), np.int32)
    out_pos = np.empty(max(total_chars, 1), np.int16)
    out_counts = np.empty(max(n_texts, 1), np.int64)
    as_p = lambda a: a.ctypes.data_as(ctypes.c_void_p)  # noqa: E731
    rc = lib.hm_lattice_tokenize_bulk(
        as_p(cps), as_p(classes), as_p(text_offsets), n_texts,
        as_p(surf_buf), as_p(surf_offsets), as_p(entry_offsets),
        as_p(entry_pos), as_p(entry_cost), len(surf_offsets) - 1,
        int(max_word), as_p(conn), conn.shape[0],
        as_p(unk_base), as_p(unk_per), as_p(unk_pos),
        as_p(out_start), as_p(out_len), as_p(out_pos), as_p(out_counts))
    if rc < 0:
        return None
    return out_start[:rc], out_len[:rc], out_pos[:rc], out_counts
