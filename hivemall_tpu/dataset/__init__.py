from .lr_datagen import lr_datagen  # noqa: F401
