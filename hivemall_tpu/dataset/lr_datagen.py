"""`lr_datagen` — synthetic logistic-regression data generator
(ref: dataset/LogisticRegressionDataGeneratorUDTF.java:47-180).

Options mirror the reference: -n_examples/-n_features/-n_dims(200)/-eps/
-prob_one/-seed/-dense/-sort/-cl (classification labels)."""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..utils.options import Options


def _options() -> Options:
    o = Options()
    o.add("n_examples", None, True, "Number of examples [default: 1000]",
          default=1000, type=int)
    o.add("n_features", None, True, "Number of non-zero features per example "
          "[default: 10]", default=10, type=int)
    o.add("n_dims", None, True, "Feature dimension [default: 200]", default=200,
          type=int)
    o.add("eps", None, True, "Label noise epsilon [default: 3.0]", default=3.0,
          type=float)
    o.add("prob_one", "prob_y_1", True, "P(y=1) [default: 0.6]", default=0.6,
          type=float)
    o.add("seed", None, True, "Random seed [default: 43]", default=43, type=int)
    o.add("dense", None, False, "Emit dense feature vectors")
    o.add("sort", None, False, "Sort feature indices in each row")
    o.add("cl", "classification", False, "Emit 0/1 labels instead of probabilities")
    return o


def lr_datagen(options: Optional[str] = None):
    """Returns (features_rows, labels): rows of "idx:value" strings (sparse,
    default) or dense float arrays (-dense)."""
    cl = _options().parse(options, "lr_datagen")
    n = cl.get_int("n_examples", 1000)
    nf = cl.get_int("n_features", 10)
    nd = cl.get_int("n_dims", 200)
    eps = cl.get_float("eps", 3.0)
    prob_one = cl.get_float("prob_one", 0.6)
    rng = np.random.RandomState(cl.get_int("seed", 43))
    dense = cl.has("dense")
    classification = cl.has("cl")

    rows: List = []
    labels = np.empty(n, dtype=np.float32)
    for i in range(n):
        label = prob_one if not classification else float(rng.rand() < prob_one)
        y = label if not classification else label
        labels[i] = y
        sign = 1.0 if (rng.rand() < prob_one) else -1.0
        if classification:
            labels[i] = 1.0 if sign > 0 else 0.0
        else:
            labels[i] = float(rng.rand())
        idx = rng.choice(nd, size=min(nf, nd), replace=False)
        if cl.has("sort"):
            idx = np.sort(idx)
        # feature value correlated with the label plus gaussian noise, the
        # reference's recipe: x ~ N(mu(label), 1) * eps scaling
        mu = 1.0 if labels[i] > 0.5 else -1.0
        vals = (rng.randn(len(idx)) + mu * eps / 3.0).astype(np.float32)
        if dense:
            row = np.zeros(nd, dtype=np.float32)
            row[idx] = vals
            rows.append(row)
        else:
            rows.append([f"{int(j)}:{float(v)}" for j, v in zip(idx, vals)])
    return rows, labels
