"""`lr_datagen` — synthetic logistic-regression data generator
(ref: dataset/LogisticRegressionDataGeneratorUDTF.java:47-180).

Options mirror the reference: -n_examples/-n_features/-n_dims(200)/-eps/
-prob_one/-seed/-dense/-sort/-cl (classification labels).

`DriftStream` extends the generator into an unbounded event stream with
seeded CONCEPT DRIFT — the workload the continuous-training pipeline
(hivemall_tpu/pipeline/, docs/continuous_training.md) trains against. The
true weight vector rotates piecewise: it is constant within a phase of
``drift_every`` events and rotates by ``drift_angle`` radians at each phase
boundary, inside a 2-plane spanned by two seeded orthonormal directions —
so the concept at any event index is a pure function of ``(seed, index)``
and the whole stream is replayable from any offset (checkpoint resume and
bench rounds see byte-identical data)."""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..utils.options import Options


def _options() -> Options:
    o = Options()
    o.add("n_examples", None, True, "Number of examples [default: 1000]",
          default=1000, type=int)
    o.add("n_features", None, True, "Number of non-zero features per example "
          "[default: 10]", default=10, type=int)
    o.add("n_dims", None, True, "Feature dimension [default: 200]", default=200,
          type=int)
    o.add("eps", None, True, "Label noise epsilon [default: 3.0]", default=3.0,
          type=float)
    o.add("prob_one", "prob_y_1", True, "P(y=1) [default: 0.6]", default=0.6,
          type=float)
    o.add("seed", None, True, "Random seed [default: 43]", default=43, type=int)
    o.add("dense", None, False, "Emit dense feature vectors")
    o.add("sort", None, False, "Sort feature indices in each row")
    o.add("cl", "classification", False, "Emit 0/1 labels instead of probabilities")
    return o


def lr_datagen(options: Optional[str] = None):
    """Returns (features_rows, labels): rows of "idx:value" strings (sparse,
    default) or dense float arrays (-dense)."""
    cl = _options().parse(options, "lr_datagen")
    n = cl.get_int("n_examples", 1000)
    nf = cl.get_int("n_features", 10)
    nd = cl.get_int("n_dims", 200)
    eps = cl.get_float("eps", 3.0)
    prob_one = cl.get_float("prob_one", 0.6)
    rng = np.random.RandomState(cl.get_int("seed", 43))
    dense = cl.has("dense")
    classification = cl.has("cl")

    rows: List = []
    labels = np.empty(n, dtype=np.float32)
    for i in range(n):
        label = prob_one if not classification else float(rng.rand() < prob_one)
        y = label if not classification else label
        labels[i] = y
        sign = 1.0 if (rng.rand() < prob_one) else -1.0
        if classification:
            labels[i] = 1.0 if sign > 0 else 0.0
        else:
            labels[i] = float(rng.rand())
        idx = rng.choice(nd, size=min(nf, nd), replace=False)
        if cl.has("sort"):
            idx = np.sort(idx)
        # feature value correlated with the label plus gaussian noise, the
        # reference's recipe: x ~ N(mu(label), 1) * eps scaling
        mu = 1.0 if labels[i] > 0.5 else -1.0
        vals = (rng.randn(len(idx)) + mu * eps / 3.0).astype(np.float32)
        if dense:
            row = np.zeros(nd, dtype=np.float32)
            row[idx] = vals
            rows.append(row)
        else:
            rows.append([f"{int(j)}:{float(v)}" for j, v in zip(idx, vals)])
    return rows, labels


class DriftStream:
    """Seeded concept-drift event stream: piecewise-rotating true weights.

    ``block(i)`` returns training batch ``i`` as fixed-shape arrays —
    ``(indices [B,K] int32, values [B,K] float32, labels [B] float32 in
    {-1,+1})`` — generated as a pure function of ``(seed, i)``: replaying
    any block after a crash/resume yields identical bytes. Labels follow
    the CURRENT phase's true weight vector (``w_true(phase_of(event))``)
    plus gaussian noise, so a model trained on old phases measurably
    degrades on new ones — the drift the eval gate exists to track.

    ``label_flip_events=(a, b)`` poisons the stream: TRAINING labels of
    events with index in [a, b) come back sign-flipped (``clean_block``
    returns the unflipped truth). This is the deterministic regression
    injector the pipeline bench uses to prove the gate refuses to publish
    a model trained on a bad-data window.

    ``holdout(at_event, n, seed)`` draws fresh rows labeled by the phase
    concept at ``at_event`` — the bench's served-model-quality probe
    (the pipeline's own gate uses a reservoir over OBSERVED events
    instead; pipeline/holdout.py).
    """

    def __init__(self, dims: int, batch: int = 64, width: int = 8, *,
                 seed: int = 42, drift_every: int = 2048,
                 drift_angle: float = 0.35, noise: float = 0.25,
                 label_flip_events: Optional[Tuple[int, int]] = None):
        if dims < 2:
            raise ValueError(f"dims must be >= 2, got {dims}")
        self.dims = int(dims)
        self.batch = int(batch)
        self.width = int(width)
        self.seed = int(seed)
        self.drift_every = int(drift_every)
        self.drift_angle = float(drift_angle)
        self.noise = float(noise)
        self.label_flip_events = label_flip_events
        # two seeded orthonormal directions span the rotation 2-plane; the
        # phase-p concept is u*cos(p*angle) + v*sin(p*angle) — a pure
        # function of p, no cumulative state to drift numerically
        rng = np.random.RandomState(self.seed)
        u = rng.randn(self.dims).astype(np.float32)
        u /= np.linalg.norm(u)
        v = rng.randn(self.dims).astype(np.float32)
        v -= u * np.dot(u, v)
        v /= np.linalg.norm(v)
        self._u, self._v = u, v
        # scale matches bench_chaos's make_stream: unit-normal-ish entries
        self._scale = np.float32(np.sqrt(self.dims))

    def phase_of(self, event_index: int) -> int:
        return int(event_index) // self.drift_every

    def w_true(self, phase: int) -> np.ndarray:
        """The phase-``phase`` concept vector (float32 [dims])."""
        th = np.float32(phase * self.drift_angle)
        return (self._u * np.cos(th) + self._v * np.sin(th)) * self._scale

    def _raw_block(self, i: int):
        b, k = self.batch, self.width
        r = np.random.RandomState((self.seed * 100_003 + i) % (2**31))
        idx = r.randint(0, self.dims, size=(b, k)).astype(np.int32)
        val = r.rand(b, k).astype(np.float32)
        # label each EVENT by the phase it falls in (a block straddling a
        # phase boundary carries both concepts, like real traffic would)
        ev = np.arange(i * b, (i + 1) * b)
        phases = ev // self.drift_every
        margins = np.empty(b, dtype=np.float32)
        for p in np.unique(phases):
            rows = phases == p
            w = self.w_true(int(p))
            margins[rows] = np.sum(w[idx[rows]] * val[rows], axis=-1)
        # label noise RELATIVE to the margin's own scale (std of a width-K
        # dot of unit-variance weights with U(0,1) values is sqrt(K/3)):
        # noise=0.25 keeps the Bayes decision clearly learnable
        margins += (self.noise * np.float32(np.sqrt(self.width / 3.0))
                    * r.randn(b).astype(np.float32))
        lab = np.where(margins > 0, 1.0, -1.0).astype(np.float32)
        return idx, val, lab, ev

    def clean_block(self, i: int):
        """Block ``i`` with TRUE labels (no poison window applied)."""
        idx, val, lab, _ = self._raw_block(i)
        return idx, val, lab

    def block(self, i: int):
        """Block ``i`` as observed: poison-window training labels flipped."""
        idx, val, lab, ev = self._raw_block(i)
        if self.label_flip_events is not None:
            a, b = self.label_flip_events
            lab = np.where((ev >= a) & (ev < b), -lab, lab)
        return idx, val, lab

    def holdout(self, at_event: int, n: int = 2048, seed: int = 999):
        """Fresh labeled rows from the concept at ``at_event``, clean
        labels, pre-parsed per-row form ``(idx_rows, val_rows, labels)``
        — directly scoreable by serving engines. The draw is seeded by
        ``(seed, at_event)``, so repeated probes across a run sample
        different rows while any single (seed, at_event) pair replays
        exactly."""
        r = np.random.RandomState((seed * 1_000_003 + at_event * 7
                                   + self.phase_of(at_event)) % (2**31))
        idx = r.randint(0, self.dims, size=(n, self.width)).astype(np.int64)
        val = r.rand(n, self.width).astype(np.float32)
        w = self.w_true(self.phase_of(at_event))
        lab = np.where(np.sum(w[idx] * val, axis=-1) > 0,
                       1.0, -1.0).astype(np.float32)
        return list(idx), list(val), lab
