"""Hive `TRANSFORM ... USING` streaming bridge — a JVM-free execution path a
real Hive cluster can drive today.

Hive's streaming contract (the same one `scoreKDD.py`-style scripts use):
the query planner pipes each map task's rows to the child process as
TSV — columns joined by ``\\t``, rows by ``\\n``, ``\\N`` for NULL, array
elements joined by ``\\x02`` (Hive's default collection-items terminator) —
and parses the child's stdout with the same framing. That makes every
registry trainer reachable from HiveQL without a JVM UDF (ref: the UDTF
surface `hivemall/UDTFWithOptions.java:48` + `define-all.hive:27-28`; this
bridge replaces the UDTF *transport*, not the trainer semantics):

    ADD FILE hivemall-tpu;                    -- bin/hivemall-tpu shim
    SELECT TRANSFORM (features, label)
        USING 'hivemall-tpu train_arow -dims 16777216'
        AS (feature INT, weight FLOAT, covar FLOAT)
    FROM training;

Each map task trains one replica over its split and emits model rows at
close — exactly the reference's mapper-side UDTF life cycle
(BinaryOnlineClassifierUDTF.java:249-298); the usual ensemble UDAF / GROUP
BY `avg(weight)` / argmin_kld reduce step merges replicas, unchanged.

Subcommands (one per trainer family, mirroring adapters/sqlite.py's
materializations):

- every linear binary classifier / regressor  -> ``feature weight [covar]``
- multiclass trainers                          -> ``label feature weight [covar]``
- ``train_fm``        -> ``feature wi vif_json`` (w0 on feature -1, NULL vif)
- ``train_randomforest_*`` -> ``model_id model_type pred_model
  var_importance oob_errors oob_tests`` (dense ``\\x02``-joined features in)
- MF family (3 input columns)                  -> ``idx pu qi bu bi mu``
- ``predict_linear -loadmodel <file> [-sigmoid]``  (rowid, features) ->
  (rowid, score); the model file is the trainer's own TSV output shipped via
  ``ADD FILE`` — the `-loadmodel` distributed-cache path
  (LearnerBaseUDTF.java:215-333) without a JVM
- ``predict_fm -loadmodel <file>``                 (rowid, features) ->
  (rowid, score) over a train_fm TSV model
- ``predict_ffm -loadmodel <blob-file>``           (rowid, ffm features)
  -> (rowid, score) over a compressed TrainedFFMModel blob (full
  pairwise scoring, V included)
- ``predict_multiclass -loadmodel <file>``         (rowid, features) ->
  (rowid, best_label, best_score) over a multiclass TSV model (the
  per-label SUM + max_label plan)
- ``predict_forest -loadmodel <file> [-regression]`` (rowid, dense
  features) -> (rowid, vote) over a forest TSV model (tree_predict +
  rf_ensemble)
- ``predict_gbt -loadmodel <file>``                (rowid, dense
  features) -> (rowid, label, score) over a GBT TSV model
  (intercept + shrinkage * summed rounds; binary sign / multiclass
  argmax)

Run as ``hivemall-tpu <subcommand> ...`` (bin/ shim) or
``python -m hivemall_tpu.adapters.hive_transform <subcommand> ...``.
"""

from __future__ import annotations

import json
import sys
from typing import IO, List, Optional, Sequence

HIVE_NULL = r"\N"
ITEM_SEP = "\x02"  # Hive's default collection-items terminator


# ------------------------------------------------------------------ framing

def _fmt(v) -> str:
    if v is None:
        return HIVE_NULL
    if isinstance(v, float):
        return repr(v)
    return str(v)


def _emit(out: IO[str], *cols) -> None:
    out.write("\t".join(_fmt(c) for c in cols))
    out.write("\n")


def _cells(line: str) -> List[Optional[str]]:
    line = line.rstrip("\n")
    return [None if c == HIVE_NULL else c for c in line.split("\t")]


def _feature_list(cell: str) -> List[str]:
    """A Hive array<string> arrives \\x02-joined; a plain string feature
    column is space- (or comma-) joined — accept all three."""
    if ITEM_SEP in cell:
        return [t for t in cell.split(ITEM_SEP) if t]
    if "," in cell and " " not in cell.strip():
        return [t for t in cell.split(",") if t]
    return cell.split()


def _dense_list(cell: str) -> List[float]:
    return [float(t) for t in _feature_list(cell)]


# ------------------------------------------------------------------ training

_MF_TRAINERS = frozenset(
    ("train_mf_sgd", "train_mf_adagrad", "train_bprmf"))


def _run_trainer(trainer: str, options: Optional[str], src: IO[str],
                 out: IO[str]) -> int:
    from ..sql.registry import get_function

    fn = get_function(trainer)
    is_forest = trainer.startswith(("train_randomforest",
                                    "train_gradient_tree"))
    if trainer in _MF_TRAINERS:
        return _run_mf_trainer(trainer, fn, options, src, out)

    feats: list = []
    labels: list = []
    for line in src:
        if not line.strip():
            continue
        cols = _cells(line)
        if len(cols) < 2 or cols[0] is None or cols[-1] is None:
            continue  # NULL feature/label rows are skipped, like the UDTF
        feats.append(_dense_list(cols[0]) if is_forest
                     else _feature_list(cols[0]))
        # multiclass labels stay strings; everything else is numeric
        labels.append(cols[-1] if trainer.startswith("train_multiclass")
                      else float(cols[-1]))

    model = fn(feats, labels, options) if options is not None \
        else fn(feats, labels)
    _emit_model_rows(trainer, model, out)
    return 0


def _run_mf_trainer(trainer: str, fn, options: Optional[str], src: IO[str],
                    out: IO[str]) -> int:
    """3-column input (user, item, rating) — or (user, pos_item, neg_item)
    for train_bprmf; emission mirrors adapters/sqlite.train_mf's one-table
    shape (ref: OnlineMatrixFactorizationUDTF close)."""
    users: List[int] = []
    items: List[int] = []
    third: List[float] = []
    for line in src:
        if not line.strip():
            continue
        cols = _cells(line)
        if len(cols) < 3 or None in cols[:3]:
            continue
        users.append(int(cols[0]))
        items.append(int(cols[1]))
        third.append(float(cols[2]))
    if trainer == "train_bprmf":
        model = fn(users, items, [int(t) for t in third], options) \
            if options is not None else fn(users, items,
                                           [int(t) for t in third])
    else:
        model = fn(users, items, third, options) if options is not None \
            else fn(users, items, third)

    rows = model.model_rows()
    tu, P, Bu = rows["users"]
    ti, Q, Bi = rows["items"]
    mu = rows["mu"]
    for u, pv, b in zip(tu, P, Bu):
        _emit(out, int(u), json.dumps([float(x) for x in pv]), None,
              float(b), None, mu)
    for i, qv, b in zip(ti, Q, Bi):
        _emit(out, int(i), None, json.dumps([float(x) for x in qv]),
              None, float(b), mu)
    return 0


def _emit_model_rows(trainer: str, model, out: IO[str]) -> None:
    """TSV rendering of the shared typed row iteration (adapters/
    model_rows.iter_model_rows — the ONE copy of the family dispatch).
    List-valued cells (FM Vif, importances, opcode programs) render as
    JSON text, everything else through _fmt (None -> \\N)."""
    from .model_rows import iter_model_rows

    # iter_model_rows raises its own descriptive ValueError for models
    # without row emission; don't catch-and-relabel (it would mask data
    # errors from the eager family branches as "no row emission")
    _, rows = iter_model_rows(model)
    for row in rows:
        _emit(out, *(json.dumps(c) if isinstance(c, list) else c
                     for c in row))


# ---------------------------------------------------------------- predicting

def _parse_predict_args(argv: Sequence[str], flags: Sequence[str] = ()):
    """Tiny arg scan: -loadmodel <file> plus boolean flags."""
    model_path = None
    seen = set()
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-loadmodel", "--loadmodel"):
            i += 1
            if i >= len(argv):
                raise SystemExit("-loadmodel needs a file argument")
            model_path = argv[i]
        elif a.lstrip("-") in flags:
            seen.add(a.lstrip("-"))
        else:
            raise SystemExit(f"unknown predict option: {a}")
        i += 1
    if model_path is None:
        raise SystemExit("predict requires -loadmodel <model.tsv> "
                         "(ship it with ADD FILE)")
    return model_path, seen


def _run_predict_linear(argv: Sequence[str], src: IO[str],
                        out: IO[str]) -> int:
    # overflow-safe sigmoid (math.exp raises OverflowError past ~|710|,
    # which real CTR scores can reach; the library sigmoid is np-based)
    from ..tools import sigmoid

    model_path, flags = _parse_predict_args(argv, flags=("sigmoid",))
    weights = {}
    with open(model_path) as f:
        for line in f:
            if not line.strip():
                continue
            cols = _cells(line)
            if cols[1] is None:
                continue  # e.g. train_ffm's feature -2 blob row (NULL wi)
            weights[int(cols[0])] = float(cols[1])  # covar column ignored

    from ..utils.feature import parse_feature

    use_sigmoid = "sigmoid" in flags
    for line in src:
        if not line.strip():
            continue
        cols = _cells(line)
        if len(cols) < 2 or cols[1] is None:
            continue
        score = 0.0
        for tok in _feature_list(cols[1]):
            name, value = parse_feature(tok)
            try:
                k = int(name)
            except ValueError:
                print(f"predict_linear: string feature {name!r} — hash "
                      "features before training/scoring", file=sys.stderr)
                return 2
            score += weights.get(k, 0.0) * value
        if use_sigmoid:
            score = float(sigmoid(score))
        _emit(out, cols[0], score)
    return 0


def _run_predict_fm(argv: Sequence[str], src: IO[str], out: IO[str]) -> int:
    model_path, _ = _parse_predict_args(argv)
    w = {}
    V = {}
    w0 = 0.0
    with open(model_path) as f:
        for line in f:
            if not line.strip():
                continue
            cols = _cells(line)
            fid = int(cols[0])
            if cols[1] is None:
                continue  # e.g. train_ffm's feature -2 blob row (NULL wi)
            if fid == -1:
                w0 = float(cols[1])
                continue
            w[fid] = float(cols[1])
            if len(cols) > 2 and cols[2] is not None:
                V[fid] = json.loads(cols[2])

    from ..utils.feature import parse_feature

    k = len(next(iter(V.values()))) if V else 0
    for line in src:
        if not line.strip():
            continue
        cols = _cells(line)
        if len(cols) < 2 or cols[1] is None:
            continue
        try:
            fv = [(int(n), x) for n, x in
                  (parse_feature(t) for t in _feature_list(cols[1]))]
        except ValueError:
            print("predict_fm: string feature name — hash features before "
                  "training/scoring", file=sys.stderr)
            return 2
        p = w0
        for name, x in fv:
            p += w.get(name, 0.0) * x
        for f in range(k):
            s = s2 = 0.0
            for name, x in fv:
                vf = V.get(name)
                if vf is None:
                    continue
                vx = vf[f] * x
                s += vx
                s2 += vx * vx
            p += 0.5 * (s * s - s2)
        _emit(out, cols[0], p)
    return 0


def _run_predict_ffm(argv: Sequence[str], src: IO[str], out: IO[str]) -> int:
    """(rowid, "field:idx:value" features) -> (rowid, score) over a
    compressed FFM blob file (TrainedFFMModel.to_blob, the
    FFMPredictionModel shipping shape) — full pairwise scoring, V
    included. Ship the blob with ADD FILE like any model artifact."""
    model_path, _ = _parse_predict_args(argv)
    from ..models.ffm import TrainedFFMModel

    with open(model_path, "rb") as f:
        raw = f.read()
    if not raw.startswith(b"HFM1"):
        # a train_ffm TSV emission (or just its blob row): pull the base91
        # text from the feature -2 row
        from ..tools import unbase91

        blob_text = None
        for line in raw.decode("utf-8", errors="replace").splitlines():
            c = _cells(line)
            if c and c[0] == "-2" and len(c) >= 3 and c[2] is not None:
                blob_text = c[2]
        if blob_text is None:
            print("predict_ffm: file is neither a raw blob nor a "
                  "train_ffm TSV emission with a feature -2 blob row",
                  file=sys.stderr)
            return 2
        raw = unbase91(blob_text)
    model = TrainedFFMModel.from_blob(raw)
    ids: List[str] = []
    rows: List[List[str]] = []
    for line in src:
        if not line.strip():
            continue
        cols = _cells(line)
        if len(cols) < 2 or cols[1] is None:
            continue
        ids.append(cols[0])
        rows.append(_feature_list(cols[1]))
    if not ids:
        return 0
    for rid, s in zip(ids, model.predict(rows)):
        _emit(out, rid, float(s))
    return 0


def _run_predict_multiclass(argv: Sequence[str], src: IO[str],
                            out: IO[str]) -> int:
    """(rowid, features) -> (rowid, best_label, best_score) over a
    multiclass model TSV (label, feature, weight[, covar]) — the per-label
    SUM + max_label SQL plan, framework-side."""
    model_path, _ = _parse_predict_args(argv)
    weights: dict = {}
    with open(model_path) as f:
        for line in f:
            if not line.strip():
                continue
            cols = _cells(line)
            weights.setdefault(cols[0], {})[int(cols[1])] = float(cols[2])
    if not weights:
        print("predict_multiclass: empty model file", file=sys.stderr)
        return 2

    from ..utils.feature import parse_feature

    for line in src:
        if not line.strip():
            continue
        cols = _cells(line)
        if len(cols) < 2 or cols[1] is None:
            continue
        try:
            fv = [(int(n), x) for n, x in
                  (parse_feature(t) for t in _feature_list(cols[1]))]
        except ValueError:
            print("predict_multiclass: string feature name — hash features "
                  "before training/scoring", file=sys.stderr)
            return 2
        best_label, best_score = None, None
        for label, w in weights.items():
            s = sum(w.get(k, 0.0) * x for k, x in fv)
            if best_score is None or s > best_score:
                best_label, best_score = label, s
        _emit(out, cols[0], best_label, best_score)
    return 0


def _run_predict_forest(argv: Sequence[str], src: IO[str],
                        out: IO[str]) -> int:
    """(rowid, dense features) -> (rowid, vote) over a forest model TSV
    (the 6-column train_randomforest_* emission) — tree_predict +
    rf_ensemble, framework-side (classification by default; pass
    -regression for mean leaf values)."""
    model_path, flags = _parse_predict_args(argv, flags=("regression",))
    model_rows = []
    with open(model_path) as f:
        for line in f:
            if not line.strip():
                continue
            c = _cells(line)
            model_rows.append((int(c[0]), c[1], c[2], c[3], c[4], c[5]))
    if not model_rows:
        print("predict_forest: empty model file", file=sys.stderr)
        return 2

    from ..parallel.forest_shard import ensemble_predict_rows

    ids: List[str] = []
    X: List[List[float]] = []
    for line in src:
        if not line.strip():
            continue
        cols = _cells(line)
        if len(cols) < 2 or cols[1] is None:
            continue
        ids.append(cols[0])
        X.append(_dense_list(cols[1]))
    if not ids:
        return 0
    preds = ensemble_predict_rows(model_rows, X,
                                  classification="regression" not in flags)
    for rid, p in zip(ids, preds):
        _emit(out, rid, float(p) if "regression" in flags else int(p))
    return 0


def _run_predict_gbt(argv: Sequence[str], src: IO[str], out: IO[str]) -> int:
    """(rowid, dense features) -> (rowid, label, score) over a GBT model
    TSV (the per-(round, class) train_gradient_tree_boosting_classifier
    emission): score_cls = intercept + shrinkage * sum over rounds of the
    class tree's leaf; binary label = score>0, multiclass = argmax."""
    model_path, _ = _parse_predict_args(argv)
    from ..models.trees.predict import compile_tree

    per_cls: dict = {}
    vocab = None
    with open(model_path) as f:
        for line in f:
            if not line.strip():
                continue
            c = _cells(line)
            cls = int(c[1])
            entry = per_cls.setdefault(
                cls, {"intercept": float(c[4]), "shrinkage": float(c[5]),
                      "trees": []})
            entry["trees"].append(compile_tree(c[2], c[3]))
            if vocab is None and len(c) > 8 and c[8] is not None:
                vocab = json.loads(c[8])
    if not per_cls:
        print("predict_gbt: empty model file", file=sys.stderr)
        return 2
    classes = sorted(per_cls)

    def to_label(index: int):
        # the emission's classes column maps score indices back to the
        # trained labels (arbitrary here; the reference requires 0..K-1
        # so its emission needs no vocabulary)
        return vocab[index] if vocab is not None else index

    for line in src:
        if not line.strip():
            continue
        cols = _cells(line)
        if len(cols) < 2 or cols[1] is None:
            continue
        x = _dense_list(cols[1])
        scores = {}
        for cls in classes:
            e = per_cls[cls]
            scores[cls] = e["intercept"] + e["shrinkage"] * sum(
                t(x) for t in e["trees"])
        if len(classes) == 1:  # binary: one tree stack, sign decides
            label = to_label(int(scores[classes[0]] > 0))
            _emit(out, cols[0], label, scores[classes[0]])
        else:
            best = max(classes, key=lambda cl: scores[cl])
            _emit(out, cols[0], to_label(best), scores[best])
    return 0


# ----------------------------------------------------------------------- CLI

def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help", "-help"):
        print(__doc__)
        return 0
    cmd, rest = argv[0], argv[1:]
    src, out = sys.stdin, sys.stdout
    if cmd == "predict_linear":
        return _run_predict_linear(rest, src, out)
    if cmd == "predict_fm":
        return _run_predict_fm(rest, src, out)
    if cmd == "predict_multiclass":
        return _run_predict_multiclass(rest, src, out)
    if cmd == "predict_forest":
        return _run_predict_forest(rest, src, out)
    if cmd == "predict_ffm":
        return _run_predict_ffm(rest, src, out)
    if cmd == "predict_gbt":
        return _run_predict_gbt(rest, src, out)

    from ..sql.registry import REGISTRY

    is_trainer = cmd.startswith("train_") or cmd == "logress"
    if cmd not in REGISTRY or not is_trainer:
        print(f"unknown subcommand {cmd!r}; expected a train_* registry "
              "name or predict_{linear,fm,ffm,multiclass,forest,gbt}",
              file=sys.stderr)
        return 2
    options = " ".join(rest) if rest else None
    return _run_trainer(cmd, options, src, out)


if __name__ == "__main__":
    sys.exit(main())
