"""SQLite engine binding — the in-process SQL host-engine adapter (L6).

The reference's primary surface IS a SQL engine: users register ~120
functions into Hive (ref: resources/ddl/define-all.hive) and train/score
with queries. This module binds the same surface to SQLite, the SQL engine
available in every CPython build — so the reference's canonical workflows
run as actual SQL here, not through a DataFrame DSL:

- `connect(...)` / `register(conn)` — install the scalar function library
  (sigmoid, mhash, feature helpers, scaling, distances/similarities, macro
  functions) and the streaming aggregates (logloss, mae/mse/rmse, r2, auc,
  voted_avg, argmin_kld, max_label, ...) into a sqlite3 connection, the
  define-all.hive analog. Aggregates wrap the evaluation layer's
  iterate/merge/terminate partials (evaluation/metrics.py), exactly the
  UDAF lifecycle Hive runs (ref: evaluation/LogarithmicLossUDAF.java:28).
- `train(conn, "train_arow", src_query, options)` — run any registry
  trainer over the rows a query yields and materialize the model as a
  table `(feature, weight[, covar])`: the UDTF train-then-emit flow
  (ref: BinaryOnlineClassifierUDTF.close():249-298).
- `explode_features(conn, src_query, out)` — test features to
  `(rowid, feature, value)` rows, enabling the reference's pure-SQL
  inference plan — join model on feature, `sigmoid(SUM(weight*value))`
  group by rowid (SURVEY.md §3.5) — with no framework code in the loop.

Feature rows in SQL are TEXT: either space-joined "name:value" items or a
JSON array of them (engines without array types serialize exactly this
way; parse_features accepts both).
"""

from __future__ import annotations

import json
import re
import sqlite3
from typing import Callable, List, Optional

from ..ensemble import (argmin_kld, max_label, rf_ensemble, voted_avg,
                        weight_voted_avg)
from ..evaluation.metrics import AUC, F1Score, LogLossAggregator, MAE, MSE, R2, RMSE
from ..sql import get_function


_IDENT = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


def _check_ident(name: str) -> str:
    """Table names are interpolated into DDL/DML (sqlite has no placeholder
    for identifiers) — accept plain identifiers only so a malformed or
    hostile name fails loudly instead of becoming SQL."""
    if not _IDENT.match(name or ""):
        raise ValueError(f"not a plain SQL identifier: {name!r}")
    return name


def _parse_list(cast: Callable) -> Callable:
    def parse(text: Optional[str]) -> List:
        if text is None:
            return []
        s = text.strip()
        if not s:
            return []
        if s.startswith("["):
            return [cast(x) for x in json.loads(s)]
        return [cast(x) for x in s.split()]

    return parse


#: TEXT -> the list-of-"name:value" rows every trainer consumes
#: (JSON array string or whitespace-joined items)
parse_features = _parse_list(str)
#: TEXT -> a dense numeric feature vector (the reference's array<double>
#: forest input): JSON array or whitespace-joined floats
parse_dense = _parse_list(float)


def _wrap_features_in(fn: Callable) -> Callable:
    """Adapt fn(list_of_fv, *rest) to fn(TEXT, *rest)."""

    def g(text, *rest):
        return fn(parse_features(text), *rest)

    return g


def _wrap_features_out(fn: Callable) -> Callable:
    """Adapt a list-returning fn to return space-joined TEXT."""

    def g(*args):
        return " ".join(str(x) for x in fn(*args))

    return g


def _agg(partial_cls, arity: int):
    """sqlite aggregate class around an iterate/merge/terminate partial
    (the Hive GenericUDAF lifecycle, ref: NDCGUDAF.java:113-196)."""

    class A:
        def __init__(self):
            self.p = partial_cls()

        def step(self, *args):
            if any(a is None for a in args):
                return
            self.p.iterate(*args)

        def finalize(self):
            try:
                return float(self.p.terminate())
            except ZeroDivisionError:
                return None

    return A, arity


class _ListAgg:
    """Collect-then-apply aggregate for the ensemble one-shots."""

    fn: Callable = staticmethod(lambda xs: None)
    arity = 1

    def __init__(self):
        self.rows = []

    def step(self, *args):
        if any(a is None for a in args):
            return
        self.rows.append(args[0] if len(args) == 1 else tuple(args))

    def finalize(self):
        if not self.rows:
            return None
        return type(self).fn(self.rows)


def _list_agg(fn: Callable, arity: int):
    return type(f"_Agg_{fn.__name__}", (_ListAgg,),
                {"fn": staticmethod(fn), "arity": arity}), arity


def _rf_ensemble_json(votes) -> str:
    label, prob, post = rf_ensemble(votes)
    return json.dumps({"label": int(label), "probability": prob,
                       "probabilities": post})


class _FMPredict:
    """fm_predict(wi, vif_json, xi): grouped FM scoring over model-joined
    feature rows — ŷ = Σ wi·xi + ½ Σ_f [(Σ vif·xi)² − Σ vif²·xi²]; the
    bias row (feature -1: wi=w0, vif NULL, xi=1) contributes w0 through
    the linear term (ref: fm/FMPredictGenericUDAF.java — identical
    iterate/terminate algebra)."""

    def __init__(self):
        self.linear = 0.0
        self.s = None  # Σ vif·xi per factor
        self.q = None  # Σ vif²·xi² per factor

    def step(self, wi, vif, xi):
        if xi is None:
            return
        x = float(xi)
        if wi is not None:
            self.linear += float(wi) * x
        if vif is not None:
            v = json.loads(vif)
            if self.s is None:
                self.s = [0.0] * len(v)
                self.q = [0.0] * len(v)
            for f, vf in enumerate(v):
                self.s[f] += vf * x
                self.q[f] += vf * vf * x * x
        return

    def finalize(self):
        pair = 0.0
        if self.s is not None:
            pair = 0.5 * sum(sf * sf - qf for sf, qf in zip(self.s, self.q))
        return self.linear + pair


_SCALARS = {
    # (sql_name, arity, registry_name or callable, marshal)
    "sigmoid": (1, "sigmoid", None),
    "mhash": (1, "mhash", None),
    "idf": (2, "idf", None),
    "tfidf": (3, "tfidf", None),
    "max2": (2, "max2", None),
    "min2": (2, "min2", None),
    "rescale": (3, "rescale", None),
    "zscore": (3, "zscore", None),
    "extract_feature": (1, "extract_feature", None),
    "extract_weight": (1, "extract_weight", None),
    "feature": (2, lambda n, v: f"{n}:{v}", None),
    "add_bias": (1, "add_bias", "features_io"),
    "l2_normalize": (1, "l2_normalize", "features_io"),
    "sort_by_feature": (1, "sort_by_feature", "features_io"),
    "cosine_similarity": (2, "cosine_similarity", "features_2in"),
    "jaccard_similarity": (2, "jaccard_similarity", "features_2in"),
    "angular_similarity": (2, "angular_similarity", "features_2in"),
    "euclid_similarity": (2, "euclid_similarity", "features_2in"),
    "cosine_distance": (2, "cosine_distance", "features_2in"),
    "euclid_distance": (2, "euclid_distance", "features_2in"),
    "manhattan_distance": (2, "manhattan_distance", "features_2in"),
    "jaccard_distance": (2, "jaccard_distance", "features_2in"),
    "hamming_distance": (2, "hamming_distance", None),
    "popcnt": (1, "popcnt", None),
    "tokenize": (1, "tokenize", "text_to_features"),
    "tokenize_ja": (1, "tokenize_ja", "text_to_features"),
    # tree_predict(model_type, pred_model, features_dense_text
    #              [, classification]) — the reference's per-row tree
    # evaluator (ref: TreePredictUDF.java:143-166); features are dense
    # array<double> TEXT (JSON or space-joined); classification defaults
    # false like the reference (TreePredictUDF.java:104) — pass 1 for
    # classification forests (int labels)
    "tree_predict": ((3, 4), None, "tree_predict"),
    # mf_predict(Pu, Qi[, Bu, Bi, mu]) / bprmf_predict(Pu, Qi[, Bi]) over
    # factor vectors as TEXT (ref: MFPredictionUDF.java:33,
    # BPRMFPredictionUDF.java); NULL factors (idx never trained) score NULL,
    # like the reference's null-tolerant UDF
    "mf_predict": ((2, 3, 4, 5), "mf_predict", "mf_predict"),
    "bprmf_predict": ((2, 3), "bprmf_predict", "mf_predict"),
    # ffm_predict(model_blob, features_text) — decodes the compressed
    # one-row blob (cached per distinct blob) and scores the FULL pairwise
    # model, the reference's FFMPredictUDF flow (fm/FFMPredictUDF.java over
    # FFMPredictionModel.java:46-200)
    "ffm_predict": (2, None, "ffm_predict"),
}


def register(conn: sqlite3.Connection) -> sqlite3.Connection:
    """Install the function library into `conn` (the define-all.hive
    analog). Returns the connection for chaining."""
    for sql_name, (arity, target, marshal) in _SCALARS.items():
        if marshal == "tree_predict":
            from functools import lru_cache

            from ..models.trees.predict import compile_tree

            # one compile per distinct tree, not per (row x tree): the
            # predict flow CROSS JOINs every row against every model row
            cached_compile = lru_cache(maxsize=4096)(compile_tree)

            def fn(model_type, pred_model, features, classification=0,
                   _c=cached_compile):
                out = _c(model_type, pred_model)(parse_dense(features))
                return int(out) if classification else float(out)
        elif marshal == "ffm_predict":
            from functools import lru_cache

            from ..models.ffm import TrainedFFMModel

            # one decode per distinct blob, not per (row x call); bytes are
            # hashable so the blob itself is the cache key
            cached_from_blob = lru_cache(maxsize=8)(TrainedFFMModel.from_blob)

            def fn(blob, features, _c=cached_from_blob):
                if blob is None or features is None:
                    return None
                m = _c(bytes(blob))
                return float(m.predict([parse_features(features)])[0])
        elif marshal == "mf_predict":
            base_mf = get_function(target)

            def fn(pu, qi, *biases, _f=base_mf):
                if pu is None or qi is None:
                    return None
                return _f(parse_dense(pu), parse_dense(qi),
                          *(0.0 if b is None else float(b) for b in biases))
        else:
            fn = target if callable(target) else get_function(target)
            if marshal == "features_io":
                fn = _wrap_features_out(_wrap_features_in(fn))
            elif marshal == "features_2in":
                base = fn

                def fn(a, b, _f=base):  # noqa: E731 - bind per-iteration
                    return _f(parse_features(a), parse_features(b))
            elif marshal == "text_to_features":
                fn = _wrap_features_out(fn)
        # every registered scalar is pure -> deterministic=True lets SQLite
        # use them in expression indexes and factor repeated calls.
        # Multi-arity names register each fixed form (never narg=-1, which
        # would let a stray extra SQL argument bind a wrapper's internal
        # defaults)
        for n in (arity if isinstance(arity, tuple) else (arity,)):
            conn.create_function(sql_name, n, fn, deterministic=True)

    class _F1TokenLists(F1Score):
        """F1Score.iterate takes label LISTS per row; SQL hands TEXT — split
        whitespace-joined labels so set() is over tokens, not characters."""

        def iterate(self, actual, predicted):  # type: ignore[override]
            super().iterate(str(actual).split(), str(predicted).split())

    for name, (cls, arity) in {
        "logloss": _agg(LogLossAggregator, 2),
        "mae": _agg(MAE, 2),
        "mse": _agg(MSE, 2),
        "rmse": _agg(RMSE, 2),
        "r2": _agg(R2, 2),
        "auc": _agg(AUC, 2),
        "f1score": _agg(_F1TokenLists, 2),
        "voted_avg": _list_agg(voted_avg, 1),
        "weight_voted_avg": _list_agg(weight_voted_avg, 1),
        "max_label": _list_agg(max_label, 2),
        "argmin_kld": _list_agg(argmin_kld, 2),
        "fm_predict": (_FMPredict, 3),
        # rf_ensemble(vote) -> JSON {label, probability, probabilities} (the
        # reference returns a struct, ref: RandomForestEnsembleUDAF.java:34)
        "rf_ensemble": _list_agg(_rf_ensemble_json, 1),
    }.items():
        conn.create_aggregate(name, arity, cls)
    return conn


def connect(database: str = ":memory:", **kw) -> sqlite3.Connection:
    return register(sqlite3.connect(database, **kw))


def _materialize_linear(q, model, model_table: str) -> None:
    from ..core.state import model_rows

    out = model_rows(model.state)
    if len(out) == 3 and out[2] is not None:
        q.execute(f"CREATE TABLE {model_table} "
                  "(feature INTEGER PRIMARY KEY, weight REAL, covar REAL)")
        q.executemany(f"INSERT INTO {model_table} VALUES (?,?,?)",
                      zip(map(int, out[0]), map(float, out[1]),
                          map(float, out[2])))
    else:
        q.execute(f"CREATE TABLE {model_table} "
                  "(feature INTEGER PRIMARY KEY, weight REAL)")
        q.executemany(f"INSERT INTO {model_table} VALUES (?,?)",
                      zip(map(int, out[0]), map(float, out[1])))


def _materialize_fm(q, model, model_table: str) -> None:
    """(feature, wi, vif JSON) rows; feature -1 carries w0 with NULL vif.
    The reference emits w0 as feature "0" (forwardAsIntFeature,
    FactorizationMachineUDTF.java:446-519) because its int features are
    1-based; this feature space is 0-based (hashed ids land in [0, dims)),
    so the bias row lives at -1 where it can never alias a real feature."""
    w0, feats, w, v = model.model_rows()
    q.execute(f"CREATE TABLE {model_table} "
              "(feature INTEGER PRIMARY KEY, wi REAL, vif TEXT)")
    q.execute(f"INSERT INTO {model_table} VALUES (-1, ?, NULL)", (float(w0),))
    q.executemany(
        f"INSERT INTO {model_table} VALUES (?,?,?)",
        ((int(f), float(wi), json.dumps([float(x) for x in vi]))
         for f, wi, vi in zip(feats, w, v)))


def _materialize_ffm(q, model, model_table: str) -> None:
    """FFM materializes its LINEAR part as joinable `(feature, wi)` rows
    (+ w0 on feature -1) AND the complete model as a one-row compressed
    blob table `{model_table}_blob` — exactly the reference's shipping
    shape: an opaque Externalizable blob scored by a dedicated UDF
    (ref: FFMPredictionModel.java:46-200 + FFMPredictUDF). Score in SQL
    with `ffm_predict(blob, features)` — full pairwise parity with the
    framework's predict, V included."""
    feats, w, w0 = model.model_rows()
    q.execute(f"CREATE TABLE {model_table} "
              "(feature INTEGER PRIMARY KEY, wi REAL)")
    q.execute(f"INSERT INTO {model_table} VALUES (-1, ?)", (float(w0),))
    q.executemany(f"INSERT INTO {model_table} VALUES (?,?)",
                  zip(map(int, feats), map(float, w)))
    q.execute(f"DROP TABLE IF EXISTS {model_table}_blob")
    q.execute(f"CREATE TABLE {model_table}_blob (model BLOB)")
    q.execute(f"INSERT INTO {model_table}_blob VALUES (?)",
              (model.to_blob(),))


def _materialize_forest(q, model, model_table: str) -> None:
    """Per-tree rows (model_id, model_type, pred_model, var_importance JSON,
    oob_errors, oob_tests) — the reference's forward at close
    (ref: RandomForestClassifierUDTF.java:343-351). Score in SQL with the
    tree_predict scalar + rf_ensemble aggregate (§3.4's predict flow)."""
    q.execute(f"CREATE TABLE {model_table} (model_id INTEGER PRIMARY KEY, "
              "model_type TEXT, pred_model TEXT, var_importance TEXT, "
              "oob_errors INTEGER, oob_tests INTEGER)")
    q.executemany(
        f"INSERT INTO {model_table} VALUES (?,?,?,?,?,?)",
        ((int(mid), str(mtype), model_text if isinstance(model_text, str)
          else json.dumps(model_text), json.dumps(imp), int(oe), int(ot))
         for mid, mtype, model_text, imp, oe, ot in model.model_rows()))


def _materialize_gbt(q, model, model_table: str) -> None:
    """One row per (boosting round, class tree) — the reference's per-round
    forward flattened relationally (GradientTreeBoostingClassifierUDTF
    .java:525-546; the per-class models array becomes a cls column). Score
    binary in SQL with
    `MAX(intercept) + MAX(shrinkage) * SUM(tree_predict(model_type,
    pred_model, features))` per row; multiclass per (row, cls) +
    max_label."""
    q.execute(f"CREATE TABLE {model_table} (iter INTEGER, cls INTEGER, "
              "model_type TEXT, pred_model TEXT, intercept REAL, "
              "shrinkage REAL, var_importance TEXT, oob_error_rate REAL, "
              "classes TEXT, PRIMARY KEY (iter, cls))")
    q.executemany(
        f"INSERT INTO {model_table} VALUES (?,?,?,?,?,?,?,?,?)",
        ((int(m), int(c), str(mt), text, float(ic), float(sh),
          json.dumps(imp), oob, vocab)
         for m, c, mt, text, ic, sh, imp, oob, vocab
         in model.model_rows()))


def _materialize_multiclass(q, model, model_table: str) -> None:
    """(label, feature, weight[, covar]) — the per-label close() emission
    (ref: MulticlassOnlineClassifierUDTF close)."""
    out = model.model_rows()
    if len(out) == 4:
        labels, feats, w, cov = out
        q.execute(f"CREATE TABLE {model_table} (label TEXT, feature INTEGER, "
                  "weight REAL, covar REAL, PRIMARY KEY (label, feature))")
        q.executemany(f"INSERT INTO {model_table} VALUES (?,?,?,?)",
                      zip(map(str, labels), map(int, feats),
                          map(float, w), map(float, cov)))
    else:
        labels, feats, w = out
        q.execute(f"CREATE TABLE {model_table} (label TEXT, feature INTEGER, "
                  "weight REAL, PRIMARY KEY (label, feature))")
        q.executemany(f"INSERT INTO {model_table} VALUES (?,?,?)",
                      zip(map(str, labels), map(int, feats), map(float, w)))


def train(conn: sqlite3.Connection, trainer: str, src_query: str,
          options: Optional[str] = None,
          model_table: Optional[str] = "model",
          warm_start_table: Optional[str] = None):
    """Run a registry trainer over `src_query`'s (features TEXT, label)
    rows; materialize the model table and return the model object.

    The SQL-engine flow of `INSERT ... SELECT train_arow(features, label)
    FROM t` (ref: define-all.hive:27-28 + the UDTF emit at close,
    BinaryOnlineClassifierUDTF.java:249-298): SQLite has no table-valued
    UDFs, so the rewrite — pull rows, train, materialize — is explicit.

    The table shape follows the trainer family, exactly the reference's
    per-family emissions: linear `(feature, weight[, covar])`; FM
    `(feature, wi, vif JSON)` with w0 on feature -1 (score in SQL with the
    fm_predict aggregate); FFM linear rows + the complete compressed blob
    (scored by ffm_predict); multiclass `(label, feature, weight[, covar])`
    (score with SUM(weight*value) per (row,label) + max_label); forests
    per-tree rows (tree_predict + rf_ensemble); GBT per-(round, class)
    rows (intercept + shrinkage * SUM(tree_predict)) — the reference
    forwards GBT per round too
    (GradientTreeBoostingClassifierUDTF.java:525-546)."""
    if model_table is not None:
        _check_ident(model_table)
    if warm_start_table is not None:
        _check_ident(warm_start_table)
    fn = get_function(trainer)
    is_forest = trainer.startswith(("train_randomforest",
                                    "train_gradient_tree"))
    rows = conn.execute(src_query).fetchall()
    # forests consume dense array<double> rows (the reference's RF input),
    # every other family consumes "name:value" feature lists
    feats = [parse_dense(r[0]) if is_forest else parse_features(r[0])
             for r in rows]
    labels = [r[1] for r in rows]

    kw = {}
    if warm_start_table is not None:
        # `-loadmodel` with the model table living IN the engine instead of
        # a file (ref: LearnerBaseUDTF.loadPredictionModel:215-333 reads the
        # model table from the distributed cache). Linear trainers only —
        # exactly the fit_linear family; FM/FFM/multiclass would silently
        # drop (or reject) the kwargs.
        import numpy as np

        from ..io.checkpoint import dense_from_rows

        if fn.__module__.rsplit(".", 1)[-1] not in ("classifier",
                                                    "regression"):
            raise ValueError(
                f"warm_start_table supports linear trainers only; "
                f"{trainer} is not one")
        m = re.search(r"-(?:dims|feature_dimensions)\s+(\d+)", options or "")
        if m is None:
            raise ValueError(
                "warm_start_table needs an explicit -dims in options so the "
                "model table maps into the right feature space")
        dims = int(m.group(1))
        cols = [r[1] for r in conn.execute(
            f"PRAGMA table_info({warm_start_table})")]
        if not cols:
            raise ValueError(f"no such table: {warm_start_table}")
        if cols not in (["feature", "weight"],
                        ["feature", "weight", "covar"]):
            raise ValueError(
                f"{warm_start_table} is not a linear model table "
                f"(columns {cols}); warm start supports linear trainers only")
        wrows = conn.execute(
            f"SELECT * FROM {warm_start_table}").fetchall()
        f0 = np.array([r[0] for r in wrows], dtype=np.int64)
        if f0.size and (int(f0.max()) >= dims or int(f0.min()) < 0):
            raise ValueError(
                f"{warm_start_table} has feature ids outside [0, {dims}) "
                f"(min {int(f0.min())}, max {int(f0.max())}); pass the "
                "-dims it was trained at")
        w0 = np.array([r[1] for r in wrows], dtype=np.float32)
        c0 = np.array([r[2] for r in wrows], dtype=np.float32) \
            if len(cols) > 2 else None
        iw, ic = dense_from_rows(dims, f0, w0, c0)
        kw = {"initial_weights": iw, "initial_covars": ic}

    model = fn(feats, labels, options, **kw) if options is not None \
        else fn(feats, labels, **kw)

    if model_table is None:  # train-only; serve from the returned object
        return model

    from ..models.ffm import TrainedFFMModel
    from ..models.fm import TrainedFMModel
    from ..models.trees.forest import TrainedForest, TrainedGBT

    # resolve the family's materializer BEFORE dropping anything so a
    # refused call leaves any existing model table intact
    if isinstance(model, TrainedFMModel):
        materialize = _materialize_fm
    elif isinstance(model, TrainedFFMModel):
        materialize = _materialize_ffm
    elif isinstance(model, TrainedGBT):
        materialize = _materialize_gbt
    elif isinstance(model, TrainedForest):
        materialize = _materialize_forest
    elif hasattr(model, "label_vocab"):  # multiclass family
        materialize = _materialize_multiclass
    elif hasattr(model, "state") and hasattr(model.state, "weights"):
        materialize = _materialize_linear
    else:
        raise ValueError(
            f"{trainer} models have no SQL materialization here; pass "
            "model_table=None and predict on the returned model object")
    q = conn.cursor()
    q.execute(f"DROP TABLE IF EXISTS {model_table}")
    # a previous train_ffm into this name also left {model_table}_blob;
    # retraining with another family must not leave ffm_predict silently
    # scoring the outdated blob
    q.execute(f"DROP TABLE IF EXISTS {model_table}_blob")
    materialize(q, model, model_table)
    conn.commit()
    return model


def train_mf(conn: sqlite3.Connection, trainer: str, src_query: str,
             options: Optional[str] = None,
             model_table: Optional[str] = "mf_model"):
    """Matrix-factorization training over `src_query`'s 3 columns —
    (user, item, rating), or (user, pos_item, neg_item) for train_bprmf —
    materializing the reference's per-index emission as ONE table
    `(idx, pu TEXT, qi TEXT, bu REAL, bi REAL, mu REAL)`: user rows carry
    pu/bu, item rows qi/bi, every row mu
    (ref: OnlineMatrixFactorizationUDTF close/forward). Score in SQL with
    the mf_predict / bprmf_predict scalars:

        SELECT t.user, t.item, mf_predict(u.pu, i.qi, u.bu, i.bi, u.mu)
        FROM test t
        JOIN mf_model u ON u.idx = t.user AND u.pu IS NOT NULL
        JOIN mf_model i ON i.idx = t.item AND i.qi IS NOT NULL
    """
    if model_table is not None:
        _check_ident(model_table)
    if trainer not in ("train_mf_sgd", "train_mf_adagrad", "train_bprmf"):
        raise ValueError(
            f"train_mf drives the 3-column MF trainers only; use train() "
            f"for {trainer}")
    fn = get_function(trainer)
    rows = conn.execute(src_query).fetchall()
    users = [r[0] for r in rows]
    items = [r[1] for r in rows]
    third = [r[2] for r in rows]
    model = fn(users, items, third, options) if options is not None \
        else fn(users, items, third)
    if model_table is None:
        return model

    mr = model.model_rows()
    tu, P, Bu = mr["users"]
    ti, Q, Bi = mr["items"]
    mu = mr["mu"]
    q = conn.cursor()
    q.execute(f"DROP TABLE IF EXISTS {model_table}")
    q.execute(f"CREATE TABLE {model_table} (idx INTEGER, pu TEXT, qi TEXT, "
              "bu REAL, bi REAL, mu REAL)")
    q.executemany(
        f"INSERT INTO {model_table} VALUES (?,?,NULL,?,NULL,?)",
        ((int(u), json.dumps([float(x) for x in pv]), float(b), mu)
         for u, pv, b in zip(tu, P, Bu)))
    q.executemany(
        f"INSERT INTO {model_table} VALUES (?,NULL,?,NULL,?,?)",
        ((int(i), json.dumps([float(x) for x in qv]), float(b), mu)
         for i, qv, b in zip(ti, Q, Bi)))
    # idx can't be PRIMARY KEY (a user and an item may share an id); the
    # documented double self-join predict plan needs the index regardless
    q.execute(f"CREATE INDEX {model_table}_idx ON {model_table}(idx)")
    conn.commit()
    return model


def explode_features(conn: sqlite3.Connection, src_query: str,
                     out_table: str = "exploded",
                     num_features: Optional[int] = None) -> None:
    """(id, features TEXT) rows -> `(rowid, feature INTEGER, value REAL)`
    — the explode step of the reference's pure-SQL inference plan
    (SURVEY.md §3.5). String feature names are hashed like
    feature_hashing() (ref: ftvec/hashing/FeatureHashingUDF.java:172);
    `num_features` is REQUIRED when names are strings and must match the
    trainer's `-dims` (same feature space as the model table). Integer ids
    are floor-modded into [0, num_features) exactly like every trainer's
    parser (`int(name) % num_features`, matching the C bulk parser), so
    out-of-range and negative ids land on the same model rows the trainer
    wrote — without the mod the join silently drops them."""
    from ..utils.feature import parse_feature
    from ..utils.hashing import mhash

    _check_ident(out_table)
    # build all rows BEFORE touching out_table so a refused call (or a bad
    # src_query) leaves any existing exploded table intact
    ins = []
    for rid, text in conn.execute(src_query):
        for fv in parse_features(text):
            name, value = parse_feature(fv)
            try:
                idx = int(name)
            except ValueError:
                # hashing must land in the SAME space the model was trained
                # at or the join silently mismatches — refuse to guess
                if num_features is None:
                    raise ValueError(
                        f"feature {name!r} is a string name; pass "
                        "num_features= matching the trainer's -dims so it "
                        "hashes into the model's feature space")
                idx = mhash(name, num_features)
            else:
                if num_features is not None:
                    idx %= num_features
                elif idx < 0:
                    raise ValueError(
                        f"feature id {idx} is negative; pass num_features= "
                        "matching the trainer's -dims so it floor-mods into "
                        "the model's feature space like the trainer did")
            ins.append((rid, idx, float(value)))
    q = conn.cursor()
    q.execute(f"DROP TABLE IF EXISTS {out_table}")
    q.execute(f"CREATE TABLE {out_table} "
              "(rowid INTEGER, feature INTEGER, value REAL)")
    q.executemany(f"INSERT INTO {out_table} VALUES (?,?,?)", ins)
    conn.commit()
