"""DataFrame DSL — the Spark-module analog.

The reference's spark module wraps every trainer as an implicit DataFrame
method (`df.train_arow('features, 'label)` etc.,
ref: spark/src/main/scala/org/apache/spark/sql/hive/HivemallOps.scala:67-475)
plus grouped aggregates (GroupedDataEx.scala:134-257). The pandas-facing
equivalent here wraps the same registry:

    hf = hivemall_ops(df)                       # df: pandas DataFrame
    model = hf.train_arow("features", "label", "-dims 1024")
    df2 = hf.amplify(3)
    agg = hf.groupby("feature").argmin_kld("weight", "covar")

Streaming predict (HivemallStreamingOps.scala:27-46) maps to
`predict_stream(model, batches)` over an iterator of DataFrames.
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, Optional

import numpy as np

from ..ensemble import argmin_kld as _argmin_kld
from ..ensemble import max_label as _max_label
from ..ensemble import voted_avg as _voted_avg
from ..ensemble import weight_voted_avg as _weight_voted_avg
from ..sql import get_function


class _GroupedOps:
    def __init__(self, df, by):
        self._df = df
        self._by = by

    def _agg(self, fn: Callable, *cols: str, name: str = "value"):
        import pandas as pd

        rows = []
        for key, grp in self._df.groupby(self._by):
            if len(cols) == 1:
                out = fn(grp[cols[0]].tolist())
            else:
                out = fn(list(zip(*(grp[c].tolist() for c in cols))))
            rows.append((key, out))
        return pd.DataFrame(rows, columns=[self._by, name])

    def voted_avg(self, col: str):
        return self._agg(_voted_avg, col)

    def weight_voted_avg(self, col: str):
        return self._agg(_weight_voted_avg, col)

    def argmin_kld(self, mean_col: str, covar_col: str):
        return self._agg(_argmin_kld, mean_col, covar_col)

    def max_label(self, score_col: str, label_col: str):
        return self._agg(_max_label, score_col, label_col)

    def rf_ensemble(self, col: str):
        from ..ensemble import rf_ensemble

        return self._agg(rf_ensemble, col)

    def maxrow(self, compare_col: str):
        from ..ensemble import maxrow as mr

        import pandas as pd

        cols = list(self._df.columns)
        ci = cols.index(compare_col)
        rows = [(k,) + tuple(mr([tuple(r) for r in g.itertuples(index=False)], ci))
                for k, g in self._df.groupby(self._by)]
        return pd.DataFrame(rows, columns=["group"] + cols)

    def _metric(self, fn, pred_col: str, actual_col: str, name: str):
        import pandas as pd

        rows = [(k, fn(g[pred_col], g[actual_col]))
                for k, g in self._df.groupby(self._by)]
        return pd.DataFrame(rows, columns=[self._by, name])

    def mae(self, pred_col: str, actual_col: str):
        from ..evaluation import mae

        return self._metric(mae, pred_col, actual_col, "mae")

    def mse(self, pred_col: str, actual_col: str):
        from ..evaluation import mse

        return self._metric(mse, pred_col, actual_col, "mse")

    def rmse(self, pred_col: str, actual_col: str):
        from ..evaluation import rmse

        return self._metric(rmse, pred_col, actual_col, "rmse")

    def f1score(self, actual_col: str, pred_col: str):
        from ..evaluation import f1score

        import pandas as pd

        rows = [(k, f1score(g[actual_col].tolist(), g[pred_col].tolist()))
                for k, g in self._df.groupby(self._by)]
        return pd.DataFrame(rows, columns=[self._by, "f1score"])


class HivemallFrame:
    """Thin wrapper exposing registry functions as DataFrame methods."""

    def __init__(self, df):
        self._df = df

    @property
    def df(self):
        return self._df

    def groupby(self, by: str) -> _GroupedOps:
        return _GroupedOps(self._df, by)

    # ---- trainers: df.train_xxx(features_col, label_col, options) ----
    def __getattr__(self, name: str):
        if name.startswith("train_"):
            fn = get_function(name)

            def trainer(features_col: str, label_col: str,
                        options: Optional[str] = None, **kw):
                feats = self._df[features_col].tolist()
                labels = self._df[label_col].to_numpy()
                return fn(feats, labels, options, **kw)

            return trainer
        raise AttributeError(name)

    # ---- row transforms mirroring HivemallOps:521-673 ----
    def amplify(self, xtimes: int) -> "HivemallFrame":
        import pandas as pd

        idx = np.repeat(np.arange(len(self._df)), xtimes)
        return HivemallFrame(self._df.iloc[idx].reset_index(drop=True))

    def rand_amplify(self, xtimes: int, num_buffers: int = 2,
                     seed: int = 31) -> "HivemallFrame":
        from ..ftvec import rand_amplify as ra

        import pandas as pd

        rows = list(ra(xtimes, num_buffers, self._df.itertuples(index=False),
                       seed=seed))
        return HivemallFrame(pd.DataFrame(rows, columns=list(self._df.columns)))

    def each_top_k(self, k: int, group_col: str, value_col: str) -> "HivemallFrame":
        from ..tools import each_top_k as etk

        import pandas as pd

        df = self._df.sort_values(group_col, kind="mergesort")
        rows_in = ((r[group_col], r[value_col], tuple(r))
                   for r in df.to_dict("records"))
        out = [(rank, value) + tuple(payload.values() if isinstance(payload, dict)
                                     else payload)
               for rank, value, payload in etk(k, rows_in)]
        cols = ["rank", "value"] + list(df.columns)
        return HivemallFrame(pd.DataFrame(out, columns=cols))


def hivemall_ops(df) -> HivemallFrame:
    return HivemallFrame(df)


def predict_stream(model, batches: Iterable, features_col: str = "features"
                   ) -> Iterator[np.ndarray]:
    """Streaming predict bridge (HivemallStreamingOps analog): yields scores
    per incoming DataFrame batch."""
    for batch in batches:
        yield model.predict(batch[features_col].tolist())
