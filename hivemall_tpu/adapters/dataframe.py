"""DataFrame DSL — the Spark-module analog.

The reference's spark module wraps every trainer as an implicit DataFrame
method (`df.train_arow('features, 'label)` etc.,
ref: spark/src/main/scala/org/apache/spark/sql/hive/HivemallOps.scala:67-475)
plus grouped aggregates (GroupedDataEx.scala:134-257). The pandas-facing
equivalent here wraps the same registry:

    hf = hivemall_ops(df)                       # df: pandas DataFrame
    model = hf.train_arow("features", "label", "-dims 1024")
    df2 = hf.amplify(3)
    agg = hf.groupby("feature").argmin_kld("weight", "covar")

Streaming predict (HivemallStreamingOps.scala:27-46) maps to
`predict_stream(model, batches)` over an iterator of DataFrames.
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, Optional

import numpy as np

from ..ensemble import argmin_kld as _argmin_kld
from ..ensemble import max_label as _max_label
from ..ensemble import voted_avg as _voted_avg
from ..ensemble import weight_voted_avg as _weight_voted_avg
from ..sql import get_function


class _GroupedOps:
    def __init__(self, df, by):
        self._df = df
        self._by = by

    def _agg(self, fn: Callable, *cols: str, name: str = "value"):
        import pandas as pd

        rows = []
        for key, grp in self._df.groupby(self._by):
            if len(cols) == 1:
                out = fn(grp[cols[0]].tolist())
            else:
                out = fn(list(zip(*(grp[c].tolist() for c in cols))))
            rows.append((key, out))
        return pd.DataFrame(rows, columns=[self._by, name])

    def voted_avg(self, col: str):
        return self._agg(_voted_avg, col)

    def weight_voted_avg(self, col: str):
        return self._agg(_weight_voted_avg, col)

    def argmin_kld(self, mean_col: str, covar_col: str):
        return self._agg(_argmin_kld, mean_col, covar_col)

    def max_label(self, score_col: str, label_col: str):
        return self._agg(_max_label, score_col, label_col)

    def rf_ensemble(self, col: str):
        from ..ensemble import rf_ensemble

        return self._agg(rf_ensemble, col)

    def maxrow(self, compare_col: str):
        from ..ensemble import maxrow as mr

        import pandas as pd

        cols = list(self._df.columns)
        ci = cols.index(compare_col)
        rows = [(k,) + tuple(mr([tuple(r) for r in g.itertuples(index=False)], ci))
                for k, g in self._df.groupby(self._by)]
        return pd.DataFrame(rows, columns=["group"] + cols)

    def _metric(self, fn, pred_col: str, actual_col: str, name: str):
        import pandas as pd

        rows = [(k, fn(g[pred_col], g[actual_col]))
                for k, g in self._df.groupby(self._by)]
        return pd.DataFrame(rows, columns=[self._by, name])

    def mae(self, pred_col: str, actual_col: str):
        from ..evaluation import mae

        return self._metric(mae, pred_col, actual_col, "mae")

    def mse(self, pred_col: str, actual_col: str):
        from ..evaluation import mse

        return self._metric(mse, pred_col, actual_col, "mse")

    def rmse(self, pred_col: str, actual_col: str):
        from ..evaluation import rmse

        return self._metric(rmse, pred_col, actual_col, "rmse")

    def f1score(self, actual_col: str, pred_col: str):
        from ..evaluation import f1score

        import pandas as pd

        rows = [(k, f1score(g[actual_col].tolist(), g[pred_col].tolist()))
                for k, g in self._df.groupby(self._by)]
        return pd.DataFrame(rows, columns=[self._by, "f1score"])


class HivemallFrame:
    """Thin wrapper exposing registry functions as DataFrame methods."""

    def __init__(self, df, mix_servs: Optional[str] = None):
        self._df = df
        self._mix_servs = mix_servs

    @property
    def df(self):
        return self._df

    def groupby(self, by: str) -> _GroupedOps:
        return _GroupedOps(self._df, by)

    def _wrap(self, df) -> "HivemallFrame":
        """Transforms keep the set_mix_servs config of the source frame."""
        return HivemallFrame(df, mix_servs=self._mix_servs)

    # ---- trainers: df.train_xxx(features_col, label_col, options) ----
    def __getattr__(self, name: str):
        if name.startswith("train_"):
            fn = get_function(name)

            def trainer(features_col: str, label_col: str,
                        options: Optional[str] = None, **kw):
                from ..utils.options import OptionError

                feats = self._df[features_col].tolist()
                labels = self._df[label_col].to_numpy()
                if self._mix_servs:
                    mix = f"-mix {self._mix_servs}"
                    try:
                        return fn(feats, labels,
                                  f"{options} {mix}" if options else mix, **kw)
                    except OptionError as e:
                        if "unknown option '-mix'" not in str(e):
                            raise
                        # batch trainers (forest/GBT) take no -mix, like the
                        # reference's own UDTFs; train without it
                        import warnings

                        warnings.warn(f"{name} does not accept -mix; "
                                      "set_mix_servs ignored for this trainer")
                return fn(feats, labels, options, **kw)

            return trainer
        raise AttributeError(name)

    def set_mix_servs(self, servers: str) -> "HivemallFrame":
        """Inject `-mix <servers>` into every subsequent train_* call
        (ref: HivemallOps.scala:692 setMixServs)."""
        return HivemallFrame(self._df, mix_servs=servers)

    # ---- row transforms mirroring HivemallOps:521-673 ----
    def amplify(self, xtimes: int) -> "HivemallFrame":
        import pandas as pd

        idx = np.repeat(np.arange(len(self._df)), xtimes)
        return self._wrap(self._df.iloc[idx].reset_index(drop=True))

    def rand_amplify(self, xtimes: int, num_buffers: int = 2,
                     seed: int = 31) -> "HivemallFrame":
        from ..ftvec import rand_amplify as ra

        import pandas as pd

        rows = list(ra(xtimes, num_buffers, self._df.itertuples(index=False),
                       seed=seed))
        return self._wrap(pd.DataFrame(rows, columns=list(self._df.columns)))

    def part_amplify(self, xtimes: int) -> "HivemallFrame":
        """Partition-local amplify (HivemallOps.scala part_amplify). A pandas
        DataFrame is one partition, so this equals `amplify` without any
        shuffle — kept as its own method so ported Spark code reads 1:1."""
        return self.amplify(xtimes)

    def explode_array(self, col: str) -> "HivemallFrame":
        """One output row per array element (HivemallOps.scala explode_array).
        Empty/None/NaN cells yield zero rows (Hive explode semantics)
        rather than pandas' NaN placeholder row."""
        keep = self._df[col].map(
            lambda a: isinstance(a, (list, tuple, np.ndarray)) and len(a) > 0)
        return self._wrap(self._df[keep].explode(col).reset_index(drop=True))

    def minhash(self, item_col: str, features_col: str, num_hashes: int = 5,
                num_keygroups: int = 2) -> "HivemallFrame":
        """Emit (clusterid, item) pairs per row — one per hash function
        (HivemallOps.scala minhash over knn/lsh/MinHashUDTF.java)."""
        from ..knn import minhash as mh

        import pandas as pd

        rows = []
        for r in self._df.to_dict("records"):
            rows.extend(mh(r[item_col], r[features_col],
                           num_hashes, num_keygroups))
        return self._wrap(pd.DataFrame(rows, columns=["clusterid", item_col]))

    def quantify(self, *cols: str) -> "HivemallFrame":
        """Map non-numeric values of the given columns (all columns when none
        given) to dense int ids in first-seen order, sharing one quantifier
        across rows (HivemallOps.scala quantify over QuantifyColumnsUDTF)."""
        from ..ftvec import Quantifier

        out = self._df.copy()
        use = list(cols) if cols else list(out.columns)
        q = Quantifier()
        for ci, c in enumerate(use):
            out[c] = [q.quantify(ci, v) for v in out[c]]
        return self._wrap(out)

    def binarize_label(self, pos_col: str, neg_col: str,
                       *feature_cols: str) -> "HivemallFrame":
        """Expand aggregated (pos_count, neg_count, features...) rows into
        `pos` label-1 rows and `neg` label-0 rows
        (HivemallOps.scala binarize_label over BinarizeLabelUDTF)."""
        from ..ftvec import binarize_label as bl

        import pandas as pd

        rows = []
        for r in self._df.to_dict("records"):
            feats = tuple(r[c] for c in feature_cols)
            rows.extend(bl(int(r[pos_col]), int(r[neg_col]), *feats))
        return self._wrap(
            pd.DataFrame(rows, columns=list(feature_cols) + ["label"]))

    def each_top_k(self, k: int, group_col: str, value_col: str) -> "HivemallFrame":
        from ..tools import each_top_k as etk

        import pandas as pd

        df = self._df.sort_values(group_col, kind="mergesort")
        # NB: tuple(dict) yields the KEYS — the payload must carry the row
        # VALUES (caught by tests/test_spark_adapter.py)
        rows_in = ((r[group_col], r[value_col], tuple(r.values()))
                   for r in df.to_dict("records"))
        out = [(rank, value) + tuple(payload)
               for rank, value, payload in etk(k, rows_in)]
        cols = ["rank", "value"] + list(df.columns)
        return self._wrap(pd.DataFrame(out, columns=cols))


def hivemall_ops(df) -> HivemallFrame:
    return HivemallFrame(df)


def lr_datagen_frame(options: Optional[str] = None):
    """Synthetic LR dataset as a DataFrame with features/label columns
    (HivemallOps.scala lr_datagen over dataset/LogisticRegressionDataGeneratorUDTF)."""
    from ..dataset import lr_datagen

    import pandas as pd

    rows, labels = lr_datagen(options)
    return pd.DataFrame({"features": list(rows), "label": labels})


def predict_stream(model, batches: Iterable, features_col: str = "features"
                   ) -> Iterator[np.ndarray]:
    """Streaming predict bridge (HivemallStreamingOps analog): yields scores
    per incoming DataFrame batch."""
    for batch in batches:
        yield model.predict(batch[features_col].tolist())
