"""Typed per-family model-row iteration shared by the host-engine adapters.

Every trainer family dumps its model as relational rows at close() in the
reference (linear: BinaryOnlineClassifierUDTF.java:249-298, multiclass
per-label, FM: forwardAsIntFeature FactorizationMachineUDTF.java:446-519,
forest: RandomForestClassifierUDTF.java:343-351, GBT per round:
GradientTreeBoostingClassifierUDTF.java:525-546). The TSV bridge
(hive_transform) and the Spark adapter share this family dispatch,
yielding typed python values (lists stay lists — each adapter picks its
own array encoding: json for TSV cells, array<float> columns for Spark).
The SQL engine binding (sqlite.py) keeps its own materialization: its
tables are engine-facing (typed SQL columns, blob side tables, indexes),
not a row-stream rendering.
"""

from __future__ import annotations

from typing import Iterable, List, Tuple


def iter_model_rows(model) -> Tuple[List[str], Iterable[tuple]]:
    """(column_names, iterable of typed row tuples) for any trained model.

    Column layouts per family (value types in parens):
    - GBT: iter(int), cls(int), model_type(str), pred_model(str),
      intercept(float), shrinkage(float), var_importance(list[float]),
      oob_error_rate(float|None), classes(str: JSON vocabulary)
    - FM: feature(int), Wi(float), Vif(list[float]|None) — w0 rides the
      feature == -1 row (the TSV/SQL convention; the reference parks it on
      feature 0's bias slot)
    - FFM: feature(int), Wi(float|None), blob(str|None) — w0 on feature -1,
      the complete compressed model (base91 text) on feature -2
    - forest: model_id(int), model_type(str), pred_model(str),
      var_importance(list[float]), oob_errors(int), oob_tests(int)
    - multiclass: label(any), feature(int), weight(float)[, covar(float)]
    - linear: feature(int), weight(float)[, covar(float)]
    """
    from ..models.ffm import TrainedFFMModel
    from ..models.fm import TrainedFMModel
    from ..models.trees.forest import TrainedForest, TrainedGBT

    if isinstance(model, TrainedGBT):
        cols = ["iter", "cls", "model_type", "pred_model", "intercept",
                "shrinkage", "var_importance", "oob_error_rate", "classes"]

        def gbt_rows():
            for m, c, mt, text, ic, sh, imp, oob, vocab in model.model_rows():
                yield (int(m), int(c), str(mt), text, float(ic), float(sh),
                       [float(x) for x in imp], oob, vocab)

        return cols, gbt_rows()

    if isinstance(model, TrainedFMModel):
        cols = ["feature", "Wi", "Vif"]

        def fm_rows():
            w0, feats, w, v = model.model_rows()
            yield (-1, float(w0), None)
            for f, wi, vi in zip(feats, w, v):
                yield (int(f), float(wi), [float(x) for x in vi])

        return cols, fm_rows()

    if isinstance(model, TrainedFFMModel):
        cols = ["feature", "Wi", "blob"]

        def ffm_rows():
            from ..tools import base91

            feats, w, w0 = model.model_rows()
            yield (-1, float(w0), None)
            for f, wi in zip(feats, w):
                yield (int(f), float(wi), None)
            yield (-2, None, base91(model.to_blob()))

        return cols, ffm_rows()

    if isinstance(model, TrainedForest):
        cols = ["model_id", "model_type", "pred_model", "var_importance",
                "oob_errors", "oob_tests"]

        def forest_rows():
            for mid, mtype, text, imp, oe, ot in model.model_rows():
                yield (int(mid), str(mtype), text,
                       [float(x) for x in imp], int(oe), int(ot))

        return cols, forest_rows()

    if hasattr(model, "label_vocab"):  # multiclass family
        rows = model.model_rows()
        cols = (["label", "feature", "weight", "covar"] if len(rows) == 4
                else ["label", "feature", "weight"])

        def mc_rows():
            for tup in zip(*rows):
                lab, feat, w = tup[0], int(tup[1]), float(tup[2])
                if len(tup) == 4:
                    yield (lab, feat, w, float(tup[3]))
                else:
                    yield (lab, feat, w)

        return cols, mc_rows()

    if hasattr(model, "state") and hasattr(model.state, "weights"):
        from ..core.state import model_rows as linear_rows

        rows = linear_rows(model.state)
        use_cov = len(rows) == 3 and rows[2] is not None
        cols = (["feature", "weight", "covar"] if use_cov
                else ["feature", "weight"])

        def lin_rows():
            if use_cov:
                for f, w, c in zip(*rows):
                    yield (int(f), float(w), float(c))
            else:
                for f, w in zip(rows[0], rows[1]):
                    yield (int(f), float(w))

        return cols, lin_rows()

    raise ValueError(f"{type(model).__name__}: model has no row emission")
