"""Apache Arrow engine bridge — the host-engine adapter layer (L6).

The reference binds to its host engines through Hive ObjectInspectors and a
Spark DataFrame DSL (ref: hivemall/UDTFWithOptions.java:48,
spark/src/main/scala/org/apache/spark/sql/hive/HivemallOps.scala:67-475).
Modern engines (Spark, DuckDB, Polars, Flight services, pandas) interchange
through Arrow, so THE engine-neutral binding here is Arrow-native:

- `arrow_ops(table)` — every registry trainer as a method over a
  pyarrow.Table with a hivemall-style features column
  (`list<string>` of "name:value" / "idx:value", exactly the reference's
  features array type), the HivemallOps analog;
- `model_to_arrow` / `model_from_arrow` — the trained model as an Arrow
  table `(feature, weight[, covar])`, the reference's model-table emission
  (`BinaryOnlineClassifierUDTF.close()`:249-298) in the interchange format
  every host engine can consume;
- `write_model_ipc` / `read_model_ipc` — Arrow IPC file round trip; reading
  one back is the `-loadmodel` warm start (LearnerBaseUDTF.java:215-333)
  without a Hive distributed cache;
- `predict_batches(model, reader)` — streaming scoring over a
  RecordBatchReader (the HivemallStreamingOps analog,
  HivemallStreamingOps.scala:27-46).

Zero-copy note: numeric label columns cross via `to_numpy()` without
copying when they have no nulls; list-of-string feature columns are
necessarily materialized (the reference pays the same ObjectInspector
deserialization per row).
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from ..sql import get_function


def _require_pyarrow():
    try:
        import pyarrow as pa  # noqa: F401

        return pa
    except ImportError as e:  # pragma: no cover - pyarrow is in this image
        raise ImportError(
            "the Arrow adapter needs pyarrow (pip install pyarrow)") from e


def table_features(table, features_col: str):
    """Extract a hivemall features column (`list<string>` of "name:value")
    from an Arrow table/batch into the list-of-rows form every train_* /
    predict consumes."""
    pa = _require_pyarrow()
    col = table.column(features_col) if hasattr(table, "column") \
        else table[features_col]
    return col.to_pylist()


def table_labels(table, label_col: str) -> np.ndarray:
    col = table.column(label_col) if hasattr(table, "column") \
        else table[label_col]
    return np.asarray(col.to_numpy(zero_copy_only=False))


class ArrowOps:
    """`arrow_ops(table).train_arow("features", "label", "-dims 1024")` —
    every `train_*` in the SQL registry, over Arrow data."""

    def __init__(self, table):
        _require_pyarrow()
        self._table = table

    @property
    def table(self):
        return self._table

    def __getattr__(self, name: str):
        if name.startswith("train_"):
            try:
                fn = get_function(name)
            except KeyError:
                raise AttributeError(name) from None

            def trainer(features_col: str, label_col: str,
                        options: Optional[str] = None):
                feats = table_features(self._table, features_col)
                labels = table_labels(self._table, label_col)
                return fn(feats, labels, options) if options is not None \
                    else fn(feats, labels)

            return trainer
        raise AttributeError(name)


def arrow_ops(table) -> ArrowOps:
    return ArrowOps(table)


def model_to_arrow(model):
    """Emit a trained linear model as the reference's model table
    `(feature int64, weight float32[, covar float32])` — ready to hand to
    any Arrow-speaking engine for the join+groupby inference plan
    (SURVEY.md §3.5)."""
    pa = _require_pyarrow()
    from ..core.state import model_rows

    rows = model_rows(model.state)
    if len(rows) == 3 and rows[2] is not None:
        feats, w, cov = rows
        return pa.table({"feature": pa.array(feats, pa.int64()),
                         "weight": pa.array(w, pa.float32()),
                         "covar": pa.array(cov, pa.float32())})
    feats, w = rows[0], rows[1]
    return pa.table({"feature": pa.array(feats, pa.int64()),
                     "weight": pa.array(w, pa.float32())})


def model_from_arrow(table, dims: int):
    """Warm-start arrays from a model table: returns (initial_weights,
    initial_covars-or-None) for init_linear_state / the trainers'
    `-loadmodel` path. Errors on a dims mismatch rather than silently
    aliasing features into a smaller table."""
    feats = np.asarray(table.column("feature").to_numpy(zero_copy_only=False),
                       dtype=np.int64)
    if feats.size and (int(feats.max()) >= dims or int(feats.min()) < 0):
        raise ValueError(
            f"model table has feature ids outside [0, {dims}) "
            f"(min {int(feats.min())}, max {int(feats.max())}); "
            "load it with the dims it was trained at")
    w = np.zeros(dims, np.float32)
    w[feats] = table.column("weight").to_numpy(zero_copy_only=False)
    cov = None
    if "covar" in table.column_names:
        cov = np.ones(dims, np.float32)
        cov[feats] = table.column("covar").to_numpy(zero_copy_only=False)
    return w, cov


def write_model_ipc(model, path: str) -> None:
    pa = _require_pyarrow()
    import pyarrow.ipc as ipc

    t = model_to_arrow(model)
    with pa.OSFile(path, "wb") as f:
        with ipc.new_file(f, t.schema) as writer:
            writer.write_table(t)


def read_model_ipc(path: str, dims: int):
    pa = _require_pyarrow()
    import pyarrow.ipc as ipc

    with pa.memory_map(path, "rb") as f:
        t = ipc.open_file(f).read_all()
    return model_from_arrow(t, dims)


def predict_batches(model, batches, features_col: str = "features"
                    ) -> Iterator[np.ndarray]:
    """Streaming scoring over an iterable of RecordBatches / Tables (e.g. a
    RecordBatchReader): yields one score array per batch."""
    for batch in batches:
        feats = table_features(batch, features_col)
        yield np.asarray(model.predict(feats))
