from .dataframe import HivemallFrame, hivemall_ops  # noqa: F401
