from .arrow import (arrow_ops, model_from_arrow, model_to_arrow,  # noqa: F401
                    predict_batches, read_model_ipc, write_model_ipc)
from .dataframe import HivemallFrame, hivemall_ops  # noqa: F401
