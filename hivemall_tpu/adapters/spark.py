"""pyspark DataFrame DSL — the Spark-module surface (HivemallOps parity).

The reference's spark module exposes every trainer as a DataFrame method
(`df.train_arow('features, 'label)`, ref: spark/src/main/scala/org/apache/
spark/sql/hive/HivemallOps.scala:67-475), grouped ensemble/metric
aggregates (GroupedDataEx.scala:134-257), `setMixServs` (:692), and a
streaming predict bridge (HivemallStreamingOps.scala:27-46). Training
runs inside each task and emits model rows that the caller merges with a
group-by aggregate — exactly the Hive flow (per-mapper UDTF + ensemble
UDAF), which maps 1:1 onto pyspark's `mapInPandas` (one trainer per
partition) + `groupBy().applyInPandas` (the merge).

pyspark is not bundled in this image, so the adapter is written against
the narrow structural contract it needs — `df.mapInPandas(fn, schema)`,
`df.groupBy(col).applyInPandas(fn, schema)`, `df.schema` — and the glue is
tested on simulated partitioned frames implementing that contract
(tests/test_spark_adapter.py). On a real cluster:

    from hivemall_tpu.adapters.spark import spark_hivemall_ops

    rows = spark_hivemall_ops(train_df).train_arow(
        "features", "label", "-dims 16777216")        # one model/partition
    model = spark_hivemall_ops(rows).groupby("feature").argmin_kld(
        "weight", "covar", key_type="bigint")          # ensemble merge

Every computation delegates to the tested pandas DSL (dataframe.py) and
the shared row emission (model_rows.py); this module only places work onto
partitions/groups and declares Spark schemas.
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional

from .dataframe import HivemallFrame, hivemall_ops

# Covariance emitters: (feature, weight, covar) — everything else emits
# (feature, weight). Mirrors each rule's use_covariance
# (models/classifier.py, models/regression.py; the names are the stable
# define-all surface, cross-checked against the trained model's actual
# columns at executor time).
_COV_LINEAR = frozenset((
    "train_cw", "train_arow", "train_arowh", "train_scw", "train_scw2",
    "train_arow_regr", "train_arowe_regr", "train_arowe2_regr",
))
_COV_MULTICLASS = frozenset((
    "train_multiclass_cw", "train_multiclass_arow", "train_multiclass_arowh",
    "train_multiclass_scw", "train_multiclass_scw2",
))
_MF_TRAINERS = frozenset(("train_mf_sgd", "train_mf_adagrad", "train_bprmf"))


def model_row_schema(trainer: str) -> str:
    """Spark DDL schema of `trainer`'s model-row emission (column layouts:
    adapters/model_rows.iter_model_rows)."""
    if trainer == "train_fm":
        return "feature bigint, Wi double, Vif array<double>"
    if trainer == "train_ffm":
        return "feature bigint, Wi double, blob string"
    if trainer == "train_gradient_tree_boosting_classifier":
        return ("iter bigint, cls bigint, model_type string, "
                "pred_model string, intercept double, shrinkage double, "
                "var_importance array<double>, oob_error_rate double, "
                "classes string")
    if trainer.startswith("train_randomforest"):
        return ("model_id bigint, model_type string, pred_model string, "
                "var_importance array<double>, oob_errors bigint, "
                "oob_tests bigint")
    if trainer.startswith("train_multiclass"):
        cov = ", covar double" if trainer in _COV_MULTICLASS else ""
        return f"label string, feature bigint, weight double{cov}"
    cov = ", covar double" if trainer in _COV_LINEAR else ""
    return f"feature bigint, weight double{cov}"


def _rows_frame(trainer: str, model, declared: str):
    """Model rows -> pandas frame matching `declared` (loud on mismatch:
    a silent schema drift would surface as nulls cluster-side)."""
    import pandas as pd

    from .model_rows import iter_model_rows

    cols, rows = iter_model_rows(model)
    declared_cols = [c.strip().split()[0] for c in declared.split(",")]
    if cols != declared_cols:
        raise ValueError(
            f"{trainer}: emitted columns {cols} != declared {declared_cols}")
    frame = pd.DataFrame(list(rows), columns=cols)
    if trainer.startswith("train_multiclass"):
        frame["label"] = frame["label"].astype(str)
    return frame


class SparkGroupedOps:
    """GroupedDataEx surface: each aggregate runs the pandas DSL per group
    via applyInPandas. `key_type` is the group column's Spark type in the
    output schema (defaults from df.schema when introspectable)."""

    def __init__(self, df, by: str):
        self._df = df
        self._by = by

    def _key_ddl(self, key_type: Optional[str]) -> str:
        if key_type:
            return key_type
        try:  # pyspark: StructType fields carry DDL-able types
            for f in self._df.schema.fields:
                if f.name == self._by:
                    return f.dataType.simpleString()
        except Exception:
            pass
        return "string"

    def _agg(self, op: str, *cols: str, name: str, val_type: str,
             key_type: Optional[str] = None, post=None):
        """`post` coerces the value column to the declared Spark type
        (e.g. str for labels, JSON for the rf_ensemble struct) — pyspark's
        Arrow conversion errors on object-dtype mismatches instead of
        casting."""
        by = self._by
        schema = f"{by} {self._key_ddl(key_type)}, {name} {val_type}"

        def fn(pdf):
            out = getattr(hivemall_ops(pdf).groupby(by), op)(*cols)
            if post is not None:
                out[out.columns[-1]] = out[out.columns[-1]].apply(post)
            return out

        return self._df.groupBy(by).applyInPandas(fn, schema=schema)

    def voted_avg(self, col: str, key_type: Optional[str] = None):
        return self._agg("voted_avg", col, name="value", val_type="double",
                         key_type=key_type)

    def weight_voted_avg(self, col: str, key_type: Optional[str] = None):
        return self._agg("weight_voted_avg", col, name="value",
                         val_type="double", key_type=key_type)

    def argmin_kld(self, mean_col: str, covar_col: str,
                   key_type: Optional[str] = None):
        return self._agg("argmin_kld", mean_col, covar_col, name="value",
                         val_type="double", key_type=key_type)

    def max_label(self, score_col: str, label_col: str,
                  key_type: Optional[str] = None):
        # labels keep their source dtype in the ensemble op -> stringify
        return self._agg("max_label", score_col, label_col, name="value",
                         val_type="string", key_type=key_type, post=str)

    def rf_ensemble(self, col: str, key_type: Optional[str] = None):
        # (label, probability, posteriori) struct -> JSON text, the same
        # encoding the SQL engine binding uses (sqlite._rf_ensemble_json)
        import json

        return self._agg(
            "rf_ensemble", col, name="value", val_type="string",
            key_type=key_type,
            post=lambda t: json.dumps({"label": int(t[0]),
                                       "probability": float(t[1]),
                                       "probabilities": [float(p)
                                                         for p in t[2]]}))

    def mae(self, pred_col: str, actual_col: str,
            key_type: Optional[str] = None):
        return self._agg("mae", pred_col, actual_col, name="mae",
                         val_type="double", key_type=key_type)

    def mse(self, pred_col: str, actual_col: str,
            key_type: Optional[str] = None):
        return self._agg("mse", pred_col, actual_col, name="mse",
                         val_type="double", key_type=key_type)

    def rmse(self, pred_col: str, actual_col: str,
             key_type: Optional[str] = None):
        return self._agg("rmse", pred_col, actual_col, name="rmse",
                         val_type="double", key_type=key_type)

    def f1score(self, actual_col: str, pred_col: str,
                key_type: Optional[str] = None):
        return self._agg("f1score", actual_col, pred_col, name="f1score",
                         val_type="double", key_type=key_type)


class SparkHivemallOps:
    def __init__(self, df, mix_servs: Optional[str] = None):
        self._df = df
        self._mix_servs = mix_servs

    @property
    def df(self):
        return self._df

    def set_mix_servs(self, servers: str) -> "SparkHivemallOps":
        """Inject `-mix <servers>` into every subsequent train_* call
        (ref: HivemallOps.scala:692 setMixServs)."""
        return SparkHivemallOps(self._df, mix_servs=servers)

    def groupby(self, by: str) -> SparkGroupedOps:
        return SparkGroupedOps(self._df, by)

    # Alias matching pyspark naming
    groupBy = groupby

    # ---- trainers: one model per partition, merged by the caller ----
    def __getattr__(self, name: str):
        if not name.startswith("train_"):
            raise AttributeError(name)
        if name in _MF_TRAINERS:
            raise NotImplementedError(
                f"{name} takes (user, item, rating) rows — use the Hive "
                "TRANSFORM bridge (adapters/hive_transform.py) or the "
                "direct API (models/mf.py) for matrix factorization")
        # fail fast on the driver: a typo'd trainer name must not surface
        # as an executor task failure after the job launches
        from ..sql import get_function

        get_function(name)
        mix = self._mix_servs
        schema = model_row_schema(name)

        def trainer(features_col: str, label_col: str,
                    options: Optional[str] = None):
            def fn(pdf_iter: Iterator) -> Iterator:
                import pandas as pd

                # Spark invokes the function on EMPTY partitions too
                # (repartition over small data); emit nothing for those
                chunks = [c for c in pdf_iter if len(c)]
                if not chunks:
                    return
                pdf = pd.concat(chunks, ignore_index=True)
                hf = HivemallFrame(pdf, mix_servs=mix)
                model = getattr(hf, name)(features_col, label_col, options)
                yield _rows_frame(name, model, schema)

            return self._df.mapInPandas(fn, schema=schema)

        return trainer

    # ---- row transforms (HivemallOps.scala:521-673) ----
    def transform(self, method: str, *args, schema=None, **kw):
        """Apply any HivemallFrame transform per partition. `schema=None`
        reuses the input schema (for row-preserving/reordering transforms);
        pass a DDL string when the transform changes columns."""
        mix = self._mix_servs
        out_schema = self._df.schema if schema is None else schema

        def fn(pdf_iter: Iterator) -> Iterator:
            import pandas as pd

            chunks = [c for c in pdf_iter if len(c)]
            if not chunks:
                return  # empty partition — emit nothing
            pdf = pd.concat(chunks, ignore_index=True)
            yield getattr(HivemallFrame(pdf, mix_servs=mix), method)(
                *args, **kw).df

        return SparkHivemallOps(
            self._df.mapInPandas(fn, schema=out_schema), mix_servs=mix)

    def amplify(self, xtimes: int) -> "SparkHivemallOps":
        return self.transform("amplify", xtimes)

    def rand_amplify(self, xtimes: int, num_buffers: int = 2,
                     seed: int = 31) -> "SparkHivemallOps":
        """Per-partition buffered shuffle amplification — the map-side
        semantics of the reference (RandomAmplifierUDTF runs per mapper)."""
        return self.transform("rand_amplify", xtimes, num_buffers, seed)

    def part_amplify(self, xtimes: int) -> "SparkHivemallOps":
        return self.transform("part_amplify", xtimes)

    def each_top_k(self, k: int, group_col: str, value_col: str, *,
                   schema: str) -> "SparkHivemallOps":
        """Per-partition top-k per group (rank/value columns prepended).
        Like the reference UDTF, input must be clustered by `group_col`
        (repartition by it first); `schema` declares the output columns
        ('rank int, value double, <input columns...>')."""
        return self.transform("each_top_k", k, group_col, value_col,
                              schema=schema)


def spark_hivemall_ops(df, mix_servs: Optional[str] = None
                       ) -> SparkHivemallOps:
    return SparkHivemallOps(df, mix_servs=mix_servs)


def lr_datagen_spark(spark, options: Optional[str] = None):
    """Synthetic LR dataset as a Spark DataFrame (HivemallOps lr_datagen
    analog): features as array<string>, label double."""
    from .dataframe import lr_datagen_frame

    pdf = lr_datagen_frame(options)
    pdf = pdf.assign(features=pdf["features"].apply(
        lambda r: [str(t) for t in r]))
    return spark.createDataFrame(pdf)


def predict_stream_spark(model, batches: Iterable, features_col: str =
                         "features") -> Iterator:
    """Streaming predict bridge (HivemallStreamingOps.scala:27-46 analog):
    score each micro-batch DataFrame as it arrives (use from
    foreachBatch). Yields one numpy score array per batch; batches may be
    pyspark DataFrames (collected via toPandas) or pandas frames."""
    from .dataframe import predict_stream

    def to_pandas(b):
        return b.toPandas() if hasattr(b, "toPandas") else b

    return predict_stream(model, (to_pandas(b) for b in batches),
                          features_col)
