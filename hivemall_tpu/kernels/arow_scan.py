"""Pallas TPU kernel: exact-sequential AROW over a block, weights in VMEM.

The engine's scan mode (core/engine.py) is reference-exact but pays an
HBM-roundtrip gather/scatter per ROW. For models that fit on-chip
(dims <= ~2^20 f32: weights + covariance = 8MB of ~16MB VMEM), this kernel
keeps BOTH tables resident in VMEM and replays the whole block's rows
sequentially in-kernel — the reference's per-row semantics
(ref: classifier/AROWClassifierUDTF.java:95-148) at on-chip latency.

Padding protocol matches core/batch.py (pad index == dims); padded lanes are
masked in-kernel. Validated bit-for-bit against the engine's scan mode in
interpret mode (tests/test_pallas_kernels.py); on real TPU it is opt-in via
`use_pallas=True` until hardware profiles pick the default (PERF.md).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _arow_kernel(K: int, r: float, idx_ref, val_ref, y_ref, w_ref, cov_ref,
                 w_out, cov_out, loss_out):
    B = idx_ref.shape[0]
    D = w_ref.shape[0]
    w_out[:] = w_ref[:]
    cov_out[:] = cov_ref[:]

    def row(b, _):
        y = y_ref[b]
        # gather lanes (K static; sequential like the reference's feature loop)
        score = jnp.float32(0.0)
        var = jnp.float32(0.0)
        for k in range(K):
            i = idx_ref[b, k]
            x = val_ref[b, k]
            safe = jnp.minimum(i, D - 1)
            w = w_out[safe]
            cv = cov_out[safe]
            score = score + w * x
            var = var + cv * x * x
        m = score * y
        beta = 1.0 / (var + r)
        alpha = (1.0 - m) * beta
        upd = (m < 1.0).astype(jnp.float32)
        for k in range(K):
            i = idx_ref[b, k]
            x = val_ref[b, k]
            safe = jnp.minimum(i, D - 1)
            live = jnp.logical_and(i < D, x != 0.0).astype(jnp.float32) * upd
            cv = cov_out[safe] * x
            w_old = w_out[safe]
            c_old = cov_out[safe]
            w_out[safe] = w_old + live * (y * alpha * cv)
            cov_out[safe] = c_old - live * (beta * cv * cv)
        loss_out[b] = jnp.where(m < 0.0, 1.0, 0.0)
        return 0

    jax.lax.fori_loop(0, B, row, 0)


@functools.partial(jax.jit, static_argnames=("r", "interpret"))
def arow_scan_block(indices, values, labels, weights, covars, r: float = 0.1,
                    interpret: bool = False):
    """Run one block of rows sequentially; returns (weights, covars, losses)."""
    from jax.experimental import pallas as pl

    B, K = indices.shape
    D = weights.shape[0]
    kernel = functools.partial(_arow_kernel, K, r)
    w, cov, loss = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((D,), jnp.float32),
            jax.ShapeDtypeStruct((D,), jnp.float32),
            jax.ShapeDtypeStruct((B,), jnp.float32),
        ),
        interpret=interpret,
    )(indices, values, labels, weights, covars)
    return w, cov, loss
