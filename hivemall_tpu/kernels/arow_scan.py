"""Pallas TPU kernel: exact-sequential AROW over a block, weights in VMEM.

The engine's scan mode (core/engine.py) is reference-exact but pays an
HBM-roundtrip gather/scatter per ROW. For models that fit on-chip
(dims <= ~2^20 f32: weights + covariance = 8MB of ~16MB VMEM), the generic
VMEM-resident scan backend (kernels/linear_scan.py) keeps BOTH tables
resident and replays the whole block's rows sequentially in-kernel — the
reference's per-row semantics (ref: classifier/AROWClassifierUDTF.java:95-148)
at on-chip latency.

This module keeps the dedicated AROW entry point as a thin wrapper over that
backend (they were separate implementations before the backend's table
layout was reworked to lower on real TPU Mosaic — scalar VMEM stores, which
the original kernels used, do not compile on hardware).

Padding protocol matches core/batch.py (pad index == dims); padded lanes are
masked in-kernel. Validated against the engine's scan mode both in interpret
mode and compiled on a real TPU chip (tests/test_pallas_kernels.py,
scripts/pallas_tpu_check.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("r", "interpret"))
def arow_scan_block(indices, values, labels, weights, covars, r: float = 0.1,
                    interpret: bool = False):
    """Run one block of rows sequentially; returns (weights, covars, losses)."""
    from ..core.state import init_linear_state
    from ..models.classifier import AROW
    from .linear_scan import pallas_scan_raw

    d = weights.shape[0]
    state = init_linear_state(d, use_covariance=True,
                              initial_weights=jnp.asarray(weights, jnp.float32),
                              initial_covars=jnp.asarray(covars, jnp.float32))
    new_state, losses = pallas_scan_raw(AROW, {"r": r}, state, indices,
                                        values, labels, interpret=interpret)
    return new_state.weights, new_state.covars, losses
