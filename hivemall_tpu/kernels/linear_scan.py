"""Generic Pallas scan backend for the linear-learner engine.

Executes the SAME Rule definitions as core/engine.py (perceptron ... AdaGradRDA,
all regressors) but with every model table VMEM-resident and the block's rows
replayed sequentially in ONE kernel — the reference's per-row semantics
without an HBM round trip per row. Usable when the model fits on-chip
(dims * (2 + n_slots) * 4B within ~12MB of VMEM).

The rule's `update(ctx, hyper)` is traced *inside* the kernel: gathers become
K scalar VMEM loads stacked into a [K] vector, the rule math lowers as vector
ops, and the deltas apply as K scalar stores. Scalar globals (Welford stats)
live in [1]-refs; `derive_w` (dual averaging) is honored lane-wise like the
engine's scan mode.

Opt-in: `fit_linear(..., options="-pallas")` routes scan-mode training here.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.engine import Rule, RowContext
from ..core.state import LinearState


def _make_kernel(rule: Rule, hyper: dict, K: int, slot_names: Tuple[str, ...],
                 global_names: Tuple[str, ...]):
    use_cov = rule.use_covariance
    n_slots = len(slot_names)
    n_globals = len(global_names)

    def kernel(*refs):
        # layout: idx, val, y, step0, w_in, [cov_in], *slots_in, [globals_in],
        #         w_out, [cov_out], *slots_out, [globals_out], loss_out
        pos = 0
        idx_ref = refs[pos]; pos += 1
        val_ref = refs[pos]; pos += 1
        y_ref = refs[pos]; pos += 1
        step_ref = refs[pos]; pos += 1
        w_in = refs[pos]; pos += 1
        cov_in = None
        if use_cov:
            cov_in = refs[pos]; pos += 1
        slots_in = refs[pos : pos + n_slots]; pos += n_slots
        glob_in = refs[pos] if n_globals else None
        pos += 1 if n_globals else 0
        w_out = refs[pos]; pos += 1
        cov_out = None
        if use_cov:
            cov_out = refs[pos]; pos += 1
        slots_out = refs[pos : pos + n_slots]; pos += n_slots
        glob_out = refs[pos] if n_globals else None
        pos += 1 if n_globals else 0
        loss_out = refs[pos]

        B = idx_ref.shape[0]
        D = w_in.shape[0]
        w_out[:] = w_in[:]
        if use_cov:
            cov_out[:] = cov_in[:]
        for s in range(n_slots):
            slots_out[s][:] = slots_in[s][:]
        if n_globals:
            glob_out[:] = glob_in[:]

        def row(b, _):
            y = y_ref[b]
            t = (step_ref[0] + b + 1).astype(jnp.float32)
            gl = {g: glob_out[gi] for gi, g in enumerate(global_names)}
            if rule.pre_row is not None:
                gl = rule.pre_row(gl, y)
                for gi, g in enumerate(global_names):
                    glob_out[gi] = gl[g]
            safe = [jnp.minimum(idx_ref[b, k], D - 1) for k in range(K)]
            live = [jnp.logical_and(idx_ref[b, k] < D,
                                    jnp.ones((), jnp.bool_)) for k in range(K)]
            livef = jnp.stack([l.astype(jnp.float32) for l in live])
            val = jnp.stack([val_ref[b, k] for k in range(K)]) * livef
            w = jnp.stack([w_out[safe[k]] for k in range(K)]) * livef
            cov = None
            variance = jnp.float32(0.0)
            if use_cov:
                cov = jnp.stack([cov_out[safe[k]] for k in range(K)])
                cov = jnp.where(livef > 0, cov, 1.0)
                variance = jnp.sum(cov * val * val)
            sl = {}
            for s, name in enumerate(slot_names):
                sl[name] = jnp.stack([slots_out[s][safe[k]] for k in range(K)]) * livef
            score = jnp.sum(w * val)
            sq_norm = jnp.sum(val * val)
            ctx = RowContext(w, cov, sl, val, y, score, sq_norm, variance, t, gl)
            out = rule.update(ctx, hyper)
            dw = out.dw * livef
            if rule.derive_w is not None:
                sl_new = {k: ctx.slots[k] + out.dslots.get(k, 0.0) for k in sl}
                w_new = rule.derive_w(sl_new, t, hyper)
                w_new = jnp.where(out.updated, w_new, ctx.w)
                for k in range(K):
                    cur = w_out[safe[k]]
                    w_out[safe[k]] = jnp.where(live[k], w_new[k], cur)
            else:
                for k in range(K):
                    w_out[safe[k]] = w_out[safe[k]] + dw[k]
            if use_cov and out.dcov is not None:
                dcov = out.dcov * livef
                for k in range(K):
                    cov_out[safe[k]] = cov_out[safe[k]] + dcov[k]
            for s, name in enumerate(slot_names):
                if name in out.dslots:
                    d = out.dslots[name] * livef
                    for k in range(K):
                        slots_out[s][safe[k]] = slots_out[s][safe[k]] + d[k]
            loss_out[b] = out.loss
            return 0

        jax.lax.fori_loop(0, B, row, 0)

    return kernel


def make_pallas_scan_step(rule: Rule, hyper: dict, interpret: bool = False):
    """step(state, indices, values, labels) -> (state, loss_sum), API-equal to
    core.engine.make_train_step(mode='scan')."""
    from jax.experimental import pallas as pl

    slot_names = tuple(sorted(rule.slot_names))
    global_names = tuple(sorted(rule.global_names))

    @jax.jit
    def step(state: LinearState, indices, values, labels):
        B, K = indices.shape
        D = state.weights.shape[0]
        kernel = _make_kernel(rule, hyper, K, slot_names, global_names)
        outs_shape = [jax.ShapeDtypeStruct((D,), jnp.float32)]
        if rule.use_covariance:
            outs_shape.append(jax.ShapeDtypeStruct((D,), jnp.float32))
        outs_shape += [jax.ShapeDtypeStruct((D,), jnp.float32)] * len(slot_names)
        if global_names:
            outs_shape.append(jax.ShapeDtypeStruct((len(global_names),), jnp.float32))
        outs_shape.append(jax.ShapeDtypeStruct((B,), jnp.float32))

        args = [indices, values, labels,
                jnp.reshape(state.step, (1,)).astype(jnp.int32),
                state.weights.astype(jnp.float32)]
        if rule.use_covariance:
            args.append(state.covars.astype(jnp.float32))
        args += [state.slots[s] for s in slot_names]
        if global_names:
            args.append(jnp.stack([state.globals[g] for g in global_names]))

        outs = pl.pallas_call(kernel, out_shape=tuple(outs_shape),
                              interpret=interpret)(*args)
        pos = 0
        w = outs[pos]; pos += 1
        cov = None
        if rule.use_covariance:
            cov = outs[pos]; pos += 1
        slots = {s: outs[pos + i] for i, s in enumerate(slot_names)}
        pos += len(slot_names)
        globals_ = dict(state.globals)
        if global_names:
            gvec = outs[pos]; pos += 1
            globals_ = {g: gvec[i] for i, g in enumerate(global_names)}
        losses = outs[pos]
        # touched: any lane of any row (computed outside the kernel — one
        # cheap scatter; the kernel itself doesn't track it)
        touched = state.touched.at[indices].max(
            jnp.ones_like(indices, dtype=jnp.int8), mode="drop")
        new_state = state.replace(weights=w, covars=cov, slots=slots,
                                  touched=touched, globals=globals_,
                                  step=state.step + B)
        return new_state, jnp.sum(losses)

    return step
