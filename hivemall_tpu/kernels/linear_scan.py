"""Generic Pallas scan backend for the linear-learner engine.

Executes the SAME Rule definitions as core/engine.py (perceptron ... AdaGradRDA,
all regressors) but with every model table VMEM-resident and the block's rows
replayed sequentially in ONE kernel — the reference's per-row semantics
(ref: BinaryOnlineClassifierUDTF.java:111-247) without an HBM round trip per
row. Usable when the model fits on-chip (dims * (2 + n_slots) * 4B within
~12MB of VMEM).

Hardware layout (lowers on real TPU Mosaic — scalar VMEM stores do not):
- model tables are reshaped to [D/128, 128]; a feature id becomes
  (row = id//128, lane = id%128). Gather = dynamic-slice the row + one-hot
  lane reduce; scatter = read-modify-write the row with a one-hot mask.
- indices/values/labels live in SMEM so feature ids are readable as scalars
  for the dynamic row slices. SMEM is ~1MB, so large blocks are chunked
  *outside* the kernel: `lax.scan` threads the tables through one grid-less
  pallas call per ~512-row chunk (tables ride HBM<->VMEM once per chunk).
- scalar globals (Welford stats) live in SMEM refs; `derive_w` (dual
  averaging) is honored lane-wise like the engine's scan mode.

The rule's `update(ctx, hyper)` is traced *inside* the kernel. Validated
against the engine's scan mode in interpret mode (tests/test_pallas_kernels.py)
and compiled on a real v5e chip (scripts/pallas_tpu_check.py).

Opt-in: `fit_linear(..., options="-pallas")` routes scan-mode training here.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.batch import pad_rows_to_multiple
from ..core.engine import Rule, RowContext
from ..core.state import LinearState

LANES = 128


def _make_kernel(rule: Rule, hyper: dict, K: int, D: int, chunk: int,
                 slot_names: Tuple[str, ...], global_names: Tuple[str, ...]):
    use_cov = rule.use_covariance
    n_slots = len(slot_names)
    n_globals = len(global_names)

    def kernel(*refs):
        from jax.experimental import pallas as pl

        # layout: idx, val, y, meta(step0, live_rows), w_in, [cov_in],
        #         *slots_in, [globals_in], w_out, [cov_out], *slots_out,
        #         [globals_out], loss_out
        pos = 0
        idx_ref = refs[pos]; pos += 1     # SMEM [chunk, K] i32
        val_ref = refs[pos]; pos += 1     # SMEM [chunk, K] f32
        y_ref = refs[pos]; pos += 1       # SMEM [chunk, 1] f32
        meta_ref = refs[pos]; pos += 1    # SMEM [2] i32
        w_in = refs[pos]; pos += 1        # VMEM [D/128, 128]
        cov_in = None
        if use_cov:
            cov_in = refs[pos]; pos += 1
        slots_in = refs[pos : pos + n_slots]; pos += n_slots
        glob_in = refs[pos] if n_globals else None  # SMEM [n_globals, 1]
        pos += 1 if n_globals else 0
        w_out = refs[pos]; pos += 1
        cov_out = None
        if use_cov:
            cov_out = refs[pos]; pos += 1
        slots_out = refs[pos : pos + n_slots]; pos += n_slots
        glob_out = refs[pos] if n_globals else None
        pos += 1 if n_globals else 0
        loss_out = refs[pos]              # SMEM [chunk, 1] f32

        w_out[:, :] = w_in[:, :]
        if use_cov:
            cov_out[:, :] = cov_in[:, :]
        for s in range(n_slots):
            slots_out[s][:, :] = slots_in[s][:, :]
        # SMEM refs only allow scalar loads; copy element-wise
        for gi in range(n_globals):
            glob_out[gi, 0] = glob_in[gi, 0]

        step0 = meta_ref[0]
        live_rows = meta_ref[1]
        iota = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)

        def row(b, _):
            row_live = (b < live_rows).astype(jnp.float32)
            y = y_ref[b, 0]
            t = (step0 + b + 1).astype(jnp.float32)

            gl = {}
            if n_globals:
                gl = {g: glob_out[gi, 0] for gi, g in enumerate(global_names)}
                if rule.pre_row is not None:
                    gl_new = rule.pre_row(dict(gl), y)
                    gl = {g: jnp.where(row_live > 0, gl_new[g], gl[g])
                          for g in global_names}
                    for gi, g in enumerate(global_names):
                        glob_out[gi, 0] = gl[g]

            rows = []
            ohs = []       # [1, LANES] one-hot lane masks
            livefs = []
            vals = []
            for k in range(K):
                fidx = idx_ref[b, k]
                live = jnp.logical_and(fidx >= 0, fidx < D)
                livef = live.astype(jnp.float32) * row_live
                sidx = jnp.where(live, fidx, 0)
                rows.append(sidx // LANES)
                ohs.append((iota == (sidx % LANES)).astype(jnp.float32))
                livefs.append(livef)
                vals.append(val_ref[b, k] * livef)

            def lane_gather(table, k, fill=0.0):
                v = jnp.sum(table[pl.ds(rows[k], 1), :] * ohs[k])
                if fill == 0.0:
                    return v * livefs[k]
                return jnp.where(livefs[k] > 0, v, fill)

            w = jnp.stack([lane_gather(w_out, k) for k in range(K)])
            val = jnp.stack(vals)
            cov = None
            variance = jnp.float32(0.0)
            if use_cov:
                cov = jnp.stack([lane_gather(cov_out, k, fill=1.0)
                                 for k in range(K)])
                variance = jnp.sum(cov * val * val)
            sl = {}
            for s, name in enumerate(slot_names):
                sl[name] = jnp.stack([lane_gather(slots_out[s], k)
                                      for k in range(K)])
            score = jnp.sum(w * val)
            sq_norm = jnp.sum(val * val)
            ctx = RowContext(w, cov, sl, val, y, score, sq_norm, variance, t, gl)
            out = rule.update(ctx, hyper)

            def lane_add(table, k, delta):
                r = table[pl.ds(rows[k], 1), :]
                table[pl.ds(rows[k], 1), :] = r + (delta * livefs[k]) * ohs[k]

            def lane_set(table, k, value, gate):
                r = table[pl.ds(rows[k], 1), :]
                m = ohs[k] * (gate * livefs[k])
                table[pl.ds(rows[k], 1), :] = r * (1.0 - m) + value * m

            if rule.derive_w is not None:
                sl_new = {n: ctx.slots[n] + out.dslots.get(n, 0.0) for n in sl}
                w_new = rule.derive_w(sl_new, t, hyper)
                w_new = jnp.where(out.updated, w_new, ctx.w)
                gate = out.updated.astype(jnp.float32)
                for k in range(K):
                    lane_set(w_out, k, w_new[k], gate)
            else:
                for k in range(K):
                    lane_add(w_out, k, out.dw[k])
            if use_cov and out.dcov is not None:
                for k in range(K):
                    lane_add(cov_out, k, out.dcov[k])
            for s, name in enumerate(slot_names):
                if name in out.dslots:
                    for k in range(K):
                        lane_add(slots_out[s], k, out.dslots[name][k])
            loss_out[b, 0] = out.loss * row_live
            return 0

        jax.lax.fori_loop(0, chunk, row, 0)

    return kernel


def _table_2d(flat: jnp.ndarray, d_pad: int) -> jnp.ndarray:
    d = flat.shape[0]
    if d_pad != d:
        flat = jnp.concatenate([flat, jnp.zeros((d_pad - d,), flat.dtype)])
    return flat.reshape(d_pad // LANES, LANES)


def _pick_chunk(b: int, k: int) -> int:
    # bound SMEM bytes: chunk*K*(4+4) <= ~32KB. SMEM is nominally 1MB but
    # Mosaic's own reservations leave well under 10% headroom (measured:
    # chunk*K=8192 overflowed by 1.6KB on v5e). Floor of 1, not more — a
    # higher floor would break the bound for very wide rows (K > 4096 still
    # cannot fit a single row's lanes; that regime doesn't fit the
    # VMEM-resident model path anyway).
    return max(1, min(b, 4096 // max(1, k)))


def pallas_scan_raw(rule: Rule, hyper: dict, state: LinearState,
                    indices, values, labels, interpret: bool = False):
    """Run one block through the VMEM-resident scan kernel.

    Returns (new_state, per_row_losses). API building block for
    make_pallas_scan_step and the dedicated AROW entry point.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    slot_names = tuple(sorted(rule.slot_names))
    global_names = tuple(sorted(rule.global_names))
    use_cov = rule.use_covariance

    indices = jnp.asarray(indices, jnp.int32)
    values = jnp.asarray(values, jnp.float32)
    labels = jnp.asarray(labels, jnp.float32)
    B, K = indices.shape
    D = state.weights.shape[0]
    d_pad = (D + LANES - 1) // LANES * LANES
    n_rows = d_pad // LANES
    chunk = _pick_chunk(B, K)
    indices, values, labels = pad_rows_to_multiple(indices, values, labels,
                                                   chunk, D)
    n_chunks = indices.shape[0] // chunk

    kernel = _make_kernel(rule, hyper, K, D, chunk, slot_names, global_names)

    smem_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    # tables are whole-array VMEM refs
    vmem_spec = pl.BlockSpec((n_rows, LANES), lambda: (0, 0))

    n_tables = 1 + (1 if use_cov else 0) + len(slot_names)
    in_specs = [smem_spec, smem_spec, smem_spec, smem_spec] + \
               [vmem_spec] * n_tables + ([smem_spec] if global_names else [])
    out_specs = [vmem_spec] * n_tables + \
                ([smem_spec] if global_names else []) + [smem_spec]
    out_shape = [jax.ShapeDtypeStruct((n_rows, LANES), jnp.float32)] * n_tables
    if global_names:
        out_shape.append(
            jax.ShapeDtypeStruct((len(global_names), 1), jnp.float32))
    out_shape.append(jax.ShapeDtypeStruct((chunk, 1), jnp.float32))
    # alias table (and globals) inputs to outputs: in-place update chunk to chunk
    aliases = {4 + t: t for t in range(n_tables)}
    if global_names:
        aliases[4 + n_tables] = n_tables

    call = pl.pallas_call(kernel, in_specs=in_specs, out_specs=out_specs,
                          out_shape=out_shape,
                          input_output_aliases=aliases,
                          interpret=interpret)

    tables0 = [_table_2d(state.weights.astype(jnp.float32), d_pad)]
    if use_cov:
        tables0.append(_table_2d(state.covars.astype(jnp.float32), d_pad))
    for s in slot_names:
        tables0.append(_table_2d(state.slots[s].astype(jnp.float32), d_pad))
    gvec0 = (jnp.stack([state.globals[g].astype(jnp.float32)
                        for g in global_names]).reshape(-1, 1)
             if global_names else None)

    idx3 = indices.reshape(n_chunks, chunk, K)
    val3 = values.reshape(n_chunks, chunk, K)
    y3 = labels.reshape(n_chunks, chunk, 1)
    step0 = jnp.asarray(state.step, jnp.int32)
    b_live = jnp.minimum(
        jnp.maximum(B - jnp.arange(n_chunks, dtype=jnp.int32) * chunk, 0),
        chunk)

    def body(carry, xs):
        tables, gvec = carry
        ci, cv, cy, coff, clive = xs
        meta = jnp.stack([step0 + coff * chunk, clive])
        args = [ci, cv, cy, meta] + list(tables) + \
               ([gvec] if gvec is not None else [])
        outs = call(*args)
        new_tables = list(outs[:n_tables])
        new_gvec = outs[n_tables] if gvec is not None else None
        losses = outs[-1]
        return (new_tables, new_gvec), losses.reshape(-1)

    (tables, gvec), losses = jax.lax.scan(
        body, (tables0, gvec0),
        (idx3, val3, y3, jnp.arange(n_chunks, dtype=jnp.int32), b_live))
    losses = losses.reshape(-1)[:B]

    pos = 0
    w = tables[pos].reshape(-1)[:D]; pos += 1
    cov = None
    if use_cov:
        cov = tables[pos].reshape(-1)[:D]; pos += 1
    slots = {}
    for s in slot_names:
        slots[s] = tables[pos].reshape(-1)[:D]; pos += 1
    globals_ = dict(state.globals)
    if global_names:
        gflat = gvec.reshape(-1)
        globals_ = {g: gflat[gi] for gi, g in enumerate(global_names)}

    # touched: any live lane of any row (one cheap scatter outside the kernel)
    touched = state.touched.at[indices[:B]].max(
        jnp.ones((B, K), dtype=jnp.int8), mode="drop")
    new_state = state.replace(weights=w, covars=cov, slots=slots,
                              touched=touched, globals=globals_,
                              step=state.step + B)
    return new_state, losses


def make_pallas_scan_step(rule: Rule, hyper: dict, interpret: bool = False):
    """step(state, indices, values, labels) -> (state, loss_sum), API-equal to
    core.engine.make_train_step(mode='scan')."""

    @jax.jit
    def step(state: LinearState, indices, values, labels):
        new_state, losses = pallas_scan_raw(rule, hyper, state, indices,
                                            values, labels,
                                            interpret=interpret)
        return new_state, jnp.sum(losses)

    return step
