"""Multi-model registry with atomic hot-swap + the /predict HTTP endpoint.

The reference swaps models by overwriting a Hive table between batch scoring
runs; an online server must swap under live load. The registry keeps one
``(engine, batcher)`` pair per model name; ``deploy()`` builds and WARMS the
new version off to the side, then publishes it with one dict assignment
(atomic under the GIL — readers see either the old or the new entry, never
a partial one) and drains the old batcher so every request admitted before
the swap still completes: an in-flight v1 -> v2 swap fails zero requests
(tests/test_serving_server.py pins this).

HTTP surface (layered on runtime/metrics_http.py — same process, one port):

- ``POST /predict``  body ``{"model": name?, "instances": [...]}`` ->
  ``{"model", "version", "predictions": [...]}``; 503 + Retry-After under
  backpressure (batcher QueueFull), 404 unknown model, 400 bad payload;
- ``GET /models``    registry listing (name, version, family, counters);
- ``GET /metrics`` / ``GET /healthz`` / ``GET /trace?n=`` — inherited from
  metrics_http: the serving latency/occupancy/queue histograms (with
  trace exemplars under ``?exemplars=1``) and the last n request traces
  as Chrome/Perfetto JSON (docs/observability.md).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Dict, Optional

import numpy as np

from ..runtime import metrics_http
from ..runtime.metrics import REGISTRY
from ..runtime.tracing import TRACER
from .batcher import BatcherClosed, DynamicBatcher, QueueFull
from .engine import ServingEngine


class ModelEntry:
    """One deployed model version: engine + its batching front."""

    def __init__(self, name: str, version: str, engine: ServingEngine,
                 batcher: DynamicBatcher) -> None:
        self.name = name
        self.version = version
        self.engine = engine
        self.batcher = batcher
        self.deployed_unix = time.time()

    def describe(self) -> dict:
        return {
            "name": self.name,
            "version": self.version,
            "family": self.engine.family,
            "deployed_unix": self.deployed_unix,
            "max_batch": self.engine.max_batch,
            "max_width": self.engine.max_width,
            # the precision surface: what dtype the tables serve at and the
            # resident bytes a request's gathers read (bf16/int8 artifacts
            # shrink this 2-4x; also gauges serving.<name>.table_bytes /
            # .weights_bits on /metrics)
            "weights_dtype": self.engine.weights_dtype,
            "table_bytes": self.engine.table_bytes,
            # where those bytes live: single-device, replicated, or
            # NamedSharding-striped over a (batch, model) mesh — including
            # mesh shape, stripe grids and per-device resident bytes
            # (docs/serving.md "Sharded serving")
            "placement": self.engine.placement,
        }


class ModelRegistry:
    """name -> ModelEntry with atomic version swap.

    Reads (`get`) are lock-free dict lookups; writes serialize on a lock.
    A handler thread holds the ENTRY it resolved, not the name, so a swap
    never invalidates an in-flight request — the old batcher drains.
    """

    def __init__(self, *, max_batch: int = 256, max_delay_ms: float = 2.0,
                 max_queue_rows: int = 4096, warmup: bool = True,
                 engine_kwargs: Optional[dict] = None) -> None:
        self._entries: Dict[str, ModelEntry] = {}
        self._lock = threading.Lock()
        self.max_batch = max_batch
        self.max_delay_ms = max_delay_ms
        self.max_queue_rows = max_queue_rows
        self.warmup = warmup
        self.engine_kwargs = dict(engine_kwargs or {})
        self._swaps = REGISTRY.counter("serving", "registry.swaps")

    def deploy(self, name: str, source, version: Optional[str] = None,
               **engine_overrides) -> ModelEntry:
        """Deploy `source` (artifact dir path, Artifact, or trained model)
        as `name`; replaces any current version atomically AFTER the new
        engine is fully warmed (no cold-cache window under load). The
        version defaults to the artifact's manifest version (so /predict
        responses correlate with the frozen directory, rollbacks included);
        bare model objects auto-increment."""
        from .artifact import Artifact, load as load_artifact

        if isinstance(source, str):
            source = load_artifact(source)
        if version is None and isinstance(source, Artifact):
            version = source.manifest.get("version")
        kw = dict(self.engine_kwargs)
        kw.update(engine_overrides)
        kw.setdefault("max_batch", self.max_batch)
        engine = ServingEngine(source, name=name, **kw)
        if version is None:
            with self._lock:
                old = self._entries.get(name)
            version = str(int(old.version) + 1) if old is not None \
                and old.version.isdigit() else "1"
        if self.warmup:
            engine.warmup()
        batcher = DynamicBatcher(
            engine.predict, max_batch=engine.max_batch,
            max_delay_ms=self.max_delay_ms,
            max_queue_rows=self.max_queue_rows, name=name)
        entry = ModelEntry(name, str(version), engine, batcher)
        with self._lock:
            old = self._entries.get(name)
            self._entries[name] = entry  # the atomic publish
        if old is not None:
            self._swaps.increment()
            # outside the lock: draining can take max_delay + a batch
            old.batcher.close(drain=True)
        REGISTRY.set_gauge(f"serving.{name}.deployed_version",
                           float(version) if str(version).isdigit() else 0.0)
        return entry

    def get(self, name: Optional[str] = None) -> Optional[ModelEntry]:
        """Resolve a model by name; with one deployed model, name may be
        omitted (the single-model convenience every demo uses)."""
        if name is not None:
            # designed lock-free read: a single dict .get() is atomic under
            # the GIL and deploy() publishes entries with one assignment —
            # readers see the old or new entry, never a partial one
            return self._entries.get(name)  # graftcheck: disable=G012 (reviewed lock-free read)
        with self._lock:  # a concurrent first deploy mutates the dict
            entries = list(self._entries.values())
        if len(entries) == 1:
            return entries[0]
        return None

    # each BatcherClosed means a full deploy landed between resolve and
    # submit; needing this many consecutive swaps inside one submit window
    # is not a reachable steady state
    _SWAP_RETRIES = 8

    def submit(self, name: Optional[str], instances):
        """Resolve + enqueue, retrying across hot swaps: a caller that
        resolved the OLD entry right before deploy() published the new one
        sees BatcherClosed from the draining batcher — re-resolving gets
        the new version, so a swap fails zero requests. Returns
        (entry, future); (None, None) means the name is genuinely unknown
        (never deployed, or undeployed). QueueFull propagates (backpressure
        is the caller's 503); BatcherClosed escapes only after
        _SWAP_RETRIES consecutive swap collisions (retryable, also 503)."""
        for _ in range(self._SWAP_RETRIES):
            entry = self.get(name)
            if entry is None:
                return None, None
            try:
                return entry, entry.batcher.submit(instances)
            except BatcherClosed:
                continue
        raise BatcherClosed(
            f"model {name!r}: {self._SWAP_RETRIES} consecutive version "
            f"swaps collided with this submit — retry")

    def undeploy(self, name: str) -> bool:
        with self._lock:
            entry = self._entries.pop(name, None)
        if entry is None:
            return False
        entry.batcher.close(drain=True)
        return True

    def list_models(self):
        with self._lock:  # a first deploy of a new name mutates the dict
            entries = list(self._entries.values())
        return [e.describe() for e in entries]

    def shutdown(self) -> None:
        with self._lock:
            entries = list(self._entries.values())
            self._entries = {}
        for e in entries:
            e.batcher.close(drain=True)


class _ServingHandler(metrics_http._Handler):
    """Extends the metrics handler with /predict and /models. The registry
    rides on the server object (see serve())."""

    predict_timeout = 30.0

    def _send_json(self, code: int, payload: dict, extra_headers=()) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        for k, v in extra_headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - http.server API
        if self.path.split("?")[0] == "/models":
            self._send_json(200, {"models": self.server.registry.list_models()})
            return
        super().do_GET()

    def do_POST(self):  # noqa: N802 - http.server API
        if self.path.split("?")[0] != "/predict":
            self._send_json(404, {"error": "not found"})
            return
        # the request's ROOT span: HTTP parse, queue wait, batched device
        # dispatch and the response write all land under it; the latency
        # histogram observation carries its trace_id as an exemplar
        with TRACER.span("server.predict") as root:
            with TRACER.span("server.parse"):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    instances = payload["instances"]
                    if not isinstance(instances, list):
                        raise TypeError("instances must be a list")
                except (KeyError, TypeError, ValueError) as e:
                    self._send_json(400, {"error": f"bad request: {e}"})
                    root.set(status=400)
                    return
            root.set(instances=len(instances),
                     model=payload.get("model") or "")
            t0 = time.perf_counter()
            try:
                # registry.submit retries across a hot swap, so a v1->v2
                # deploy never fails a request; only an unknown name /
                # undeploy 404s
                entry, future = self.server.registry.submit(
                    payload.get("model"), instances)
                if entry is None:
                    self._send_json(404,
                                    {"error": f"unknown model "
                                              f"{payload.get('model')!r}"})
                    root.set(status=404)
                    return
                preds = future.result(timeout=self.predict_timeout)
            except (QueueFull, BatcherClosed) as e:
                self._send_json(503, {"error": str(e)},
                                extra_headers=(("Retry-After", "1"),))
                root.set(status=503)
                return
            except Exception as e:  # scoring bug — surface, don't hang
                self._send_json(500, {"error": f"{type(e).__name__}: {e}"})
                root.set(status=500)
                return
            self.server.latency.observe(
                time.perf_counter() - t0,
                trace_id=TRACER.exemplar_id(root))
            root.set(status=200, version=entry.version)
            self._send_json(200, {
                "model": entry.name,
                "version": entry.version,
                "predictions": [_jsonable(p) for p in preds],
            })


def _jsonable(p):
    if isinstance(p, (np.generic,)):
        return p.item()
    if isinstance(p, np.ndarray):
        return p.tolist()
    return p


def serve(registry: ModelRegistry, port: int = 0, host: str = "127.0.0.1"
          ) -> ThreadingHTTPServer:
    """Start the serving endpoint on a daemon thread (stdlib only, the
    serve_metrics recipe); ``server.server_address[1]`` is the bound port.
    The same server answers /predict, /models, /metrics and /healthz."""
    server = ThreadingHTTPServer((host, port), _ServingHandler)
    server.registry = registry
    server.latency = REGISTRY.histogram("serving.http.latency_seconds")
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="hivemall-tpu-serving")
    t.start()
    return server
