"""Multi-model registry with atomic hot-swap + the /predict HTTP endpoint.

The reference swaps models by overwriting a Hive table between batch scoring
runs; an online server must swap under live load. The registry keeps one
``(engine, batcher)`` pair per model name; ``deploy()`` builds and WARMS the
new version off to the side, then publishes it with one dict assignment
(atomic under the GIL — readers see either the old or the new entry, never
a partial one) and drains the old batcher so every request admitted before
the swap still completes: an in-flight v1 -> v2 swap fails zero requests
(tests/test_serving_server.py pins this).

HTTP surface (layered on runtime/metrics_http.py — same process, one port):

- ``POST /predict``  body ``{"model": name?, "instances": [...]}`` ->
  ``{"model", "version", "predictions": [...]}``. Overload contract
  (docs/serving.md "Overload behavior"): requests may carry an
  ``x-priority`` header (high/normal/low, or body key ``priority``) and
  an ``x-deadline-ms`` budget (or body key ``deadline_ms``); a request
  that expires in the queue gets **504** (``reason: deadline``), an
  over-quota or shed request gets **503 + Retry-After** priced from the
  live drain-rate estimate (``reason: quota`` / ``shed``); 404 unknown
  model, 400 bad payload. A client ``traceparent`` header (W3C) is
  adopted as the request trace's root parent and echoed back on every
  response; malformed headers fall back to a fresh trace;
- ``POST /topk``     body ``{"model": name?, "queries": [...], "k"?,
  "probe"?}`` -> ``{"model", "version", "k", "results": [{"items",
  "scores"}, ...]}``. The top-K retrieval surface (serving/retrieval.py)
  — deploy() must have been given ``retrieval=`` options for the model
  (400 otherwise). Same priority/deadline/traceparent contract and error
  mapping as /predict, through the model's SEPARATE retrieval batcher;
- ``GET /models``    registry listing (name, version, family, admission
  and placement state, counters);
- ``GET /healthz``   overload-aware: reports ``degraded`` (still 200 —
  alive, shedding predictably) when any model's queue passes the depth
  threshold OR any registered SLO is paging on its burn rate
  (runtime/slo.py — the ``slo`` block carries the detail), BEFORE the
  process ever looks dead;
- ``GET /slo`` / ``GET /debug/bundle`` — inherited from metrics_http:
  per-objective multi-window burn rates + alert states, and the
  flight-recorder snapshot (models, metrics + time-series history,
  traces, recompile attributions) in one JSON document
  (docs/observability.md "SLOs & burn rates", "Flight recorder");
- ``GET /metrics`` / ``GET /trace?n=`` — inherited from metrics_http:
  the serving latency/occupancy/queue histograms, per-priority
  shed/expiry/quota counters and live controller state (with trace
  exemplars under ``?exemplars=1``), and the last n request traces as
  Chrome/Perfetto JSON (docs/observability.md).
"""

from __future__ import annotations

import json
import math
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Dict, Optional

import numpy as np

from ..runtime import metrics_http
from ..runtime.metrics import REGISTRY
from ..runtime.tracing import TRACER
from .admission import (PRIORITY_NAMES, DeadlineExpired, priority_class,
                        priority_name)
from .batcher import BatcherClosed, DynamicBatcher, QueueFull
from .engine import ServingEngine


class ModelEntry:
    """One deployed model version: engine + its batching front."""

    def __init__(self, name: str, version: str, engine: ServingEngine,
                 batcher: DynamicBatcher,
                 lineage: Optional[list] = None, cache=None,
                 retrieval_engine=None,
                 retrieval_batcher: Optional[DynamicBatcher] = None) -> None:
        self.name = name
        self.version = version
        self.engine = engine
        self.batcher = batcher
        # the hot-row score cache this entry's batcher fronts with —
        # owned by the REGISTRY and shared across this name's versions
        # (the version lives in the key; serving/cache.py). None = off.
        self.cache = cache
        # the top-K retrieval surface (serving/retrieval.py): present only
        # when deploy() was given ``retrieval=`` options and the family is
        # MF/FM. Its batcher is separate from the pointwise one — a /topk
        # flood cannot starve /predict of dispatch slots, and vice versa.
        self.retrieval_engine = retrieval_engine
        self.retrieval_batcher = retrieval_batcher
        self.deployed_unix = time.time()
        # version lineage: the publisher's recent gate decisions (publish /
        # refusal / rollback records — hivemall_tpu/pipeline) surfaced on
        # /models, so "why is v7 serving and where did v6 go" is answerable
        # from the serving endpoint alone. Immutable after deploy.
        self.lineage = list(lineage or [])

    def describe(self) -> dict:
        return {
            "name": self.name,
            "version": self.version,
            "family": self.engine.family,
            "deployed_unix": self.deployed_unix,
            "max_batch": self.engine.max_batch,
            "max_width": self.engine.max_width,
            # the precision surface: what dtype the tables serve at and the
            # resident bytes a request's gathers read (bf16/int8 artifacts
            # shrink this 2-4x; also gauges serving.<name>.table_bytes /
            # .weights_bits on /metrics)
            "weights_dtype": self.engine.weights_dtype,
            "table_bytes": self.engine.table_bytes,
            # where those bytes live: single-device, replicated, or
            # NamedSharding-striped over a (batch, model) mesh — including
            # mesh shape, stripe grids and per-device resident bytes
            # (docs/serving.md "Sharded serving")
            "placement": self.engine.placement,
            # the overload surface: queue depth per priority class,
            # quota fractions, live AIMD controller window, drain-rate
            # estimate and shed/expiry/quota-reject counters
            "admission": self.batcher.overload_state(),
            # the hot-row cache surface: budget, resident bytes, hit/miss/
            # coalesced/evicted counters and the live hit ratio
            # (docs/serving.md "Score caching & coalescing")
            "cache": self.cache.stats() if self.cache is not None
            else {"enabled": False},
            # publisher lineage: recent gate decisions for this model's
            # version sequence (empty for hand-deployed models)
            "lineage": [dict(d) for d in self.lineage],
            # the top-K retrieval surface: catalog size, block/K geometry,
            # sharding and LSH index state (docs/serving.md "Top-K
            # retrieval"). {"enabled": False} = /topk 400s for this model.
            "retrieval": {"enabled": True,
                          **self.retrieval_engine.describe()}
            if self.retrieval_engine is not None else {"enabled": False},
        }


class ModelRegistry:
    """name -> ModelEntry with atomic version swap.

    Reads (`get`) are lock-free dict lookups; writes serialize on a lock.
    A handler thread holds the ENTRY it resolved, not the name, so a swap
    never invalidates an in-flight request — the old batcher drains.
    """

    # serving-grade admission defaults: every model's batcher gets the
    # full overload posture unless a deploy overrides it — low-priority
    # work quota-sheds at 60% queue fill, normal at 85%, high keeps
    # headroom to the cap (docs/serving.md "Overload behavior"); adaptive
    # caps stay equal to the bases (off) unless configured, so light-load
    # latency semantics are identical to the fixed-window batcher.
    DEFAULT_QUOTA_FRACS = (1.0, 0.85, 0.6)

    def __init__(self, *, max_batch: int = 256, max_delay_ms: float = 2.0,
                 max_queue_rows: int = 4096, warmup: bool = True,
                 engine_kwargs: Optional[dict] = None,
                 max_delay_ms_cap: Optional[float] = None,
                 max_batch_cap: Optional[int] = None,
                 priority_quota_fracs: Optional[tuple] = None,
                 starvation_limit: int = 8,
                 express_high: bool = True,
                 degraded_depth_fraction: float = 0.75,
                 score_cache_bytes: Optional[int] = None) -> None:
        self._entries: Dict[str, ModelEntry] = {}
        # hot-row score caches, one per model NAME, shared across that
        # name's versions (the version is in every key, so a hot-swap
        # invalidates atomically and old-version entries age out of the
        # byte budget — serving/cache.py). ``score_cache_bytes`` is the
        # registry-wide default budget; None/0 leaves caching OFF (the
        # conservative default: admission counters then mean exactly what
        # PR 10 pinned), a deploy can override per model.
        self._caches: Dict[str, object] = {}
        self.score_cache_bytes = score_cache_bytes
        self._lock = threading.Lock()
        self.max_batch = max_batch
        self.max_delay_ms = max_delay_ms
        self.max_queue_rows = max_queue_rows
        self.warmup = warmup
        self.engine_kwargs = dict(engine_kwargs or {})
        self.max_delay_ms_cap = max_delay_ms_cap
        self.max_batch_cap = max_batch_cap
        self.priority_quota_fracs = tuple(
            priority_quota_fracs or self.DEFAULT_QUOTA_FRACS)
        self.starvation_limit = starvation_limit
        # high-priority requests get a dedicated drain lane by default —
        # they never wait behind an in-flight lower-class dispatch
        # (serving/batcher.py "express lane")
        self.express_high = express_high
        # /healthz flips to "degraded" when any model's queue fills past
        # this fraction — overload is reported while the process is still
        # very much alive and shedding predictably
        self.degraded_depth_fraction = float(degraded_depth_fraction)
        self._swaps = REGISTRY.counter("serving", "registry.swaps")

    def deploy(self, name: str, source, version: Optional[str] = None,
               batcher_overrides: Optional[dict] = None,
               lineage: Optional[list] = None,
               score_cache_bytes: Optional[int] = None,
               retrieval: Optional[dict] = None,
               **engine_overrides) -> ModelEntry:
        """Deploy `source` (artifact dir path, Artifact, or trained model)
        as `name`; replaces any current version atomically AFTER the new
        engine is fully warmed (no cold-cache window under load). The
        version defaults to the artifact's manifest version (so /predict
        responses correlate with the frozen directory, rollbacks included);
        bare model objects auto-increment. ``batcher_overrides`` tunes
        this model's admission posture (max_queue_rows, quota fractions,
        adaptive caps, starvation limit) over the registry defaults —
        per-model quotas are per-model BATCHERS: each model owns its
        queue, so one model's flood can never 503 another. ``lineage``
        attaches the publisher's gate-decision records to the entry
        (surfaced on /models — the continuous-training pipeline passes its
        recent publish/refusal/rollback history here).
        ``score_cache_bytes`` overrides the registry's hot-row cache
        budget for this model (None inherits the registry default — or,
        failing that, whatever cache an earlier deploy enabled for this
        name; an explicit 0 disables); the cache OBJECT persists across
        this name's versions — swap invalidation is the version key, not
        a flush (docs/serving.md "Score caching & coalescing").
        ``retrieval`` (a dict of RetrievalEngine kwargs, ``{}`` for the
        defaults) additionally stands up the top-K catalog-scoring surface
        for this model — MF/FM only — behind its OWN DynamicBatcher, so
        ``POST /topk`` rides the same admission/priority/deadline
        machinery without sharing dispatch slots with /predict
        (docs/serving.md "Top-K retrieval"). Opt-in: None (default) means
        /topk answers 400 for this model."""
        from .artifact import Artifact, load as load_artifact

        if isinstance(source, str):
            source = load_artifact(source)
        if version is None and isinstance(source, Artifact):
            version = source.manifest.get("version")
        kw = dict(self.engine_kwargs)
        kw.update(engine_overrides)
        kw.setdefault("max_batch", self.max_batch)
        engine = ServingEngine(source, name=name, **kw)
        if version is None:
            with self._lock:
                old = self._entries.get(name)
            version = str(int(old.version) + 1) if old is not None \
                and old.version.isdigit() else "1"
        if self.warmup:
            engine.warmup()
        bkw = dict(max_batch=engine.max_batch,
                   max_delay_ms=self.max_delay_ms,
                   max_queue_rows=self.max_queue_rows,
                   max_delay_ms_cap=self.max_delay_ms_cap,
                   max_batch_cap=self.max_batch_cap,
                   priority_quota_fracs=self.priority_quota_fracs,
                   starvation_limit=self.starvation_limit,
                   express_high=self.express_high)
        bkw.update(batcher_overrides or {})
        cache_bytes = self.score_cache_bytes if score_cache_bytes is None \
            else score_cache_bytes
        cache = None
        if cache_bytes:
            from .cache import ScoreCache

            with self._lock:
                cache = self._caches.get(name)
                if cache is None or cache.max_bytes != int(cache_bytes):
                    cache = ScoreCache(int(cache_bytes), name=name)
                    self._caches[name] = cache
        elif score_cache_bytes is not None:
            with self._lock:  # explicit 0: caching OFF for this name
                self._caches.pop(name, None)
        else:
            # no override and no registry default: a cache an earlier
            # deploy enabled for this name SURVIVES the redeploy — the
            # object persisting across versions is the hot-swap story
            # (old-version entries age out of the byte budget)
            with self._lock:
                cache = self._caches.get(name)
        r_engine = r_batcher = None
        if retrieval is not None:
            from .retrieval import RetrievalEngine

            rkw = dict(retrieval)
            # the catalog shards wherever the pointwise tables do unless
            # the retrieval options say otherwise
            if kw.get("placement") is not None:
                rkw.setdefault("placement", kw.get("placement"))
            r_engine = RetrievalEngine(source, name=name, **rkw)
            if self.warmup:
                r_engine.warmup()
            rbkw = dict(max_batch=r_engine.max_batch,
                        max_delay_ms=self.max_delay_ms,
                        max_queue_rows=self.max_queue_rows,
                        max_delay_ms_cap=self.max_delay_ms_cap,
                        max_batch_cap=self.max_batch_cap,
                        priority_quota_fracs=self.priority_quota_fracs,
                        starvation_limit=self.starvation_limit,
                        express_high=self.express_high)
            rbkw.update(batcher_overrides or {})
            rbkw["max_batch"] = r_engine.max_batch
            # no score cache / row keys: a top-K row is (query, k, probe)
            # and the result is a ranking, not a scalar — the hot-row
            # cache's single-score contract doesn't apply
            r_batcher = DynamicBatcher(r_engine.topk_batch,
                                       name=f"{name}.topk", **rbkw)
        batcher = DynamicBatcher(engine.predict, name=name, cache=cache,
                                 cache_version=str(version),
                                 row_key_fn=engine.row_keys, **bkw)
        entry = ModelEntry(name, str(version), engine, batcher,
                           lineage=lineage, cache=cache,
                           retrieval_engine=r_engine,
                           retrieval_batcher=r_batcher)
        with self._lock:
            old = self._entries.get(name)
            self._entries[name] = entry  # the atomic publish
        if old is not None:
            self._swaps.increment()
            # outside the lock: draining can take max_delay + a batch
            old.batcher.close(drain=True)
            if old.retrieval_batcher is not None:
                old.retrieval_batcher.close(drain=True)
        REGISTRY.set_gauge(f"serving.{name}.deployed_version",
                           float(version) if str(version).isdigit() else 0.0)
        return entry

    def get(self, name: Optional[str] = None) -> Optional[ModelEntry]:
        """Resolve a model by name; with one deployed model, name may be
        omitted (the single-model convenience every demo uses)."""
        if name is not None:
            # designed lock-free read: a single dict .get() is atomic under
            # the GIL and deploy() publishes entries with one assignment —
            # readers see the old or new entry, never a partial one
            return self._entries.get(name)  # graftcheck: disable=G012 (reviewed lock-free read)
        with self._lock:  # a concurrent first deploy mutates the dict
            entries = list(self._entries.values())
        if len(entries) == 1:
            return entries[0]
        return None

    # each BatcherClosed means a full deploy landed between resolve and
    # submit; needing this many consecutive swaps inside one submit window
    # is not a reachable steady state
    _SWAP_RETRIES = 8

    def submit(self, name: Optional[str], instances, *,
               priority="normal", deadline_ms: Optional[float] = None):
        """Resolve + enqueue, retrying across hot swaps: a caller that
        resolved the OLD entry right before deploy() published the new one
        sees BatcherClosed from the draining batcher — re-resolving gets
        the new version, so a swap fails zero requests. Returns
        (entry, future); (None, None) means the name is genuinely unknown
        (never deployed, or undeployed). QueueFull propagates (backpressure
        is the caller's 503); BatcherClosed escapes only after
        _SWAP_RETRIES consecutive swap collisions (retryable, also 503).
        ``priority``/``deadline_ms`` thread through to the batcher's
        admission decision (serving/batcher.py)."""
        for _ in range(self._SWAP_RETRIES):
            entry = self.get(name)
            if entry is None:
                return None, None
            try:
                return entry, entry.batcher.submit(
                    instances, priority=priority, deadline_ms=deadline_ms)
            except BatcherClosed:  # graftcheck: disable=G031 (retry rebinds to the NEW batcher; waiting adds only latency)
                continue
        raise BatcherClosed(
            f"model {name!r}: {self._SWAP_RETRIES} consecutive version "
            f"swaps collided with this submit — retry")

    def submit_topk(self, name: Optional[str], rows, *,
                    priority="normal", deadline_ms: Optional[float] = None):
        """submit(), but into the model's RETRIEVAL batcher. ``rows`` is a
        list of ``(query, k, probe)`` tuples (serving/retrieval.py
        ``topk_batch``). Returns (entry, future); (None, None) means the
        name is unknown; (entry, None) means the model is deployed but
        without a retrieval surface (deploy() had no ``retrieval=`` — the
        caller's 400). Swap-retry semantics match submit()."""
        for _ in range(self._SWAP_RETRIES):
            entry = self.get(name)
            if entry is None:
                return None, None
            if entry.retrieval_batcher is None:
                return entry, None
            try:
                return entry, entry.retrieval_batcher.submit(
                    rows, priority=priority, deadline_ms=deadline_ms)
            except BatcherClosed:  # graftcheck: disable=G031 (retry rebinds to the NEW batcher; waiting adds only latency)
                continue
        raise BatcherClosed(
            f"model {name!r}: {self._SWAP_RETRIES} consecutive version "
            f"swaps collided with this submit — retry")

    def health(self) -> dict:
        """Overload-aware health: ``degraded`` (still alive — shedding
        predictably) when any model's queue fills past
        ``degraded_depth_fraction``; the status a load balancer should
        read BEFORE the process ever looks dead."""
        with self._lock:
            entries = list(self._entries.values())
        models, worst = {}, 0.0
        for e in entries:
            st = e.batcher.overload_state()
            worst = max(worst, st["depth_fraction"])
            models[e.name] = {
                "depth_fraction": st["depth_fraction"],
                "depth_rows": st["depth_rows"],
                "controller": st["controller"],
                "shed": st["shed"], "expired": st["expired"],
                "quota_rejected": st["quota_rejected"],
            }
        info = {
            "status": "degraded" if worst >= self.degraded_depth_fraction
            else "ok",
            "degraded_depth_fraction": self.degraded_depth_fraction,
            "worst_depth_fraction": round(worst, 4),
            "models": models,
        }
        try:
            import jax

            info["process_index"] = jax.process_index()
            info["local_devices"] = len(jax.local_devices())
        except Exception:  # graftcheck: disable=G029 (probe: jax absent means health omits device fields)
            pass
        return info

    def undeploy(self, name: str) -> bool:
        with self._lock:
            entry = self._entries.pop(name, None)
            self._caches.pop(name, None)
        if entry is None:
            return False
        entry.batcher.close(drain=True)
        if entry.retrieval_batcher is not None:
            entry.retrieval_batcher.close(drain=True)
        return True

    def list_models(self):
        with self._lock:  # a first deploy of a new name mutates the dict
            entries = list(self._entries.values())
        return [e.describe() for e in entries]

    def shutdown(self) -> None:
        with self._lock:
            entries = list(self._entries.values())
            self._entries = {}
            self._caches = {}
        for e in entries:
            e.batcher.close(drain=True)
            if e.retrieval_batcher is not None:
                e.retrieval_batcher.close(drain=True)


class _ServingHandler(metrics_http._Handler):
    """Extends the metrics handler with /predict, /models and the
    overload-aware /healthz. The registry rides on the server object
    (see serve())."""

    # persistent connections: the overload bench (and any real client)
    # reuses sockets instead of burning an ephemeral port per request;
    # every response carries Content-Length, so keep-alive is safe
    protocol_version = "HTTP/1.1"

    predict_timeout = 30.0

    def _send_json(self, code: int, payload: dict, extra_headers=()) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        for k, v in extra_headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - http.server API
        path = self.path.split("?")[0]
        if path == "/models":
            self._send_json(200, {"models": self.server.registry.list_models()})
            return
        if path == "/healthz":
            # overload-aware liveness: "degraded" reports a server that is
            # alive and shedding predictably BEFORE it ever looks dead.
            # Queue depth is the instantaneous signal; the SLO engine's
            # burn state (runtime/slo.py) is the over-time one — a paging
            # objective degrades health even while the queue happens to
            # look shallow, so a front door routing on /healthz sees
            # both (ROADMAP fleet-serving: per-replica health a router
            # can trust)
            from ..runtime.slo import ENGINE

            info = self.server.registry.health()
            slo_block = ENGINE.health_block()
            info["slo"] = slo_block
            if slo_block["paging"]:
                info["status"] = "degraded"
            self._send_json(200, info)
            return
        super().do_GET()

    def _drain_body(self) -> None:
        """Read and discard the request body so the keep-alive connection
        stays in sync on paths that never parse it (the door 503, the
        POST 404)."""
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:  # garbage header: nothing trustworthy to drain
            length = 0
        self.rfile.read(length)

    def do_POST(self):  # noqa: N802 - http.server API
        route = self.path.split("?")[0]
        if route not in ("/predict", "/topk"):
            self._drain_body()
            self._send_json(404, {"error": "not found"})
            return
        # concurrency admission, at the door: past the in-flight limit the
        # request is refused BEFORE its body is parsed — under overload
        # the handler threads' own parse work would otherwise starve the
        # batcher worker of the very CPU that IS the service capacity.
        # The body is still drained so the keep-alive connection stays
        # usable; 503s are deliberately cheap.
        sem = getattr(self.server, "inflight", None)
        held = None
        if sem is not None:
            if sem.acquire(blocking=False):
                held = sem
            else:
                # the door must not undo the priority classes: requests
                # whose x-priority HEADER says "high" may still enter
                # through the reserved slots (body-priority requests
                # cannot — the point of the door is deciding before the
                # body is parsed)
                hdr = (self.headers.get("x-priority") or "").strip().lower()
                reserve = getattr(self.server, "inflight_reserve", None)
                if hdr in ("high", "0") and reserve is not None \
                        and reserve.acquire(blocking=False):
                    held = reserve
            if held is None:
                self._drain_body()
                self.server.concurrency_rejected.increment()
                self._send_json(503,
                                {"error": "too many in-flight requests",
                                 "reason": "concurrency"},
                                extra_headers=(("Retry-After", "1"),))
                return
        try:
            self._topk() if route == "/topk" else self._predict()
        finally:
            if held is not None:
                held.release()

    def _predict(self) -> None:
        # the request's ROOT span: HTTP parse, queue wait, batched device
        # dispatch and the response write all land under it; the latency
        # histogram observation carries its trace_id as an exemplar. A
        # client W3C traceparent is adopted as the root's parent (PR 5
        # leftover) and echoed back with OUR root span as the new parent;
        # a malformed header parses to None — a fresh trace.
        remote = TRACER.parse_traceparent(self.headers.get("traceparent"))
        with TRACER.span("server.predict", remote=remote) as root:
            tp = TRACER.format_traceparent(root)
            tp_hdr = (("traceparent", tp),) if tp else ()
            with TRACER.span("server.parse"):
                close_hdr = ()
                try:
                    try:
                        length = int(self.headers.get("Content-Length", 0))
                    except ValueError:
                        # body length unknowable: the socket cannot be
                        # drained back into sync — close it with the 400
                        close_hdr = (("Connection", "close"),)
                        raise
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    instances = payload["instances"]
                    if not isinstance(instances, list):
                        raise TypeError("instances must be a list")
                    # priority class + deadline budget: body keys win over
                    # the x-priority / x-deadline-ms headers
                    cls = priority_class(
                        payload.get("priority",
                                    self.headers.get("x-priority")
                                    or "normal"))
                    deadline_ms = payload.get(
                        "deadline_ms", self.headers.get("x-deadline-ms"))
                    if deadline_ms is not None:
                        deadline_ms = float(deadline_ms)
                        if not math.isfinite(deadline_ms) \
                                or deadline_ms <= 0:
                            raise ValueError(
                                f"deadline_ms must be a positive number, "
                                f"got {deadline_ms}")
                except (KeyError, TypeError, ValueError) as e:
                    self._send_json(400, {"error": f"bad request: {e}"},
                                    extra_headers=tp_hdr + close_hdr)
                    root.set(status=400)
                    return
            root.set(instances=len(instances),
                     model=payload.get("model") or "",
                     priority=priority_name(cls),
                     **({"deadline_ms": deadline_ms}
                        if deadline_ms is not None else {}))
            t0 = time.perf_counter()
            try:
                # registry.submit retries across a hot swap, so a v1->v2
                # deploy never fails a request; only an unknown name /
                # undeploy 404s
                entry, future = self.server.registry.submit(
                    payload.get("model"), instances,
                    priority=cls, deadline_ms=deadline_ms)
                if entry is None:
                    self._send_json(404,
                                    {"error": f"unknown model "
                                              f"{payload.get('model')!r}"},
                                    extra_headers=tp_hdr)
                    root.set(status=404)
                    return
                preds = future.result(timeout=self.predict_timeout)
            except DeadlineExpired as e:
                # expired IN the queue: no dispatch slot was spent on it
                self._send_json(504, {"error": str(e),
                                      "reason": "deadline"},
                                extra_headers=tp_hdr)
                root.set(status=504)
                return
            except (QueueFull, BatcherClosed) as e:
                # quota refusal, low-priority shed, or a swap-collision
                # storm — all retryable; Retry-After is priced from the
                # live drain-rate estimate so clients back off usefully
                ra = getattr(e, "retry_after_s", None) or 1.0
                self._send_json(
                    503, {"error": str(e),
                          "reason": getattr(e, "reason", "busy")},
                    extra_headers=tp_hdr + (
                        ("Retry-After", str(int(math.ceil(ra)))),))
                root.set(status=503)
                return
            except Exception as e:  # scoring bug — surface, don't hang
                self._send_json(500, {"error": f"{type(e).__name__}: {e}"},
                                extra_headers=tp_hdr)
                root.set(status=500)
                return
            dt = time.perf_counter() - t0
            self.server.latency.observe(
                dt, trace_id=TRACER.exemplar_id(root))
            # per-priority-class twin of the aggregate histogram: the
            # class rides the metric name (serving.http.latency_seconds.
            # high/normal/low — the counter convention), so /metrics can
            # answer "is the high class actually protected" and the SLO
            # engine can target one class (docs/serving.md)
            self.server.latency_by_class[cls].observe(dt)
            root.set(status=200, version=entry.version)
            self._send_json(200, {
                "model": entry.name,
                "version": entry.version,
                "predictions": [_jsonable(p) for p in preds],
            }, extra_headers=tp_hdr)

    def _topk(self) -> None:
        # /predict's twin for the retrieval surface: same root-span /
        # traceparent / priority / deadline / error-mapping contract, but
        # the rows are (query, k, probe) tuples into the model's SEPARATE
        # retrieval batcher and the answer is a ranking per query
        # (docs/serving.md "Top-K retrieval")
        remote = TRACER.parse_traceparent(self.headers.get("traceparent"))
        with TRACER.span("server.topk", remote=remote) as root:
            tp = TRACER.format_traceparent(root)
            tp_hdr = (("traceparent", tp),) if tp else ()
            with TRACER.span("server.parse"):
                close_hdr = ()
                try:
                    try:
                        length = int(self.headers.get("Content-Length", 0))
                    except ValueError:
                        close_hdr = (("Connection", "close"),)
                        raise
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    queries = payload["queries"]
                    if not isinstance(queries, list):
                        raise TypeError("queries must be a list")
                    k = payload.get("k")
                    if k is not None:
                        k = int(k)
                        if k < 1:
                            raise ValueError(f"k must be >= 1, got {k}")
                    probe = payload.get("probe")
                    if probe is not None:
                        probe = bool(probe)
                    cls = priority_class(
                        payload.get("priority",
                                    self.headers.get("x-priority")
                                    or "normal"))
                    deadline_ms = payload.get(
                        "deadline_ms", self.headers.get("x-deadline-ms"))
                    if deadline_ms is not None:
                        deadline_ms = float(deadline_ms)
                        if not math.isfinite(deadline_ms) \
                                or deadline_ms <= 0:
                            raise ValueError(
                                f"deadline_ms must be a positive number, "
                                f"got {deadline_ms}")
                except (KeyError, TypeError, ValueError) as e:
                    self._send_json(400, {"error": f"bad request: {e}"},
                                    extra_headers=tp_hdr + close_hdr)
                    root.set(status=400)
                    return
            root.set(queries=len(queries),
                     model=payload.get("model") or "",
                     priority=priority_name(cls),
                     **({"k": k} if k is not None else {}),
                     **({"deadline_ms": deadline_ms}
                        if deadline_ms is not None else {}))
            t0 = time.perf_counter()
            try:
                rows = [(q, k, probe) for q in queries]
                entry, future = self.server.registry.submit_topk(
                    payload.get("model"), rows,
                    priority=cls, deadline_ms=deadline_ms)
                if entry is None:
                    self._send_json(404,
                                    {"error": f"unknown model "
                                              f"{payload.get('model')!r}"},
                                    extra_headers=tp_hdr)
                    root.set(status=404)
                    return
                if future is None:
                    # deployed, but deploy() stood up no retrieval surface
                    self._send_json(
                        400, {"error": f"model {entry.name!r} has no "
                                       f"retrieval surface (deploy with "
                                       f"retrieval= to enable /topk)"},
                        extra_headers=tp_hdr)
                    root.set(status=400)
                    return
                results = future.result(timeout=self.predict_timeout)
            except DeadlineExpired as e:
                self._send_json(504, {"error": str(e),
                                      "reason": "deadline"},
                                extra_headers=tp_hdr)
                root.set(status=504)
                return
            except (QueueFull, BatcherClosed) as e:
                ra = getattr(e, "retry_after_s", None) or 1.0
                self._send_json(
                    503, {"error": str(e),
                          "reason": getattr(e, "reason", "busy")},
                    extra_headers=tp_hdr + (
                        ("Retry-After", str(int(math.ceil(ra)))),))
                root.set(status=503)
                return
            except Exception as e:  # scoring bug — surface, don't hang
                self._send_json(500, {"error": f"{type(e).__name__}: {e}"},
                                extra_headers=tp_hdr)
                root.set(status=500)
                return
            dt = time.perf_counter() - t0
            self.server.latency.observe(
                dt, trace_id=TRACER.exemplar_id(root))
            self.server.latency_by_class[cls].observe(dt)
            root.set(status=200, version=entry.version)
            self._send_json(200, {
                "model": entry.name,
                "version": entry.version,
                "k": k if k is not None else entry.retrieval_engine.k,
                "results": list(results),
            }, extra_headers=tp_hdr)


def _jsonable(p):
    if isinstance(p, (np.generic,)):
        return p.item()
    if isinstance(p, np.ndarray):
        return p.tolist()
    return p


def serve(registry: ModelRegistry, port: int = 0, host: str = "127.0.0.1",
          max_concurrent_requests: Optional[int] = None
          ) -> ThreadingHTTPServer:
    """Start the serving endpoint on a daemon thread (stdlib only, the
    serve_metrics recipe); ``server.server_address[1]`` is the bound port.
    The same server answers /predict, /models, /metrics and /healthz.

    ``max_concurrent_requests`` bounds in-flight /predict handlers: past
    the limit requests get an immediate cheap 503 (``reason:
    concurrency``) before their body is parsed — the third admission
    dimension next to queue-row quotas and deadlines (docs/serving.md
    "Overload behavior"). A quarter of the limit again is reserved for
    requests whose ``x-priority`` header says high, so the door cannot
    undo the priority classes. None (default) leaves it unbounded."""
    server = ThreadingHTTPServer((host, port), _ServingHandler)
    server.registry = registry
    server.latency = REGISTRY.histogram("serving.http.latency_seconds")
    # the per-priority-class split of the same histogram (indexed by the
    # admission class int): multi-tenancy per-tenant counters will ride
    # this shape
    server.latency_by_class = tuple(
        REGISTRY.histogram(f"serving.http.latency_seconds.{p}")
        for p in PRIORITY_NAMES)
    if max_concurrent_requests is None:
        server.inflight = server.inflight_reserve = None
    else:
        n = int(max_concurrent_requests)
        server.inflight = threading.BoundedSemaphore(n)
        server.inflight_reserve = threading.BoundedSemaphore(
            max(2, n // 4))
    server.concurrency_rejected = REGISTRY.counter(
        "serving", "http.concurrency_rejected")
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="hivemall-tpu-serving")
    t.start()
    return server
