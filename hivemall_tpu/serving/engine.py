"""Shape-bucketed online predictors — zero steady-state recompiles.

XLA compiles one program per input shape, so a naive server retraces on
every distinct (batch, row-width) pair — the recompilation-count failure
mode the ads-infra paper tracks as a production metric (PAPERS.md). The
serving discipline here is the training-side G001 discipline
(core/batch.py) applied to inference:

- row width pads to a power of two >= 8 (``pad_to_bucket``), capped at
  ``max_width`` (longer rows truncate, counted);
- batch size pads to a power of two >= ``min_batch_bucket``, capped at
  ``max_batch`` (bigger requests chunk);
- ``warmup()`` drives a dummy batch through EVERY (batch, width) bucket at
  load time, so the steady state never compiles — witnessed at run time by
  ``runtime.metrics.recompile_guard`` around every predict call
  (counter ``graftcheck.recompiles.serving.<name>`` stays flat).

Every family reuses the SAME jitted scorer its live model uses
(core/engine.make_predict, models/fm._fm_scores, models/ffm._ffm_scores_jit,
models/multiclass._mc_scores, models/trees/grow.predict_forest_binned), so
served predictions are bit-identical to the trained object's — padding rows
are row-independent no-ops. MF is the exception by design: its predict is a
host-side embedding lookup (numpy gather-dot, no device batch work to
amortize), identical to TrainedMFModel.predict.

Attribution caveat: because those scorers (and their jit caches) are shared
process-wide, a deploy WARMING another same-family model concurrently with
an open predict guard can transiently attribute its warmup compiles to the
serving engine's counter. The flat-counter invariant is exact whenever no
deploy is in flight; sharing the cache is the point (a new version of the
same shapes warms for free), so the counter trades per-engine attribution
for that.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core.batch import FeatureBlock, pack_rows, pad_to_bucket
from ..runtime.metrics import REGISTRY, recompile_guard
from ..runtime.tracing import TRACER
from .artifact import Artifact, family_of, load, manifest_dtype, \
    manifest_quant, rebuild_model

# serving latency is sub-ms-to-seconds shaped; finer low end than the
# metrics default
LATENCY_BUCKETS = (0.0002, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                   0.05, 0.1, 0.25, 0.5, 1.0, 2.5)


# The serving dtype contract (graftcheck G017-G021, docs/static_analysis.md
# "quantized artifacts"): request payloads and host staging are f32, device
# tables reload at their MANIFEST dtype (artifact.manifest_dtype) — never at
# whatever width the widened-at-rest pack happens to hold — and nothing on
# the score path allocates f64. Quantized artifacts extend the contract
# downward: bf16 tables serve AT bf16 through the families' own scorers
# (the gathered window promotes to f32 inside the dot product), and int8
# tables serve through the _q8_* scorers below, which gather the int8 rows,
# widen ONLY that [B, K] window, and fold the per-block absmax scale into
# the f32 accumulation — the full table is never dequantized (G019; the
# per-window cast pattern of ops/mxu_scatter.py).


_QUANT_JIT: dict = {}


def _quant_jit_fns() -> dict:
    """Build (once per process) the jitted dequant-free int8 scorers.

    Shared across every engine instance the way the families' own scorers
    are, so a second int8 model of the same shapes warms for free and
    ``recompile_guard`` can watch one stable set of jit caches. Built
    lazily: importing serving must not drag jax in before the engine is
    actually used (the bench.py parent-process contract).

    ``block_shift`` is static (= log2 of the manifest's scale-block rows),
    so ``id >> block_shift`` resolves each gathered id to its scale block
    with one shift — one extra tiny gather against the f32 scale array
    replaces any widened copy of the table.
    """
    if _QUANT_JIT:
        return _QUANT_JIT
    from functools import partial

    import jax
    import jax.numpy as jnp

    from ..models.fm import _row_predict

    @partial(jax.jit, static_argnums=(4,))
    def q8_linear_scores(qw, scales, indices, values, block_shift):
        # per-window dequant: only the gathered [B, K] rows widen (G019),
        # the scale folds into the product, and the sum accumulates f32
        # (G021); pad lanes gather q=0 so they stay no-ops
        wq = qw.at[indices].get(mode="fill", fill_value=0)
        sg = scales.at[indices >> block_shift].get(mode="fill",
                                                   fill_value=0.0)
        return jnp.sum(wq.astype(jnp.float32) * sg * values, axis=-1)

    @partial(jax.jit, static_argnums=(4,))
    def q8_mc_scores(qW, scales, indices, values, block_shift):
        # weights [L, D] int8, scales [L, nb] f32 (blocked along features,
        # the gathered axis) — the [L, B, K] gathered window widens, the
        # einsum accumulates f32
        Wq = jnp.take(qW, indices, axis=1, mode="fill", fill_value=0)
        S = jnp.take(scales, indices >> block_shift, axis=1, mode="fill",
                     fill_value=0.0)
        return jnp.einsum("lbk,bk->bl", Wq.astype(jnp.float32) * S, values)

    @partial(jax.jit, static_argnums=(7,))
    def q8_fm_scores(w0, qw, w_scales, qv, v_scales, indices, values,
                     block_shift):
        # same _row_predict core as the live FM scorer, fed per-row
        # dequantized windows: w [D] and v [D, F] gather int8, widen the
        # [K] / [K, F] window, fold the row-block scales
        def one(idx, val):
            sw = w_scales.at[idx >> block_shift].get(mode="fill",
                                                     fill_value=0.0)
            wg = qw.at[idx].get(mode="fill",
                                fill_value=0).astype(jnp.float32) * sw
            sv = v_scales.at[idx >> block_shift].get(mode="fill",
                                                     fill_value=0.0)
            vg = qv.at[idx].get(mode="fill",
                                fill_value=0).astype(jnp.float32) * sv
            p, _ = _row_predict(w0, wg, vg, val)
            return p

        return jax.vmap(one)(indices, values)

    _QUANT_JIT.update(linear=q8_linear_scores, multiclass=q8_mc_scores,
                      fm=q8_fm_scores)
    return _QUANT_JIT


class _Servable:
    """THE servable protocol: host staging + padded scoring, placement-free.

    Every placement (single-device, replicated, model-sharded —
    serving/placement.py) serves through this same interface; the engine,
    batcher, registry and /predict endpoint depend on nothing else. The
    single-device family adapters below implement it with tables on one
    device; serving/sharded.py implements it with NamedSharding-striped
    tables — ``make_servable(obj, placement=...)`` picks.

    The request path is three explicitly separated stages so the tracer
    (runtime/tracing.py) can attribute time per stage:

    - ``stage(instances, b_pad, width_cap)`` — host-side parse + pad to
      ``[b_pad, width_bucket]`` arrays (the "pad" span);
    - ``dispatch(staged)`` — the device scoring call on staged arrays,
      asynchronous for the jitted families (the "dispatch" span);
    - ``finalize(raw, n)`` — map padded raw output back to ``n``
      user-facing predictions; materializing the device result here is
      where the host blocks (the "block" span).

    ``run_padded`` composes stage+dispatch for callers that don't need
    the split (warmup).
    """

    family: str = ""
    jit_fns: Tuple = ()
    # families with a row-width axis warm up over width buckets; the rest
    # only have the batch axis
    has_width: bool = True
    # the dtype the weight tables SERVE at (the manifest weights_dtype for
    # artifacts) — surfaced per model on /models and /metrics
    weights_dtype: str = "float32"
    # placement surface: single-device servables leave the defaults; the
    # sharded servables (serving/sharded.py) fill in their mesh shape and
    # the /models placement block
    mesh_shape: Optional[Tuple[int, ...]] = None
    placement_info: Optional[dict] = None

    def device_tables(self):
        """The resident score tables (arrays or pytrees of arrays) —
        whatever a request's gathers actually read. Feeds table_bytes."""
        return []

    def table_bytes(self) -> int:
        """Resident bytes of the score tables — the quantity bf16/int8
        artifacts shrink 2-4x (reported per model on /models + /metrics
        and in the bench_serving --quantize artifact)."""
        import jax

        total = 0
        for leaf in jax.tree_util.tree_leaves(self.device_tables()):
            size = getattr(leaf, "size", None)
            dt = getattr(leaf, "dtype", None)
            if size is not None and dt is not None:
                total += int(size) * np.dtype(dt).itemsize
        return total

    def stage(self, instances, b_pad: int, width_cap: int):
        raise NotImplementedError

    def dispatch(self, staged):
        raise NotImplementedError

    def row_keys(self, instances, width_cap: int):
        """Per-row canonical cache keys for the hot-row score cache
        (serving/cache.py), or None when this request — or this family —
        is not cacheable. The key hashes the canonical PRE-PARSED row
        form (what staging actually scores: ids mod dims, f32 values for
        the sparse families; binned int32 rows for trees; normalized
        (field, id, value) triples for FFM), so a string row and its
        pre-parsed twin share one cache line. The default is None —
        uncacheable — for any family without an override."""
        return None

    def run_padded(self, instances, b_pad: int, width_cap: int):
        return self.dispatch(self.stage(instances, b_pad, width_cap))

    def finalize(self, raw, n: int):
        return np.asarray(raw)[:n]

    def dummy_instance(self, width: Optional[int]):
        raise NotImplementedError

    def max_nnz(self, instances) -> int:
        return max((len(r) for r in instances), default=1)

    def count_overwide(self, instances, width_cap: int) -> int:
        """How many rows will actually truncate at ``width_cap`` — the
        operator signal for sizing max_width (exact, not per-chunk)."""
        return sum(1 for r in instances if len(r) > width_cap)


def _is_preparsed(instances) -> bool:
    """Pre-parsed requests, honored end to end (sparse-row families only;
    a LIST is always rows to parse):

    - 2-TUPLE ``(idx_rows, val_rows)`` of per-row arrays — the
      models.base._stage_rows convention;
    - 3-TUPLE ``(flat_idx, flat_val, lens)`` — the same rows pre-packed
      into flat arrays with per-row lengths, so staging needs no
      per-request concatenate at all.

    In-process callers (bench_serving --quantize, embedded scorers) skip
    the string-parse cost per call this way — essential when the thing
    being measured is table bandwidth, not tokenization."""
    return isinstance(instances, tuple) and len(instances) in (2, 3)


def _preparsed_len(instances) -> int:
    """Row count of a pre-parsed request (either tuple form)."""
    return len(instances[2] if len(instances) == 3 else instances[0])


def _preparsed_offsets(instances):
    """Element offsets for slicing a flat pre-parsed request — computed
    ONCE per predict call (not per chunk: the cumsum is O(rows), and a
    large offline predict chunks thousands of times)."""
    if len(instances) == 2:
        return None
    lens = instances[2]
    off = np.zeros(len(lens) + 1, np.int64)
    np.cumsum(lens, out=off[1:])
    return off


def _preparsed_chunk(instances, s: int, e: int, off=None):
    """Rows [s:e) of a pre-parsed request, preserving its form (the flat
    form slices by the precomputed element offsets ``off``)."""
    if len(instances) == 2:
        return (instances[0][s:e], instances[1][s:e])
    flat_i, flat_v, lens = instances
    return (flat_i[off[s]:off[e]], flat_v[off[s]:off[e]], lens[s:e])


class _SparseRowServable(_Servable):
    """Shared staging for the "feature[:value]" row families (linear,
    multiclass, FM): parse -> width-bucket -> one padded FeatureBlock.
    Subclasses only provide the jitted score call."""

    def __init__(self, dims: int) -> None:
        self.dims = dims

    def count_overwide(self, instances, width_cap: int) -> int:
        if _is_preparsed(instances):
            if len(instances) == 3:
                return int(np.count_nonzero(
                    np.asarray(instances[2]) > width_cap))
            instances = instances[0]
        return sum(1 for r in instances if len(r) > width_cap)

    def stage(self, instances, b_pad: int, width_cap: int):
        if _is_preparsed(instances):
            return self._stage_preparsed(instances, b_pad, width_cap)
        from ..models.base import _stage_rows

        idx_rows, val_rows = _stage_rows(instances, self.dims)
        n = len(idx_rows)
        width = min(pad_to_bucket(self.max_nnz(idx_rows)), width_cap)
        return pack_rows(idx_rows, val_rows, np.zeros(n, dtype=np.float32),
                         self.dims, width=width, batch_size=b_pad)

    def _stage_preparsed(self, instances, b_pad: int, width_cap: int):
        """Vectorized staging for pre-parsed requests: one masked
        [n, width] gather over the flattened rows replaces the per-row
        Python loop of pack_rows. Semantics are identical (hash ids mod
        dims, truncate rows past width_cap, pad lanes carry index == dims
        with value 0) but the host cost drops to C-speed array ops — on
        the quantized-serving bench the staging would otherwise price the
        host side and bury the table-bandwidth difference the precisions
        exist to change. The flat 3-tuple form skips even the
        concatenate: for wide-batch requests the per-row-array overhead
        alone is several ms."""
        if len(instances) == 3:
            flat_i, flat_v, lens = instances
            n = len(lens)
            lens = np.asarray(lens, np.int64)
            flat_i = np.asarray(flat_i)
            flat_v = np.asarray(flat_v, np.float32)
        else:
            idx_rows, val_rows = instances
            n = len(idx_rows)
            lens = np.fromiter((len(r) for r in idx_rows), np.int64,
                               count=n)
            flat_i = (np.concatenate(
                [np.asarray(r, np.int64).ravel() for r in idx_rows])
                if n else np.zeros(0, np.int64))
            flat_v = (np.concatenate(
                [np.asarray(r, np.float32).ravel() for r in val_rows])
                if n else np.zeros(0, np.float32))
        max_nnz = int(lens.max()) if n else 1
        width = min(pad_to_bucket(max(1, max_nnz)), width_cap)
        k = np.minimum(lens, width)
        indices = np.full((b_pad, width), self.dims, dtype=np.int32)
        values = np.zeros((b_pad, width), dtype=np.float32)
        nnz = np.zeros(b_pad, dtype=np.int32)
        total = int(lens.sum())
        if total:
            off = np.zeros(n, np.int64)
            np.cumsum(lens[:-1], out=off[1:])
            pos = np.arange(width, dtype=np.int64)
            mask = pos[None, :] < k[:, None]
            src = np.minimum(off[:, None] + pos[None, :], total - 1)
            indices[:n] = np.where(mask, flat_i[src] % self.dims,
                                   self.dims)
            values[:n] = np.where(mask, flat_v[src], np.float32(0.0))
        nnz[:n] = k.astype(np.int32)
        return FeatureBlock(indices, values,
                            np.zeros(b_pad, dtype=np.float32), nnz)

    def dummy_instance(self, width):
        return [(i, 1.0) for i in range(width)]

    def row_keys(self, instances, width_cap: int):
        """blake2b-128 digests over (ids mod dims as int64, values as
        f32), in row order. Rows wider than ``width_cap`` make the WHOLE
        request uncacheable (None): truncation semantics live in staging,
        and replicating them here would be a second source of truth. Row
        order is part of the key — a permuted duplicate is a different
        fp-reduction order, so it conservatively gets its own entry."""
        from hashlib import blake2b

        if _is_preparsed(instances):
            if len(instances) == 3:
                flat_i, flat_v, lens = instances
                lens = np.asarray(lens, np.int64)
                if lens.size and int(lens.max()) > width_cap:
                    return None
                flat_i = np.asarray(flat_i, np.int64) % self.dims
                flat_v = np.asarray(flat_v, np.float32)
                off = np.zeros(len(lens) + 1, np.int64)
                np.cumsum(lens, out=off[1:])
                idx_rows = [flat_i[off[i]:off[i + 1]]
                            for i in range(len(lens))]
                val_rows = [flat_v[off[i]:off[i + 1]]
                            for i in range(len(lens))]
            else:
                idx_rows = [np.asarray(r, np.int64) % self.dims
                            for r in instances[0]]
                val_rows = [np.asarray(v, np.float32) for v in instances[1]]
        else:
            from ..models.base import _stage_rows

            try:
                idx_rows, val_rows = _stage_rows(instances, self.dims)
            except Exception:  # graftcheck: disable=G028 (None = uncacheable; the error re-surfaces on the predict path)
                return None
        keys = []
        for idx, val in zip(idx_rows, val_rows):
            if len(idx) > width_cap:
                return None
            keys.append(blake2b(
                np.ascontiguousarray(idx, np.int64).tobytes()
                + np.ascontiguousarray(val, np.float32).tobytes(),
                digest_size=16).digest())
        return keys


class _LinearServable(_SparseRowServable):
    family = "linear"

    def __init__(self, state, dims: int) -> None:
        from ..core.engine import make_predict

        super().__init__(dims)
        self.state = state
        self.weights_dtype = np.dtype(state.weights.dtype).name
        self._predict = make_predict(use_covariance=False)
        self.jit_fns = (self._predict,)

    def dispatch(self, staged):
        return self._predict(self.state, staged.indices, staged.values)

    def device_tables(self):
        # weights only: the serving predict is built use_covariance=False,
        # so a resident covariance table is reload baggage, not score-path
        # bytes — counting it would overstate what requests actually gather
        return [self.state.weights]


class _ArgmaxLabelServable(_SparseRowServable):
    """Shared label selection for the multiclass servables (f32 and int8):
    argmax over the [B, L] score matrix, mapped through label_vocab."""

    label_vocab: list

    def finalize(self, raw, n):
        scores = np.asarray(raw)[:n]
        return [self.label_vocab[i] for i in np.argmax(scores, axis=1)]


class _MulticlassServable(_ArgmaxLabelServable):
    family = "multiclass"

    def __init__(self, state, label_vocab, dims: int) -> None:
        from ..models.multiclass import _mc_scores

        super().__init__(dims)
        self.state = state
        self.label_vocab = list(label_vocab)
        self.weights_dtype = np.dtype(state.weights.dtype).name
        self._scores = _mc_scores
        self.jit_fns = (_mc_scores,)

    def dispatch(self, staged):
        return self._scores(self.state.weights, staged.indices,
                            staged.values)

    def device_tables(self):
        # _mc_scores reads the weight matrix only (see _LinearServable)
        return [self.state.weights]


class _FMServable(_SparseRowServable):
    family = "fm"

    def __init__(self, state, dims: int) -> None:
        from ..models.fm import _fm_scores

        super().__init__(dims)
        self.state = state
        self.weights_dtype = np.dtype(state.w.dtype).name
        self._scores = _fm_scores
        self.jit_fns = (_fm_scores,)

    def dispatch(self, staged):
        return self._scores(self.state, staged.indices, staged.values)

    def device_tables(self):
        return [self.state.w, self.state.v]


class _FFMServable(_Servable):
    family = "ffm"

    def __init__(self, state, hyper) -> None:
        from ..models.ffm import _ffm_scores_jit

        self.state = state
        self.hyper = hyper
        self._scores = _ffm_scores_jit
        self.jit_fns = (_ffm_scores_jit,)

    def device_tables(self):
        # _row_predict reads v/w/w0; the FTRL optimizer slots riding on the
        # state pytree are not score-path bytes
        return [self.state.v, self.state.w, self.state.w0]

    def stage(self, instances, b_pad, width_cap):
        from ..utils.feature import FMFeature

        hy = self.hyper
        parsed = [[FMFeature.parse(f, num_features=hy.num_features,
                                   num_fields=hy.num_fields) for f in row]
                  for row in instances]
        width = min(pad_to_bucket(self.max_nnz(parsed)), width_cap)
        idx = np.full((b_pad, width), hy.num_features, np.int32)
        val = np.zeros((b_pad, width), np.float32)
        fld = np.zeros((b_pad, width), np.int32)
        for r, row in enumerate(parsed):
            for c, f in enumerate(row[:width]):
                idx[r, c] = f.index % hy.num_features
                val[r, c] = f.value
                fld[r, c] = (f.field if f.field >= 0 else 0) % hy.num_fields
        return idx, val, fld

    def dispatch(self, staged):
        idx, val, fld = staged
        return self._scores(self.hyper, self.state, idx, val, fld)

    def dummy_instance(self, width):
        return [f"{k % 8}:{k}:1.0" for k in range(width)]

    def row_keys(self, instances, width_cap: int):
        """blake2b-128 over the canonical (field, id, value) triples —
        ids mod num_features, fields normalized exactly as staging does
        (negative -> 0, mod num_fields), values f32 — so a string row and
        a differently-written equivalent share one cache line. Rows wider
        than ``width_cap`` make the request uncacheable (truncation
        semantics live in staging, not here); unparseable rows too — the
        parse error re-surfaces on the predict path with its real
        message."""
        from hashlib import blake2b

        from ..utils.feature import FMFeature

        hy = self.hyper
        keys = []
        try:
            for row in instances:
                if len(row) > width_cap:
                    return None
                idx = np.empty(len(row), np.int64)
                fld = np.empty(len(row), np.int64)
                val = np.empty(len(row), np.float32)
                for c, f in enumerate(row):
                    p = FMFeature.parse(f, num_features=hy.num_features,
                                        num_fields=hy.num_fields)
                    idx[c] = p.index % hy.num_features
                    fld[c] = (p.field if p.field >= 0 else 0) % hy.num_fields
                    val[c] = p.value
                keys.append(blake2b(
                    idx.tobytes() + fld.tobytes() + val.tobytes(),
                    digest_size=16).digest())
        except Exception:  # graftcheck: disable=G028 (None = uncacheable; the error re-surfaces on the predict path)
            return None
        return keys


class _PairServable(_Servable):
    """Shared (user, item) pair staging for the MF servables (f32 and
    quantized): there is no [B, K] device batch shape to bucket, so
    has_width is False and jit_fns is empty."""

    family = "mf"
    has_width = False

    def stage(self, instances, b_pad, width_cap):
        pairs = np.asarray(instances, np.int64).reshape(len(instances), 2)
        u = np.zeros(b_pad, np.int64)
        i = np.zeros(b_pad, np.int64)
        u[:len(instances)] = pairs[:, 0]
        i[:len(instances)] = pairs[:, 1]
        return u, i

    def dummy_instance(self, width):
        return (0, 0)

    def row_keys(self, instances, width_cap: int):
        """A (user, item) pair IS its own canonical 16-byte key — no
        digest needed (same length as the sparse families' blake2b-128,
        so cache cost accounting is uniform)."""
        try:
            pairs = np.ascontiguousarray(
                np.asarray(instances, np.int64).reshape(len(instances), 2))
        except (TypeError, ValueError):
            return None
        return [p.tobytes() for p in pairs]


class _MFServable(_PairServable):
    """Host-side embedding lookup — numpy gather-dot, bit-identical to
    TrainedMFModel.predict."""

    def __init__(self, model) -> None:
        self.model = model
        self.weights_dtype = np.dtype(model.state.P.dtype).name

    def device_tables(self):
        return [self.model.state.P, self.model.state.Q,
                self.model.state.Bu, self.model.state.Bi]

    def dispatch(self, staged):
        u, i = staged
        return self.model.predict(u, i)


class _QuantLinearServable(_SparseRowServable):
    """int8 linear rows served dequant-free: gather the int8 window, fold
    the per-block absmax scale into the f32 dot product (_quant_jit_fns)."""

    family = "linear"
    weights_dtype = "int8"

    def __init__(self, qw, scales, block_rows: int, dims: int) -> None:
        super().__init__(dims)
        self.qw = qw
        self.scales = scales
        self.block_shift = int(block_rows).bit_length() - 1
        self._scores = _quant_jit_fns()["linear"]
        self.jit_fns = (self._scores,)

    def dispatch(self, staged):
        return self._scores(self.qw, self.scales, staged.indices,
                            staged.values, self.block_shift)

    def device_tables(self):
        return [self.qw, self.scales]


class _QuantMulticlassServable(_ArgmaxLabelServable):
    """int8 multiclass [L, D] table, scales blocked along the feature
    axis; argmax label selection shared with _MulticlassServable."""

    family = "multiclass"
    weights_dtype = "int8"

    def __init__(self, qW, scales, block_rows: int, label_vocab,
                 dims: int) -> None:
        super().__init__(dims)
        self.qW = qW
        self.scales = scales
        self.label_vocab = list(label_vocab)
        self.block_shift = int(block_rows).bit_length() - 1
        self._scores = _quant_jit_fns()["multiclass"]
        self.jit_fns = (self._scores,)

    def dispatch(self, staged):
        return self._scores(self.qW, self.scales, staged.indices,
                            staged.values, self.block_shift)

    def device_tables(self):
        return [self.qW, self.scales]


class _QuantFMServable(_SparseRowServable):
    """int8 FM: w [D] and v [D, F] gather int8, the per-row-block scales
    fold into the gathered windows, and the same _row_predict core as the
    live scorer combines them (f32 throughout)."""

    family = "fm"
    weights_dtype = "int8"

    def __init__(self, w0, qw, w_scales, qv, v_scales, block_rows: int,
                 dims: int) -> None:
        super().__init__(dims)
        self.w0 = w0
        self.qw = qw
        self.w_scales = w_scales
        self.qv = qv
        self.v_scales = v_scales
        self.block_shift = int(block_rows).bit_length() - 1
        self._scores = _quant_jit_fns()["fm"]
        self.jit_fns = (self._scores,)

    def dispatch(self, staged):
        return self._scores(self.w0, self.qw, self.w_scales, self.qv,
                            self.v_scales, staged.indices, staged.values,
                            self.block_shift)

    def device_tables(self):
        return [self.qw, self.w_scales, self.qv, self.v_scales]


class _QuantMFServable(_PairServable):
    """MF embedding lookup over reduced P/Q tables (bf16 or int8): gather
    the requested rows, widen ONLY the gathered window to f32 — never the
    table — and fold the int8 row-block scales when present. Host-side
    numpy like _MFServable (no device batch work to amortize); pair
    staging shared via _PairServable."""

    def __init__(self, P, Q, Bu, Bi, mu, use_bias: bool, *,
                 p_scales=None, q_scales=None, block_rows: int = 1,
                 weights_dtype: str = "bfloat16") -> None:
        self.P = P
        self.Q = Q
        self.Bu = Bu
        self.Bi = Bi
        self.mu = np.float32(mu)
        self.use_bias = bool(use_bias)
        self.p_scales = p_scales
        self.q_scales = q_scales
        self.block_shift = int(block_rows).bit_length() - 1
        self.weights_dtype = weights_dtype

    def _rows(self, table, scales, ids):
        g = np.asarray(table[ids], np.float32)  # per-window widen (G019)
        if scales is not None:
            g = g * scales[ids >> self.block_shift]
        return g

    def dispatch(self, staged):
        u, i = staged
        out = np.sum(self._rows(self.P, self.p_scales, u)
                     * self._rows(self.Q, self.q_scales, i),
                     axis=-1) + self.mu
        if self.use_bias:
            out = out + self.Bu[u] + self.Bi[i]
        return out

    def device_tables(self):
        return [t for t in (self.P, self.Q, self.p_scales, self.q_scales,
                            self.Bu, self.Bi) if t is not None]


class _TreeServable(_Servable):
    """Shared host binning + padded vmapped tree walk (forest, GBT)."""

    has_width = False

    def __init__(self, trees_flat, bins) -> None:
        from ..models.trees.binning import BinInfo
        from ..models.trees.grow import predict_forest_binned, stack_trees

        # f32 request staging with edges narrowed ALONGSIDE: an edge that IS
        # a data value stays equal to it (both sides of the searchsorted
        # round identically), so every training-valued instance bins as the
        # tree was grown. Request values within one f32 ulp of an edge may
        # bin to the neighbor — the f32-resolution quantization the serving
        # dtype contract accepts (request payloads stage f32, G018). NOT
        # acceptable is distinct edges that collapse under f32 — nominal
        # category codes >= 2^24, or quantile edges of large-magnitude
        # quantitative features (timestamps ~1.7e9 have f32 spacing of 128)
        # — where a duplicated edge makes a bin entirely unreachable: any
        # collapsing bin keeps the model on the f64 path end to end.
        if any(np.unique(np.asarray(b.edges, np.float32)).size
               != len(b.edges) for b in bins):
            self.stage_dtype = np.float64  # graftcheck: disable=G018 (distinct bin edges collapse under f32; binning parity needs f64)
            self.bins = bins
        else:
            self.stage_dtype = np.float32
            self.bins = [BinInfo(b.nominal, np.asarray(b.edges, np.float32),
                                 b.n_bins) for b in bins]
        self.n_features = len(bins)
        self.stacked = stack_trees(trees_flat) if trees_flat else None
        self._walk = predict_forest_binned
        self.jit_fns = (predict_forest_binned,)

    def device_tables(self):
        return ([self.stacked] if self.stacked is not None else []) + \
            [b.edges for b in self.bins]

    def stage(self, instances, b_pad, width_cap):
        from ..models.trees.binning import bin_data

        X = np.asarray(instances, self.stage_dtype).reshape(
            len(instances), self.n_features)
        Xb = np.zeros((b_pad, self.n_features), np.int32)
        Xb[:len(instances)] = bin_data(X, self.bins)
        return Xb

    def dispatch(self, staged):
        if self.stacked is None:
            return np.zeros((0, staged.shape[0]), dtype=np.float32)
        return self._walk(self.stacked, staged)

    def dummy_instance(self, width):
        return [0.0] * self.n_features

    def row_keys(self, instances, width_cap: int):
        """blake2b-128 over the BINNED row (int32 bin ids) — the canonical
        form the tree walk actually consumes, so any two raw rows that
        bin identically share one cache line (and an edge-straddling
        perturbation correctly does not). Malformed requests are
        uncacheable (None); the shape error re-surfaces on the predict
        path."""
        from hashlib import blake2b

        from ..models.trees.binning import bin_data

        try:
            X = np.asarray(instances, self.stage_dtype).reshape(
                len(instances), self.n_features)
        except (TypeError, ValueError):
            return None
        Xb = np.ascontiguousarray(bin_data(X, self.bins), np.int32)
        return [blake2b(row.tobytes(), digest_size=16).digest()
                for row in Xb]


class _ForestServable(_TreeServable):
    family = "forest"

    def __init__(self, trees, bins, classification: bool,
                 n_classes: int) -> None:
        super().__init__(trees, bins)
        self.classification = classification
        self.n_classes = n_classes

    def finalize(self, raw, n):
        from ..models.trees.forest import forest_vote

        leaf_vals = np.asarray(raw)[:, :n]  # [T, n]
        if self.classification:
            return forest_vote(leaf_vals, self.n_classes)
        return leaf_vals.mean(axis=0)


class _GBTServable(_TreeServable):
    family = "gbt"

    def __init__(self, trees_flat, n_rounds: int, n_class_trees: int,
                 intercept, shrinkage: float, classes, bins) -> None:
        super().__init__(trees_flat, bins)
        self.n_rounds = n_rounds
        self.K = n_class_trees
        # staged at the tree path's dtype: f32 normally, f64 when the
        # collapse guard kept the model on the f64 path end to end
        self.intercept = np.asarray(intercept, self.stage_dtype)
        self.shrinkage = float(shrinkage)
        self.classes = np.asarray(classes)

    def finalize(self, raw, n):
        from ..models.trees.forest import gbt_decision_scores

        leaf_vals = np.asarray(raw)[:, :n]
        scores = gbt_decision_scores(leaf_vals, self.intercept,
                                     self.shrinkage, self.n_rounds, self.K)
        if scores.shape[1] == 1:
            return self.classes[(scores[:, 0] > 0).astype(int)]
        return self.classes[np.argmax(scores, axis=1)]


def _quant_servable_from_artifact(art: Artifact) -> _Servable:
    """Quantized artifact -> dequant-free servable. bf16 tables reload AT
    bf16 through the families' own scorers (raw uint16 bit patterns view
    back losslessly — io.checkpoint.bf16_unpack_raw); int8 tables keep
    their q arrays + f32 scales and score through the _q8_* kernels."""
    import jax.numpy as jnp

    from ..io.checkpoint import QUANT_SCHEME_BF16, QUANT_SCHEME_INT8, \
        SCALE_SUFFIX, bf16_unpack_raw

    meta, a = art.meta, art.arrays
    quant = manifest_quant(meta)
    fam = art.family
    if quant["scheme"] == QUANT_SCHEME_BF16:
        if fam == "linear":
            from ..core.state import init_linear_state

            state = init_linear_state(
                int(meta["dims"]), use_covariance=False,
                dtype=jnp.bfloat16,
                initial_weights=bf16_unpack_raw(a["weight"]))
            return _LinearServable(state, int(meta["dims"]))
        if fam == "multiclass":
            from ..models.multiclass import MulticlassState

            W = jnp.asarray(bf16_unpack_raw(a["weights"]), jnp.bfloat16)
            state = MulticlassState(
                weights=W, covars=None,
                touched=jnp.ones(W.shape, jnp.int8),
                step=jnp.zeros((), jnp.int32))
            return _MulticlassServable(state, meta["label_vocab"],
                                       int(meta["dims"]))
        if fam == "fm":
            from ..models.fm import FMState

            w = jnp.asarray(bf16_unpack_raw(a["w"]), jnp.bfloat16)
            v = jnp.asarray(bf16_unpack_raw(a["v"]), jnp.bfloat16)
            # training-only fields are placeholders: _fm_scores reads
            # w0/w/v only, and the quantized payload dropped the rest
            state = FMState(
                w0=jnp.asarray(a["w0"], jnp.float32), w=w, v=v,
                lambda_w0=jnp.zeros((), jnp.float32),
                lambda_w=jnp.zeros((), jnp.float32),
                lambda_v=jnp.zeros((v.shape[1],), jnp.float32),
                touched=jnp.ones((w.shape[0],), jnp.int8),
                step=jnp.zeros((), jnp.int32))
            return _FMServable(state, int(meta["dims"]))
        if fam == "mf":
            return _QuantMFServable(
                bf16_unpack_raw(a["P"]), bf16_unpack_raw(a["Q"]),
                np.asarray(a["Bu"], np.float32),
                np.asarray(a["Bi"], np.float32), float(a["mu"]),
                bool(meta["use_bias"]), weights_dtype="bfloat16")
    elif quant["scheme"] == QUANT_SCHEME_INT8:
        br = int(quant["block_rows"])
        if fam == "linear":
            return _QuantLinearServable(
                jnp.asarray(a["weight"], jnp.int8),
                jnp.asarray(a["weight" + SCALE_SUFFIX], jnp.float32),
                br, int(meta["dims"]))
        if fam == "multiclass":
            return _QuantMulticlassServable(
                jnp.asarray(a["weights"], jnp.int8),
                jnp.asarray(a["weights" + SCALE_SUFFIX], jnp.float32),
                br, meta["label_vocab"], int(meta["dims"]))
        if fam == "fm":
            return _QuantFMServable(
                jnp.asarray(a["w0"], jnp.float32),
                jnp.asarray(a["w"], jnp.int8),
                jnp.asarray(a["w" + SCALE_SUFFIX], jnp.float32),
                jnp.asarray(a["v"], jnp.int8),
                jnp.asarray(a["v" + SCALE_SUFFIX], jnp.float32),
                br, int(meta["dims"]))
        if fam == "mf":
            return _QuantMFServable(
                np.asarray(a["P"], np.int8), np.asarray(a["Q"], np.int8),
                np.asarray(a["Bu"], np.float32),
                np.asarray(a["Bi"], np.float32), float(a["mu"]),
                bool(meta["use_bias"]),
                p_scales=np.asarray(a["P" + SCALE_SUFFIX], np.float32),
                q_scales=np.asarray(a["Q" + SCALE_SUFFIX], np.float32),
                block_rows=br, weights_dtype="int8")
    raise ValueError(f"unknown quantized artifact: family {fam!r}, "
                     f"scheme {quant['scheme']!r}")


def _servable_from_artifact(art: Artifact) -> _Servable:
    import jax.numpy as jnp

    meta = art.meta
    a = art.arrays
    if manifest_quant(meta) is not None:
        return _quant_servable_from_artifact(art)
    # every device table reloads at its MANIFEST dtype: the pack stores
    # reduced tables widened (value-exact), so asarray without a pin would
    # silently serve a bf16-trained model at 2x HBM traffic (G020)
    table_dt = manifest_dtype(meta)
    if art.family == "linear":
        from ..core.state import init_linear_state
        from ..io.checkpoint import dense_from_rows

        w, c = dense_from_rows(int(meta["dims"]), a["feature"], a["weight"],
                               a.get("covar"))
        state = init_linear_state(
            int(meta["dims"]), use_covariance=bool(meta["use_covariance"]),
            dtype=table_dt, initial_weights=w, initial_covars=c)
        return _LinearServable(state, int(meta["dims"]))
    if art.family == "multiclass":
        from ..models.multiclass import MulticlassState

        weights = jnp.asarray(a["weights"], table_dt)
        state = MulticlassState(
            weights=weights,
            covars=jnp.asarray(a["covars"], table_dt) if "covars" in a
            else None,
            touched=jnp.ones(weights.shape, jnp.int8),
            step=jnp.zeros((), jnp.int32))
        return _MulticlassServable(state, meta["label_vocab"],
                                   int(meta["dims"]))
    if art.family == "fm":
        from ..models.fm import FMState

        state = FMState(
            w0=jnp.asarray(a["w0"], table_dt),
            w=jnp.asarray(a["w"], table_dt),
            v=jnp.asarray(a["v"], table_dt),
            lambda_w0=jnp.asarray(a["lambda_w0"], table_dt),
            lambda_w=jnp.asarray(a["lambda_w"], table_dt),
            lambda_v=jnp.asarray(a["lambda_v"], table_dt),
            touched=jnp.asarray(a["touched"], jnp.int8),
            step=jnp.zeros((), jnp.int32))
        return _FMServable(state, int(meta["dims"]))
    if art.family == "ffm":
        model = rebuild_model(art)
        return _FFMServable(model.state, model.hyper)
    if art.family == "mf":
        return _MFServable(rebuild_model(art))
    if art.family == "forest":
        from .artifact import _unpack_bins, _unpack_trees

        trees = _unpack_trees("tree", int(meta["n_trees"]), a)
        return _ForestServable(trees, _unpack_bins(meta, a),
                               bool(meta["classification"]),
                               int(meta["n_classes"]))
    if art.family == "gbt":
        from .artifact import _unpack_bins, _unpack_trees

        n = int(meta["n_rounds"]) * int(meta["n_class_trees"])
        trees = _unpack_trees("tree", n, a)
        return _GBTServable(trees, int(meta["n_rounds"]),
                            int(meta["n_class_trees"]), a["intercept"],
                            float(meta["shrinkage"]), a["classes"],
                            _unpack_bins(meta, a))
    raise ValueError(f"unknown artifact family {art.family!r}")


def _servable_from_model(model) -> _Servable:
    family = family_of(model)
    if family == "linear":
        return _LinearServable(model.state, model.dims)
    if family == "multiclass":
        return _MulticlassServable(model.state, model.label_vocab, model.dims)
    if family == "fm":
        return _FMServable(model.state, model.dims)
    if family == "ffm":
        return _FFMServable(model.state, model.hyper)
    if family == "mf":
        return _MFServable(model)
    if family == "forest":
        return _ForestServable([t.tree for t in model.trees], model.bins,
                               model.classification, model.n_classes)
    if family == "gbt":
        flat = [t for round_trees in model.trees for t in round_trees]
        return _GBTServable(flat, len(model.trees),
                            len(model.trees[0]) if model.trees else 0,
                            model.intercept, model.shrinkage, model.classes,
                            model.bins)
    raise ValueError(f"unknown family {family!r}")


def _dtype_bits(name: str) -> int:
    """Bits per element of a weights_dtype name (bf16 is not a stock numpy
    dtype string, so map it explicitly)."""
    if name == "bfloat16":
        return 16
    try:
        return int(np.dtype(name).itemsize) * 8
    except TypeError:
        return 32


# Warmup dummy instances keyed by bucket shape, shared across engines:
# deploying N same-family models re-warms the same (batch, width) mesh, and
# re-CONSTRUCTING the dummy rows per model is pure host-side waste (jit
# caches are already shared — see the module docstring). dummy_instance is
# shape-determined (family + width + feature count), so one construction
# serves every model. Plain dict mutation is GIL-atomic; a racing deploy at
# worst constructs one duplicate.
_WARMUP_DUMMIES: dict = {}


def _warmup_dummy(servable: _Servable, width: int):
    # mesh shape is part of the key: a sharded servable's warmup sweep is
    # logically per-mesh (the jit caches it fills are keyed by mesh), so a
    # (1, 4) engine must not hand its cache hit to a (2, 2) one — even
    # though the dummy CONTENT only depends on shape, keeping the keys
    # honest keeps the dedup test meaningful per mesh
    key = (servable.family, width, getattr(servable, "n_features", None),
           servable.mesh_shape)
    inst = _WARMUP_DUMMIES.get(key)
    if inst is None:
        inst = _WARMUP_DUMMIES[key] = servable.dummy_instance(width)
    return inst


# the protocol's public name: external servable implementations (and type
# hints) should spell it Servable; the underscore spelling predates the
# placement refactor and the in-tree adapters keep it
Servable = _Servable


def make_servable(obj, placement=None) -> _Servable:
    """Artifact | artifact dir path | trained model -> family servable.

    ``placement`` (None | kind string | serving.placement.Placement)
    decides where the score tables live: the default single-device
    adapters below, or the NamedSharding-striped servables of
    serving/sharded.py for ``replicated`` / ``model_sharded``. A
    ``device_byte_budget`` on the placement is enforced here — a model
    whose per-device resident score-table bytes exceed it refuses to load
    (ModelExceedsDeviceBudget) instead of OOMing at first request."""
    from .placement import resolve_placement

    placement = resolve_placement(placement)
    if isinstance(obj, str):
        obj = load(obj)
    if placement.kind != "single_device":
        from .sharded import sharded_servable

        return sharded_servable(obj, placement)
    servable = _servable_from_artifact(obj) if isinstance(obj, Artifact) \
        else _servable_from_model(obj)
    if placement.device_byte_budget is not None:
        placement.check_budget(servable.table_bytes(),
                               f"{servable.family} model "
                               f"({servable.weights_dtype})")
    return servable


class ServingEngine:
    """Bucketed, warmed, metered predictor for one model version.

    `predict(instances)` is thread-safe for the jitted families (the state
    is immutable and jit dispatch is reentrant); the dynamic batcher
    (serving/batcher.py) serializes calls anyway so each batch is one
    device dispatch.
    """

    def __init__(self, source, *, name: str = "default",
                 max_batch: int = 512, max_width: int = 256,
                 min_batch_bucket: int = 8, placement=None) -> None:
        if max_batch < min_batch_bucket:
            raise ValueError("max_batch must be >= min_batch_bucket")
        self.servable = source if isinstance(source, _Servable) \
            else make_servable(source, placement=placement)
        self.placement = self.servable.placement_info or \
            {"kind": "single_device", "devices": 1, "mesh_shape": None,
             "batch_shards": 1, "model_shards": 1}
        bs = int(self.placement.get("batch_shards", 1))
        if bs > 1 and (min_batch_bucket % bs or max_batch % bs):
            # every batch bucket must split evenly over the batch axis —
            # buckets are min_batch_bucket * 2^k capped at max_batch, so
            # divisibility of the two ends covers the whole ladder
            raise ValueError(
                f"batch_shards={bs} must divide min_batch_bucket "
                f"({min_batch_bucket}) and max_batch ({max_batch})")
        self.family = self.servable.family
        self.name = name
        self.max_batch = int(max_batch)
        self.max_width = int(max_width)
        self.min_batch_bucket = int(min_batch_bucket)
        self._latency = REGISTRY.histogram(
            f"serving.{name}.predict_seconds", LATENCY_BUCKETS)
        self._rows = REGISTRY.counter("serving", f"{name}.rows")
        self._truncated = REGISTRY.counter("serving", f"{name}.truncated_rows")
        self.warmed_buckets: List[Tuple[int, Optional[int]]] = []
        # dispatch-level service-rate estimate (rows/sec EWMA over recent
        # predicts) — the capacity signal the overload surface reads:
        # /metrics exports it and the batcher's Retry-After math uses its
        # own copy of the same quantity. The express and general batcher
        # lanes both call predict, so the read-modify-write is guarded.
        self.rows_per_sec = 0.0
        self._rate_lock = threading.Lock()
        # per-model precision surface (/models + /metrics): the dtype the
        # tables serve at and the resident bytes a request's gathers read —
        # what bf16/int8 artifacts shrink 2-4x
        self.weights_dtype = self.servable.weights_dtype
        self.table_bytes = int(self.servable.table_bytes())
        REGISTRY.set_gauge(f"serving.{name}.table_bytes",
                           float(self.table_bytes))
        REGISTRY.set_gauge(f"serving.{name}.weights_bits",
                           float(_dtype_bits(self.weights_dtype)))
        # placement gauges: how many devices this model's bytes spread over
        # and what one device actually holds (total for single-device)
        self.per_device_table_bytes = int(getattr(
            self.servable, "per_device_table_bytes", 0)) or self.table_bytes
        REGISTRY.set_gauge(f"serving.{name}.model_shards",
                           float(self.placement.get("model_shards", 1)))
        REGISTRY.set_gauge(f"serving.{name}.per_device_table_bytes",
                           float(self.per_device_table_bytes))

    # -- buckets -------------------------------------------------------------

    def batch_buckets(self) -> List[int]:
        out, b = [], self.min_batch_bucket
        while b < self.max_batch:
            out.append(b)
            b <<= 1
        out.append(self.max_batch)
        return out

    def width_buckets(self) -> List[Optional[int]]:
        if not self.servable.has_width:
            return [None]
        out, w = [], 8
        while w < self.max_width:
            out.append(w)
            w <<= 1
        out.append(self.max_width)
        return out

    def bucket_batch(self, n: int) -> int:
        b = self.min_batch_bucket
        while b < n:
            b <<= 1
        return min(b, self.max_batch)

    # -- serving -------------------------------------------------------------

    def warmup(self) -> int:
        """Precompile every (batch, width) bucket; returns the number of jit
        cache misses the sweep cost (all of them paid here, none in steady
        state). Idempotent — a second warmup compiles nothing."""
        t0 = time.perf_counter()
        # the warmup span makes every deploy-time compile visible as a
        # jit_recompile instant INSIDE a trace (recompile_guard emits them)
        with TRACER.span("engine.warmup", args={"engine": self.name,
                                                "family": self.family}), \
                recompile_guard(f"serving.{self.name}.warmup",
                                *self.servable.jit_fns) as g:
            for width in self.width_buckets():
                # dummy construction is keyed by bucket shape and shared
                # across engines (_WARMUP_DUMMIES) — pure host-side dedup,
                # the jit-cache semantics are untouched
                inst = _warmup_dummy(self.servable, width or 8)
                for b in self.batch_buckets():
                    raw = self.servable.run_padded([inst], b, self.max_width)
                    self.servable.finalize(raw, 1)
                    self.warmed_buckets.append((b, width))
        REGISTRY.set_gauge(f"serving.{self.name}.warmup_seconds",
                           time.perf_counter() - t0)
        REGISTRY.set_gauge(f"serving.{self.name}.warmup_compiles",
                           float(g.compiles))
        return g.compiles

    def row_keys(self, instances):
        """Per-row canonical cache keys for this request, or None when it
        is not cacheable (unsupported family, over-wide rows, malformed
        input — which then fails through the normal predict path). The
        hot-row score cache keys ``(model_version, row_key)`` on these
        (serving/cache.py; docs/serving.md "Score caching &
        coalescing")."""
        try:
            return self.servable.row_keys(instances, self.max_width)
        except Exception:  # graftcheck: disable=G028 (None = uncacheable; the error re-surfaces on the predict path)
            return None

    def predict(self, instances: Sequence):
        """Score a request of any size (chunks above max_batch). Each
        chunk's path is traced stage by stage — bucket selection, host
        pad, device dispatch, host block — as child spans of whatever
        request span is active (runtime/tracing.py), so a slow predict is
        attributable from the trace alone.

        ``instances`` is a list of rows, or — for the sparse-row families
        ONLY (other families treat any tuple as a plain sequence of rows)
        — a pre-parsed tuple: ``(idx_rows, val_rows)`` per-row arrays (the
        ``models.base._stage_rows`` convention) or the flat
        ``(flat_idx, flat_val, lens)`` packed form (see _is_preparsed)."""
        pre = (isinstance(self.servable, _SparseRowServable)
               and _is_preparsed(instances))
        off = _preparsed_offsets(instances) if pre else None
        n = _preparsed_len(instances) if pre else len(instances)
        if n == 0:
            return []
        t0 = time.perf_counter()
        outs = []
        with TRACER.span("engine.predict",
                         args={"engine": self.name, "family": self.family,
                               "rows": n}) as pspan:
            for s in range(0, n, self.max_batch):
                if pre:
                    chunk = _preparsed_chunk(instances, s,
                                             min(s + self.max_batch, n),
                                             off)
                    chunk_n = _preparsed_len(chunk)
                else:
                    chunk = instances[s:s + self.max_batch]
                    chunk_n = len(chunk)
                with TRACER.span("engine.bucket") as bspan:
                    if self.servable.has_width:
                        overwide = self.servable.count_overwide(
                            chunk, self.max_width)
                        if overwide:
                            self._truncated.increment(overwide)
                    b_pad = self.bucket_batch(chunk_n)
                    bspan.set(rows=chunk_n, b_pad=b_pad)
                with TRACER.span("engine.pad", args={"b_pad": b_pad}):
                    staged = self.servable.stage(chunk, b_pad,
                                                 self.max_width)
                with recompile_guard(f"serving.{self.name}",
                                     *self.servable.jit_fns):
                    with TRACER.span("engine.dispatch"):
                        raw = self.servable.dispatch(staged)
                    # finalize materializes the device result on the host
                    # — this is where an async dispatch is actually waited
                    # on (block_until_ready by another name)
                    with TRACER.span("engine.block"):
                        out = self.servable.finalize(raw, chunk_n)
                outs.append(out)
            self._rows.increment(n)
            dt = time.perf_counter() - t0
            self._latency.observe(dt, trace_id=TRACER.exemplar_id(pspan))
            if dt > 0:
                inst = n / dt
                with self._rate_lock:
                    self.rows_per_sec = inst if self.rows_per_sec <= 0.0 \
                        else 0.8 * self.rows_per_sec + 0.2 * inst
                    rate = self.rows_per_sec
                REGISTRY.set_gauge(f"serving.{self.name}.engine_rows_per_sec",
                                   rate)
        if len(outs) == 1:
            return outs[0]
        if isinstance(outs[0], np.ndarray):
            return np.concatenate(outs)
        return [x for o in outs for x in o]
