"""Shape-bucketed online predictors — zero steady-state recompiles.

XLA compiles one program per input shape, so a naive server retraces on
every distinct (batch, row-width) pair — the recompilation-count failure
mode the ads-infra paper tracks as a production metric (PAPERS.md). The
serving discipline here is the training-side G001 discipline
(core/batch.py) applied to inference:

- row width pads to a power of two >= 8 (``pad_to_bucket``), capped at
  ``max_width`` (longer rows truncate, counted);
- batch size pads to a power of two >= ``min_batch_bucket``, capped at
  ``max_batch`` (bigger requests chunk);
- ``warmup()`` drives a dummy batch through EVERY (batch, width) bucket at
  load time, so the steady state never compiles — witnessed at run time by
  ``runtime.metrics.recompile_guard`` around every predict call
  (counter ``graftcheck.recompiles.serving.<name>`` stays flat).

Every family reuses the SAME jitted scorer its live model uses
(core/engine.make_predict, models/fm._fm_scores, models/ffm._ffm_scores_jit,
models/multiclass._mc_scores, models/trees/grow.predict_forest_binned), so
served predictions are bit-identical to the trained object's — padding rows
are row-independent no-ops. MF is the exception by design: its predict is a
host-side embedding lookup (numpy gather-dot, no device batch work to
amortize), identical to TrainedMFModel.predict.

Attribution caveat: because those scorers (and their jit caches) are shared
process-wide, a deploy WARMING another same-family model concurrently with
an open predict guard can transiently attribute its warmup compiles to the
serving engine's counter. The flat-counter invariant is exact whenever no
deploy is in flight; sharing the cache is the point (a new version of the
same shapes warms for free), so the counter trades per-engine attribution
for that.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core.batch import pack_rows, pad_to_bucket
from ..runtime.metrics import REGISTRY, recompile_guard
from ..runtime.tracing import TRACER
from .artifact import Artifact, family_of, load, manifest_dtype, \
    rebuild_model

# serving latency is sub-ms-to-seconds shaped; finer low end than the
# metrics default
LATENCY_BUCKETS = (0.0002, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                   0.05, 0.1, 0.25, 0.5, 1.0, 2.5)


# The serving dtype contract (graftcheck G017-G021, docs/static_analysis.md
# "preparing for quantized artifacts"): request payloads and host staging are
# f32, device tables reload at their MANIFEST dtype (artifact.manifest_dtype)
# — never at whatever width the widened-at-rest pack happens to hold — and
# nothing on the score path allocates f64.


class _Servable:
    """Family adapter: host staging + padded jitted scoring.

    The request path is three explicitly separated stages so the tracer
    (runtime/tracing.py) can attribute time per stage:

    - ``stage(instances, b_pad, width_cap)`` — host-side parse + pad to
      ``[b_pad, width_bucket]`` arrays (the "pad" span);
    - ``dispatch(staged)`` — the device scoring call on staged arrays,
      asynchronous for the jitted families (the "dispatch" span);
    - ``finalize(raw, n)`` — map padded raw output back to ``n``
      user-facing predictions; materializing the device result here is
      where the host blocks (the "block" span).

    ``run_padded`` composes stage+dispatch for callers that don't need
    the split (warmup).
    """

    family: str = ""
    jit_fns: Tuple = ()
    # families with a row-width axis warm up over width buckets; the rest
    # only have the batch axis
    has_width: bool = True

    def stage(self, instances, b_pad: int, width_cap: int):
        raise NotImplementedError

    def dispatch(self, staged):
        raise NotImplementedError

    def run_padded(self, instances, b_pad: int, width_cap: int):
        return self.dispatch(self.stage(instances, b_pad, width_cap))

    def finalize(self, raw, n: int):
        return np.asarray(raw)[:n]

    def dummy_instance(self, width: Optional[int]):
        raise NotImplementedError

    def max_nnz(self, instances) -> int:
        return max((len(r) for r in instances), default=1)

    def count_overwide(self, instances, width_cap: int) -> int:
        """How many rows will actually truncate at ``width_cap`` — the
        operator signal for sizing max_width (exact, not per-chunk)."""
        return sum(1 for r in instances if len(r) > width_cap)


class _SparseRowServable(_Servable):
    """Shared staging for the "feature[:value]" row families (linear,
    multiclass, FM): parse -> width-bucket -> one padded FeatureBlock.
    Subclasses only provide the jitted score call."""

    def __init__(self, dims: int) -> None:
        self.dims = dims

    def stage(self, instances, b_pad: int, width_cap: int):
        from ..models.base import _stage_rows

        idx_rows, val_rows = _stage_rows(instances, self.dims)
        n = len(idx_rows)
        width = min(pad_to_bucket(self.max_nnz(idx_rows)), width_cap)
        return pack_rows(idx_rows, val_rows, np.zeros(n, dtype=np.float32),
                         self.dims, width=width, batch_size=b_pad)

    def dummy_instance(self, width):
        return [(i, 1.0) for i in range(width)]


class _LinearServable(_SparseRowServable):
    family = "linear"

    def __init__(self, state, dims: int) -> None:
        from ..core.engine import make_predict

        super().__init__(dims)
        self.state = state
        self._predict = make_predict(use_covariance=False)
        self.jit_fns = (self._predict,)

    def dispatch(self, staged):
        return self._predict(self.state, staged.indices, staged.values)


class _MulticlassServable(_SparseRowServable):
    family = "multiclass"

    def __init__(self, state, label_vocab, dims: int) -> None:
        from ..models.multiclass import _mc_scores

        super().__init__(dims)
        self.state = state
        self.label_vocab = list(label_vocab)
        self._scores = _mc_scores
        self.jit_fns = (_mc_scores,)

    def dispatch(self, staged):
        return self._scores(self.state.weights, staged.indices,
                            staged.values)

    def finalize(self, raw, n):
        scores = np.asarray(raw)[:n]
        return [self.label_vocab[i] for i in np.argmax(scores, axis=1)]


class _FMServable(_SparseRowServable):
    family = "fm"

    def __init__(self, state, dims: int) -> None:
        from ..models.fm import _fm_scores

        super().__init__(dims)
        self.state = state
        self._scores = _fm_scores
        self.jit_fns = (_fm_scores,)

    def dispatch(self, staged):
        return self._scores(self.state, staged.indices, staged.values)


class _FFMServable(_Servable):
    family = "ffm"

    def __init__(self, state, hyper) -> None:
        from ..models.ffm import _ffm_scores_jit

        self.state = state
        self.hyper = hyper
        self._scores = _ffm_scores_jit
        self.jit_fns = (_ffm_scores_jit,)

    def stage(self, instances, b_pad, width_cap):
        from ..utils.feature import FMFeature

        hy = self.hyper
        parsed = [[FMFeature.parse(f, num_features=hy.num_features,
                                   num_fields=hy.num_fields) for f in row]
                  for row in instances]
        width = min(pad_to_bucket(self.max_nnz(parsed)), width_cap)
        idx = np.full((b_pad, width), hy.num_features, np.int32)
        val = np.zeros((b_pad, width), np.float32)
        fld = np.zeros((b_pad, width), np.int32)
        for r, row in enumerate(parsed):
            for c, f in enumerate(row[:width]):
                idx[r, c] = f.index % hy.num_features
                val[r, c] = f.value
                fld[r, c] = (f.field if f.field >= 0 else 0) % hy.num_fields
        return idx, val, fld

    def dispatch(self, staged):
        idx, val, fld = staged
        return self._scores(self.hyper, self.state, idx, val, fld)

    def dummy_instance(self, width):
        return [f"{k % 8}:{k}:1.0" for k in range(width)]


class _MFServable(_Servable):
    """Host-side embedding lookup — numpy gather-dot, bit-identical to
    TrainedMFModel.predict; there is no [B, K] device batch shape to
    bucket, so has_width is False and jit_fns is empty."""

    family = "mf"
    has_width = False

    def __init__(self, model) -> None:
        self.model = model

    def stage(self, instances, b_pad, width_cap):
        pairs = np.asarray(instances, np.int64).reshape(len(instances), 2)
        u = np.zeros(b_pad, np.int64)
        i = np.zeros(b_pad, np.int64)
        u[:len(instances)] = pairs[:, 0]
        i[:len(instances)] = pairs[:, 1]
        return u, i

    def dispatch(self, staged):
        u, i = staged
        return self.model.predict(u, i)

    def dummy_instance(self, width):
        return (0, 0)


class _TreeServable(_Servable):
    """Shared host binning + padded vmapped tree walk (forest, GBT)."""

    has_width = False

    def __init__(self, trees_flat, bins) -> None:
        from ..models.trees.binning import BinInfo
        from ..models.trees.grow import predict_forest_binned, stack_trees

        # f32 request staging with edges narrowed ALONGSIDE: an edge that IS
        # a data value stays equal to it (both sides of the searchsorted
        # round identically), so every training-valued instance bins as the
        # tree was grown. Request values within one f32 ulp of an edge may
        # bin to the neighbor — the f32-resolution quantization the serving
        # dtype contract accepts (request payloads stage f32, G018). NOT
        # acceptable is distinct edges that collapse under f32 — nominal
        # category codes >= 2^24, or quantile edges of large-magnitude
        # quantitative features (timestamps ~1.7e9 have f32 spacing of 128)
        # — where a duplicated edge makes a bin entirely unreachable: any
        # collapsing bin keeps the model on the f64 path end to end.
        if any(np.unique(np.asarray(b.edges, np.float32)).size
               != len(b.edges) for b in bins):
            self.stage_dtype = np.float64  # graftcheck: disable=G018 (distinct bin edges collapse under f32; binning parity needs f64)
            self.bins = bins
        else:
            self.stage_dtype = np.float32
            self.bins = [BinInfo(b.nominal, np.asarray(b.edges, np.float32),
                                 b.n_bins) for b in bins]
        self.n_features = len(bins)
        self.stacked = stack_trees(trees_flat) if trees_flat else None
        self._walk = predict_forest_binned
        self.jit_fns = (predict_forest_binned,)

    def stage(self, instances, b_pad, width_cap):
        from ..models.trees.binning import bin_data

        X = np.asarray(instances, self.stage_dtype).reshape(
            len(instances), self.n_features)
        Xb = np.zeros((b_pad, self.n_features), np.int32)
        Xb[:len(instances)] = bin_data(X, self.bins)
        return Xb

    def dispatch(self, staged):
        if self.stacked is None:
            return np.zeros((0, staged.shape[0]), dtype=np.float32)
        return self._walk(self.stacked, staged)

    def dummy_instance(self, width):
        return [0.0] * self.n_features


class _ForestServable(_TreeServable):
    family = "forest"

    def __init__(self, trees, bins, classification: bool,
                 n_classes: int) -> None:
        super().__init__(trees, bins)
        self.classification = classification
        self.n_classes = n_classes

    def finalize(self, raw, n):
        from ..models.trees.forest import forest_vote

        leaf_vals = np.asarray(raw)[:, :n]  # [T, n]
        if self.classification:
            return forest_vote(leaf_vals, self.n_classes)
        return leaf_vals.mean(axis=0)


class _GBTServable(_TreeServable):
    family = "gbt"

    def __init__(self, trees_flat, n_rounds: int, n_class_trees: int,
                 intercept, shrinkage: float, classes, bins) -> None:
        super().__init__(trees_flat, bins)
        self.n_rounds = n_rounds
        self.K = n_class_trees
        # staged at the tree path's dtype: f32 normally, f64 when the
        # collapse guard kept the model on the f64 path end to end
        self.intercept = np.asarray(intercept, self.stage_dtype)
        self.shrinkage = float(shrinkage)
        self.classes = np.asarray(classes)

    def finalize(self, raw, n):
        from ..models.trees.forest import gbt_decision_scores

        leaf_vals = np.asarray(raw)[:, :n]
        scores = gbt_decision_scores(leaf_vals, self.intercept,
                                     self.shrinkage, self.n_rounds, self.K)
        if scores.shape[1] == 1:
            return self.classes[(scores[:, 0] > 0).astype(int)]
        return self.classes[np.argmax(scores, axis=1)]


def _servable_from_artifact(art: Artifact) -> _Servable:
    import jax.numpy as jnp

    meta = art.meta
    a = art.arrays
    # every device table reloads at its MANIFEST dtype: the pack stores
    # reduced tables widened (value-exact), so asarray without a pin would
    # silently serve a bf16-trained model at 2x HBM traffic (G020)
    table_dt = manifest_dtype(meta)
    if art.family == "linear":
        from ..core.state import init_linear_state
        from ..io.checkpoint import dense_from_rows

        w, c = dense_from_rows(int(meta["dims"]), a["feature"], a["weight"],
                               a.get("covar"))
        state = init_linear_state(
            int(meta["dims"]), use_covariance=bool(meta["use_covariance"]),
            dtype=table_dt, initial_weights=w, initial_covars=c)
        return _LinearServable(state, int(meta["dims"]))
    if art.family == "multiclass":
        from ..models.multiclass import MulticlassState

        weights = jnp.asarray(a["weights"], table_dt)
        state = MulticlassState(
            weights=weights,
            covars=jnp.asarray(a["covars"], table_dt) if "covars" in a
            else None,
            touched=jnp.ones(weights.shape, jnp.int8),
            step=jnp.zeros((), jnp.int32))
        return _MulticlassServable(state, meta["label_vocab"],
                                   int(meta["dims"]))
    if art.family == "fm":
        from ..models.fm import FMState

        state = FMState(
            w0=jnp.asarray(a["w0"], table_dt),
            w=jnp.asarray(a["w"], table_dt),
            v=jnp.asarray(a["v"], table_dt),
            lambda_w0=jnp.asarray(a["lambda_w0"], table_dt),
            lambda_w=jnp.asarray(a["lambda_w"], table_dt),
            lambda_v=jnp.asarray(a["lambda_v"], table_dt),
            touched=jnp.asarray(a["touched"], jnp.int8),
            step=jnp.zeros((), jnp.int32))
        return _FMServable(state, int(meta["dims"]))
    if art.family == "ffm":
        model = rebuild_model(art)
        return _FFMServable(model.state, model.hyper)
    if art.family == "mf":
        return _MFServable(rebuild_model(art))
    if art.family == "forest":
        from .artifact import _unpack_bins, _unpack_trees

        trees = _unpack_trees("tree", int(meta["n_trees"]), a)
        return _ForestServable(trees, _unpack_bins(meta, a),
                               bool(meta["classification"]),
                               int(meta["n_classes"]))
    if art.family == "gbt":
        from .artifact import _unpack_bins, _unpack_trees

        n = int(meta["n_rounds"]) * int(meta["n_class_trees"])
        trees = _unpack_trees("tree", n, a)
        return _GBTServable(trees, int(meta["n_rounds"]),
                            int(meta["n_class_trees"]), a["intercept"],
                            float(meta["shrinkage"]), a["classes"],
                            _unpack_bins(meta, a))
    raise ValueError(f"unknown artifact family {art.family!r}")


def _servable_from_model(model) -> _Servable:
    family = family_of(model)
    if family == "linear":
        return _LinearServable(model.state, model.dims)
    if family == "multiclass":
        return _MulticlassServable(model.state, model.label_vocab, model.dims)
    if family == "fm":
        return _FMServable(model.state, model.dims)
    if family == "ffm":
        return _FFMServable(model.state, model.hyper)
    if family == "mf":
        return _MFServable(model)
    if family == "forest":
        return _ForestServable([t.tree for t in model.trees], model.bins,
                               model.classification, model.n_classes)
    if family == "gbt":
        flat = [t for round_trees in model.trees for t in round_trees]
        return _GBTServable(flat, len(model.trees),
                            len(model.trees[0]) if model.trees else 0,
                            model.intercept, model.shrinkage, model.classes,
                            model.bins)
    raise ValueError(f"unknown family {family!r}")


def make_servable(obj) -> _Servable:
    """Artifact | artifact dir path | trained model -> family servable."""
    if isinstance(obj, str):
        obj = load(obj)
    if isinstance(obj, Artifact):
        return _servable_from_artifact(obj)
    return _servable_from_model(obj)


class ServingEngine:
    """Bucketed, warmed, metered predictor for one model version.

    `predict(instances)` is thread-safe for the jitted families (the state
    is immutable and jit dispatch is reentrant); the dynamic batcher
    (serving/batcher.py) serializes calls anyway so each batch is one
    device dispatch.
    """

    def __init__(self, source, *, name: str = "default",
                 max_batch: int = 512, max_width: int = 256,
                 min_batch_bucket: int = 8) -> None:
        if max_batch < min_batch_bucket:
            raise ValueError("max_batch must be >= min_batch_bucket")
        self.servable = source if isinstance(source, _Servable) \
            else make_servable(source)
        self.family = self.servable.family
        self.name = name
        self.max_batch = int(max_batch)
        self.max_width = int(max_width)
        self.min_batch_bucket = int(min_batch_bucket)
        self._latency = REGISTRY.histogram(
            f"serving.{name}.predict_seconds", LATENCY_BUCKETS)
        self._rows = REGISTRY.counter("serving", f"{name}.rows")
        self._truncated = REGISTRY.counter("serving", f"{name}.truncated_rows")
        self.warmed_buckets: List[Tuple[int, Optional[int]]] = []

    # -- buckets -------------------------------------------------------------

    def batch_buckets(self) -> List[int]:
        out, b = [], self.min_batch_bucket
        while b < self.max_batch:
            out.append(b)
            b <<= 1
        out.append(self.max_batch)
        return out

    def width_buckets(self) -> List[Optional[int]]:
        if not self.servable.has_width:
            return [None]
        out, w = [], 8
        while w < self.max_width:
            out.append(w)
            w <<= 1
        out.append(self.max_width)
        return out

    def bucket_batch(self, n: int) -> int:
        b = self.min_batch_bucket
        while b < n:
            b <<= 1
        return min(b, self.max_batch)

    # -- serving -------------------------------------------------------------

    def warmup(self) -> int:
        """Precompile every (batch, width) bucket; returns the number of jit
        cache misses the sweep cost (all of them paid here, none in steady
        state). Idempotent — a second warmup compiles nothing."""
        t0 = time.perf_counter()
        # the warmup span makes every deploy-time compile visible as a
        # jit_recompile instant INSIDE a trace (recompile_guard emits them)
        with TRACER.span("engine.warmup", args={"engine": self.name,
                                                "family": self.family}), \
                recompile_guard(f"serving.{self.name}.warmup",
                                *self.servable.jit_fns) as g:
            for width in self.width_buckets():
                inst = self.servable.dummy_instance(width or 8)
                for b in self.batch_buckets():
                    raw = self.servable.run_padded([inst], b, self.max_width)
                    self.servable.finalize(raw, 1)
                    self.warmed_buckets.append((b, width))
        REGISTRY.set_gauge(f"serving.{self.name}.warmup_seconds",
                           time.perf_counter() - t0)
        REGISTRY.set_gauge(f"serving.{self.name}.warmup_compiles",
                           float(g.compiles))
        return g.compiles

    def predict(self, instances: Sequence):
        """Score a request of any size (chunks above max_batch). Each
        chunk's path is traced stage by stage — bucket selection, host
        pad, device dispatch, host block — as child spans of whatever
        request span is active (runtime/tracing.py), so a slow predict is
        attributable from the trace alone."""
        n = len(instances)
        if n == 0:
            return []
        t0 = time.perf_counter()
        outs = []
        with TRACER.span("engine.predict",
                         args={"engine": self.name, "family": self.family,
                               "rows": n}) as pspan:
            for s in range(0, n, self.max_batch):
                chunk = instances[s:s + self.max_batch]
                with TRACER.span("engine.bucket") as bspan:
                    if self.servable.has_width:
                        overwide = self.servable.count_overwide(
                            chunk, self.max_width)
                        if overwide:
                            self._truncated.increment(overwide)
                    b_pad = self.bucket_batch(len(chunk))
                    bspan.set(rows=len(chunk), b_pad=b_pad)
                with TRACER.span("engine.pad", args={"b_pad": b_pad}):
                    staged = self.servable.stage(chunk, b_pad,
                                                 self.max_width)
                with recompile_guard(f"serving.{self.name}",
                                     *self.servable.jit_fns):
                    with TRACER.span("engine.dispatch"):
                        raw = self.servable.dispatch(staged)
                    # finalize materializes the device result on the host
                    # — this is where an async dispatch is actually waited
                    # on (block_until_ready by another name)
                    with TRACER.span("engine.block"):
                        out = self.servable.finalize(raw, len(chunk))
                outs.append(out)
            self._rows.increment(n)
            self._latency.observe(time.perf_counter() - t0,
                                  trace_id=TRACER.exemplar_id(pspan))
        if len(outs) == 1:
            return outs[0]
        if isinstance(outs[0], np.ndarray):
            return np.concatenate(outs)
        return [x for o in outs for x in o]
