"""Top-K retrieval serving: score one user against the full item catalog.

Every other serving path is pointwise — a row in, a score out — but the
embedding families (MF, FM) are *retrieval* models: the production-shaped
query is "given user u, return the top-K of N items", a [B,F]x[F,N] matmul
plus top_k that is MXU-shaped and bandwidth-bound (the ads-infra paper's
scoring tier, PAPERS.md). This module is that workload as a subsystem:

- **Staged query, streamed catalog.** The user side is gathered ONCE per
  request into ``(qvec, base)`` such that for every item j

      score(u, j) = base_u + bias_j + <qvec_u, vec_j>

  For MF that is ``mu + Bu[u]`` / ``Bi[j]`` / ``P[u]·Q[j]``; for FM it is
  algebra on the factorization identity — with item feature j one-hot at
  value 1, ``FM(x_u + e_j) = p(x_u) + w[j] + <sumVfX(x_u), v[j]>``
  exactly — so ONE block scorer serves both families. The catalog is then
  scored in fixed-size jitted blocks with a running top-K merge
  (``lax.top_k`` over carry ++ block), so no [N_items] score vector is
  ever materialized and the jit cache is independent of catalog size.
- **Zero steady-state recompiles.** Batch sizes pad to pow2 buckets, FM
  query widths pad to the engine width buckets, candidate slices pad to
  pow2 buckets; :meth:`RetrievalEngine.warmup` sweeps them all and
  ``recompile_guard`` pins the steady state (counter
  ``graftcheck.recompiles.serving.<name>.topk``).
- **Sharded catalogs.** Under a :class:`~.placement.ModelSharded`
  placement the catalog is striped over the model axis by the PR 9 grid
  arithmetic (core.striping.stripe_grid); each device scores its local
  item slice and the cross-stripe merge is an ``all_gather`` of the
  per-device block scores + global ids into the same top-K carry. int8
  catalogs serve dequant-free per the ``_q8_*`` pattern: only the sliced
  window widens to f32, scales fold by ``id >> block_shift``, and the
  accumulation is f32 (graftcheck G019/G021).
- **LSH candidate pruning.** ``freeze(..., retrieval_index=...)`` builds
  signed-random-projection buckets over the item vectors into the
  artifact (manifest ``index`` block, arrays ``index__*``); probe-time
  hashes ``qvec`` once, unions the Hamming-<=1 buckets, and the SAME
  blocked scorer consumes the padded candidate slice. Requests fall back
  to exact scoring (counted) when a bucket union is smaller than k or
  larger than ``candidate_cap`` — recall@K vs exact is measured and
  gated in ``scripts/bench_serving.py --topk``.

Tie-breaking: the streamed merge concatenates the carry BEFORE the new
block and blocks arrive in ascending-id order, so equal scores resolve to
the LOWEST item id — bit-for-bit the order of a stable argsort on the
materialized scores (the bench parity pin). The sharded merge interleaves
stripes per step, so exact ties across stripes may resolve differently;
its gate is score parity with the single-device engine (see
docs/serving.md "Top-K retrieval").

Ordering contract with the score cache: /topk results are never row-cached
(a top-K set is not a row score); the hot-row cache stays a /predict
concern.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core.striping import stripe_grid
from ..runtime.metrics import REGISTRY, recompile_guard
from ..runtime.tracing import TRACER
from .artifact import Artifact, family_of, host_score_tables, load
from .engine import LATENCY_BUCKETS
from .placement import MODEL_AXIS, ModelSharded, resolve_placement

RETRIEVAL_FAMILIES = ("mf", "fm")

# jitted retrieval kernels are keyed by everything closure-static and
# shared process-wide (the engine.py _QUANT_JIT discipline): two engines
# with the same block geometry — or one engine across hot-swaps — reuse
# one jit cache
_RETRIEVAL_JIT: dict = {}
_RETRIEVAL_JIT_LOCK = threading.Lock()


def _retrieval_jit(key, build):
    with _RETRIEVAL_JIT_LOCK:
        fn = _RETRIEVAL_JIT.get(key)
        if fn is None:
            fn = _RETRIEVAL_JIT[key] = build()
        return fn


def _pow2_at_least(n: int) -> int:
    return 1 << max(0, int(n - 1).bit_length())


# --- jitted kernels ----------------------------------------------------------
#
# One score expression, used by the streamed merge step AND the
# materializing parity baseline, so "blocked top-K == argsort of the
# materialized scores" is an identity on the score bits, not a tolerance.


def _make_block_scorer(bk: int, block_shift: Optional[int],
                       bias_scaled: bool):
    import jax
    import jax.numpy as jnp

    def score(vec, bias, vscale, bscale, qvec, base, start, n_valid):
        blk = jax.lax.dynamic_slice_in_dim(vec, start, bk, axis=0)
        bb = jax.lax.dynamic_slice_in_dim(bias, start, bk, axis=0)
        ids = start + jnp.arange(bk, dtype=jnp.int32)
        w = blk.astype(jnp.float32)  # per-window widen only (G019)
        b = bb.astype(jnp.float32)
        if block_shift is not None:
            # scales are [nb, F] for 2-D tables (io.checkpoint
            # quantize_int8: per block-of-rows, per column) — the gather
            # aligns shapes, the fold is elementwise
            w = w * vscale.at[ids >> block_shift].get(
                mode="fill", fill_value=0.0)
            if bias_scaled:
                b = b * bscale.at[ids >> block_shift].get(
                    mode="fill", fill_value=0.0)
        scores = base[:, None] + qvec @ w.T + b[None, :]
        # pad lanes (catalog rows past n_valid) must lose every merge
        return jnp.where(ids[None, :] < n_valid, scores, -jnp.inf), ids

    return score


def _build_block_step(bk: int, k_pad: int, block_shift: Optional[int],
                      bias_scaled: bool):
    """One streamed-merge step: score a [bk] catalog block, merge into the
    running [B, k_pad] carry. Carry-first concat + ascending block ids =
    stable-argsort tie order (lax.top_k keeps the lowest position)."""
    import jax
    import jax.numpy as jnp

    score = _make_block_scorer(bk, block_shift, bias_scaled)

    def step(vec, bias, vscale, bscale, qvec, base, start, n_valid, cv, ci):
        scores, ids = score(vec, bias, vscale, bscale, qvec, base, start,
                            n_valid)
        vals = jnp.concatenate([cv, scores], axis=1)
        cand = jnp.concatenate(
            [ci, jnp.broadcast_to(ids[None, :], scores.shape)], axis=1)
        tv, pos = jax.lax.top_k(vals, k_pad)
        return tv, jnp.take_along_axis(cand, pos, axis=1)

    # the carry buffers are donated: run_blocks rebinds (cv, ci) to the
    # step's outputs every iteration, so the ingoing pair is dead — XLA
    # reuses it instead of holding 2x the carry live across the sweep
    return jax.jit(step, donate_argnums=(8, 9))


def _build_block_scores(bk: int, block_shift: Optional[int],
                        bias_scaled: bool):
    """Materializing baseline (bench/tests only — not a serving path)."""
    import jax

    score = _make_block_scorer(bk, block_shift, bias_scaled)

    def block_scores(vec, bias, vscale, bscale, qvec, base, start, n_valid):
        return score(vec, bias, vscale, bscale, qvec, base, start,
                     n_valid)[0]

    return jax.jit(block_scores)


def _build_cand_step(k_pad: int, block_shift: Optional[int],
                     bias_scaled: bool):
    """Score a padded candidate slice [B, C] (LSH probe output) directly:
    per-request gather instead of the block sweep. One fn per engine;
    jit caches per (B, C) bucket shape, all swept at warmup."""
    import jax
    import jax.numpy as jnp

    def cand(vec, bias, vscale, bscale, qvec, base, ids, mask):
        rows = vec.at[ids].get(mode="fill", fill_value=0)
        w = rows.astype(jnp.float32)
        b = bias.at[ids].get(mode="fill", fill_value=0).astype(jnp.float32)
        if block_shift is not None:
            w = w * vscale.at[ids >> block_shift].get(
                mode="fill", fill_value=0.0)
            if bias_scaled:
                b = b * bscale.at[ids >> block_shift].get(
                    mode="fill", fill_value=0.0)
        scores = base[:, None] + jnp.einsum("bf,bcf->bc", qvec, w) + b
        scores = jnp.where(mask, scores, -jnp.inf)
        tv, pos = jax.lax.top_k(scores, k_pad)
        return tv, jnp.take_along_axis(ids, pos, axis=1)

    return jax.jit(cand)


def _build_fm_stage():
    """FM query staging: (p, sumVfX) per row — exactly models.fm's
    _row_predict on gathered slices, so base_u matches the /predict path."""
    import jax

    from ..models.fm import _row_predict

    def stage(w0, w, v, idx, val):
        def one(i, x):
            wg = w.at[i].get(mode="fill", fill_value=0.0)
            vg = v.at[i].get(mode="fill", fill_value=0.0)
            return _row_predict(w0, wg, vg, x)

        return jax.vmap(one)(idx, val)

    return jax.jit(stage)


def _build_q8_fm_stage(block_shift: int):
    """int8 FM query staging: per-window widen + scale fold (q8_fm_scores
    extended to also return sumVfX)."""
    import jax
    import jax.numpy as jnp

    from ..models.fm import _row_predict

    def stage(w0, qw, ws, qv, vs, idx, val):
        def one(i, x):
            sw = ws.at[i >> block_shift].get(mode="fill", fill_value=0.0)
            wg = qw.at[i].get(mode="fill",
                              fill_value=0).astype(jnp.float32) * sw
            sv = vs.at[i >> block_shift].get(mode="fill", fill_value=0.0)
            vg = qv.at[i].get(mode="fill", fill_value=0).astype(
                jnp.float32) * sv
            return _row_predict(w0, wg, vg, x)

        return jax.vmap(one)(idx, val)

    return jax.jit(stage)


# --- sharded kernels ---------------------------------------------------------


def _build_sh_block_step(mesh, stripe: int, bk: int, k_pad: int,
                         block_shift: Optional[int], bias_scaled: bool):
    """Sharded streamed-merge step: each device scores a [bk] window of
    its LOCAL stripe, the cross-stripe merge is an all_gather of scores +
    global ids into the replicated carry (psum's role in the pointwise
    path becomes a top-K merge here)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..runtime.jax_compat import shard_map

    def local(vec_l, bias_l, vscale_l, bscale_l, qvec, base, start, n_valid,
              cv, ci):
        blk = jax.lax.dynamic_slice_in_dim(vec_l, start, bk, axis=0)
        bb = jax.lax.dynamic_slice_in_dim(bias_l, start, bk, axis=0)
        lids = start + jnp.arange(bk, dtype=jnp.int32)
        gids = (jax.lax.axis_index(MODEL_AXIS) * stripe + lids).astype(
            jnp.int32)
        w = blk.astype(jnp.float32)
        b = bb.astype(jnp.float32)
        if block_shift is not None:
            w = w * vscale_l.at[lids >> block_shift].get(
                mode="fill", fill_value=0.0)
            if bias_scaled:
                b = b * bscale_l.at[lids >> block_shift].get(
                    mode="fill", fill_value=0.0)
        scores = base[:, None] + qvec @ w.T + b[None, :]
        scores = jnp.where(gids[None, :] < n_valid, scores, -jnp.inf)
        allv = jax.lax.all_gather(scores, MODEL_AXIS)  # [n, B, bk]
        alli = jax.lax.all_gather(gids, MODEL_AXIS)  # [n, bk]
        allv = jnp.moveaxis(allv, 0, 1).reshape(scores.shape[0], -1)
        alli = alli.reshape(-1)
        vals = jnp.concatenate([cv, allv], axis=1)
        cand = jnp.concatenate(
            [ci, jnp.broadcast_to(alli[None, :], allv.shape)], axis=1)
        tv, pos = jax.lax.top_k(vals, k_pad)
        return tv, jnp.take_along_axis(cand, pos, axis=1)

    m = MODEL_AXIS
    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(m), P(m), P(m), P(m), P(), P(), P(), P(),
                             P(), P()),
                   out_specs=(P(), P()))
    return jax.jit(fn)


def _build_sh_cand_step(mesh, stripe: int, k_pad: int,
                        block_shift: Optional[int], bias_scaled: bool):
    """Sharded candidate scorer: global candidate ids translate into each
    stripe (foreign lanes drop), per-device partial scores psum back up.
    Pad lanes carry mask 0, so their (real row 0) contribution zeroes out
    and the replicated mask pins them to -inf before the top_k."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..core.striping import translate_to_stripe
    from ..runtime.jax_compat import shard_map

    def local(vec_l, bias_l, vscale_l, bscale_l, qvec, base, ids, mask):
        lid, m = translate_to_stripe(ids, mask, MODEL_AXIS, stripe)
        rows = vec_l.at[lid].get(mode="fill",
                                 fill_value=0).astype(jnp.float32)
        b = bias_l.at[lid].get(mode="fill", fill_value=0).astype(jnp.float32)
        if block_shift is not None:
            rows = rows * vscale_l.at[lid >> block_shift].get(
                mode="fill", fill_value=0.0)
            if bias_scaled:
                b = b * bscale_l.at[lid >> block_shift].get(
                    mode="fill", fill_value=0.0)
        part = (jnp.einsum("bf,bcf->bc", qvec, rows) + b) * m
        s = jax.lax.psum(part, MODEL_AXIS)
        scores = jnp.where(mask > 0, base[:, None] + s, -jnp.inf)
        tv, pos = jax.lax.top_k(scores, k_pad)
        return tv, jnp.take_along_axis(ids, pos, axis=1)

    m_ = MODEL_AXIS
    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(m_), P(m_), P(m_), P(m_), P(), P(), P(),
                             P()),
                   out_specs=(P(), P()))
    return jax.jit(fn)


def _build_sh_fm_stage(mesh, stripe: int):
    """Sharded FM query staging: models.fm.sharded_gather_predict (the ONE
    feature-sharded gather+predict) already psums (p, sumVfX) — exactly
    the staging pair."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..models.fm import sharded_gather_predict
    from ..runtime.jax_compat import shard_map

    def local(w0, w_l, v_l, idx, val):
        out = sharded_gather_predict(w_l, v_l, w0, idx, val, MODEL_AXIS,
                                     stripe)
        return out[4], out[5]  # p, sum_vfx

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(), P(MODEL_AXIS), P(MODEL_AXIS), P(), P()),
                   out_specs=(P(), P()))
    return jax.jit(fn)


def _build_sh_q8_fm_stage(mesh, stripe: int, block_shift: int):
    """Sharded int8 FM staging: serving/sharded.py's _build_q8_fm partials
    extended to return sumVfX alongside p."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..core.striping import translate_to_stripe
    from ..runtime.jax_compat import shard_map

    def local(w0, qw_l, ws_l, qv_l, vs_l, idx, val):
        lidx, vmask = translate_to_stripe(idx, val, MODEL_AXIS, stripe)
        sw = ws_l.at[lidx >> block_shift].get(mode="fill", fill_value=0.0)
        wg = qw_l.at[lidx].get(mode="fill",
                               fill_value=0).astype(jnp.float32) * sw
        sv = vs_l.at[lidx >> block_shift].get(mode="fill", fill_value=0.0)
        vg = qv_l.at[lidx].get(mode="fill", fill_value=0).astype(
            jnp.float32) * sv
        vx = vg * vmask[..., None]
        linear, sum_vfx, sum_v2x2 = jax.lax.psum(
            (jnp.sum(wg * vmask, axis=-1),
             jnp.sum(vx, axis=-2),
             jnp.sum(vx * vx, axis=-2)), MODEL_AXIS)
        p = w0 + linear + 0.5 * jnp.sum(sum_vfx * sum_vfx - sum_v2x2,
                                        axis=-1)
        return p, sum_vfx

    m = MODEL_AXIS
    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(), P(m), P(m), P(m), P(m), P(), P()),
                   out_specs=(P(), P()))
    return jax.jit(fn)


def _build_sh_mf_stage(mesh, stripe_u: int, block_shift: Optional[int]):
    """Sharded MF query staging: gather P[u] / Bu[u] from the user stripes
    (serving/sharded.py _build_mf gather pattern), psum up the owned
    lanes. Returns (qvec, base=mu+Bu[u])."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..core.striping import translate_to_stripe
    from ..runtime.jax_compat import shard_map

    def local(p_l, bu_l, mu, ps_l, users):
        ones = jnp.ones(users.shape, jnp.float32)
        lid, _ = translate_to_stripe(users, ones, MODEL_AXIS, stripe_u)
        g = p_l.at[lid].get(mode="fill", fill_value=0).astype(jnp.float32)
        if block_shift is not None:
            g = g * ps_l.at[lid >> block_shift].get(
                mode="fill", fill_value=0.0)
        bu = bu_l.at[lid].get(mode="fill", fill_value=0.0)
        g, bu = jax.lax.psum((g, bu), MODEL_AXIS)
        return g, mu + bu

    m = MODEL_AXIS
    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(m), P(m), P(), P(m), P()),
                   out_specs=(P(), P()))
    return jax.jit(fn)


# --- LSH index ---------------------------------------------------------------


def build_srp_index(item_vectors, n_planes: int = 8, seed: int = 0,
                    item_lo: int = 0):
    """Signed-random-projection buckets over item vectors (the randomized-
    hashing paper's candidate pruning, PAPERS.md): deterministic in
    ``seed``, built from the f32 vectors (BEFORE any quantization — the
    index approximates angles, not stored bits).

    Returns ``(planes [P,F] f32, item_ids [N] int64 global ids grouped by
    bucket, offsets [2^P+1] int64)`` — the ``index__*`` arrays
    freeze(..., retrieval_index=...) packs into the artifact."""
    vecs = np.asarray(item_vectors, np.float32)
    if vecs.ndim != 2 or vecs.shape[0] == 0:
        raise ValueError(
            f"retrieval index needs a non-empty [N, F] vector table, got "
            f"shape {vecs.shape}")
    n_planes = int(n_planes)
    if not 1 <= n_planes <= 24:
        raise ValueError(f"n_planes must be in [1, 24], got {n_planes}")
    rng = np.random.RandomState(int(seed))
    planes = rng.standard_normal((n_planes, vecs.shape[1])).astype(
        np.float32)
    # MIPS shift trick: hash items CENTERED on the catalog mean. For any
    # query q, <q, x_j> = <q, x_j - c> + <q, c> and the second term is
    # constant over j, so top-K by score == top-K by <q, x_j - c> — and
    # centered directions spread a trained catalog (whose vectors cluster
    # in a halfspace) across the bucket space instead of piling into a
    # few buckets, which is what lets the probe actually prune. The query
    # hashes UNCENTERED (its shift is the same constant), so the center
    # never needs to ship in the artifact.
    bits = ((vecs - vecs.mean(axis=0)) @ planes.T) > 0.0
    codes = (bits.astype(np.int64)
             << np.arange(n_planes, dtype=np.int64)).sum(axis=1)
    order = np.argsort(codes, kind="stable")
    item_ids = (order + int(item_lo)).astype(np.int64)
    counts = np.bincount(codes, minlength=1 << n_planes)
    offsets = np.zeros((1 << n_planes) + 1, np.int64)
    np.cumsum(counts, out=offsets[1:])
    return planes, item_ids, offsets


class SRPIndex:
    """Query-time view of a frozen SRP index: hash qvec once, union the
    Hamming-<=1 buckets (1 + n_planes probes) into a sorted candidate id
    list per query. Host-side — probing is O(P·F + candidates)."""

    def __init__(self, planes, item_ids, offsets, item_lo: int,
                 item_hi: int, n_planes: int, seed: int) -> None:
        self.planes = np.asarray(planes, np.float32)
        self.item_ids = np.asarray(item_ids, np.int64)
        self.offsets = np.asarray(offsets, np.int64)
        self.item_lo = int(item_lo)
        self.item_hi = int(item_hi)
        self.n_planes = int(n_planes)
        self.seed = int(seed)

    @classmethod
    def from_artifact(cls, artifact: "Artifact") -> Optional["SRPIndex"]:
        info = artifact.meta.get("index")
        if not info:
            return None
        if info.get("scheme") != "srp_lsh":
            raise ValueError(
                f"unknown retrieval index scheme {info.get('scheme')!r} "
                f"(this build reads 'srp_lsh')")
        a = artifact.arrays
        return cls(a["index__planes"], a["index__item_ids"],
                   a["index__offsets"], int(info["item_lo"]),
                   int(info["item_hi"]), int(info["planes"]),
                   int(info["seed"]))

    def probe(self, qvecs: np.ndarray) -> List[np.ndarray]:
        bits = (np.asarray(qvecs, np.float32) @ self.planes.T) > 0.0
        codes = (bits.astype(np.int64)
                 << np.arange(self.n_planes, dtype=np.int64)).sum(axis=1)
        out = []
        for code in codes:
            buckets = [code] + [code ^ (1 << i)
                                for i in range(self.n_planes)]
            parts = [self.item_ids[self.offsets[b]:self.offsets[b + 1]]
                     for b in buckets]
            ids = np.concatenate(parts)
            ids.sort()  # ascending ids = stable tie order in the scorer
            out.append(ids)
        return out

    def describe(self) -> dict:
        return {"scheme": "srp_lsh", "planes": self.n_planes,
                "seed": self.seed,
                "item_range": [self.item_lo, self.item_hi],
                "buckets": 1 << self.n_planes}


# --- catalogs ----------------------------------------------------------------


class _SingleCatalog:
    """The padded item tables on ONE device + the jitted scorers over
    them. ``vec``/``bias`` are zero-padded to a block_items multiple so
    dynamic_slice windows never clamp (a clamped window would desync the
    slice content from the computed ids)."""

    def __init__(self, vec, bias, vscale, bscale, n_items: int,
                 block_items: int, k_pad: int,
                 block_shift: Optional[int], bias_scaled: bool) -> None:
        import jax.numpy as jnp

        self.n_items = int(n_items)
        self.bk = int(block_items)
        self.k_pad = int(k_pad)
        self.n_pad = -(-self.n_items // self.bk) * self.bk
        self.n_steps = self.n_pad // self.bk
        pad = self.n_pad - self.n_items
        vec = np.asarray(vec)
        bias = np.asarray(bias)
        if pad:
            vec = np.concatenate(
                [vec, np.zeros((pad,) + vec.shape[1:], vec.dtype)])
            bias = np.concatenate([bias, np.zeros((pad,), bias.dtype)])
        self.vec = jnp.asarray(vec)  # serving dtype (f32/bf16/int8, G020)
        self.bias = jnp.asarray(bias)
        if block_shift is not None:
            nb_pad = self.n_pad >> block_shift
            vscale = np.asarray(vscale, np.float32)  # [nb] or [nb, F]
            vs = np.zeros((nb_pad,) + vscale.shape[1:], np.float32)
            vs[:len(vscale)] = vscale
            self.vscale = jnp.asarray(vs)
            if bias_scaled:
                bscale = np.asarray(bscale, np.float32)
                bs = np.zeros((nb_pad,) + bscale.shape[1:], np.float32)
                bs[:len(bscale)] = bscale
                self.bscale = jnp.asarray(bs)
            else:
                self.bscale = self.vscale
        else:
            # inert stand-ins: traced but never read (block_shift is None
            # inside the kernels), keeps every kernel one signature
            self.vscale = self.bscale = self.bias
        self._step = _retrieval_jit(
            ("block", self.bk, self.k_pad, block_shift, bias_scaled),
            lambda: _build_block_step(self.bk, self.k_pad, block_shift,
                                      bias_scaled))
        self._scores = _retrieval_jit(
            ("scores", self.bk, block_shift, bias_scaled),
            lambda: _build_block_scores(self.bk, block_shift, bias_scaled))
        self._cand = _retrieval_jit(
            ("cand", self.k_pad, block_shift, bias_scaled),
            lambda: _build_cand_step(self.k_pad, block_shift, bias_scaled))
        # _scores is the bench baseline, deliberately NOT in jit_fns: it
        # is not a serving path and must not count against the zero-
        # steady-state-recompiles pin
        self.jit_fns = (self._step, self._cand)

    def run_blocks(self, qvec: np.ndarray, base: np.ndarray):
        import jax.numpy as jnp

        b = qvec.shape[0]
        cv = jnp.full((b, self.k_pad), -np.inf, jnp.float32)
        ci = jnp.full((b, self.k_pad), self.n_pad, jnp.int32)
        q = jnp.asarray(qvec)
        bs = jnp.asarray(base)
        nv = np.int32(self.n_items)
        for s in range(self.n_steps):
            cv, ci = self._step(self.vec, self.bias, self.vscale,
                                self.bscale, q, bs, np.int32(s * self.bk),
                                nv, cv, ci)
        return cv, ci

    def run_cand(self, qvec, base, ids, mask):
        import jax.numpy as jnp

        return self._cand(self.vec, self.bias, self.vscale, self.bscale,
                          jnp.asarray(qvec), jnp.asarray(base),
                          jnp.asarray(ids), jnp.asarray(mask))

    def block_scores(self, qvec: np.ndarray, base: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        q = jnp.asarray(qvec)
        bs = jnp.asarray(base)
        nv = np.int32(self.n_items)
        outs = [np.asarray(self._scores(self.vec, self.bias, self.vscale,
                                        self.bscale, q, bs,
                                        np.int32(s * self.bk), nv))
                for s in range(self.n_steps)]
        return np.concatenate(outs, axis=1)[:, :self.n_items]

    @property
    def table_bytes(self) -> int:
        n = self.vec.nbytes + self.bias.nbytes
        if self.vscale is not self.bias:
            n += self.vscale.nbytes
            if self.bscale is not self.vscale:
                n += self.bscale.nbytes
        return int(n)


class _ShardedCatalog:
    """Item tables striped over the serving mesh's model axis (the PR 9
    grid arithmetic: stripe aligned to block_items so no merge window
    straddles a stripe boundary, and to the int8 scale blocks)."""

    def __init__(self, vec, bias, vscale, bscale, n_items: int,
                 block_items: int, k_pad: int,
                 block_shift: Optional[int], bias_scaled: bool, mesh,
                 n_shards: int) -> None:
        from .sharded import _mesh_key, _stripe_put

        self.n_items = int(n_items)
        self.bk = int(block_items)
        self.k_pad = int(k_pad)
        stripe, padded = stripe_grid(self.n_items, n_shards,
                                     align=self.bk)
        self.stripe = stripe
        self.n_pad = padded
        self.n_steps = stripe // self.bk
        self.vec = _stripe_put(np.asarray(vec), 0, self.n_items, padded,
                               mesh)
        self.bias = _stripe_put(np.asarray(bias), 0, self.n_items, padded,
                                mesh)
        if block_shift is not None:
            vs = np.asarray(vscale, np.float32)
            self.vscale = _stripe_put(vs, 0, len(vs),
                                      padded >> block_shift, mesh)
            if bias_scaled:
                bs = np.asarray(bscale, np.float32)
                self.bscale = _stripe_put(bs, 0, len(bs),
                                          padded >> block_shift, mesh)
            else:
                self.bscale = self.vscale
        else:
            self.vscale = self.bscale = self.bias  # inert striped stand-in
        mk = _mesh_key(mesh)
        self._step = _retrieval_jit(
            ("sh_block", mk, stripe, self.bk, self.k_pad, block_shift,
             bias_scaled),
            lambda: _build_sh_block_step(mesh, stripe, self.bk, self.k_pad,
                                         block_shift, bias_scaled))
        self._cand = _retrieval_jit(
            ("sh_cand", mk, stripe, self.k_pad, block_shift, bias_scaled),
            lambda: _build_sh_cand_step(mesh, stripe, self.k_pad,
                                        block_shift, bias_scaled))
        self.jit_fns = (self._step, self._cand)

    def run_blocks(self, qvec: np.ndarray, base: np.ndarray):
        import jax.numpy as jnp

        b = qvec.shape[0]
        cv = jnp.full((b, self.k_pad), -np.inf, jnp.float32)
        ci = jnp.full((b, self.k_pad), self.n_pad, jnp.int32)
        q = jnp.asarray(qvec)
        bs = jnp.asarray(base)
        nv = np.int32(self.n_items)
        for s in range(self.n_steps):
            cv, ci = self._step(self.vec, self.bias, self.vscale,
                                self.bscale, q, bs, np.int32(s * self.bk),
                                nv, cv, ci)
        return cv, ci

    def run_cand(self, qvec, base, ids, mask):
        import jax.numpy as jnp

        return self._cand(self.vec, self.bias, self.vscale, self.bscale,
                          jnp.asarray(qvec), jnp.asarray(base),
                          jnp.asarray(ids),
                          jnp.asarray(mask, jnp.float32))

    def block_scores(self, qvec, base):
        raise NotImplementedError(
            "the materializing parity baseline runs on the single-device "
            "engine; the sharded gate is score parity against it "
            "(docs/serving.md 'Top-K retrieval')")

    @property
    def table_bytes(self) -> int:
        n = self.vec.nbytes + self.bias.nbytes
        if self.vscale is not self.bias:
            n += self.vscale.nbytes
            if self.bscale is not self.vscale:
                n += self.bscale.nbytes
        return int(n)


# --- query stagers -----------------------------------------------------------


class _MFStager:
    """MF user staging is a host gather: qvec = P[u] (scale-folded for
    int8), base = mu + Bu[u]. No device work, so no jit_fns."""

    has_width = False
    jit_fns: tuple = ()

    def __init__(self, p_table, bu, mu, p_scales,
                 block_shift: Optional[int], num_users: int) -> None:
        self.p_table = p_table
        self.bu = np.asarray(bu, np.float32)
        self.mu = float(np.asarray(mu))
        self.p_scales = None if p_scales is None \
            else np.asarray(p_scales, np.float32)
        self.block_shift = block_shift
        self.num_users = int(num_users)

    def width_buckets(self) -> list:
        return [None]

    def dummy(self, width=None):
        return 0

    def _uids(self, queries) -> np.ndarray:
        uids = np.empty(len(queries), np.int64)
        for i, q in enumerate(queries):
            if isinstance(q, dict):
                q = q["user"]
            elif isinstance(q, (list, tuple, np.ndarray)):
                q = q[0]
            u = int(q)
            if not 0 <= u < self.num_users:
                raise ValueError(
                    f"user id {u} out of range [0, {self.num_users})")
            uids[i] = u
        return uids

    def stage(self, queries: Sequence, b_pad: int):
        u = self._uids(queries)
        g = np.asarray(self.p_table[u], np.float32)
        if self.p_scales is not None:
            g = g * self.p_scales[u >> self.block_shift]
        base = self.mu + self.bu[u]
        n = len(u)
        if b_pad > n:
            g = np.concatenate(
                [g, np.zeros((b_pad - n, g.shape[1]), np.float32)])
            base = np.concatenate([base, np.zeros(b_pad - n, np.float32)])
        return np.ascontiguousarray(g, np.float32), \
            np.ascontiguousarray(base, np.float32)


class _ShardedMFStager:
    """MF user staging against user-striped P/Bu (the predict path's
    gather pattern). Out-of-range users land in no stripe and stage to
    (0, mu) instead of raising — the sharded trade documented on the
    /predict path too."""

    has_width = False

    def __init__(self, p_l, bu_l, mu_rep, ps_l, num_users: int, fn) -> None:
        self.tables = (p_l, bu_l, mu_rep, ps_l)
        self.num_users = int(num_users)
        self.fn = fn
        self.jit_fns = (fn,)

    def width_buckets(self) -> list:
        return [None]

    def dummy(self, width=None):
        return 0

    def stage(self, queries: Sequence, b_pad: int):
        u = np.zeros(b_pad, np.int64)
        for i, q in enumerate(queries):
            if isinstance(q, dict):
                q = q["user"]
            elif isinstance(q, (list, tuple, np.ndarray)):
                q = q[0]
            u[i] = int(q)
        g, base = self.fn(*self.tables, u)
        return np.asarray(g, np.float32), np.asarray(base, np.float32)


class _FMStager:
    """FM query staging: parse/pad sparse rows to a width bucket, run the
    jitted (p, sumVfX) stage. One class covers single-device, sharded and
    q8 variants — they differ only in (tables, fn)."""

    has_width = True

    def __init__(self, tables: tuple, fn, dims: int, max_width: int) -> None:
        self.tables = tables
        self.fn = fn
        self.dims = int(dims)
        self.max_width = int(max_width)
        self.jit_fns = (fn,)

    def width_buckets(self) -> list:
        out, w = [], 8
        while w < self.max_width:
            out.append(w)
            w <<= 1
        out.append(self.max_width)
        return out

    def dummy(self, width: Optional[int] = None):
        w = min(width or 8, self.max_width)
        return [(i % self.dims, 1.0) for i in range(w)]

    def stage(self, queries: Sequence, b_pad: int):
        from ..models.base import _stage_rows

        idx_rows, val_rows = _stage_rows(list(queries), self.dims)
        width = max((len(r) for r in idx_rows), default=1)
        w_pad = min(max(8, _pow2_at_least(width)), self.max_width)
        idx = np.full((b_pad, w_pad), self.dims, np.int64)
        val = np.zeros((b_pad, w_pad), np.float32)
        for i, (ir, vr) in enumerate(zip(idx_rows, val_rows)):
            t = min(len(ir), w_pad)  # over-wide rows truncate (engine rule)
            idx[i, :t] = ir[:t]
            val[i, :t] = vr[:t]
        base, qvec = self.fn(*self.tables, idx, val)
        return np.asarray(qvec, np.float32), np.asarray(base, np.float32)


# --- the engine --------------------------------------------------------------


class RetrievalEngine:
    """Blocked streamed top-K over an MF/FM catalog (module docstring).

    ``source`` is an :class:`Artifact`, an artifact path, or a trained
    model (an LSH index rides only in artifacts). Queries are user ids
    (MF) or sparse feature rows (FM); results are
    ``{"items": [...], "scores": [...]}`` per query, item ids in the
    catalog's id space (MF item index / FM feature index).

    ``k`` is the engine ceiling: per-request k clamps to it (and pads to
    ``k_pad``, the pow2 the merge carry is compiled at). ``probe``
    requests candidate pruning; without an index — or when the bucket
    union is < k or > ``candidate_cap`` — the request falls back to
    exact scoring (counter ``retrieval.<name>.fallback``)."""

    def __init__(self, source, *, name: str = "default", k: int = 16,
                 block_items: int = 4096, max_batch: int = 8,
                 max_width: int = 64, candidate_cap: int = 1024,
                 probe_default: bool = False,
                 item_range: Optional[Tuple[int, int]] = None,
                 placement=None) -> None:
        from ..io.checkpoint import QUANT_SCHEME_INT8

        if isinstance(source, str):
            source = load(source)
        family = source.family if isinstance(source, Artifact) \
            else family_of(source)
        if family not in RETRIEVAL_FAMILIES:
            raise ValueError(
                f"family {family!r} has no retrieval path — top-K serves "
                f"the embedding families ({', '.join(RETRIEVAL_FAMILIES)})")
        self.name = name
        self.family = family
        spec = host_score_tables(source)
        meta = spec["meta"]
        quant = spec["quant"]
        is_int8 = bool(quant) and quant["scheme"] == QUANT_SCHEME_INT8
        block_rows = int(quant["block_rows"]) if is_int8 else 1
        block_shift = block_rows.bit_length() - 1 if is_int8 else None
        self.weights_dtype = spec["weights_dtype"]

        self.index = SRPIndex.from_artifact(source) \
            if isinstance(source, Artifact) else None
        full = (0, int(meta["num_items"])) if family == "mf" \
            else (0, int(meta["dims"]))
        if self.index is not None:
            lo, hi = self.index.item_lo, self.index.item_hi
            if item_range is not None and tuple(item_range) != (lo, hi):
                raise ValueError(
                    f"item_range {tuple(item_range)} does not match the "
                    f"artifact index's ({lo}, {hi})")
        elif item_range is not None:
            lo, hi = int(item_range[0]), int(item_range[1])
        else:
            lo, hi = full
        if not (full[0] <= lo < hi <= full[1]):
            raise ValueError(
                f"item_range ({lo}, {hi}) outside the catalog's {full}")
        self.item_lo, self.item_hi = lo, hi
        self.n_items = hi - lo

        block_items = int(block_items)
        if block_items < 1:
            raise ValueError(f"block_items must be >= 1, got {block_items}")
        if is_int8 and (block_items % block_rows or lo % block_rows):
            raise ValueError(
                f"int8 catalogs need block_items ({block_items}) and "
                f"item_lo ({lo}) aligned to the quant block_rows "
                f"({block_rows}) so scale blocks never straddle a window")
        self.block_items = block_items
        self.k = int(k)
        if not 1 <= self.k <= self.n_items:
            raise ValueError(
                f"k={k} out of range [1, {self.n_items}] for this catalog")
        self.k_pad = _pow2_at_least(self.k)
        self.max_batch = _pow2_at_least(int(max_batch))
        self.max_width = max(8, _pow2_at_least(int(max_width)))
        self.cand_min = max(16, self.k_pad)
        self.candidate_cap = max(_pow2_at_least(int(candidate_cap)),
                                 self.cand_min)
        self.probe_default = bool(probe_default)

        striped = {nm: arr for nm, arr, _axis, _grid in spec["striped"]}
        scales = spec["scales"]
        if family == "mf":
            use_bias = bool(meta.get("use_bias", True))
            bi = striped["Bi"] if use_bias \
                else np.zeros_like(striped["Bi"])
            vec_host = striped["Q"][lo:hi]
            bias_host = bi[lo:hi]
            vscale = scales.get("Q")
            bscale = None
            bias_scaled = False
        else:
            vec_host = striped["v"][lo:hi]
            bias_host = striped["w"][lo:hi]
            vscale = scales.get("v")
            bscale = scales.get("w")
            bias_scaled = is_int8
        if block_shift is not None:
            blo, bhi = lo >> block_shift, ((hi - 1) >> block_shift) + 1
            vscale = np.asarray(vscale, np.float32)[blo:bhi]
            if bias_scaled:
                bscale = np.asarray(bscale, np.float32)[blo:bhi]

        placement = resolve_placement(placement)
        self.sharded = isinstance(placement, ModelSharded)
        self.placement_info = placement.describe() \
            if hasattr(placement, "describe") else {"kind": placement.kind}
        if self.sharded:
            mesh = placement.mesh()
            n_sh = int(placement.model_shards)
            self.mesh_shape = tuple(int(s) for s in
                                    (placement.batch_shards, n_sh))
            self._catalog = _ShardedCatalog(
                vec_host, bias_host, vscale, bscale, self.n_items,
                self.block_items, self.k_pad, block_shift, bias_scaled,
                mesh, n_sh)
            self._stager = self._make_sharded_stager(
                spec, striped, scales, meta, mesh, n_sh, block_shift)
        else:
            self.mesh_shape = ()
            self._catalog = _SingleCatalog(
                vec_host, bias_host, vscale, bscale, self.n_items,
                self.block_items, self.k_pad, block_shift, bias_scaled)
            self._stager = self._make_single_stager(
                spec, striped, scales, meta, block_shift)
        self.jit_fns = tuple(self._catalog.jit_fns) \
            + tuple(self._stager.jit_fns)

        self._queries_ctr = REGISTRY.counter("retrieval",
                                             f"{name}.queries")
        self._exact_ctr = REGISTRY.counter("retrieval", f"{name}.exact")
        self._probed_ctr = REGISTRY.counter("retrieval", f"{name}.probed")
        self._fallback_ctr = REGISTRY.counter("retrieval",
                                              f"{name}.fallback")
        self._cand_ctr = REGISTRY.counter("retrieval",
                                          f"{name}.candidates")
        self._latency = REGISTRY.histogram(
            f"retrieval.{name}.topk_seconds", LATENCY_BUCKETS)
        REGISTRY.set_gauge(f"retrieval.{name}.catalog_items",
                           float(self.n_items))
        REGISTRY.set_gauge(f"retrieval.{name}.table_bytes",
                           float(self.table_bytes()))

    # -- construction helpers ------------------------------------------------

    def _make_single_stager(self, spec, striped, scales, meta,
                            block_shift):
        import jax.numpy as jnp

        if self.family == "mf":
            use_bias = bool(meta.get("use_bias", True))
            bu = striped["Bu"] if use_bias \
                else np.zeros_like(striped["Bu"])
            return _MFStager(striped["P"], bu, spec["replicated"]["mu"],
                             scales.get("P"), block_shift,
                             int(meta["num_users"]))
        dims = int(meta["dims"])
        w0 = jnp.asarray(spec["replicated"]["w0"], jnp.float32)
        if block_shift is not None:
            tables = (w0, jnp.asarray(striped["w"]),
                      jnp.asarray(scales["w"], jnp.float32),
                      jnp.asarray(striped["v"]),
                      jnp.asarray(scales["v"], jnp.float32))
            fn = _retrieval_jit(("q8_fm_stage", block_shift),
                                lambda: _build_q8_fm_stage(block_shift))
        else:
            tables = (w0, jnp.asarray(striped["w"]),
                      jnp.asarray(striped["v"]))
            fn = _retrieval_jit(("fm_stage",), _build_fm_stage)
        return _FMStager(tables, fn, dims, self.max_width)

    def _make_sharded_stager(self, spec, striped, scales, meta, mesh,
                             n_sh, block_shift):
        from .sharded import _mesh_key, _replicate_put, _stripe_put

        mk = _mesh_key(mesh)
        block_rows = 1 if block_shift is None else 1 << block_shift
        if self.family == "mf":
            use_bias = bool(meta.get("use_bias", True))
            num_users = int(meta["num_users"])
            stripe_u, padded_u = stripe_grid(num_users, n_sh,
                                             align=block_rows)
            p_l = _stripe_put(striped["P"], 0, num_users, padded_u, mesh)
            bu = striped["Bu"] if use_bias \
                else np.zeros_like(striped["Bu"])
            bu_l = _stripe_put(bu, 0, num_users, padded_u, mesh)
            mu_rep = _replicate_put(spec["replicated"]["mu"], mesh)
            if block_shift is not None:
                ps = np.asarray(scales["P"], np.float32)
                ps_l = _stripe_put(ps, 0, len(ps),
                                   padded_u >> block_shift, mesh)
            else:
                ps_l = bu_l  # inert striped stand-in, never read
            fn = _retrieval_jit(
                ("sh_mf_stage", mk, stripe_u, block_shift),
                lambda: _build_sh_mf_stage(mesh, stripe_u, block_shift))
            return _ShardedMFStager(p_l, bu_l, mu_rep, ps_l, num_users, fn)
        dims = int(meta["dims"])
        stripe_f, padded_f = stripe_grid(dims, n_sh, align=block_rows)
        w0 = _replicate_put(np.asarray(spec["replicated"]["w0"],
                                       np.float32), mesh)
        w_l = _stripe_put(striped["w"], 0, dims, padded_f, mesh)
        v_l = _stripe_put(striped["v"], 0, dims, padded_f, mesh)
        if block_shift is not None:
            ws = np.asarray(scales["w"], np.float32)
            vs = np.asarray(scales["v"], np.float32)
            ws_l = _stripe_put(ws, 0, len(ws), padded_f >> block_shift,
                               mesh)
            vs_l = _stripe_put(vs, 0, len(vs), padded_f >> block_shift,
                               mesh)
            tables = (w0, w_l, ws_l, v_l, vs_l)
            fn = _retrieval_jit(
                ("sh_q8_fm_stage", mk, stripe_f, block_shift),
                lambda: _build_sh_q8_fm_stage(mesh, stripe_f, block_shift))
        else:
            tables = (w0, w_l, v_l)
            fn = _retrieval_jit(
                ("sh_fm_stage", mk, stripe_f),
                lambda: _build_sh_fm_stage(mesh, stripe_f))
        return _FMStager(tables, fn, dims, self.max_width)

    # -- buckets -------------------------------------------------------------

    def batch_buckets(self) -> list:
        out, b = [], 1
        while b < self.max_batch:
            out.append(b)
            b <<= 1
        out.append(self.max_batch)
        return out

    def _bucket(self, n: int) -> int:
        return min(_pow2_at_least(n), self.max_batch)

    def cand_buckets(self) -> list:
        out, c = [], self.cand_min
        while c < self.candidate_cap:
            out.append(c)
            c <<= 1
        out.append(self.candidate_cap)
        return out

    def _cand_bucket(self, m: int) -> int:
        return min(max(_pow2_at_least(m), self.cand_min),
                   self.candidate_cap)

    # -- serving -------------------------------------------------------------

    def warmup(self) -> int:
        """Precompile every (batch, width) x (block merge, candidate)
        bucket; all jit misses are paid here, none in steady state.
        Idempotent across engines sharing _RETRIEVAL_JIT geometry."""
        t0 = time.perf_counter()
        with TRACER.span("retrieval.warmup",
                         args={"engine": self.name,
                               "family": self.family}), \
                recompile_guard(f"serving.{self.name}.topk.warmup",
                                *self.jit_fns) as g:
            for b in self.batch_buckets():
                qvec = base = None
                for w in self._stager.width_buckets():
                    qvec, base = self._stager.stage(
                        [self._stager.dummy(w)] * b, b)
                cv, _ci = self._catalog.run_blocks(qvec, base)
                np.asarray(cv)  # block: compiles surface here
                if self.index is not None:
                    for c in self.cand_buckets():
                        ids = np.zeros((b, c), np.int32)
                        mask = np.zeros((b, c), bool)
                        tv, _ti = self._catalog.run_cand(qvec, base, ids,
                                                         mask)
                        np.asarray(tv)
        REGISTRY.set_gauge(f"retrieval.{self.name}.warmup_seconds",
                           time.perf_counter() - t0)
        REGISTRY.set_gauge(f"retrieval.{self.name}.warmup_compiles",
                           float(g.compiles))
        return g.compiles

    def topk(self, queries: Sequence, k: Optional[int] = None,
             probe: Optional[bool] = None) -> List[dict]:
        """Top-K for a list of queries (one shared k/probe)."""
        return self.topk_batch([(q, k, probe) for q in queries])

    def topk_batch(self, rows: Sequence[tuple]) -> List[dict]:
        """Batcher entry point: rows of ``(query, k|None, probe|None)``.
        Chunks above max_batch; per-row k clamps to the engine k."""
        n = len(rows)
        if n == 0:
            return []
        t0 = time.perf_counter()
        outs: List[dict] = []
        with TRACER.span("retrieval.topk",
                         args={"engine": self.name, "rows": n}) as rspan:
            for s in range(0, n, self.max_batch):
                outs.extend(self._topk_chunk(rows[s:s + self.max_batch]))
            self._queries_ctr.increment(n)
            self._latency.observe(time.perf_counter() - t0,
                                  trace_id=TRACER.exemplar_id(rspan))
        return outs

    def _topk_chunk(self, rows: Sequence[tuple]) -> List[dict]:
        n = len(rows)
        queries = [r[0] for r in rows]
        ks = []
        for _q, rk, _p in rows:
            kk = self.k if rk is None else int(rk)
            if kk < 1:
                raise ValueError(f"k must be >= 1, got {kk}")
            ks.append(min(kk, self.k))
        probes = [self.probe_default if rp is None else bool(rp)
                  for _q, _k, rp in rows]
        b_pad = self._bucket(n)
        with recompile_guard(f"serving.{self.name}.topk", *self.jit_fns):
            with TRACER.span("topk.gather",
                             args={"rows": n, "b_pad": b_pad}):
                qvec, base = self._stager.stage(queries, b_pad)
            exact_idx = []
            cand: dict = {}
            for i in range(n):
                if probes[i] and self.index is None:
                    self._fallback_ctr.increment()  # probe without index
                if probes[i] and self.index is not None:
                    cand[i] = None  # resolved below
                else:
                    exact_idx.append(i)
            if cand:
                probed = self.index.probe(qvec[sorted(cand)])
                for i, c in zip(sorted(cand), probed):
                    if len(c) < ks[i] or len(c) > self.candidate_cap:
                        del cand[i]
                        exact_idx.append(i)
                        self._fallback_ctr.increment()
                    else:
                        cand[i] = c
                exact_idx.sort()
            pidx = sorted(cand)
            results: List[Optional[dict]] = [None] * n
            cv = ci = pv = pi = None
            with TRACER.span("topk.block_score",
                             args={"exact": len(exact_idx),
                                   "probed": len(pidx)}):
                if exact_idx:
                    bb = self._bucket(len(exact_idx))
                    qe = np.zeros((bb, qvec.shape[1]), np.float32)
                    qe[:len(exact_idx)] = qvec[exact_idx]
                    be = np.zeros((bb,), np.float32)
                    be[:len(exact_idx)] = base[exact_idx]
                    cv, ci = self._catalog.run_blocks(qe, be)
                    self._exact_ctr.increment(len(exact_idx))
                if pidx:
                    cmax = max(len(cand[i]) for i in pidx)
                    c_pad = self._cand_bucket(cmax)
                    bb = self._bucket(len(pidx))
                    ids = np.zeros((bb, c_pad), np.int32)
                    mask = np.zeros((bb, c_pad), bool)
                    total = 0
                    for r, i in enumerate(pidx):
                        c = cand[i] - self.item_lo  # catalog-row space
                        ids[r, :len(c)] = c
                        mask[r, :len(c)] = True
                        total += len(c)
                    qp = np.zeros((bb, qvec.shape[1]), np.float32)
                    qp[:len(pidx)] = qvec[pidx]
                    bp = np.zeros((bb,), np.float32)
                    bp[:len(pidx)] = base[pidx]
                    pv, pi = self._catalog.run_cand(qp, bp, ids, mask)
                    self._probed_ctr.increment(len(pidx))
                    self._cand_ctr.increment(total)
            with TRACER.span("topk.merge"):
                if exact_idx:
                    cvh, cih = np.asarray(cv), np.asarray(ci)
                    for r, i in enumerate(exact_idx):
                        results[i] = self._row_result(cvh[r], cih[r], ks[i])
                if pidx:
                    pvh, pih = np.asarray(pv), np.asarray(pi)
                    for r, i in enumerate(pidx):
                        results[i] = self._row_result(pvh[r], pih[r], ks[i])
        return results  # type: ignore[return-value]

    def _row_result(self, vals: np.ndarray, ids: np.ndarray,
                    k: int) -> dict:
        return {
            "items": (ids[:k].astype(np.int64) + self.item_lo).tolist(),
            # f32 carry values; .tolist() alone widens to Python floats
            "scores": vals[:k].tolist(),
        }

    def score_catalog(self, queries: Sequence) -> np.ndarray:
        """Materialized exact scores [n, n_items] — the naive-argsort
        baseline's input (bench parity pin). Shares the block score
        expression bit-for-bit with the streamed merge. Not a serving
        path; single-device engines only."""
        outs = []
        for s in range(0, len(queries), self.max_batch):
            chunk = queries[s:s + self.max_batch]
            qvec, base = self._stager.stage(chunk, self._bucket(len(chunk)))
            outs.append(self._catalog.block_scores(qvec, base)[:len(chunk)])
        return np.concatenate(outs, axis=0)

    # -- introspection -------------------------------------------------------

    def table_bytes(self) -> int:
        n = self._catalog.table_bytes
        for t in getattr(self._stager, "tables", ()):
            n += int(getattr(t, "nbytes", 0))
        return n

    def describe(self) -> dict:
        return {
            "family": self.family,
            "weights_dtype": self.weights_dtype,
            "k": self.k,
            "catalog_items": self.n_items,
            "item_range": [self.item_lo, self.item_hi],
            "block_items": self.block_items,
            "max_batch": self.max_batch,
            "candidate_cap": self.candidate_cap,
            "probe_default": self.probe_default,
            "placement": self.placement_info,
            "index": None if self.index is None else self.index.describe(),
            "table_bytes": self.table_bytes(),
        }
