"""Hot-row score cache + in-flight coalescing — the serving L0 fast path.

Production scoring traffic from millions of users is Zipfian: the same hot
rows arrive over and over (PAPERS.md ads-infra paper; "Randomized Hashing"
shows hashed-feature mass concentrates on few buckets). This module makes
repetition cheap, in front of the batcher:

- **score cache**: a per-model, byte-bounded LRU keyed by
  ``(model_version, row_key)`` over the canonical pre-parsed row form
  (serving/engine.py ``row_keys``), valued with the engine's own finalized
  per-row prediction. A request whose rows are ALL cached resolves its
  Future immediately — no queue capacity, no class quota, no batch slot
  (effective goodput rises under the PR 10 overload machinery instead of
  fighting it). The staleness contract is *version-exact*: the version is
  in the key, so a hot-swap invalidates atomically for free and the old
  version's entries simply age out of the byte budget.
- **in-flight coalescing**: identical rows already queued share ONE
  computation. The first request carrying a new row key becomes that key's
  *leader*; a later request covered entirely by cache entries + in-flight
  leaders becomes a *follower* — it attaches to the leaders' Futures
  instead of enqueueing. The leader populates the cache on completion and
  resolves every follower; a leader whose dispatch FAILS (shed,
  deadline-expired, engine error, swap-drop) fails its followers with the
  same reason and populates nothing. Followers deliberately inherit the
  leader's FATE wholesale — its priority class's queue position, its
  effective deadline, its failure mode — not their own parameters: a
  follower consumed no admission resources, so the only honest answer it
  can carry is the shared computation's. Callers for whom that trade is
  wrong (a high-priority request that must not ride a low leader's
  outcome) should serve cache-off. Leadership registers only AFTER
  admission succeeds (``lead()``), so an admission-refused request never
  had followers — refusals stay synchronous where the registry's
  swap-retry can see them.
- a request with ANY uncovered row flows into the batcher unchanged (it
  computes every row itself, leading its new keys) — partial requests are
  never split, so batch assembly, ordering and admission semantics stay
  exactly the PR 10 machinery.
- **negative caching**: a leader whose ADMISSION is refused (quota shed)
  leaves a short-TTL negative entry per new key (``note_refusal``). A hot
  row hammering an overloaded server is then answered with the same
  refusal straight from the cache front (plan kind "refused") instead of
  re-entering — and re-losing — admission on every request, so the
  admission lock and shed scan stop burning CPU on traffic that cannot be
  served anyway. The TTL is deliberately tiny (default 50 ms — the same
  order as a batch dispatch): capacity recovers the moment the queue
  drains, and a successful computation or hot-swap clears the verdict
  early. Counters ``cache.negative.{stored,hit}`` on /metrics.

Substrate: `utils.collections.LRUMap` with the byte-cost eviction hook.
The cache deliberately wraps a PLAIN LRUMap under its own lock rather than
using `SynchronizedLRUMap`: lookup, insert, byte accounting, the inflight
table, and the hit/miss counters must commit atomically per request — a
per-op synchronized map would leave check-then-act windows between them
(pinned in tests/test_serving_cache.py).

Lock discipline (graftcheck G012-G016): every mutable field is guarded by
``_lock``; Future ``set_result``/``set_exception`` ALWAYS run after
release (done-callbacks execute synchronously on the calling thread — the
G013 blocking-under-lock hazard). The batcher calls ``admit`` before
taking ``_cv`` and ``settle``/``abort`` outside it, so the cache lock and
the batcher CV are never nested in either order (no G016 cycle).

Observability: per-model counters ``serving.<name>.cache.{hit,miss,
coalesced,evicted}`` (row granularity; hit ratio = hit / (hit + miss),
coalesced rows are neither — they share a leader's computation) plus
``serving.<name>.cache.resident_bytes`` / ``.entries`` gauges on
/metrics, a stats block on /models (server.py), and ``cache.hit`` /
``cache.coalesced`` instant events inside the request span (batcher.py).
"""

from __future__ import annotations

import sys
import threading
import time
from concurrent.futures import CancelledError, Future
from typing import Dict, List, Optional, Sequence, Tuple

from ..runtime.metrics import REGISTRY
from ..utils.collections import LRUMap

# Estimated host bytes one cache entry holds beyond key/value payload:
# the OrderedDict node + tuple key + float boxing. An order-of-magnitude
# budget honesty constant, not an exact allocator measurement — the byte
# budget bounds resident memory, it does not meter it to the byte.
ENTRY_OVERHEAD_BYTES = 120


def _entry_cost(key: Tuple[str, bytes], value) -> int:
    version, digest = key
    try:
        value_bytes = sys.getsizeof(value)
    except TypeError:  # exotic prediction object without a size: estimate
        value_bytes = 64
    return ENTRY_OVERHEAD_BYTES + len(version) + len(digest) + value_bytes


class _Follower:
    """One coalesced request: its Future resolves when every leader it
    depends on completes. ``values`` is prefilled with the cache hits
    captured at admission (so a later eviction or hot-swap cannot change
    an already-admitted request's answer); ``settled`` flips under the
    cache lock exactly once — the loser of a two-leader race (one fails,
    one completes) sees it and leaves the Future alone."""

    __slots__ = ("future", "values", "remaining", "settled")

    def __init__(self, future: Future, values: list, remaining: int) -> None:
        self.future = future
        self.values = values
        self.remaining = remaining
        self.settled = False


class _Inflight:
    """One in-flight row key: the followers waiting on it, each with the
    slot positions the key fills in that follower's request."""

    __slots__ = ("followers",)

    def __init__(self) -> None:
        self.followers: List[Tuple[_Follower, List[int]]] = []


class LeadToken:
    """Returned by ``admit`` for a request that must compute: the caller
    enqueues it unchanged, registers it with ``lead()`` once admission
    SUCCEEDS, and hands its Future's outcome back through ``settle``. A
    refused admission simply never registers — nothing to clean up."""

    __slots__ = ("version", "keys", "led")

    def __init__(self, version: str, keys: Sequence[bytes],
                 led: List[bytes]) -> None:
        self.version = version
        self.keys = list(keys)
        self.led = led  # the subset of keys this request computes FIRST


class CachePlan:
    """The admission decision: ``kind`` is "hit" (``values`` ready — the
    caller resolves the Future itself, outside any lock), "coalesced"
    (the cache owns the Future's resolution), "lead" (``token`` must
    be settled when the computed Future completes), or "refused" (a row
    key sits in the negative cache from a recent admission refusal —
    ``error`` carries that refusal; the caller raises it synchronously
    WITHOUT re-entering admission)."""

    __slots__ = ("kind", "values", "token", "hit_rows", "coalesced_rows",
                 "error")

    def __init__(self, kind: str, values=None, token=None,
                 hit_rows: int = 0, coalesced_rows: int = 0,
                 error: Optional[BaseException] = None) -> None:
        self.kind = kind
        self.values = values
        self.token = token
        self.hit_rows = hit_rows
        self.coalesced_rows = coalesced_rows
        self.error = error


class ScoreCache:
    """Byte-bounded, version-keyed score cache + in-flight coalescing
    table for one model NAME (shared across its versions — the point:
    swap invalidation is a key change, not a flush)."""

    def __init__(self, max_bytes: int, *, name: str = "default",
                 negative_ttl_s: float = 0.050) -> None:
        if max_bytes <= 0:
            raise ValueError(f"max_bytes must be > 0, got {max_bytes}")
        self.max_bytes = int(max_bytes)
        self.name = name
        self.negative_ttl_s = float(negative_ttl_s)
        self._lock = threading.Lock()
        # entry count is unbounded by design — the byte budget is the
        # bound; the hook keeps resident accounting exact on both the
        # capacity path (never taken) and the explicit budget evictions
        self._map: LRUMap = LRUMap(1 << 62, on_evict=self._on_evict_locked)
        self._inflight: Dict[Tuple[str, bytes], _Inflight] = {}
        # negative cache: key -> (monotonic expiry, the refusal error) —
        # a shed/quota-refused leader key stops re-entering admission for
        # negative_ttl_s (note_refusal / the "refused" plan kind)
        self._negative: Dict[Tuple[str, bytes],
                             Tuple[float, BaseException]] = {}
        self._resident = 0
        self._hit = REGISTRY.counter("serving", f"{name}.cache.hit")
        self._miss = REGISTRY.counter("serving", f"{name}.cache.miss")
        self._coalesced = REGISTRY.counter("serving",
                                           f"{name}.cache.coalesced")
        self._evicted = REGISTRY.counter("serving", f"{name}.cache.evicted")
        self._neg_stored = REGISTRY.counter(
            "serving", f"{name}.cache.negative.stored")
        self._neg_hit = REGISTRY.counter(
            "serving", f"{name}.cache.negative.hit")
        self._g_bytes = f"serving.{name}.cache.resident_bytes"
        self._g_entries = f"serving.{name}.cache.entries"

    # -- admission (called by DynamicBatcher.submit BEFORE its CV) ----------

    def admit(self, version: str, keys: Sequence[bytes],
              future: Future) -> CachePlan:
        """One atomic decision for a request whose per-row ``keys`` are
        known. Classification per row: cached / in-flight / new. Any new
        key -> "lead" (the whole request computes, unchanged; the caller
        registers the token with ``lead()`` ONLY after admission
        succeeds). No new keys + any in-flight -> "coalesced" (the cache
        resolves ``future`` when the leaders complete). All cached ->
        "hit" (``plan.values`` ready; caller resolves)."""
        n = len(keys)
        with self._lock:
            fulls = [(version, k) for k in keys]
            if self._negative:
                refusal = self._negative_hit_locked(fulls)
                if refusal is not None:
                    self._neg_hit.increment()
                    return CachePlan("refused", error=refusal)
            # classify with the no-rotation peek (dict.get): rows are only
            # promoted to MRU when actually SERVED from the cache below
            cached = [self._map.get(f) is not None or f in self._map
                      for f in fulls]
            new: List[bytes] = []
            seen = set()
            for f, c in zip(fulls, cached):
                if not c and f not in self._inflight and f not in seen:
                    seen.add(f)
                    new.append(f[1])
            if new:
                # miss rows are counted in lead(), i.e. only for requests
                # the batcher actually ADMITS — a quota/closed refusal (or
                # its swap retry) computes nothing and must not depress
                # the gated hit ratio
                return CachePlan("lead",
                                 token=LeadToken(version, keys, list(new)))
            values = [None] * n
            pending: Dict[Tuple[str, bytes], List[int]] = {}
            hits = 0
            for i, (f, c) in enumerate(zip(fulls, cached)):
                if c:
                    values[i] = self._map[f]  # serve: rotates to MRU
                    hits += 1
                else:
                    pending.setdefault(f, []).append(i)
            self._hit.increment(hits)
            if not pending:
                return CachePlan("hit", values=values, hit_rows=n)
            coal = n - hits
            self._coalesced.increment(coal)
            fol = _Follower(future, values, remaining=len(pending))
            for f, slots in pending.items():
                self._inflight[f].followers.append((fol, slots))
            return CachePlan("coalesced", hit_rows=hits, coalesced_rows=coal)

    def _negative_hit_locked(self, fulls) -> Optional[BaseException]:
        """The stored refusal when any requested key is negatively cached
        and unexpired; expired entries encountered on the way are dropped
        (the lazy half of expiry — note_refusal sweeps the rest)."""
        now = time.monotonic()
        for f in fulls:
            rec = self._negative.get(f)
            if rec is None:
                continue
            if rec[0] > now:
                return rec[1]
            del self._negative[f]
        return None

    def note_refusal(self, token: LeadToken, exc: BaseException) -> None:
        """Admission REFUSED this leader (quota shed). Its new keys enter
        short-TTL negative entries, so a hot row hammering an overloaded
        server is answered with the SAME refusal from the cache front for
        ``negative_ttl_s`` instead of re-entering admission (and losing
        the quota race again) on every request. Version is in the key, so
        a hot-swap clears a row's negative verdict atomically; a
        successful computation of the key (some twin leader admitted
        meanwhile) clears it too."""
        if self.negative_ttl_s <= 0 or not token.led:
            return
        expiry = time.monotonic() + self.negative_ttl_s
        with self._lock:
            if len(self._negative) > 4096:  # sweep: bound stale entries
                now = time.monotonic()
                self._negative = {f: r for f, r in self._negative.items()
                                  if r[0] > now}
            for k in token.led:
                full = (token.version, k)
                if full not in self._negative:
                    self._neg_stored.increment()
                self._negative[full] = (expiry, exc)

    def lead(self, token: LeadToken) -> None:
        """Register the token's new keys as in-flight — called by the
        batcher AFTER the leader is successfully admitted, so a follower
        can only ever attach to a leader that is actually QUEUED. An
        admission-refused leader (quota / closed batcher) therefore never
        had followers to strand: its refusal raises synchronously where
        the registry's swap-retry loop can see it, and no other request's
        Future fails asynchronously with an admission error it could have
        retried. The cost of deferring registration is a tiny window
        where an identical concurrent request classifies as a second
        leader and computes a duplicate — bit-identical scores, never a
        failure; keys a racing twin registered first (or that got cached
        meanwhile) drop out of this token's led set, and the twin's
        completion settles those followers."""
        with self._lock:
            # every row of an admitted lead request is computed, cached
            # or not — that is what the miss counter means (hit ratio =
            # served-from-cache / looked-up-by-admitted-requests)
            self._miss.increment(len(token.keys))
            led = []
            for k in token.led:
                full = (token.version, k)
                if full not in self._inflight and full not in self._map:
                    self._inflight[full] = _Inflight()
                    led.append(k)
            token.led = led

    # -- completion (leader Future done-callback, outside the batcher CV) ---

    def settle(self, token: LeadToken, future: Future) -> None:
        """The leader's Future completed. Success populates the cache for
        EVERY row of the leader (led keys and refreshes alike) and
        resolves followers; failure fails followers with the SAME reason
        and populates nothing (the ISSUE's fault contract)."""
        if future.cancelled():
            self._fail(token, CancelledError("leader request cancelled"))
            return
        exc = future.exception()
        if exc is not None:
            self._fail(token, exc)
            return
        preds = future.result()
        ready: List[_Follower] = []
        with self._lock:
            by_key: Dict[Tuple[str, bytes], object] = {}
            for k, v in zip(token.keys, preds):
                full = (token.version, k)
                if full not in by_key:
                    by_key[full] = v
                self._put_locked(full, v)
            for k in token.led:
                rec = self._inflight.pop((token.version, k), None)
                if rec is None:
                    continue
                v = by_key.get((token.version, k))
                for fol, slots in rec.followers:
                    if fol.settled:
                        continue
                    for s in slots:
                        fol.values[s] = v
                    fol.remaining -= 1
                    if fol.remaining == 0:
                        fol.settled = True
                        ready.append(fol)
            self._export_gauges_locked()
        # outside the lock: set_result runs done-callbacks synchronously
        # (G013 — arbitrary callback code must never run under _lock)
        for fol in ready:
            if not fol.future.cancelled():
                fol.future.set_result(fol.values)

    def _fail(self, token: LeadToken, exc: BaseException) -> None:
        failed: List[_Follower] = []
        with self._lock:
            for k in token.led:
                rec = self._inflight.pop((token.version, k), None)
                if rec is None:
                    continue
                for fol, _slots in rec.followers:
                    if not fol.settled:
                        fol.settled = True
                        failed.append(fol)
        for fol in failed:  # outside the lock (G013)
            if not fol.future.cancelled():
                fol.future.set_exception(exc)

    # -- map + accounting (all under _lock) ---------------------------------

    def _on_evict_locked(self, key, value) -> None:
        # fires ONLY through _map.evict_oldest(), whose every call site
        # (_put_locked's budget loop, clear) holds _lock — the hook
        # indirection through the LRUMap callback is what the analyzer
        # cannot trace
        self._resident -= _entry_cost(key, value)  # graftcheck: disable=G012 (hook invoked only under _lock via evict_oldest)
        self._evicted.increment()

    def _put_locked(self, full: Tuple[str, bytes], value) -> None:
        # a key that just computed successfully is admittable again —
        # its negative verdict (if any) is stale by proof
        self._negative.pop(full, None)
        old = self._map.get(full)
        if old is not None or full in self._map:
            self._resident -= _entry_cost(full, old)
        self._map[full] = value
        self._resident += _entry_cost(full, value)
        while self._resident > self.max_bytes and len(self._map):
            self._map.evict_oldest()

    def _export_gauges_locked(self) -> None:
        REGISTRY.set_gauge(self._g_bytes, float(self._resident))
        REGISTRY.set_gauge(self._g_entries, float(len(self._map)))

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        """One consistent snapshot — the /models "cache" block
        (docs/serving.md "Score caching & coalescing")."""
        with self._lock:
            entries = len(self._map)
            resident = self._resident
            inflight = len(self._inflight)
            negative = len(self._negative)
            hit, miss = self._hit.value, self._miss.value
            coalesced, evicted = self._coalesced.value, self._evicted.value
            neg_stored = self._neg_stored.value
            neg_hit = self._neg_hit.value
        looked = hit + miss
        return {
            "enabled": True,
            "budget_bytes": self.max_bytes,
            "resident_bytes": resident,
            "entries": entries,
            "inflight_keys": inflight,
            "hit_rows": hit,
            "miss_rows": miss,
            "coalesced_rows": coalesced,
            "evicted_entries": evicted,
            "hit_ratio": round(hit / looked, 4) if looked else 0.0,
            "negative_ttl_s": self.negative_ttl_s,
            "negative_keys": negative,
            "negative_stored": neg_stored,
            "negative_hits": neg_hit,
        }

    def clear(self) -> None:
        """Drop every cached entry (tests / operator reset). In-flight
        leadership is untouched — leaders still settle their followers."""
        with self._lock:
            while len(self._map):
                self._map.evict_oldest()
            self._negative.clear()
            self._export_gauges_locked()
