"""Pluggable servable placement — where a model's score tables live.

The serving engine historically had exactly one answer: every table on one
device, so the biggest serveable model was the smallest device's memory —
while training stripes 2^22+-dim tables across a whole mesh
(parallel/sharded.py, core/striping.py) and N-1 devices idled at
inference. Placement makes the answer a parameter of ``make_servable`` /
``ServingEngine`` instead of a property of the servable classes:

- ``SingleDevice()``   — the default; the existing per-family servables,
  tables wherever jax puts them (one device);
- ``Replicated()``     — every device holds the full tables; request
  batches shard along the ``batch`` mesh axis (throughput from idle
  devices, no size headroom);
- ``ModelSharded(n)``  — tables stripe along the feature axis over the
  ``model`` mesh axis with ``NamedSharding`` (serving/sharded.py), batches
  optionally shard along ``batch``: a table bigger than one device serves.

All three run behind the same ``Servable`` protocol (serving/engine.py):
stage → dispatch → finalize, bucketed and warmed identically, so the
zero-steady-state-recompile guarantee holds per placement and the batcher,
registry and /predict endpoint never see the difference.

``Replicated`` IS ``ModelSharded`` with a ``(n, 1)`` mesh: a stripe that
spans the whole table is a replica, and the shared sharded score path
degenerates to the single-device math (the psum over a size-1 axis is the
identity). One implementation, three placements.

``device_byte_budget`` simulates a device memory ceiling: a placement
refuses (``ModelExceedsDeviceBudget``) at load when its per-device
resident score-table bytes exceed the budget — scripts/bench_serving.py
``--sharded`` uses it to demonstrate a model that only fits sharded, and
operators can pin deploys to a known HBM headroom.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

# The serving mesh axes. Distinct from the training axes
# (parallel/mesh.py: "workers"/"shards") on purpose: a serving mesh is
# request-batch x table-stripe, not replica x stripe, and G008 validates
# PartitionSpecs against whichever mesh is actually in scope.
BATCH_AXIS = "batch"
MODEL_AXIS = "model"


class ModelExceedsDeviceBudget(ValueError):
    """Per-device resident score-table bytes exceed the placement's
    ``device_byte_budget`` — the model does not fit this placement; shard
    it (or raise the budget)."""


class Placement:
    """Base placement: single-device (the historical behavior)."""

    kind = "single_device"

    def __init__(self, device_byte_budget: Optional[int] = None) -> None:
        self.device_byte_budget = (None if device_byte_budget is None
                                   else int(device_byte_budget))

    # -- mesh geometry (trivial for single-device) --------------------------

    @property
    def batch_shards(self) -> int:
        return 1

    @property
    def model_shards(self) -> int:
        return 1

    def describe(self) -> dict:
        """The /models placement block: what an operator needs to see to
        know where a deployed model's bytes actually are."""
        return {"kind": self.kind, "devices": 1, "mesh_shape": None,
                "batch_shards": self.batch_shards,
                "model_shards": self.model_shards}

    def check_budget(self, per_device_bytes: int, what: str) -> None:
        if self.device_byte_budget is not None \
                and per_device_bytes > self.device_byte_budget:
            raise ModelExceedsDeviceBudget(
                f"{what}: {per_device_bytes} resident score-table bytes per "
                f"device exceed the {self.kind} placement's budget of "
                f"{self.device_byte_budget} bytes — serve it model-sharded "
                f"(ModelSharded) or raise device_byte_budget")


SingleDevice = Placement


class ModelSharded(Placement):
    """Stripe the score tables over ``model_shards`` devices; shard request
    batches over ``batch_shards``. The mesh is ``(batch, model)`` —
    ``named_mesh`` over the first ``batch_shards * model_shards`` devices
    (runtime/jax_compat.py), matching the SNIPPETS Mesh/NamedSharding/
    PartitionSpec serving pattern. ``model_shards=None`` takes every
    available device."""

    kind = "model_sharded"

    def __init__(self, model_shards: Optional[int] = None, *,
                 batch_shards: int = 1,
                 devices: Optional[Sequence] = None,
                 device_byte_budget: Optional[int] = None) -> None:
        super().__init__(device_byte_budget)
        if model_shards is not None and model_shards < 1:
            raise ValueError(f"model_shards must be >= 1, got {model_shards}")
        if batch_shards < 1 or (batch_shards & (batch_shards - 1)):
            # batch buckets are powers of two (engine.batch_buckets), so a
            # non-power-of-two batch axis could never divide them evenly
            raise ValueError(
                f"batch_shards must be a power of two, got {batch_shards}")
        self._model_shards = model_shards
        self._batch_shards = int(batch_shards)
        self._devices = list(devices) if devices is not None else None
        self._mesh = None

    @property
    def batch_shards(self) -> int:
        return self._batch_shards

    @property
    def model_shards(self) -> int:
        if self._model_shards is None:
            import jax

            n = len(self._devices) if self._devices is not None \
                else jax.device_count()
            self._model_shards = max(1, n // self._batch_shards)
        return self._model_shards

    def mesh(self):
        """The (batch, model) serving mesh — built once, cached (every
        servable of this placement places onto the SAME mesh object, and
        the sharded-jit cache keys on its device list)."""
        if self._mesh is None:
            from ..runtime.jax_compat import named_mesh

            self._mesh = named_mesh(
                (self.batch_shards, self.model_shards),
                (BATCH_AXIS, MODEL_AXIS), self._devices)
        return self._mesh

    def describe(self) -> dict:
        shape = (self.batch_shards, self.model_shards)
        return {"kind": self.kind,
                "devices": shape[0] * shape[1],
                "mesh_shape": list(shape),
                "mesh_axes": [BATCH_AXIS, MODEL_AXIS],
                "batch_shards": self.batch_shards,
                "model_shards": self.model_shards}


class Replicated(ModelSharded):
    """Full tables on every device, batches sharded across all of them —
    the (n, 1) corner of the sharded placement (see module docstring)."""

    kind = "replicated"

    def __init__(self, batch_shards: Optional[int] = None, *,
                 devices: Optional[Sequence] = None,
                 device_byte_budget: Optional[int] = None) -> None:
        if batch_shards is None:
            import jax

            n = len(devices) if devices is not None else jax.device_count()
            # largest power of two that fits the device count, capped at
            # the engine's default min_batch_bucket (8): every batch
            # bucket must split evenly over the batch axis, so a bigger
            # default would refuse to construct on big hosts — pass
            # batch_shards (and a matching min_batch_bucket) explicitly
            # to spread wider
            batch_shards = min(1 << (max(1, n).bit_length() - 1), 8)
        super().__init__(model_shards=1, batch_shards=batch_shards,
                         devices=devices,
                         device_byte_budget=device_byte_budget)


_BY_NAME = {"single_device": SingleDevice, "replicated": Replicated,
            "model_sharded": ModelSharded, "sharded": ModelSharded}


def resolve_placement(placement: Union[None, str, Placement]) -> Placement:
    """None | kind-string | Placement -> Placement (the make_servable /
    ServingEngine / ModelRegistry.deploy argument surface)."""
    if placement is None:
        return SingleDevice()
    if isinstance(placement, str):
        try:
            return _BY_NAME[placement]()
        except KeyError:
            raise ValueError(
                f"unknown placement {placement!r}; one of "
                f"{sorted(_BY_NAME)}") from None
    if isinstance(placement, Placement):
        return placement
    raise TypeError(f"placement must be None, a kind string, or a "
                    f"Placement, got {type(placement).__name__}")
