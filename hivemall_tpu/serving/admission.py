"""Admission-control primitives for overload-grade serving.

One queue and one fixed 503 threshold degrade by collapse: past saturation
every request waits the full queue, then times out, and goodput falls off a
cliff. The production alternative (PAPERS.md ads-infra paper; AdaBatch for
the batching-window argument) is *predictable* degradation, built from four
pieces this module provides to `serving/batcher.py`:

- **priority classes** (`PRIORITY_NAMES`, `priority_class`): requests are
  high / normal / low; queues drain strictly-high-first with a bounded
  starvation escape for the lower classes;
- **admission quotas** (`quota_rows`): each class may fill the queue only
  up to its fraction of ``max_queue_rows`` — low-priority work is refused
  (503, ``reason="quota"``) while the queue still has headroom for high;
- **load shedding** (`ShedLowPriority`): when a higher class needs room,
  the newest lowest-priority queued requests are evicted (503,
  ``reason="shed"``, `Retry-After` from the live drain-rate estimate) —
  degradation drops the least valuable work first instead of everything
  at once;
- **deadline expiry** (`DeadlineExpired`): requests carry a ``deadline_ms``
  budget and expire *in the queue* (504) before wasting a dispatch slot —
  under sustained overload the queue self-cleans instead of serving
  answers nobody is waiting for anymore.

`AIMDController` is the adaptive-batching half: an additive-increase /
multiplicative-decrease controller that widens the batching window
(``max_delay``/``max_batch``) toward its caps while a backlog persists and
decays it back to baseline when the queue goes idle — light-load latency
stays pinned at the base window, overload throughput gets the wide one.
"""

from __future__ import annotations

from typing import Optional

# class 0 drains first; the tuple order IS the drain (and shed-survival)
# order. Three classes cover the production taxonomy (interactive /
# default / batch) without inviting priority inflation.
PRIORITY_NAMES = ("high", "normal", "low")


def priority_class(value) -> int:
    """Normalize a priority (class index or name, e.g. from an
    ``x-priority`` header) to its class index. Raises ValueError on
    anything else — the server maps that to a 400."""
    if isinstance(value, bool):
        raise ValueError(f"invalid priority {value!r}")
    if isinstance(value, int):
        if 0 <= value < len(PRIORITY_NAMES):
            return value
        raise ValueError(
            f"priority class {value} out of range 0..{len(PRIORITY_NAMES) - 1}")
    if isinstance(value, str):
        v = value.strip().lower()
        if v in PRIORITY_NAMES:
            return PRIORITY_NAMES.index(v)
        if v.isdigit() and int(v) < len(PRIORITY_NAMES):
            return int(v)
    raise ValueError(f"invalid priority {value!r} "
                     f"(expected one of {PRIORITY_NAMES} or 0..2)")


def priority_name(cls: int) -> str:
    return PRIORITY_NAMES[cls]


class QueueFull(RuntimeError):
    """Admission control: queue at capacity — caller should shed (503).

    ``reason`` distinguishes the admission-time quota refusal ("quota")
    from an in-queue eviction ("shed", see ShedLowPriority);
    ``retry_after_s`` is the batcher's live drain-time estimate, surfaced
    as the HTTP ``Retry-After`` header so clients back off for a useful
    interval instead of a constant."""

    def __init__(self, msg: str, *, reason: str = "quota",
                 retry_after_s: Optional[float] = None) -> None:
        super().__init__(msg)
        self.reason = reason
        self.retry_after_s = retry_after_s


class ShedLowPriority(QueueFull):
    """An accepted request was evicted from the queue to admit
    higher-priority work (503 + Retry-After, ``reason="shed"``)."""

    def __init__(self, msg: str, *,
                 retry_after_s: Optional[float] = None) -> None:
        super().__init__(msg, reason="shed", retry_after_s=retry_after_s)


class DeadlineExpired(RuntimeError):
    """The request's ``deadline_ms`` budget elapsed while it was still
    queued; it never reached dispatch (504, shed-counted)."""


class AIMDController:
    """Additive-increase / multiplicative-decrease batching-window control.

    The window starts at the base ``(delay, batch)`` pair. Every dispatch
    that leaves more than one batch of backlog behind widens both
    additively toward their caps (AdaBatch's grow-the-batch-under-load
    argument applied to inference micro-batching); every time the worker
    finds the queue empty both decay multiplicatively back toward base.
    Light load therefore serves at the base window — latency pinned —
    while sustained overload earns the wide window's amortization.

    Thread discipline: mutated ONLY under the owning batcher's condition
    variable (the worker updates it while holding ``_cv``); `state()`
    reads are taken under the same lock via ``DynamicBatcher``'s
    accessors. With equal base and cap (the defaults) the controller is a
    fixed window — exact legacy behavior.
    """

    def __init__(self, *, base_delay_s: float, cap_delay_s: float,
                 base_batch: int, cap_batch: int,
                 add_delay_s: Optional[float] = None,
                 add_batch: Optional[int] = None,
                 decay: float = 0.5) -> None:
        self.base_delay_s = float(base_delay_s)
        self.cap_delay_s = max(float(cap_delay_s), self.base_delay_s)
        self.base_batch = int(base_batch)
        self.cap_batch = max(int(cap_batch), self.base_batch)
        # one base-delay step per overloaded dispatch reaches the cap in a
        # few batches; the batch step is a quarter of base so both knobs
        # arrive at their caps on a similar schedule
        self.add_delay_s = float(add_delay_s) if add_delay_s is not None \
            else max(self.base_delay_s, 1e-4)
        self.add_batch = int(add_batch) if add_batch is not None \
            else max(1, self.base_batch // 4)
        self.decay = float(decay)
        self.delay_s = self.base_delay_s
        self.batch_rows = self.base_batch

    @property
    def adaptive(self) -> bool:
        return (self.cap_delay_s > self.base_delay_s
                or self.cap_batch > self.base_batch)

    def on_take(self, depth_rows_after: int) -> None:
        """One batch was dispatched leaving ``depth_rows_after`` queued;
        a backlog deeper than the current batch is the overload signal."""
        if depth_rows_after >= self.batch_rows:
            self.delay_s = min(self.cap_delay_s,
                               self.delay_s + self.add_delay_s)
            self.batch_rows = min(self.cap_batch,
                                  self.batch_rows + self.add_batch)

    def on_idle(self) -> None:
        """The worker found every queue empty — decay toward base."""
        self.delay_s = max(self.base_delay_s, self.delay_s * self.decay)
        self.batch_rows = max(self.base_batch,
                              int(self.batch_rows * self.decay))

    def state(self) -> dict:
        return {
            "delay_ms": round(self.delay_s * 1e3, 3),
            "batch_rows": self.batch_rows,
            "base_delay_ms": round(self.base_delay_s * 1e3, 3),
            "cap_delay_ms": round(self.cap_delay_s * 1e3, 3),
            "base_batch": self.base_batch,
            "cap_batch": self.cap_batch,
            "adaptive": self.adaptive,
        }
