"""Online inference: frozen artifacts, bucketed engines, overload-grade
micro-batching (priorities / quotas / deadlines / adaptive windows),
hot-swap registry + /predict and top-K /topk endpoints — docs/serving.md.

    from hivemall_tpu.serving import freeze, ModelRegistry, serve

    freeze(model, "artifacts/ctr/1")
    registry = ModelRegistry()
    registry.deploy("ctr", "artifacts/ctr/1")
    server = serve(registry, port=8080)
"""

from .admission import (AIMDController, DeadlineExpired, PRIORITY_NAMES,
                        QueueFull, ShedLowPriority, priority_class)
from .artifact import Artifact, family_of, freeze, load
from .batcher import BatcherClosed, DynamicBatcher
from .cache import ScoreCache
from .engine import Servable, ServingEngine, make_servable
from .placement import (ModelExceedsDeviceBudget, ModelSharded, Placement,
                        Replicated, SingleDevice)
from .retrieval import RetrievalEngine, SRPIndex, build_srp_index
from .server import ModelEntry, ModelRegistry, serve

__all__ = [
    "Artifact", "family_of", "freeze", "load",
    "DynamicBatcher", "QueueFull", "BatcherClosed", "ScoreCache",
    "AIMDController", "DeadlineExpired", "ShedLowPriority",
    "PRIORITY_NAMES", "priority_class",
    "Servable", "ServingEngine", "make_servable",
    "Placement", "SingleDevice", "Replicated", "ModelSharded",
    "ModelExceedsDeviceBudget",
    "RetrievalEngine", "SRPIndex", "build_srp_index",
    "ModelRegistry", "ModelEntry", "serve",
]
