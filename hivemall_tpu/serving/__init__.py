"""Online inference: frozen artifacts, bucketed engines, micro-batching,
hot-swap registry + /predict endpoint — docs/serving.md.

    from hivemall_tpu.serving import freeze, ModelRegistry, serve

    freeze(model, "artifacts/ctr/1")
    registry = ModelRegistry()
    registry.deploy("ctr", "artifacts/ctr/1")
    server = serve(registry, port=8080)
"""

from .artifact import Artifact, family_of, freeze, load
from .batcher import BatcherClosed, DynamicBatcher, QueueFull
from .engine import Servable, ServingEngine, make_servable
from .placement import (ModelExceedsDeviceBudget, ModelSharded, Placement,
                        Replicated, SingleDevice)
from .server import ModelEntry, ModelRegistry, serve

__all__ = [
    "Artifact", "family_of", "freeze", "load",
    "DynamicBatcher", "QueueFull", "BatcherClosed",
    "Servable", "ServingEngine", "make_servable",
    "Placement", "SingleDevice", "Replicated", "ModelSharded",
    "ModelExceedsDeviceBudget",
    "ModelRegistry", "ModelEntry", "serve",
]
