"""Frozen serving artifacts — immutable, versioned, inference-only models.

The reference's prediction story is offline: dump the model as a Hive table
at close(), score with SQL joins (SURVEY.md §3.5). Online serving needs a
different persistence contract (the immutable-artifact discipline of
production scoring stacks, PAPERS.md ads-infra paper): a model version is a
directory that never changes after `freeze()` —

    <dir>/
      manifest.json   # family, schema, shapes, sha256 of the array pack
      arrays.npz      # every array needed to reproduce predict() bit-exactly

`freeze(model, dir)` accepts any trained model the framework produces
(linear, multiclass, FM, FFM, MF, random forest, GBT — the same family
dispatch as adapters/model_rows.py, whose column schema is recorded in the
manifest) and `load(dir)` returns an `Artifact`; `serving.engine.
make_servable(artifact)` turns it into a jit-served predictor whose outputs
are bit-identical to the live model's (tests/test_serving_artifact.py pins
this for every family).

Artifacts are *inference-only*: optimizer slots are dropped (io/checkpoint
remains the mid-training resume path). The linear family stores the
(feature, weight[, covar]) interchange rows — the exact npz layout of
io/checkpoint.save_model_rows, reconstructed through dense_from_rows — and
the FFM family stores the to_blob() compressed blob (utils/codec recipe),
so both reuse the established codecs rather than inventing new ones.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

FORMAT = "hivemall-tpu-artifact"
FORMAT_VERSION = 1
MANIFEST_FILE = "manifest.json"
ARRAYS_FILE = "arrays.npz"


def _host(x) -> np.ndarray:
    """Device array -> host numpy, bf16 widened to f32 (np.savez cannot
    round-trip ml_dtypes reliably; the widening is value-exact — the
    io/checkpoint at-rest protocol)."""
    import jax

    from ..io.checkpoint import np_saveable

    return np_saveable(jax.device_get(x))


def manifest_dtype(meta: dict, default: str = "float32"):
    """The dtype a family's device tables must reload at — the dtype the
    model TRAINED with (``meta["weights_dtype"]``, recorded at freeze),
    not whatever width the widened-at-rest pack holds. This is the load
    half of the widen-at-rest / narrow-at-serve contract graftcheck G020
    enforces: ``jnp.asarray(pack[...])`` without this pin resurrects a
    bf16 table as f32 and silently doubles serving HBM traffic."""
    from ..io.checkpoint import dtype_from_name

    return dtype_from_name(meta.get("weights_dtype", default))


def manifest_quant(meta: dict) -> Optional[dict]:
    """The manifest's quantization block, or None for full-precision
    artifacts. Shape (recorded by ``freeze(..., quantize=...)``):

        {"scheme": "bf16" | "int8_absmax",
         "block_rows": 64,            # int8 scale-block rows (power of two)
         "tables": ["weight", ...]}   # quantized pack entries

    For int8, each quantized table name ``t`` has a sibling f32 scale
    array ``t + io.checkpoint.SCALE_SUFFIX`` in the pack; for bf16, the
    pack entry holds raw uint16 bit patterns (io.checkpoint.bf16_pack_raw).
    This is the scale-carrying extension of the ``manifest_dtype`` pin:
    the dtype says WHAT width the table serves at, the quant block says
    how to read the reduced payload without ever widening it at rest."""
    return meta.get("quant")


def family_of(model) -> str:
    """Family tag for any trained model — the adapters/model_rows.py
    dispatch order, as a name."""
    from ..models.ffm import TrainedFFMModel
    from ..models.fm import TrainedFMModel
    from ..models.mf import TrainedMFModel
    from ..models.trees.forest import TrainedForest, TrainedGBT

    if isinstance(model, TrainedGBT):
        return "gbt"
    if isinstance(model, TrainedFMModel):
        return "fm"
    if isinstance(model, TrainedFFMModel):
        return "ffm"
    if isinstance(model, TrainedForest):
        return "forest"
    if isinstance(model, TrainedMFModel):
        return "mf"
    if hasattr(model, "label_vocab"):
        return "multiclass"
    if hasattr(model, "state") and hasattr(model.state, "weights"):
        return "linear"
    raise ValueError(f"{type(model).__name__}: no serving family")


@dataclass
class Artifact:
    """A loaded artifact: manifest + host arrays (still inert — feed to
    serving.engine.make_servable for a predictor)."""

    path: str
    manifest: dict
    arrays: Dict[str, np.ndarray] = field(repr=False)

    @property
    def family(self) -> str:
        return self.manifest["family"]

    @property
    def meta(self) -> dict:
        return self.manifest["meta"]


def _vocab_jsonable(vocab):
    return [v.item() if hasattr(v, "item") else v for v in vocab]


def _pack_trees(prefix: str, trees, arrays: dict) -> None:
    for i, t in enumerate(trees):
        arrays[f"{prefix}{i}__feature"] = np.asarray(t.feature, np.int32)
        arrays[f"{prefix}{i}__threshold_bin"] = np.asarray(t.threshold_bin,
                                                          np.int32)
        arrays[f"{prefix}{i}__nominal"] = np.asarray(t.nominal, bool)
        arrays[f"{prefix}{i}__left"] = np.asarray(t.left, np.int32)
        arrays[f"{prefix}{i}__right"] = np.asarray(t.right, np.int32)
        arrays[f"{prefix}{i}__leaf_value"] = np.asarray(t.leaf_value,
                                                       np.float32)


def _unpack_trees(prefix: str, n: int, arrays: dict):
    from ..models.trees.grow import TreeArrays

    out = []
    for i in range(n):
        feature = arrays[f"{prefix}{i}__feature"]
        out.append(TreeArrays(
            feature=feature,
            threshold_bin=arrays[f"{prefix}{i}__threshold_bin"],
            nominal=arrays[f"{prefix}{i}__nominal"],
            left=arrays[f"{prefix}{i}__left"],
            right=arrays[f"{prefix}{i}__right"],
            leaf_dist=None,
            leaf_value=arrays[f"{prefix}{i}__leaf_value"],
            n_nodes=int(feature.shape[0]),
        ))
    return out


def _pack_bins(bins, arrays: dict, meta: dict) -> None:
    meta["bins_nominal"] = [bool(b.nominal) for b in bins]
    for f, b in enumerate(bins):
        # widen-at-rest: the pack keeps the training-precision edges; the
        # serving engine narrows to f32 at load (_TreeServable)
        arrays[f"bin{f}__edges"] = np.asarray(b.edges, np.float64)  # graftcheck: disable=G018 (at-rest precision; serving narrows at load)


def _unpack_bins(meta: dict, arrays: dict):
    from ..models.trees.binning import BinInfo

    out = []
    for f, nominal in enumerate(meta["bins_nominal"]):
        edges = arrays[f"bin{f}__edges"]
        out.append(BinInfo(bool(nominal), edges, len(edges)))
    return out


def _build_payload(model):
    """(family, arrays dict, meta dict) for any trained model."""
    from ..adapters.model_rows import iter_model_rows

    family = family_of(model)
    arrays: Dict[str, np.ndarray] = {}
    meta: dict = {}
    try:
        meta["columns"], _ = iter_model_rows(model)
    except ValueError:
        meta["columns"] = None

    if family == "linear":
        # the io/checkpoint.save_model_rows interchange layout: untouched
        # entries are 0 (weights) / 1 (covars) by construction, so
        # dense_from_rows reproduces the live tables exactly
        rows = model.model_rows()
        arrays["feature"] = np.asarray(rows[0], np.int64)
        arrays["weight"] = _host(rows[1])
        if len(rows) == 3 and rows[2] is not None:
            arrays["covar"] = _host(rows[2])
        meta.update(dims=int(model.dims), rule=model.rule.name,
                    use_covariance=bool(model.rule.use_covariance),
                    weights_dtype=np.dtype(model.state.weights.dtype).name)
    elif family == "multiclass":
        st = model.state
        arrays["weights"] = _host(st.weights)
        if st.covars is not None:
            arrays["covars"] = _host(st.covars)
        meta.update(dims=int(model.dims),
                    label_vocab=_vocab_jsonable(model.label_vocab),
                    use_covariance=st.covars is not None,
                    weights_dtype=np.dtype(st.weights.dtype).name)
    elif family == "fm":
        st, hy = model.state, model.hyper
        for k in ("w0", "w", "v", "lambda_w0", "lambda_w", "lambda_v"):
            arrays[k] = _host(getattr(st, k))
        arrays["touched"] = _host(st.touched)
        meta.update(dims=int(model.dims), factors=int(hy.factors),
                    classification=bool(hy.classification),
                    sigma=float(hy.sigma), seed=int(hy.seed),
                    lambda0=float(hy.lambda0),
                    weights_dtype=np.dtype(st.w.dtype).name)
    elif family == "ffm":
        # the utils/codec compressed-blob recipe (FFMPredictionModel
        # writeExternal analog); half_float=False keeps bit-exactness
        blob = model.to_blob(half_float=False)
        arrays["blob"] = np.frombuffer(blob, np.uint8)
        hy = model.hyper
        meta.update(factors=int(hy.factors),
                    num_features=int(hy.num_features),
                    num_fields=int(hy.num_fields), v_dims=int(hy.v_dims))
    elif family == "mf":
        st = model.state
        for k in ("P", "Q", "Bu", "Bi", "mu"):
            arrays[k] = _host(getattr(st, k))
        meta.update(use_bias=bool(model.use_bias),
                    num_users=int(arrays["P"].shape[0]),
                    num_items=int(arrays["Q"].shape[0]),
                    factor=int(arrays["P"].shape[1]),
                    weights_dtype=np.dtype(st.P.dtype).name)
    elif family == "forest":
        _pack_trees("tree", [t.tree for t in model.trees], arrays)
        _pack_bins(model.bins, arrays, meta)
        meta.update(n_trees=len(model.trees),
                    classification=bool(model.classification),
                    n_classes=int(model.n_classes),
                    attrs=list(model.attrs))
    elif family == "gbt":
        flat = [t for round_trees in model.trees for t in round_trees]
        _pack_trees("tree", flat, arrays)
        _pack_bins(model.bins, arrays, meta)
        arrays["intercept"] = np.asarray(model.intercept, np.float64)  # graftcheck: disable=G018 (at-rest training dtype; serving narrows at load)
        arrays["classes"] = np.asarray(model.classes)
        meta.update(n_rounds=len(model.trees),
                    n_class_trees=len(model.trees[0]) if model.trees else 0,
                    shrinkage=float(model.shrinkage))
    return family, arrays, meta


# Families with a float weight table the quantized serving path understands
# (the sparse-row scorers + the MF embedding lookup). Trees walk int32
# structure, FFM rides an opaque codec blob — neither has a weight table to
# quantize, so freeze(quantize=...) refuses rather than silently no-ops.
QUANTIZABLE_FAMILIES = ("linear", "multiclass", "fm", "mf")


def _build_quantized_payload(model, quantize: str, block_rows: int):
    """(family, arrays, meta) holding ONLY the score-path tables, reduced.

    Quantized artifacts are serving-only by construction: the linear
    covariance, FM regularizer/touched slots, and MF touched masks are
    training state the scorers never read, and keeping them full-width
    would erase most of the byte savings — they are dropped, and the
    manifest's ``quant`` block records the layout (manifest_quant).
    Weight tables store as:

    - ``bf16``  — raw uint16 bit patterns (value-rounding to bf16 IS the
      quantization; io.checkpoint.bf16_pack_raw);
    - ``int8``  — per-block absmax int8 (io.checkpoint.quantize_int8)
      with the f32 scale array alongside (``<name>__scale``), blocked
      along the axis the scorers gather by (features for linear/fm and
      multiclass, users/items for MF) so the serve path can fold
      ``scales[id >> log2(block_rows)]`` into the dot product without
      ever materializing a widened table.
    """
    from ..adapters.model_rows import iter_model_rows
    from ..io.checkpoint import (QUANT_SCHEME_BF16, QUANT_SCHEME_INT8,
                                 SCALE_SUFFIX, bf16_pack_raw, quantize_int8)

    family = family_of(model)
    if family not in QUANTIZABLE_FAMILIES:
        raise ValueError(
            f"freeze(quantize={quantize!r}): family {family!r} has no "
            f"quantized serving path (supported: "
            f"{', '.join(QUANTIZABLE_FAMILIES)})")
    arrays: Dict[str, np.ndarray] = {}
    meta: dict = {}
    try:
        meta["columns"], _ = iter_model_rows(model)
    except ValueError:
        meta["columns"] = None

    # (pack name, host f32 table, quantized axis): the axis is the one the
    # serving gather indexes by, so scale blocks align with gathered ids
    if family == "linear":
        tables = [("weight", _host(model.state.weights), 0)]
        meta.update(dims=int(model.dims), rule=model.rule.name,
                    use_covariance=False)  # covariance dropped: never scored
    elif family == "multiclass":
        tables = [("weights", _host(model.state.weights), 1)]
        meta.update(dims=int(model.dims),
                    label_vocab=_vocab_jsonable(model.label_vocab),
                    use_covariance=False)
    elif family == "fm":
        st, hy = model.state, model.hyper
        tables = [("w", _host(st.w), 0), ("v", _host(st.v), 0)]
        arrays["w0"] = np.asarray(_host(st.w0), np.float32)
        meta.update(dims=int(model.dims), factors=int(hy.factors),
                    classification=bool(hy.classification))
    else:  # mf
        st = model.state
        tables = [("P", _host(st.P), 0), ("Q", _host(st.Q), 0)]
        for k in ("Bu", "Bi", "mu"):  # bias terms: tiny, stay f32
            arrays[k] = np.asarray(_host(getattr(st, k)), np.float32)
        meta.update(use_bias=bool(model.use_bias),
                    num_users=int(st.P.shape[0]),
                    num_items=int(st.Q.shape[0]),
                    factor=int(st.P.shape[1]))

    if quantize == "bf16":
        for name, tab, _axis in tables:
            arrays[name] = bf16_pack_raw(tab)
        meta["weights_dtype"] = "bfloat16"
        meta["quant"] = {"scheme": QUANT_SCHEME_BF16,
                         "tables": [n for n, _, _ in tables]}
    else:  # int8
        for name, tab, axis in tables:
            q, scales = quantize_int8(tab, block_rows, axis=axis)
            arrays[name] = q
            arrays[name + SCALE_SUFFIX] = scales
        meta["weights_dtype"] = "int8"
        meta["quant"] = {"scheme": QUANT_SCHEME_INT8,
                         "block_rows": int(block_rows),
                         "tables": [n for n, _, _ in tables]}
    return family, arrays, meta


def _add_retrieval_index(model, family: str, arrays: dict, meta: dict,
                         opts: dict) -> None:
    """Build the retrieval LSH index into a freeze payload (freeze's
    ``retrieval_index=``): SRP buckets over the model's f32 item vectors
    — always the pre-quantization tables, so a bf16/int8 artifact carries
    the same index as its f32 twin."""
    import jax

    if family not in ("mf", "fm"):
        raise ValueError(
            f"retrieval_index: family {family!r} has no retrieval path "
            f"(mf/fm only)")
    n_planes = int(opts.pop("planes", 8))
    seed = int(opts.pop("seed", 0))
    item_range = opts.pop("item_range", None)
    if opts:
        raise ValueError(
            f"retrieval_index: unknown keys {sorted(opts)} (accepted: "
            f"planes, seed, item_range)")
    if family == "mf":
        vecs = np.asarray(jax.device_get(model.state.Q), np.float32)
        full = (0, vecs.shape[0])
    else:
        vecs = np.asarray(jax.device_get(model.state.v), np.float32)
        full = (0, vecs.shape[0])
    if item_range is None:
        lo, hi = full
    else:
        lo, hi = int(item_range[0]), int(item_range[1])
        if not (full[0] <= lo < hi <= full[1]):
            raise ValueError(
                f"retrieval_index: item_range ({lo}, {hi}) outside the "
                f"model's {full}")
    from .retrieval import build_srp_index

    planes, item_ids, offsets = build_srp_index(vecs[lo:hi], n_planes,
                                                seed, item_lo=lo)
    arrays["index__planes"] = planes
    arrays["index__item_ids"] = item_ids
    arrays["index__offsets"] = offsets
    meta["index"] = {"scheme": "srp_lsh", "planes": n_planes,
                     "seed": seed, "item_lo": lo, "item_hi": hi}


def freeze(model, path: str, *, name: Optional[str] = None,
           version: Optional[str] = None, quantize: Optional[str] = None,
           quant_block_rows: Optional[int] = None,
           retrieval_index: Optional[dict] = None) -> dict:
    """Freeze a trained model into an immutable artifact directory.

    Returns the manifest. The directory must not already hold an artifact
    (versions are immutable — freeze a NEW directory and hot-swap it in via
    serving.server.ModelRegistry.deploy).

    ``quantize="bf16"|"int8"`` stores the weight tables reduced (linear/
    multiclass/FM/MF only; see _build_quantized_payload) — the serving
    engine then scores them dequant-free at the manifest dtype.
    ``quant_block_rows`` sets the int8 scale-block row count (power of
    two; default io.checkpoint.QUANT_BLOCK_ROWS).

    ``retrieval_index={"planes": int, "seed": int, "item_range": (lo, hi)}``
    (MF/FM only, every key optional) additionally builds the top-K
    retrieval LSH index into the artifact: signed-random-projection
    buckets over the item vectors (MF: Q rows; FM: v rows over
    ``item_range``, default the full feature space) as ``index__*``
    arrays plus a manifest ``meta["index"]`` block. The index hashes the
    f32 vectors BEFORE quantization — it approximates angles, not stored
    bits — and is deterministic in ``seed``
    (serving/retrieval.py; docs/serving.md "Top-K retrieval").
    """
    os.makedirs(path, exist_ok=True)
    mpath = os.path.join(path, MANIFEST_FILE)
    if os.path.exists(mpath):
        raise FileExistsError(
            f"{mpath} exists — artifacts are immutable; freeze a new "
            f"version directory instead")
    if quantize is None:
        if quant_block_rows is not None:
            raise ValueError("quant_block_rows requires quantize=")
        family, arrays, meta = _build_payload(model)
    elif quantize in ("bf16", "int8"):
        from ..io.checkpoint import QUANT_BLOCK_ROWS

        family, arrays, meta = _build_quantized_payload(
            model, quantize, quant_block_rows or QUANT_BLOCK_ROWS)
    else:
        raise ValueError(f"quantize must be 'bf16' or 'int8', "
                         f"got {quantize!r}")
    if retrieval_index is not None:
        _add_retrieval_index(model, family, arrays, meta,
                             dict(retrieval_index))
    apath = os.path.join(path, ARRAYS_FILE)
    # savez into memory so the pack is written AND hashed in one pass (a
    # large FM/FFM table would otherwise pay a second full-file read)
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    data = buf.getvalue()
    digest = hashlib.sha256(data).hexdigest()
    with open(apath, "wb") as f:
        f.write(data)
    manifest = {
        "format": FORMAT,
        "format_version": FORMAT_VERSION,
        "family": family,
        "name": name or family,
        "version": version or "1",
        "created_unix": time.time(),
        "arrays": ARRAYS_FILE,
        "sha256": digest,
        "meta": meta,
    }
    # atomic manifest publish: the artifact "exists" only once the rename
    # lands, so a concurrent load never sees a half-written directory
    fd, tmp = tempfile.mkstemp(dir=path, prefix=".manifest-")
    with os.fdopen(fd, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, mpath)
    return manifest


def load(path: str, verify: bool = True) -> Artifact:
    """Load an artifact directory (manifest + host arrays); verifies the
    array pack against the manifest hash unless `verify=False`."""
    with open(os.path.join(path, MANIFEST_FILE)) as f:
        manifest = json.load(f)
    if manifest.get("format") != FORMAT:
        raise ValueError(f"{path}: not a {FORMAT} directory")
    if manifest.get("format_version", 0) > FORMAT_VERSION:
        raise ValueError(
            f"{path}: artifact format v{manifest['format_version']} is newer "
            f"than this runtime (v{FORMAT_VERSION})")
    apath = os.path.join(path, manifest["arrays"])
    # one read serves both the hash check and np.load — the deploy/hot-swap
    # path should not pay double I/O on a large pack
    with open(apath, "rb") as f:
        data = f.read()
    if verify:
        digest = hashlib.sha256(data).hexdigest()
        if digest != manifest["sha256"]:
            raise ValueError(f"{apath}: sha256 mismatch — artifact corrupt "
                             f"or tampered")
    with np.load(io.BytesIO(data)) as z:
        arrays = {k: z[k] for k in z.files}
    return Artifact(path=path, manifest=manifest, arrays=arrays)


# Families the model-sharded placement can stripe: the ones whose score
# path is gathers against float (or int8+scale) weight tables along one
# axis. Trees walk int32 structure and FFM rides an opaque codec blob —
# neither has a stripeable table, so sharded placement refuses loudly.
SHARDABLE_FAMILIES = ("linear", "multiclass", "fm", "mf")


def host_score_tables(source) -> dict:
    """Family-normalized HOST view of the score-path tables — the sharded
    load path's input (serving/sharded.py stripes these with
    ``NamedSharding`` over the serving mesh).

    ``source`` is an :class:`Artifact` or a trained model. Returns::

        {"family": str,
         "weights_dtype": str,              # the dtype tables SERVE at
         "quant": None | manifest quant block,
         "meta": {...},                     # dims / label_vocab / factors /
                                            # classification / use_bias / ...
         "striped": [(name, array, axis, grid)],
         "scales": {name: f32 scale array}, # int8 only, same axis as name
         "replicated": {name: array}}       # w0 / mu — tiny, every device

    ``grid`` names which id space the striped axis gathers by
    ("features" for linear/multiclass/FM, "users"/"items" for MF) — each
    grid gets its own stripe arithmetic (core.striping.stripe_grid).
    Arrays come back at their SERVING dtype: the manifest dtype pin (G020)
    is applied HERE, so a bf16-trained table leaves as a host bf16 array
    (never the widened-at-rest f32) and int8 tables leave as int8 plus
    their f32 scales. The score path has no covariances and no optimizer
    slots by construction — only what a request's gathers actually read
    stripes, which is also what per-device budget checks meter."""
    from ..io.checkpoint import (QUANT_SCHEME_BF16, SCALE_SUFFIX,
                                 bf16_unpack_raw, dense_from_rows)

    if isinstance(source, Artifact):
        family, a, meta = source.family, source.arrays, dict(source.meta)
        quant = manifest_quant(source.meta)
    else:
        family, a, meta, quant = family_of(source), None, {}, None
    if family not in SHARDABLE_FAMILIES:
        raise ValueError(
            f"family {family!r} has no sharded serving path (stripeable "
            f"families: {', '.join(SHARDABLE_FAMILIES)}); serve it "
            f"single-device")

    out = {"family": family, "quant": quant, "meta": meta,
           "striped": [], "scales": {}, "replicated": {}}

    def table(name, out_name=None):
        """Pack entry at its serving dtype (artifact source only);
        ``out_name`` keys int8 scales when the striped name differs from
        the pack name (linear stores "weight", serves as "weights")."""
        if quant is None:
            # the manifest dtype pin: the pack stores reduced tables
            # widened value-exactly; reload at the TRAINED width (G020)
            return np.asarray(a[name]).astype(manifest_dtype(meta))
        if quant["scheme"] == QUANT_SCHEME_BF16:
            return bf16_unpack_raw(a[name])
        out["scales"][out_name or name] = np.asarray(a[name + SCALE_SUFFIX],
                                                     np.float32)
        return np.asarray(a[name], np.int8)

    if a is not None:  # ---- artifact source --------------------------------
        out["weights_dtype"] = meta.get("weights_dtype", "float32")
        if family == "linear":
            if quant is None:
                w, _ = dense_from_rows(int(meta["dims"]), a["feature"],
                                       a["weight"], None)
                w = w.astype(manifest_dtype(meta))
            else:
                w = table("weight", out_name="weights")
            out["striped"].append(("weights", w, 0, "features"))
        elif family == "multiclass":
            out["striped"].append(("weights", table("weights"), 1,
                                   "features"))
        elif family == "fm":
            out["striped"] += [("w", table("w"), 0, "features"),
                               ("v", table("v"), 0, "features")]
            out["replicated"]["w0"] = np.asarray(a["w0"], np.float32)
        else:  # mf
            out["striped"] += [("P", table("P"), 0, "users"),
                               ("Q", table("Q"), 0, "items"),
                               ("Bu", np.asarray(a["Bu"], np.float32), 0,
                                "users"),
                               ("Bi", np.asarray(a["Bi"], np.float32), 0,
                                "items")]
            out["replicated"]["mu"] = np.asarray(a["mu"], np.float32)
            meta.setdefault("num_users", int(out["striped"][0][1].shape[0]))
            meta.setdefault("num_items", int(out["striped"][1][1].shape[0]))
        return out

    # ---- live trained model -------------------------------------------------
    import jax

    def host(x):
        return np.asarray(jax.device_get(x))

    if family == "linear":
        w = host(source.state.weights)
        out["striped"].append(("weights", w, 0, "features"))
        meta["dims"] = int(source.dims)
    elif family == "multiclass":
        w = host(source.state.weights)
        out["striped"].append(("weights", w, 1, "features"))
        meta.update(dims=int(source.dims),
                    label_vocab=list(source.label_vocab))
    elif family == "fm":
        st = source.state
        w = host(st.w)
        out["striped"] += [("w", w, 0, "features"),
                           ("v", host(st.v), 0, "features")]
        out["replicated"]["w0"] = np.asarray(host(st.w0), np.float32)
        meta.update(dims=int(source.dims),
                    classification=bool(source.hyper.classification))
    else:  # mf
        st = source.state
        w = host(st.P)
        out["striped"] += [("P", w, 0, "users"),
                           ("Q", host(st.Q), 0, "items"),
                           ("Bu", np.asarray(host(st.Bu), np.float32), 0,
                            "users"),
                           ("Bi", np.asarray(host(st.Bi), np.float32), 0,
                            "items")]
        out["replicated"]["mu"] = np.asarray(host(st.mu), np.float32)
        meta.update(use_bias=bool(source.use_bias),
                    num_users=int(w.shape[0]),
                    num_items=int(out["striped"][1][1].shape[0]))
    out["weights_dtype"] = np.dtype(w.dtype).name
    return out


def rebuild_model(artifact: Artifact):
    """Reconstruct a predictable model object from an artifact.

    Families whose live predict path is a plain dataclass reconstruct the
    original Trained* type; linear/multiclass return the state pytrees the
    engine's jitted predictors consume (serving.engine wraps either shape).
    """
    a, meta = artifact.arrays, artifact.meta
    family = artifact.family

    if manifest_quant(meta) is not None:
        raise ValueError(
            f"rebuild_model: {family!r} artifact is quantized — there is no "
            f"full-precision model to rebuild; serve it via "
            f"serving.engine.make_servable (dequant-free score path)")
    if family == "ffm":
        from ..models.ffm import TrainedFFMModel

        return TrainedFFMModel.from_blob(a["blob"].tobytes())
    if family == "mf":
        import jax.numpy as jnp

        from ..models.mf import MFState, TrainedMFModel

        n_u, n_i = int(meta["num_users"]), int(meta["num_items"])
        dt = manifest_dtype(meta)  # reload at the TRAINED dtype (G020)
        st = MFState(
            P=jnp.asarray(a["P"], dt), Q=jnp.asarray(a["Q"], dt),
            Bu=jnp.asarray(a["Bu"], dt), Bi=jnp.asarray(a["Bi"], dt),
            mu=jnp.asarray(a["mu"], dt), P_gg=None, Q_gg=None,
            touched_u=jnp.ones((n_u,), jnp.int8),
            touched_i=jnp.ones((n_i,), jnp.int8),
            step=jnp.zeros((), jnp.int32))
        return TrainedMFModel(state=st, use_bias=bool(meta["use_bias"]))
    raise ValueError(f"rebuild_model: family {family!r} is served via "
                     f"serving.engine.make_servable, not a model object")
