"""Frozen serving artifacts — immutable, versioned, inference-only models.

The reference's prediction story is offline: dump the model as a Hive table
at close(), score with SQL joins (SURVEY.md §3.5). Online serving needs a
different persistence contract (the immutable-artifact discipline of
production scoring stacks, PAPERS.md ads-infra paper): a model version is a
directory that never changes after `freeze()` —

    <dir>/
      manifest.json   # family, schema, shapes, sha256 of the array pack
      arrays.npz      # every array needed to reproduce predict() bit-exactly

`freeze(model, dir)` accepts any trained model the framework produces
(linear, multiclass, FM, FFM, MF, random forest, GBT — the same family
dispatch as adapters/model_rows.py, whose column schema is recorded in the
manifest) and `load(dir)` returns an `Artifact`; `serving.engine.
make_servable(artifact)` turns it into a jit-served predictor whose outputs
are bit-identical to the live model's (tests/test_serving_artifact.py pins
this for every family).

Artifacts are *inference-only*: optimizer slots are dropped (io/checkpoint
remains the mid-training resume path). The linear family stores the
(feature, weight[, covar]) interchange rows — the exact npz layout of
io/checkpoint.save_model_rows, reconstructed through dense_from_rows — and
the FFM family stores the to_blob() compressed blob (utils/codec recipe),
so both reuse the established codecs rather than inventing new ones.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

FORMAT = "hivemall-tpu-artifact"
FORMAT_VERSION = 1
MANIFEST_FILE = "manifest.json"
ARRAYS_FILE = "arrays.npz"


def _host(x) -> np.ndarray:
    """Device array -> host numpy, bf16 widened to f32 (np.savez cannot
    round-trip ml_dtypes reliably; the widening is value-exact — the
    io/checkpoint at-rest protocol)."""
    import jax

    from ..io.checkpoint import np_saveable

    return np_saveable(jax.device_get(x))


def manifest_dtype(meta: dict, default: str = "float32"):
    """The dtype a family's device tables must reload at — the dtype the
    model TRAINED with (``meta["weights_dtype"]``, recorded at freeze),
    not whatever width the widened-at-rest pack holds. This is the load
    half of the widen-at-rest / narrow-at-serve contract graftcheck G020
    enforces: ``jnp.asarray(pack[...])`` without this pin resurrects a
    bf16 table as f32 and silently doubles serving HBM traffic."""
    from ..io.checkpoint import dtype_from_name

    return dtype_from_name(meta.get("weights_dtype", default))


def family_of(model) -> str:
    """Family tag for any trained model — the adapters/model_rows.py
    dispatch order, as a name."""
    from ..models.ffm import TrainedFFMModel
    from ..models.fm import TrainedFMModel
    from ..models.mf import TrainedMFModel
    from ..models.trees.forest import TrainedForest, TrainedGBT

    if isinstance(model, TrainedGBT):
        return "gbt"
    if isinstance(model, TrainedFMModel):
        return "fm"
    if isinstance(model, TrainedFFMModel):
        return "ffm"
    if isinstance(model, TrainedForest):
        return "forest"
    if isinstance(model, TrainedMFModel):
        return "mf"
    if hasattr(model, "label_vocab"):
        return "multiclass"
    if hasattr(model, "state") and hasattr(model.state, "weights"):
        return "linear"
    raise ValueError(f"{type(model).__name__}: no serving family")


@dataclass
class Artifact:
    """A loaded artifact: manifest + host arrays (still inert — feed to
    serving.engine.make_servable for a predictor)."""

    path: str
    manifest: dict
    arrays: Dict[str, np.ndarray] = field(repr=False)

    @property
    def family(self) -> str:
        return self.manifest["family"]

    @property
    def meta(self) -> dict:
        return self.manifest["meta"]


def _vocab_jsonable(vocab):
    return [v.item() if hasattr(v, "item") else v for v in vocab]


def _pack_trees(prefix: str, trees, arrays: dict) -> None:
    for i, t in enumerate(trees):
        arrays[f"{prefix}{i}__feature"] = np.asarray(t.feature, np.int32)
        arrays[f"{prefix}{i}__threshold_bin"] = np.asarray(t.threshold_bin,
                                                          np.int32)
        arrays[f"{prefix}{i}__nominal"] = np.asarray(t.nominal, bool)
        arrays[f"{prefix}{i}__left"] = np.asarray(t.left, np.int32)
        arrays[f"{prefix}{i}__right"] = np.asarray(t.right, np.int32)
        arrays[f"{prefix}{i}__leaf_value"] = np.asarray(t.leaf_value,
                                                       np.float32)


def _unpack_trees(prefix: str, n: int, arrays: dict):
    from ..models.trees.grow import TreeArrays

    out = []
    for i in range(n):
        feature = arrays[f"{prefix}{i}__feature"]
        out.append(TreeArrays(
            feature=feature,
            threshold_bin=arrays[f"{prefix}{i}__threshold_bin"],
            nominal=arrays[f"{prefix}{i}__nominal"],
            left=arrays[f"{prefix}{i}__left"],
            right=arrays[f"{prefix}{i}__right"],
            leaf_dist=None,
            leaf_value=arrays[f"{prefix}{i}__leaf_value"],
            n_nodes=int(feature.shape[0]),
        ))
    return out


def _pack_bins(bins, arrays: dict, meta: dict) -> None:
    meta["bins_nominal"] = [bool(b.nominal) for b in bins]
    for f, b in enumerate(bins):
        # widen-at-rest: the pack keeps the training-precision edges; the
        # serving engine narrows to f32 at load (_TreeServable)
        arrays[f"bin{f}__edges"] = np.asarray(b.edges, np.float64)  # graftcheck: disable=G018 (at-rest precision; serving narrows at load)


def _unpack_bins(meta: dict, arrays: dict):
    from ..models.trees.binning import BinInfo

    out = []
    for f, nominal in enumerate(meta["bins_nominal"]):
        edges = arrays[f"bin{f}__edges"]
        out.append(BinInfo(bool(nominal), edges, len(edges)))
    return out


def _build_payload(model):
    """(family, arrays dict, meta dict) for any trained model."""
    from ..adapters.model_rows import iter_model_rows

    family = family_of(model)
    arrays: Dict[str, np.ndarray] = {}
    meta: dict = {}
    try:
        meta["columns"], _ = iter_model_rows(model)
    except ValueError:
        meta["columns"] = None

    if family == "linear":
        # the io/checkpoint.save_model_rows interchange layout: untouched
        # entries are 0 (weights) / 1 (covars) by construction, so
        # dense_from_rows reproduces the live tables exactly
        rows = model.model_rows()
        arrays["feature"] = np.asarray(rows[0], np.int64)
        arrays["weight"] = _host(rows[1])
        if len(rows) == 3 and rows[2] is not None:
            arrays["covar"] = _host(rows[2])
        meta.update(dims=int(model.dims), rule=model.rule.name,
                    use_covariance=bool(model.rule.use_covariance),
                    weights_dtype=np.dtype(model.state.weights.dtype).name)
    elif family == "multiclass":
        st = model.state
        arrays["weights"] = _host(st.weights)
        if st.covars is not None:
            arrays["covars"] = _host(st.covars)
        meta.update(dims=int(model.dims),
                    label_vocab=_vocab_jsonable(model.label_vocab),
                    use_covariance=st.covars is not None,
                    weights_dtype=np.dtype(st.weights.dtype).name)
    elif family == "fm":
        st, hy = model.state, model.hyper
        for k in ("w0", "w", "v", "lambda_w0", "lambda_w", "lambda_v"):
            arrays[k] = _host(getattr(st, k))
        arrays["touched"] = _host(st.touched)
        meta.update(dims=int(model.dims), factors=int(hy.factors),
                    classification=bool(hy.classification),
                    sigma=float(hy.sigma), seed=int(hy.seed),
                    lambda0=float(hy.lambda0),
                    weights_dtype=np.dtype(st.w.dtype).name)
    elif family == "ffm":
        # the utils/codec compressed-blob recipe (FFMPredictionModel
        # writeExternal analog); half_float=False keeps bit-exactness
        blob = model.to_blob(half_float=False)
        arrays["blob"] = np.frombuffer(blob, np.uint8)
        hy = model.hyper
        meta.update(factors=int(hy.factors),
                    num_features=int(hy.num_features),
                    num_fields=int(hy.num_fields), v_dims=int(hy.v_dims))
    elif family == "mf":
        st = model.state
        for k in ("P", "Q", "Bu", "Bi", "mu"):
            arrays[k] = _host(getattr(st, k))
        meta.update(use_bias=bool(model.use_bias),
                    num_users=int(arrays["P"].shape[0]),
                    num_items=int(arrays["Q"].shape[0]),
                    factor=int(arrays["P"].shape[1]),
                    weights_dtype=np.dtype(st.P.dtype).name)
    elif family == "forest":
        _pack_trees("tree", [t.tree for t in model.trees], arrays)
        _pack_bins(model.bins, arrays, meta)
        meta.update(n_trees=len(model.trees),
                    classification=bool(model.classification),
                    n_classes=int(model.n_classes),
                    attrs=list(model.attrs))
    elif family == "gbt":
        flat = [t for round_trees in model.trees for t in round_trees]
        _pack_trees("tree", flat, arrays)
        _pack_bins(model.bins, arrays, meta)
        arrays["intercept"] = np.asarray(model.intercept, np.float64)  # graftcheck: disable=G018 (at-rest training dtype; serving narrows at load)
        arrays["classes"] = np.asarray(model.classes)
        meta.update(n_rounds=len(model.trees),
                    n_class_trees=len(model.trees[0]) if model.trees else 0,
                    shrinkage=float(model.shrinkage))
    return family, arrays, meta


def freeze(model, path: str, *, name: Optional[str] = None,
           version: Optional[str] = None) -> dict:
    """Freeze a trained model into an immutable artifact directory.

    Returns the manifest. The directory must not already hold an artifact
    (versions are immutable — freeze a NEW directory and hot-swap it in via
    serving.server.ModelRegistry.deploy).
    """
    os.makedirs(path, exist_ok=True)
    mpath = os.path.join(path, MANIFEST_FILE)
    if os.path.exists(mpath):
        raise FileExistsError(
            f"{mpath} exists — artifacts are immutable; freeze a new "
            f"version directory instead")
    family, arrays, meta = _build_payload(model)
    apath = os.path.join(path, ARRAYS_FILE)
    # savez into memory so the pack is written AND hashed in one pass (a
    # large FM/FFM table would otherwise pay a second full-file read)
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    data = buf.getvalue()
    digest = hashlib.sha256(data).hexdigest()
    with open(apath, "wb") as f:
        f.write(data)
    manifest = {
        "format": FORMAT,
        "format_version": FORMAT_VERSION,
        "family": family,
        "name": name or family,
        "version": version or "1",
        "created_unix": time.time(),
        "arrays": ARRAYS_FILE,
        "sha256": digest,
        "meta": meta,
    }
    # atomic manifest publish: the artifact "exists" only once the rename
    # lands, so a concurrent load never sees a half-written directory
    fd, tmp = tempfile.mkstemp(dir=path, prefix=".manifest-")
    with os.fdopen(fd, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, mpath)
    return manifest


def load(path: str, verify: bool = True) -> Artifact:
    """Load an artifact directory (manifest + host arrays); verifies the
    array pack against the manifest hash unless `verify=False`."""
    with open(os.path.join(path, MANIFEST_FILE)) as f:
        manifest = json.load(f)
    if manifest.get("format") != FORMAT:
        raise ValueError(f"{path}: not a {FORMAT} directory")
    if manifest.get("format_version", 0) > FORMAT_VERSION:
        raise ValueError(
            f"{path}: artifact format v{manifest['format_version']} is newer "
            f"than this runtime (v{FORMAT_VERSION})")
    apath = os.path.join(path, manifest["arrays"])
    # one read serves both the hash check and np.load — the deploy/hot-swap
    # path should not pay double I/O on a large pack
    with open(apath, "rb") as f:
        data = f.read()
    if verify:
        digest = hashlib.sha256(data).hexdigest()
        if digest != manifest["sha256"]:
            raise ValueError(f"{apath}: sha256 mismatch — artifact corrupt "
                             f"or tampered")
    with np.load(io.BytesIO(data)) as z:
        arrays = {k: z[k] for k in z.files}
    return Artifact(path=path, manifest=manifest, arrays=arrays)


def rebuild_model(artifact: Artifact):
    """Reconstruct a predictable model object from an artifact.

    Families whose live predict path is a plain dataclass reconstruct the
    original Trained* type; linear/multiclass return the state pytrees the
    engine's jitted predictors consume (serving.engine wraps either shape).
    """
    a, meta = artifact.arrays, artifact.meta
    family = artifact.family

    if family == "ffm":
        from ..models.ffm import TrainedFFMModel

        return TrainedFFMModel.from_blob(a["blob"].tobytes())
    if family == "mf":
        import jax.numpy as jnp

        from ..models.mf import MFState, TrainedMFModel

        n_u, n_i = int(meta["num_users"]), int(meta["num_items"])
        dt = manifest_dtype(meta)  # reload at the TRAINED dtype (G020)
        st = MFState(
            P=jnp.asarray(a["P"], dt), Q=jnp.asarray(a["Q"], dt),
            Bu=jnp.asarray(a["Bu"], dt), Bi=jnp.asarray(a["Bi"], dt),
            mu=jnp.asarray(a["mu"], dt), P_gg=None, Q_gg=None,
            touched_u=jnp.ones((n_u,), jnp.int8),
            touched_i=jnp.ones((n_i,), jnp.int8),
            step=jnp.zeros((), jnp.int32))
        return TrainedMFModel(state=st, use_bias=bool(meta["use_bias"]))
    raise ValueError(f"rebuild_model: family {family!r} is served via "
                     f"serving.engine.make_servable, not a model object")
