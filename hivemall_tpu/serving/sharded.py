"""Model-sharded servables — score models bigger than one device.

Training stripes the hashed weight table across a mesh (parallel/sharded.py,
core/striping.py); this module gives SERVING the same headroom: an
artifact's score tables load with ``NamedSharding`` over the placement's
``(batch, model)`` mesh — each device holds one [stripe] slice of every
striped table, request batches shard along ``batch`` — so a table that
exceeds one device's memory serves, and the N-1 devices that idled under
single-device placement do work (the ads-serving shape: auction scoring
against sharded embedding tables, PAPERS.md).

Three invariants carried over from the single-device engine:

- **bit-compatible striping.** The load path pads and stripes with
  ``core.striping.stripe_grid`` / ``restripe_array`` — the sharded
  trainers' own grid arithmetic — and scores through the SAME per-device
  bodies training uses (``parallel.sharded.stripe_score``,
  ``models.fm.sharded_gather_predict``), so a served-sharded score cannot
  drift from a trained-sharded one.
- **dequant-free quantized scoring.** int8 tables stripe as int8 with
  their f32 scale arrays striped on the block grid — the stripe is
  aligned up to ``block_rows`` (stripe_grid's ``align``), so a scale
  block never straddles devices and ``local_id >> block_shift`` indexes
  the local scale slice directly; bf16 tables stripe AT bf16 and each
  gathered window widens per-window (G019) exactly like the single-device
  ``_q8_*`` scorers.
- **zero steady-state recompiles.** The sharded jitted scorers are
  process-shared, keyed by (family kind, mesh device list, stripe[,
  block_shift]) in ``_SHARDED_JIT`` — a second engine on the same mesh
  warms for free — and ``ServingEngine.warmup`` sweeps every
  (batch, width) bucket through them exactly as single-device, witnessed
  live by recompile_guard.

Staging is untouched: the sharded servables inherit the sparse-row / pair
staging of their single-device counterparts (serving/engine.py), so
request parsing, width bucketing, pad lanes (index == dims, value 0) and
the pre-parsed request forms behave identically — ``translate_to_stripe``
routes every lane to its owning stripe on device, foreign/pad lanes
contributing exactly 0.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.striping import restripe_array, stripe_grid
from .artifact import host_score_tables
from .engine import _ArgmaxLabelServable, _PairServable, _SparseRowServable
from .placement import BATCH_AXIS, MODEL_AXIS, ModelSharded

# Process-shared sharded scorers: (kind, mesh key, stripe grid, block) ->
# jitted shard_map product. Plain dict mutation is GIL-atomic (the
# _WARMUP_DUMMIES argument); a racing deploy at worst builds one duplicate.
_SHARDED_JIT: dict = {}


def _mesh_key(mesh):
    return (tuple(int(d.id) for d in mesh.devices.flat),
            tuple(int(s) for s in mesh.devices.shape))


def _sharded_jit(kind: str, mesh, grid: tuple,
                 block_shift: Optional[int] = None,
                 use_bias: bool = False):
    key = (kind, _mesh_key(mesh), grid, block_shift, use_bias)
    fn = _SHARDED_JIT.get(key)
    if fn is None:
        fn = _SHARDED_JIT[key] = _BUILDERS[kind](
            mesh, grid, block_shift=block_shift, use_bias=use_bias)
    return fn


# --- per-family sharded score bodies ----------------------------------------
# Each builder returns jax.jit(shard_map(body)) for one (mesh, stripe
# grid). Tables arrive pre-placed with the matching NamedSharding, so
# dispatch never reshards; idx/val arrive as host arrays and take the
# in_specs placement (batch-sharded, replicated over model).


def _build_linear(mesh, grid, block_shift=None, use_bias=False):
    import jax
    from jax.sharding import PartitionSpec as P

    from ..parallel.sharded import stripe_score
    from ..runtime.jax_compat import shard_map

    (stripe,) = grid
    # the per-device body shared with ShardedTrainer.make_predict — serving
    # and training stripe scoring are the same function
    fn = shard_map(stripe_score(MODEL_AXIS, stripe), mesh=mesh,
                   in_specs=(P(MODEL_AXIS), P(BATCH_AXIS), P(BATCH_AXIS)),
                   out_specs=P(BATCH_AXIS))
    return jax.jit(fn)


def _build_q8_linear(mesh, grid, block_shift=None, use_bias=False):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..core.striping import translate_to_stripe
    from ..runtime.jax_compat import shard_map

    (stripe,) = grid

    def local(qw_l, s_l, idx, val):
        lidx, vmask = translate_to_stripe(idx, val, MODEL_AXIS, stripe)
        wq = qw_l.at[lidx].get(mode="fill", fill_value=0)
        sg = s_l.at[lidx >> block_shift].get(mode="fill", fill_value=0.0)
        # per-window dequant (G019): only the gathered [B, K] rows widen,
        # the scale folds into the product, the sum accumulates f32 (G021)
        return jax.lax.psum(
            jnp.sum(wq.astype(jnp.float32) * sg * vmask, axis=-1),
            MODEL_AXIS)

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(MODEL_AXIS), P(MODEL_AXIS), P(BATCH_AXIS),
                             P(BATCH_AXIS)),
                   out_specs=P(BATCH_AXIS))
    return jax.jit(fn)


def _build_multiclass(mesh, grid, block_shift=None, use_bias=False):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..core.striping import translate_to_stripe
    from ..runtime.jax_compat import shard_map

    (stripe,) = grid

    def local(W_l, idx, val):
        lidx, vmask = translate_to_stripe(idx, val, MODEL_AXIS, stripe)
        Wg = jnp.take(W_l, lidx, axis=1, mode="fill", fill_value=0.0)
        return jax.lax.psum(jnp.einsum("lbk,bk->bl", Wg, vmask), MODEL_AXIS)

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(None, MODEL_AXIS), P(BATCH_AXIS),
                             P(BATCH_AXIS)),
                   out_specs=P(BATCH_AXIS))
    return jax.jit(fn)


def _build_q8_multiclass(mesh, grid, block_shift=None, use_bias=False):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..core.striping import translate_to_stripe
    from ..runtime.jax_compat import shard_map

    (stripe,) = grid

    def local(qW_l, s_l, idx, val):
        lidx, vmask = translate_to_stripe(idx, val, MODEL_AXIS, stripe)
        Wq = jnp.take(qW_l, lidx, axis=1, mode="fill", fill_value=0)
        S = jnp.take(s_l, lidx >> block_shift, axis=1, mode="fill",
                     fill_value=0.0)
        return jax.lax.psum(
            jnp.einsum("lbk,bk->bl", Wq.astype(jnp.float32) * S, vmask),
            MODEL_AXIS)

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(None, MODEL_AXIS), P(None, MODEL_AXIS),
                             P(BATCH_AXIS), P(BATCH_AXIS)),
                   out_specs=P(BATCH_AXIS))
    return jax.jit(fn)


def _build_fm(mesh, grid, block_shift=None, use_bias=False):
    import jax
    from jax.sharding import PartitionSpec as P

    from ..models.fm import sharded_gather_predict
    from ..runtime.jax_compat import shard_map

    (stripe,) = grid

    def local(w0, w_l, v_l, idx, val):
        # the ONE copy of feature-sharded FM prediction, shared with the
        # sharded train step — p is its 5th output
        return sharded_gather_predict(w_l, v_l, w0, idx, val, MODEL_AXIS,
                                      stripe)[4]

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(), P(MODEL_AXIS), P(MODEL_AXIS),
                             P(BATCH_AXIS), P(BATCH_AXIS)),
                   out_specs=P(BATCH_AXIS))
    return jax.jit(fn)


def _build_q8_fm(mesh, grid, block_shift=None, use_bias=False):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..core.striping import translate_to_stripe
    from ..runtime.jax_compat import shard_map

    (stripe,) = grid

    def local(w0, qw_l, ws_l, qv_l, vs_l, idx, val):
        lidx, vmask = translate_to_stripe(idx, val, MODEL_AXIS, stripe)
        sw = ws_l.at[lidx >> block_shift].get(mode="fill", fill_value=0.0)
        wg = qw_l.at[lidx].get(mode="fill",
                               fill_value=0).astype(jnp.float32) * sw
        sv = vs_l.at[lidx >> block_shift].get(mode="fill", fill_value=0.0)
        vg = qv_l.at[lidx].get(mode="fill",
                               fill_value=0).astype(jnp.float32) * sv
        vx = vg * vmask[..., None]
        linear, sum_vfx, sum_v2x2 = jax.lax.psum(
            (jnp.sum(wg * vmask, axis=-1),
             jnp.sum(vx, axis=-2),
             jnp.sum(vx * vx, axis=-2)), MODEL_AXIS)
        return w0 + linear + 0.5 * jnp.sum(sum_vfx * sum_vfx - sum_v2x2,
                                           axis=-1)

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(), P(MODEL_AXIS), P(MODEL_AXIS),
                             P(MODEL_AXIS), P(MODEL_AXIS), P(BATCH_AXIS),
                             P(BATCH_AXIS)),
                   out_specs=P(BATCH_AXIS))
    return jax.jit(fn)


def _build_mf(mesh, grid, block_shift=None, use_bias=False):
    """MF pair scoring over striped P/Q/Bu/Bi: each device contributes the
    rows it owns (foreign ids hit the drop slot and gather 0), one fused
    psum assembles the full gathered windows, the dot product runs on the
    assembled f32 windows. ``block_shift`` set means int8 tables with
    scale arrays riding along (two extra striped inputs)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..core.striping import translate_to_stripe
    from ..runtime.jax_compat import shard_map

    stripe_u, stripe_i = grid
    quant = block_shift is not None

    def gather(table, scales, ids, stripe):
        lid, _ = translate_to_stripe(ids, jnp.ones(ids.shape, jnp.float32),
                                     MODEL_AXIS, stripe)
        g = table.at[lid].get(mode="fill", fill_value=0)
        g = g.astype(jnp.float32)  # per-window widen (G019): bf16/int8
        if scales is not None:
            g = g * scales.at[lid >> block_shift].get(mode="fill",
                                                      fill_value=0.0)
        return g, lid

    def local(P_l, Q_l, Bu_l, Bi_l, mu, ps_l, qs_l, u, i):
        Pg, lu = gather(P_l, ps_l if quant else None, u, stripe_u)
        Qg, li = gather(Q_l, qs_l if quant else None, i, stripe_i)
        bu = Bu_l.at[lu].get(mode="fill", fill_value=0.0)
        bi = Bi_l.at[li].get(mode="fill", fill_value=0.0)
        Pg, Qg, bu, bi = jax.lax.psum((Pg, Qg, bu, bi), MODEL_AXIS)
        out = jnp.sum(Pg * Qg, axis=-1) + mu
        if use_bias:
            out = out + bu + bi
        return out

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(MODEL_AXIS), P(MODEL_AXIS), P(MODEL_AXIS),
                             P(MODEL_AXIS), P(), P(MODEL_AXIS),
                             P(MODEL_AXIS), P(BATCH_AXIS), P(BATCH_AXIS)),
                   out_specs=P(BATCH_AXIS))
    return jax.jit(fn)


_BUILDERS = {"linear": _build_linear, "q8_linear": _build_q8_linear,
             "multiclass": _build_multiclass,
             "q8_multiclass": _build_q8_multiclass,
             "fm": _build_fm, "q8_fm": _build_q8_fm, "mf": _build_mf}


# --- table placement ---------------------------------------------------------


def _stripe_put(arr, axis: int, dims: int, dims_padded: int, mesh):
    """Pad a host table to the stripe grid (core.striping.restripe_array —
    the elastic-resume pad math) and place it striped along ``axis`` over
    the mesh's model axis. Weight fills are always 0 (the score path has
    no covariances, whose fill would be 1)."""
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    a = restripe_array(np.asarray(arr), axis, dims, dims_padded, fill=0)
    spec = [None] * a.ndim
    spec[axis] = MODEL_AXIS
    return jax.device_put(a, NamedSharding(mesh, P(*spec)))


def _replicate_put(arr, mesh):
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    return jax.device_put(np.asarray(arr), NamedSharding(mesh, P()))


# --- the sharded servables ---------------------------------------------------


class _ShardedMixin:
    """Placement bookkeeping shared by every sharded servable: what the
    engine surfaces on /models (placement_info), what the warmup-dummy
    cache keys on (mesh_shape), and what budget checks meter
    (per_device_table_bytes)."""

    mesh_shape: tuple = ()
    per_device_table_bytes: int = 0
    placement_info: Optional[dict] = None

    def _init_placement(self, placement: ModelSharded, spec: dict,
                        grids: dict) -> None:
        mesh = placement.mesh()
        self.mesh_shape = tuple(int(s) for s in mesh.devices.shape)
        self.weights_dtype = spec["weights_dtype"]
        self.placement_info = dict(placement.describe())
        self.placement_info["stripe_grids"] = {
            g: {"dims": d, "stripe": s, "dims_padded": p}
            for g, (d, s, p) in grids.items()}

    def device_tables(self):
        # dedupe by identity: the fixed-arity MF body takes Bu/Bi again as
        # inert scale stand-ins on non-quantized runs, which must not
        # double-count in table_bytes
        seen, out = set(), []
        for t in self._tables:
            if id(t) not in seen:
                seen.add(id(t))
                out.append(t)
        return out


class _ShardedRowServable(_ShardedMixin, _SparseRowServable):
    """Sharded sparse-row families (linear / FM, any precision): staging
    inherited from the single-device path, dispatch through the
    process-shared sharded jit."""

    def __init__(self, kind: str, family: str, tables, dims: int,
                 placement: ModelSharded, grid: tuple,
                 block_shift: Optional[int] = None) -> None:
        _SparseRowServable.__init__(self, dims)
        self.family = family
        self._tables = tuple(tables)
        self._scores = _sharded_jit(kind, placement.mesh(), grid,
                                    block_shift=block_shift)
        self.jit_fns = (self._scores,)

    def dispatch(self, staged):
        return self._scores(*self._tables, staged.indices, staged.values)


class _ShardedLabelServable(_ShardedRowServable, _ArgmaxLabelServable):
    """Multiclass on a mesh: sharded dispatch + the shared argmax/vocab
    label selection."""

    def __init__(self, kind: str, tables, dims: int, label_vocab,
                 placement: ModelSharded, grid: tuple,
                 block_shift: Optional[int] = None) -> None:
        super().__init__(kind, "multiclass", tables, dims, placement, grid,
                         block_shift=block_shift)
        self.label_vocab = list(label_vocab)


class _ShardedMFServable(_ShardedMixin, _PairServable):
    """MF on a mesh: pair staging inherited; P/Q/Bu/Bi striped over their
    own (users, items) grids; jitted sharded gather-dot (unlike the
    host-numpy single-device MF servable, the gathers here ARE device
    batch work — assembling rows across stripes is the point)."""

    def __init__(self, tables, placement: ModelSharded, grid: tuple,
                 use_bias: bool, block_shift: Optional[int] = None) -> None:
        self._tables = tuple(tables)
        self._scores = _sharded_jit("mf", placement.mesh(), grid,
                                    block_shift=block_shift,
                                    use_bias=use_bias)
        self.jit_fns = (self._scores,)

    def dispatch(self, staged):
        u, i = staged
        return self._scores(*self._tables, u, i)


# --- the sharded load path ---------------------------------------------------


def sharded_servable(source, placement: ModelSharded):
    """Artifact | trained model -> sharded servable on ``placement``.

    The load path: normalize the score tables to host arrays at their
    serving dtype (serving.artifact.host_score_tables — the manifest dtype
    pin applies there), derive each id-grid's stripe with the trainers'
    own grid arithmetic (stripe_grid; int8 aligns the stripe to the scale
    block), pad + place every striped table with NamedSharding along the
    model axis and every scalar replicated, then bind the family's
    process-shared sharded scorer. Budget checks run against the
    PER-DEVICE resident bytes — the quantity sharding actually divides."""
    spec = host_score_tables(source)
    quant = spec["quant"]
    scheme = quant["scheme"] if quant else None
    from ..io.checkpoint import QUANT_SCHEME_INT8

    is_int8 = scheme == QUANT_SCHEME_INT8
    block_rows = int(quant["block_rows"]) if is_int8 else 1
    block_shift = block_rows.bit_length() - 1 if is_int8 else None
    n = placement.model_shards
    mesh = placement.mesh()
    meta = spec["meta"]

    # one stripe grid per id space (features; users+items for MF)
    grid_dims = {"features": int(meta["dims"]) if "dims" in meta else None,
                 "users": int(meta.get("num_users", 0)),
                 "items": int(meta.get("num_items", 0))}
    grids = {}
    for _, _, _, grid in spec["striped"]:
        if grid not in grids:
            stripe, padded = stripe_grid(grid_dims[grid], n,
                                         align=block_rows)
            grids[grid] = (grid_dims[grid], stripe, padded)

    # budget BEFORE placement: per-device bytes are computable from host
    # array shapes alone, and the whole point of the refusal is to fire
    # before jax.device_put can OOM a real device
    per_device = 0
    for name, arr, axis, grid in spec["striped"]:
        _, stripe, _ = grids[grid]
        per_device += stripe * (arr.size // arr.shape[axis]) \
            * arr.dtype.itemsize
        scales = spec["scales"].get(name)
        if scales is not None:
            per_device += (stripe // block_rows) \
                * (scales.size // scales.shape[axis]) * 4
    for arr in spec["replicated"].values():
        per_device += int(np.asarray(arr).size) * 4
    placement.check_budget(
        int(per_device), f"{spec['family']} model ({spec['weights_dtype']})")

    placed = {}
    for name, arr, axis, grid in spec["striped"]:
        dims_g, stripe, padded = grids[grid]
        placed[name] = _stripe_put(arr, axis, dims_g, padded, mesh)
        scales = spec["scales"].get(name)
        if scales is not None:
            # scales stripe WITH their blocks: the block grid is the row
            # grid divided by block_rows, and the stripe is block-aligned
            nb = -(-dims_g // block_rows)
            placed[name + "__scale"] = _stripe_put(
                scales, axis, nb, padded // block_rows, mesh)
    for name, arr in spec["replicated"].items():
        placed[name] = _replicate_put(arr, mesh)

    family = spec["family"]
    if family == "linear":
        grid = (grids["features"][1],)
        if is_int8:
            sv = _ShardedRowServable(
                "q8_linear", "linear",
                (placed["weights"], placed["weights__scale"]),
                grid_dims["features"], placement, grid,
                block_shift=block_shift)
        else:
            sv = _ShardedRowServable("linear", "linear",
                                     (placed["weights"],),
                                     grid_dims["features"], placement, grid)
    elif family == "multiclass":
        grid = (grids["features"][1],)
        if is_int8:
            sv = _ShardedLabelServable(
                "q8_multiclass",
                (placed["weights"], placed["weights__scale"]),
                grid_dims["features"], meta["label_vocab"], placement, grid,
                block_shift=block_shift)
        else:
            sv = _ShardedLabelServable(
                "multiclass", (placed["weights"],), grid_dims["features"],
                meta["label_vocab"], placement, grid)
    elif family == "fm":
        grid = (grids["features"][1],)
        if is_int8:
            sv = _ShardedRowServable(
                "q8_fm", "fm",
                (placed["w0"], placed["w"], placed["w__scale"],
                 placed["v"], placed["v__scale"]),
                grid_dims["features"], placement, grid,
                block_shift=block_shift)
        else:
            sv = _ShardedRowServable(
                "fm", "fm", (placed["w0"], placed["w"], placed["v"]),
                grid_dims["features"], placement, grid)
    else:  # mf
        grid = (grids["users"][1], grids["items"][1])
        tables = [placed["P"], placed["Q"], placed["Bu"], placed["Bi"],
                  placed["mu"]]
        if is_int8:
            tables += [placed["P__scale"], placed["Q__scale"]]
        else:
            # the mf body takes a fixed arity; non-quant runs pass the bias
            # tables again as inert stand-ins for the scale slots (never
            # read: the body only touches them when block_shift is set)
            tables += [placed["Bu"], placed["Bi"]]
        sv = _ShardedMFServable(tables, placement, grid,
                                bool(meta["use_bias"]),
                                block_shift=block_shift)
        sv.family = "mf"
    sv.per_device_table_bytes = int(per_device)
    sv._init_placement(placement, spec, grids)
    sv.placement_info["per_device_table_bytes"] = int(per_device)
    return sv
