"""Dynamic micro-batching: keep the accelerator hot without unbounded queues.

One request at a time under-fills the device (a [8, K] gather-dot costs the
same dispatch as [512, K]); the batcher merges concurrent requests into one
padded batch — the request-batching layer every production scoring stack
carries (PAPERS.md ads-infra paper). Policy:

- a batch closes when it holds ``max_batch`` rows OR the oldest queued
  request has waited ``max_delay_ms`` (latency ceiling under light load,
  full batches under heavy load);
- admission control is explicit: a queue deeper than ``max_queue_rows``
  REJECTS new work (`QueueFull` -> HTTP 503 in serving/server.py) instead
  of growing an unbounded backlog — shed load early, keep served latency
  bounded;
- every request gets a `concurrent.futures.Future`; a worker failure fails
  the affected requests, never the process.

Metrics (runtime.metrics.REGISTRY): queue-depth gauge, batch-occupancy and
queue-delay histograms, accepted/rejected counters.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, List, Sequence

from ..runtime.metrics import REGISTRY

OCCUPANCY_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)
DELAY_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.05, 0.1, 0.25, 1.0)


class QueueFull(RuntimeError):
    """Admission control: queue at capacity — caller should shed (503)."""


class BatcherClosed(RuntimeError):
    """submit() after close()."""


class _Pending:
    __slots__ = ("instances", "future", "enqueued")

    def __init__(self, instances) -> None:
        self.instances = instances
        self.future: Future = Future()
        self.enqueued = time.perf_counter()


class DynamicBatcher:
    """Micro-batching front of one ServingEngine (or any ``predict_fn``
    taking a list of instances and returning an indexable of results)."""

    def __init__(self, predict_fn: Callable[[List], Sequence], *,
                 max_batch: int = 256, max_delay_ms: float = 2.0,
                 max_queue_rows: int = 4096, name: str = "default") -> None:
        self.predict_fn = predict_fn
        self.max_batch = int(max_batch)
        self.max_delay = float(max_delay_ms) / 1000.0
        self.max_queue_rows = int(max_queue_rows)
        self.name = name
        self._cv = threading.Condition()
        self._q: deque = deque()
        self._depth_rows = 0
        self._closed = False
        self._accepted = REGISTRY.counter("serving", f"{name}.batcher.accepted")
        self._rejected = REGISTRY.counter("serving", f"{name}.batcher.rejected")
        self._occupancy = REGISTRY.histogram(
            f"serving.{name}.batch_occupancy", OCCUPANCY_BUCKETS)
        self._delay = REGISTRY.histogram(
            f"serving.{name}.queue_delay_seconds", DELAY_BUCKETS)
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"hivemall-batcher-{name}")
        self._thread.start()

    # -- producer side -------------------------------------------------------

    def submit(self, instances: Sequence) -> Future:
        """Enqueue one request (a list of instances); the Future resolves to
        the list of predictions for exactly those instances, in order."""
        if not instances:
            f: Future = Future()
            f.set_result([])
            return f
        p = _Pending(list(instances))
        with self._cv:
            if self._closed:
                raise BatcherClosed(f"batcher {self.name!r} is closed")
            if self._depth_rows + len(p.instances) > self.max_queue_rows:
                self._rejected.increment()
                raise QueueFull(
                    f"batcher {self.name!r}: queue holds {self._depth_rows} "
                    f"rows (cap {self.max_queue_rows}) — shed load")
            self._q.append(p)
            self._depth_rows += len(p.instances)
            REGISTRY.set_gauge(f"serving.{self.name}.queue_depth_rows",
                               float(self._depth_rows))
            self._cv.notify()
        self._accepted.increment()
        return p.future

    def close(self, drain: bool = True) -> None:
        """Stop accepting work. ``drain=True`` (the hot-swap path) lets the
        worker finish everything already queued before the thread exits, so
        an in-flight version swap fails zero requests."""
        dropped: List[_Pending] = []
        with self._cv:
            if self._closed:
                return
            self._closed = True
            if not drain:
                while self._q:
                    dropped.append(self._q.popleft())
                self._depth_rows = 0
            self._cv.notify_all()
        # outside the lock: set_exception runs done-callbacks synchronously,
        # and arbitrary callback code must never execute while _cv is held
        # (a callback that needs the lock would stall every producer — the
        # G013 blocking-under-lock hazard)
        for p in dropped:
            p.future.set_exception(
                BatcherClosed(f"batcher {self.name!r} closed"))
        self._thread.join(timeout=30.0)

    # -- worker side ---------------------------------------------------------

    def _take_batch(self):
        """Block for the first request, then gather more until max_batch or
        the first request's max_delay deadline. Returns [] at shutdown."""
        with self._cv:
            while not self._q:
                if self._closed:
                    return []
                self._cv.wait()
            batch = [self._q.popleft()]
            rows = len(batch[0].instances)
            deadline = batch[0].enqueued + self.max_delay
            while rows < self.max_batch:
                if self._q:
                    nxt = self._q[0]
                    if rows + len(nxt.instances) > self.max_batch:
                        break
                    batch.append(self._q.popleft())
                    rows += len(nxt.instances)
                    continue
                remaining = deadline - time.perf_counter()
                if remaining <= 0 or self._closed:
                    break
                self._cv.wait(timeout=remaining)
            self._depth_rows -= rows
            REGISTRY.set_gauge(f"serving.{self.name}.queue_depth_rows",
                               float(self._depth_rows))
        return batch

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if not batch:
                return
            now = time.perf_counter()
            rows: List = []
            for p in batch:
                self._delay.observe(now - p.enqueued)
                rows.extend(p.instances)
            self._occupancy.observe(len(rows))
            try:
                preds = self.predict_fn(rows)
            except Exception as e:  # fail the batch, not the process
                for p in batch:
                    if not p.future.cancelled():
                        p.future.set_exception(e)
                continue
            off = 0
            for p in batch:
                k = len(p.instances)
                if not p.future.cancelled():
                    p.future.set_result(list(preds[off:off + k]))
                off += k
