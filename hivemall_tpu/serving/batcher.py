"""Overload-grade dynamic micro-batching: priorities, quotas, adaptive
windows, deadline-aware shedding — without unbounded queues.

One request at a time under-fills the device (a [8, K] gather-dot costs the
same dispatch as [512, K]); the batcher merges concurrent requests into one
padded batch — the request-batching layer every production scoring stack
carries (PAPERS.md ads-infra paper). Under light load it behaves exactly
like the PR 3 batcher; under overload it degrades *predictably* instead of
collapsing (serving/admission.py holds the primitives):

- **priority classes**: one FIFO queue per class (high/normal/low),
  drained strictly-high-first into SINGLE-CLASS batches — the anchor's
  class fixes the batch, so a high-priority request neither waits out a
  lower class's widened co-ride window nor rides inside its dispatch
  quantum, and a higher-priority arrival closes an in-progress lower
  window immediately; a class skipped ``starvation_limit`` consecutive
  batches while it had queued work anchors the next batch, so
  low-priority latency under sustained high-priority flood is bounded,
  not infinite;
- **admission quotas**: class *c* may fill the queue only to
  ``priority_quota_fracs[c] * max_queue_rows`` — low sheds first (503
  ``reason="quota"``), high keeps headroom to the full cap, and an
  arriving higher-priority request evicts the newest lowest-priority
  queued work (503 ``reason="shed"``) rather than being refused;
- **adaptive batching** (AIMD): the co-ride window (``max_delay``) and
  batch target (``max_batch``) widen additively toward
  ``max_delay_ms_cap``/``max_batch_cap`` while a backlog persists and
  decay multiplicatively when the queue idles — light-load latency stays
  pinned at the base window while overload throughput grows. A
  high-priority rider always caps the window at the BASE delay: the wide
  window is paid by the classes that can afford it;
- **deadline expiry**: requests carry ``deadline_ms``; one that expires
  while queued fails with `DeadlineExpired` (HTTP 504) and never reaches
  dispatch — a slot freed for work someone is still waiting on;
- a batch closes when it holds the controller's current batch-row target
  OR the anchor request's window elapses OR a member's deadline arrives;
- every request gets a `concurrent.futures.Future`; a worker failure
  fails the affected requests, never the process;
- **hot-row cache + coalescing** (optional — ``cache=`` a
  serving/cache.py ScoreCache): consulted BEFORE the admission lock, so
  a request whose rows are all cached (version-exact keys) resolves
  without consuming queue capacity, class quota, or a batch slot, and a
  request fully covered by cache + in-flight leaders shares those
  leaders' computation. Anything else flows unchanged. Note one
  deliberate asymmetry: a CLOSED (draining) batcher still serves cache
  hits — the entry was resolved before the swap, and its answer is
  labeled with the version it was admitted under, exactly like a request
  that beat the swap by a millisecond.

The admission decision is ONE lock acquisition: quota check, shed
selection, queue append and every counter update happen under ``_cv`` with
no check-then-act window (evicted futures fail AFTER release — Future
callbacks must never run under the CV, the G013 discipline).

Metrics (runtime.metrics.REGISTRY): queue-depth gauges (total and
per-class), batch-occupancy and queue-delay histograms, accepted /
quota_rejected / shed / expired counters per class, live controller state
(``adaptive_delay_ms`` / ``adaptive_batch_rows``) and the drain-rate
estimate (``rows_per_sec``) that prices ``Retry-After``.

Tracing (runtime.tracing.TRACER): the request's span is captured at
submit() and carried ON the queue entry across the thread hop — the worker
parents its spans to it explicitly (contextvars do not cross threads). The
enqueue->dispatch wait is recorded retroactively as a ``queue.wait`` child
span; the merged device call runs under a ``batch.predict`` span parented
to the first traced request of the batch, and every other request in the
batch gets a ``batched`` instant event linking to that trace. A submit with
no ambient span (direct batcher users) opens its own ``serving.request``
root, ended by the future's done-callback. Expired requests get a
``deadline.expired`` instant event instead of device-side spans.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, List, Optional, Sequence, Tuple

from ..runtime.metrics import REGISTRY
from ..runtime.tracing import TRACER
from .admission import (AIMDController, DeadlineExpired, PRIORITY_NAMES,
                        QueueFull, ShedLowPriority, priority_class)

OCCUPANCY_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)
DELAY_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.05, 0.1, 0.25, 1.0)


class BatcherClosed(RuntimeError):
    """submit() after close()."""


class _Pending:
    # every field publishes immutably in __init__ BEFORE the entry is
    # visible to the worker thread (set post-append would race the take)
    __slots__ = ("instances", "future", "enqueued", "span", "owns_span",
                 "cls", "deadline")

    def __init__(self, instances, span, owns_span: bool, cls: int,
                 deadline_ms: Optional[float]) -> None:
        self.instances = instances
        self.future: Future = Future()
        self.enqueued = time.perf_counter()
        self.span = span  # the request's trace span (maybe NULL_SPAN)
        self.owns_span = owns_span  # True: we opened it, done-cb ends it
        self.cls = cls  # priority class index (0 drains first)
        self.deadline = None if deadline_ms is None \
            else self.enqueued + float(deadline_ms) / 1e3


class DynamicBatcher:
    """Micro-batching front of one ServingEngine (or any ``predict_fn``
    taking a list of instances and returning an indexable of results).

    Defaults reproduce the legacy fixed-window, single-class behavior
    exactly: caps equal bases (no adaptivity) and every class may use the
    whole queue (quota fractions all 1.0). The overload posture is opted
    into with ``max_delay_ms_cap`` / ``max_batch_cap`` /
    ``priority_quota_fracs`` — ModelRegistry passes serving-grade
    defaults (docs/serving.md "Overload behavior").
    """

    def __init__(self, predict_fn: Callable[[List], Sequence], *,
                 max_batch: int = 256, max_delay_ms: float = 2.0,
                 max_queue_rows: int = 4096, name: str = "default",
                 max_batch_cap: Optional[int] = None,
                 max_delay_ms_cap: Optional[float] = None,
                 priority_quota_fracs: Optional[Sequence[float]] = None,
                 starvation_limit: int = 8,
                 express_high: bool = False,
                 cache=None, cache_version: str = "",
                 row_key_fn=None) -> None:
        self.predict_fn = predict_fn
        # the hot-row score cache front (serving/cache.py): consulted in
        # submit() BEFORE the admission lock, so a fully-cached or fully-
        # coalesced request resolves without consuming queue capacity,
        # class quota, or a batch slot. The cache object is shared across
        # this model's versions (ModelRegistry owns it); cache_version is
        # THIS batcher's version — captured at admission into every key,
        # which is the whole hot-swap invalidation story. row_key_fn is
        # the engine's canonical per-row key derivation (None per request
        # = not cacheable, flows unchanged).
        self._cache = cache
        self._cache_version = str(cache_version)
        self._row_key_fn = row_key_fn if cache is not None else None
        self.max_batch = int(max_batch)
        self.max_delay = float(max_delay_ms) / 1000.0
        self.max_queue_rows = int(max_queue_rows)
        self.name = name
        n_cls = len(PRIORITY_NAMES)
        fracs = tuple(float(f) for f in (priority_quota_fracs
                                         or (1.0,) * n_cls))
        if len(fracs) != n_cls or fracs[0] != 1.0 \
                or any(not 0.0 < f <= 1.0 for f in fracs) \
                or any(a < b for a, b in zip(fracs, fracs[1:])):
            raise ValueError(
                f"priority_quota_fracs must be {n_cls} non-increasing "
                f"fractions in (0, 1] starting at 1.0, got {fracs}")
        self._quota_rows = tuple(int(self.max_queue_rows * f)
                                 for f in fracs)
        self.priority_quota_fracs = fracs
        self.starvation_limit = int(starvation_limit)
        self._ctl = AIMDController(
            base_delay_s=self.max_delay,
            cap_delay_s=(float(max_delay_ms_cap) / 1000.0
                         if max_delay_ms_cap is not None else self.max_delay),
            base_batch=self.max_batch,
            cap_batch=int(max_batch_cap) if max_batch_cap is not None
            else self.max_batch)
        self._cv = threading.Condition()
        self._qs: Tuple[deque, ...] = tuple(deque() for _ in range(n_cls))
        self._class_rows = [0] * n_cls
        self._skips = [0] * n_cls  # consecutive batches a class waited out
        self._depth_rows = 0
        self._closed = False
        self._ewma_rows_per_s = 0.0  # drain-rate estimate (Retry-After)
        self._accepted = REGISTRY.counter("serving", f"{name}.batcher.accepted")
        self._rejected = REGISTRY.counter("serving", f"{name}.batcher.rejected")
        self._accepted_c = tuple(
            REGISTRY.counter("serving", f"{name}.batcher.accepted.{p}")
            for p in PRIORITY_NAMES)
        self._quota_rejected_c = tuple(
            REGISTRY.counter("serving", f"{name}.batcher.quota_rejected.{p}")
            for p in PRIORITY_NAMES)
        self._shed_c = tuple(
            REGISTRY.counter("serving", f"{name}.batcher.shed.{p}")
            for p in PRIORITY_NAMES)
        self._expired_c = tuple(
            REGISTRY.counter("serving", f"{name}.batcher.expired.{p}")
            for p in PRIORITY_NAMES)
        self._occupancy = REGISTRY.histogram(
            f"serving.{name}.batch_occupancy", OCCUPANCY_BUCKETS)
        self._delay = REGISTRY.histogram(
            f"serving.{name}.queue_delay_seconds", DELAY_BUCKETS)
        # gauge keys precomputed once: their setters run under _cv on
        # every admission and take — no f-string work on the hot lock
        self._g_depth = f"serving.{name}.queue_depth_rows"
        self._g_depth_c = tuple(f"serving.{name}.queue_depth_rows.{p}"
                                for p in PRIORITY_NAMES)
        self._g_delay = f"serving.{name}.adaptive_delay_ms"
        self._g_batch = f"serving.{name}.adaptive_batch_rows"
        self._g_rate = f"serving.{name}.rows_per_sec"
        # the express lane: a dedicated worker that drains ONLY class 0,
        # so a high-priority request never waits behind an in-flight
        # lower-class dispatch quantum (the engines' jitted predict is
        # thread-safe; capacity reservation for the interactive tier is
        # the ads-paper pattern). The general worker then never touches
        # class 0 and only IT drives the AIMD controller — an idle
        # express lane must not decay the window the loaded general lane
        # earned.
        self.express_high = bool(express_high)
        self._threads = []
        general = tuple(range(1 if self.express_high else 0,
                              len(PRIORITY_NAMES)))
        for tag, classes, drives in (
                [("express", (0,), False)] if self.express_high else []) \
                + [("general", general, True)]:
            t = threading.Thread(
                target=self._loop, args=(classes, drives), daemon=True,
                name=f"hivemall-batcher-{name}-{tag}")
            t.start()
            self._threads.append(t)

    # -- producer side -------------------------------------------------------

    def submit(self, instances: Sequence, *, priority="normal",
               deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one request (a list of instances); the Future resolves
        to the list of predictions for exactly those instances, in order.

        ``priority`` is a class name or index (serving/admission.py);
        ``deadline_ms`` is this request's total queue+dispatch budget —
        expiry in the queue fails the Future with `DeadlineExpired`.
        Over-quota admission raises `QueueFull` (reason "quota"); an
        accepted request later evicted for higher-priority work fails
        with `ShedLowPriority` (reason "shed"). Both carry
        ``retry_after_s`` from the live drain-rate estimate.

        With a cache attached, a fully-covered request resolves without
        queueing; a COALESCED request inherits its leader's fate wholesale
        (queue position, effective deadline, failure mode — see
        serving/cache.py), its own ``priority``/``deadline_ms`` validated
        but not separately enforced."""
        cls = priority_class(priority)
        if deadline_ms is not None:
            deadline_ms = float(deadline_ms)
            if not deadline_ms > 0:
                raise ValueError(f"deadline_ms must be > 0, "
                                 f"got {deadline_ms}")
        if not instances:
            f: Future = Future()
            f.set_result([])
            return f
        # capture the caller's span for the thread hop; with no ambient
        # span open our own request root (ended by the done-callback). A
        # rejected submit abandons an owned span un-ended — it is never
        # committed, which is the point: 503s don't fill the ring.
        cur = TRACER.current()
        if cur is not None:
            span, owns = cur, False
        else:
            span = TRACER.begin("serving.request", parent=None,
                                args={"batcher": self.name,
                                      "rows": len(instances)})
            owns = span.recording
        p = _Pending(list(instances), span, owns, cls, deadline_ms)
        k = len(p.instances)
        # the hot-row cache front, BEFORE the admission lock: a fully
        # cached request resolves right here (no queue capacity, no class
        # quota, no batch slot) and a request fully covered by cache +
        # in-flight leaders attaches to those leaders' Futures
        # (serving/cache.py). Any uncovered row -> the request flows
        # unchanged below, leading its new keys; its Future's outcome
        # settles the cache (populate on success, fail followers with the
        # same reason on shed/expiry/engine error).
        token = None
        if self._cache is not None and self._row_key_fn is not None:
            keys = self._row_key_fn(p.instances)
            if keys is not None:
                plan = self._cache.admit(self._cache_version, keys,
                                         p.future)
                if plan.kind == "hit":
                    if span.recording:
                        span.event("cache.hit", rows=plan.hit_rows,
                                   version=self._cache_version)
                    if owns:
                        p.future.add_done_callback(
                            lambda f, s=span: TRACER.end(s))
                    # outside every lock: set_result runs done-callbacks
                    # synchronously (G013)
                    p.future.set_result(plan.values)
                    return p.future
                if plan.kind == "coalesced":
                    if span.recording:
                        span.event("cache.coalesced",
                                   rows=plan.coalesced_rows,
                                   hit_rows=plan.hit_rows,
                                   version=self._cache_version)
                    if owns:
                        p.future.add_done_callback(
                            lambda f, s=span: TRACER.end(s))
                    return p.future  # the cache settles it with the leaders
                if plan.kind == "refused":
                    # a row of this request was quota-refused within the
                    # negative TTL: repeat the refusal synchronously from
                    # the cache front — no admission lock, no shed scan.
                    # The owned span is abandoned un-ended on purpose,
                    # like every rejected submit (503s don't fill the
                    # ring).
                    if span.recording:
                        span.event("cache.negative",
                                   version=self._cache_version)
                    raise plan.error
                token = plan.token
        evicted: List[_Pending] = []
        err: Optional[Exception] = None
        ra = None
        # the whole admission decision is ONE lock acquisition: quota
        # check, shed selection, append and counters — no check-then-act
        # window for a concurrent submit to slip through
        with self._cv:
            if self._closed:
                err = BatcherClosed(f"batcher {self.name!r} is closed")
            else:
                quota = self._quota_rows[cls]
                if self._depth_rows + k > quota:
                    ra = self._retry_after_locked()
                    # make room by dropping the newest strictly-lower-
                    # priority queued work (oldest keep their place in
                    # line) — but only when the lower classes actually
                    # hold enough rows to admit this request: shedding
                    # someone and STILL rejecting would destroy accepted
                    # work for nothing
                    need = self._depth_rows + k - quota
                    if sum(self._class_rows[c]
                           for c in range(cls + 1, len(self._qs))) >= need:
                        self._shed_lower_locked(cls, need, evicted)
                if self._depth_rows + k > quota:
                    self._quota_rejected_c[cls].increment()
                    self._rejected.increment()
                    err = QueueFull(
                        f"batcher {self.name!r}: {PRIORITY_NAMES[cls]}"
                        f"-priority admission quota is {quota} rows, queue "
                        f"holds {self._depth_rows} — shed load",
                        reason="quota", retry_after_s=ra)
                else:
                    self._qs[cls].append(p)
                    self._class_rows[cls] += k
                    self._depth_rows += k
                    self._accepted.increment()
                    self._accepted_c[cls].increment()
                    self._set_depth_gauges_locked()
                    if self.express_high:
                        # two workers wait on one CV; notify() could wake
                        # the lane that cannot serve this class
                        self._cv.notify_all()
                    else:
                        self._cv.notify()
        # outside the lock: set_exception runs done-callbacks synchronously,
        # and arbitrary callback code must never execute while _cv is held
        # (the G013 blocking-under-lock hazard)
        for ev in evicted:
            if not ev.future.cancelled():
                ev.future.set_exception(ShedLowPriority(
                    f"batcher {self.name!r}: {PRIORITY_NAMES[ev.cls]}-"
                    f"priority request shed for higher-priority work",
                    retry_after_s=ra))
        if err is not None:
            # a refused leader registered nothing (leadership is taken by
            # lead() below, only on success), so no follower can be
            # stranded on an admission error — the refusal stays
            # synchronous, where registry.submit's swap-retry can see it
            if token is not None and isinstance(err, QueueFull):
                # quota refusal of a lead request: its new keys enter the
                # short-TTL negative cache, so the hot row stops
                # re-entering admission until capacity can have recovered
                # (a closed batcher is NOT cached — the registry's
                # swap-retry must see BatcherClosed fresh every time)
                self._cache.note_refusal(token, err)
            raise err
        if token is not None:
            # NOW the request is queued: take leadership of its new keys,
            # then let its outcome settle the cache — success populates
            # and resolves followers; shed / expiry / engine error /
            # drop-on-close fails them with the same reason. settle runs
            # as a done-callback — outside _cv by the G013 discipline
            # every set_result/set_exception site already follows.
            self._cache.lead(token)
            p.future.add_done_callback(
                lambda f, t=token: self._cache.settle(t, f))
        if owns:
            p.future.add_done_callback(lambda f, s=span: TRACER.end(s))
        return p.future

    def _shed_lower_locked(self, cls: int, need_rows: int,
                           out: List[_Pending]) -> None:
        """Evict up to ``need_rows`` rows of strictly-lower-priority queued
        work, lowest class first, newest first within a class. Counters
        update here (same lock acquisition as the admission decision);
        the caller fails the evicted futures after releasing ``_cv``."""
        for c in range(len(self._qs) - 1, cls, -1):
            q = self._qs[c]
            while q and need_rows > 0:
                victim = q.pop()
                k = len(victim.instances)
                self._class_rows[c] -= k
                self._depth_rows -= k
                self._shed_c[c].increment()
                out.append(victim)
                need_rows -= k
            if need_rows <= 0:
                break
        if out:
            self._set_depth_gauges_locked()

    def _retry_after_locked(self) -> float:
        """Seconds until the current backlog drains at the observed
        service rate — the Retry-After a shed client should honor."""
        if self._ewma_rows_per_s <= 0.0:
            return 1.0
        return min(30.0, max(1.0, self._depth_rows / self._ewma_rows_per_s))

    def _set_depth_gauges_locked(self) -> None:
        REGISTRY.set_gauge(self._g_depth, float(self._depth_rows))
        for c, key in enumerate(self._g_depth_c):
            REGISTRY.set_gauge(key, float(self._class_rows[c]))

    def overload_state(self) -> dict:
        """One consistent snapshot of the admission surface — what
        /healthz and /models report (docs/serving.md "Overload
        behavior")."""
        with self._cv:
            ctl = self._ctl.state()
            depth = self._depth_rows
            per_class = {p: self._class_rows[c]
                         for c, p in enumerate(PRIORITY_NAMES)}
            rate = self._ewma_rows_per_s
            shed = {p: self._shed_c[c].value
                    for c, p in enumerate(PRIORITY_NAMES)}
            expired = {p: self._expired_c[c].value
                       for c, p in enumerate(PRIORITY_NAMES)}
            quota_rej = {p: self._quota_rejected_c[c].value
                         for c, p in enumerate(PRIORITY_NAMES)}
        return {
            "depth_rows": depth,
            "max_queue_rows": self.max_queue_rows,
            "depth_fraction": round(depth / self.max_queue_rows, 4)
            if self.max_queue_rows else 0.0,
            "class_rows": per_class,
            "quota_fracs": {p: self.priority_quota_fracs[c]
                            for c, p in enumerate(PRIORITY_NAMES)},
            "starvation_limit": self.starvation_limit,
            "controller": ctl,
            "rows_per_sec": round(rate, 1),
            "shed": shed,
            "expired": expired,
            "quota_rejected": quota_rej,
        }

    def close(self, drain: bool = True) -> None:
        """Stop accepting work. ``drain=True`` (the hot-swap path) lets the
        worker finish everything already queued before the thread exits, so
        an in-flight version swap fails zero requests."""
        dropped: List[_Pending] = []
        with self._cv:
            if self._closed:
                return
            self._closed = True
            if not drain:
                for q in self._qs:
                    while q:
                        dropped.append(q.popleft())
                self._class_rows = [0] * len(self._qs)
                self._depth_rows = 0
            self._cv.notify_all()
        # outside the lock: set_exception runs done-callbacks synchronously,
        # and arbitrary callback code must never execute while _cv is held
        # (a callback that needs the lock would stall every producer — the
        # G013 blocking-under-lock hazard)
        for p in dropped:
            p.future.set_exception(
                BatcherClosed(f"batcher {self.name!r} closed"))
        for t in self._threads:
            t.join(timeout=30.0)

    # -- worker side ---------------------------------------------------------

    def _next_live_locked(self, expired: List[_Pending], classes=None):
        """The next request to serve — the first live head scanning
        ``classes`` in order (default: every class, highest priority
        first) — WITHOUT popping it. Expired heads met on the way are
        popped into ``expired`` (they never reach dispatch; the caller
        fails them outside the lock). Returns (cls, pending) or None when
        none of the scanned classes holds live work."""
        order = range(len(self._qs)) if classes is None else classes
        for c in order:
            q = self._qs[c]
            while q:
                p = q[0]
                if p.deadline is not None \
                        and time.perf_counter() >= p.deadline:
                    q.popleft()
                    k = len(p.instances)
                    self._class_rows[c] -= k
                    self._depth_rows -= k
                    self._expired_c[c].increment()
                    expired.append(p)
                    continue
                return c, p
        return None

    def _forced_class_locked(self) -> Optional[int]:
        """The starvation escape: a class skipped ``starvation_limit``
        consecutive batches while it had queued work anchors the next
        batch. The LONGEST-skipped class wins (ties go to the lower
        class), so under a sustained high flood normal and low both make
        bounded progress instead of low monopolizing the escape."""
        best = None
        for c in range(len(self._qs) - 1, 0, -1):
            if self._qs[c] and self._skips[c] >= self.starvation_limit \
                    and (best is None or self._skips[c] > self._skips[best]):
                best = c
        return best

    def _take_batch(self, classes, drive_controller: bool):
        """Assemble one batch from this lane's ``classes``:
        strict-priority pulls up to the controller's current row target,
        waiting out the anchor's co-ride window. Only the general lane
        drives the AIMD controller (``drive_controller``) — the express
        lane always dispatches at the base window. Returns
        (batch, expired): ``expired`` entries passed their deadline in
        the queue and must be failed by the caller OUTSIDE the lock.
        (None, expired) signals shutdown; ([], expired) is an expiry
        flush — deliver their 504s and call again."""
        expired: List[_Pending] = []
        with self._cv:
            while True:
                # wait for live work (expired heads purge as they surface)
                while True:
                    if self._next_live_locked(expired, classes) is not None:
                        break
                    if self._closed:
                        self._set_depth_gauges_locked()
                        return None, expired
                    if expired:
                        # nothing live but expiries in hand: deliver their
                        # 504s NOW — a dead request's answer must not wait
                        # for the next arrival to wake this worker
                        self._set_depth_gauges_locked()
                        return [], expired
                    if drive_controller:
                        self._ctl.on_idle()  # queue idle: decay to base
                        self._export_ctl_gauges_locked()
                    self._cv.wait()
                # single-class batches: the anchor (highest-priority live
                # head, or the starvation-forced class) fixes the batch's
                # class, and only that class co-rides — a high-priority
                # request never waits out a lower class's widened window
                # or rides inside its dispatch quantum
                batch: List[_Pending] = []
                rows = 0
                cap = self._ctl.batch_rows if drive_controller \
                    else self._ctl.base_batch
                close_at = 0.0
                anchor_cls = classes[0]
                forced = self._forced_class_locked() if drive_controller \
                    else None
                order = classes if forced is None else \
                    [forced] + [c for c in classes if c != forced]
                while rows < cap:
                    if not batch:
                        nxt = self._next_live_locked(expired, order)
                        if nxt is None:
                            break  # the lone live head expired: re-wait
                    else:
                        # a strictly-higher-priority arrival in THIS
                        # lane's classes closes the window NOW: its batch
                        # dispatches next instead of waiting out a lower
                        # class's co-ride window
                        higher = [c for c in classes if c < anchor_cls]
                        if higher and self._next_live_locked(
                                expired, higher) is not None:
                            break
                        nxt = self._next_live_locked(expired, (anchor_cls,))
                    if nxt is not None:
                        c, p = nxt
                        if batch and rows + len(p.instances) > cap:
                            break
                        self._qs[c].popleft()
                        k = len(p.instances)
                        self._class_rows[c] -= k
                        self._depth_rows -= k
                        if not batch:
                            anchor_cls = c
                        batch.append(p)
                        rows += k
                        # high-priority batches cap the co-ride window at
                        # the BASE delay — the widened window is paid by
                        # the classes that can afford it; a member's
                        # deadline closes the batch early so it still
                        # dispatches in time
                        w = self._ctl.base_delay_s if c == 0 \
                            else self._ctl.delay_s
                        t_close = p.enqueued + w
                        if p.deadline is not None:
                            t_close = min(t_close, p.deadline)
                        close_at = min(close_at, t_close) if len(batch) > 1 \
                            else t_close
                        continue
                    remaining = close_at - time.perf_counter()
                    if remaining <= 0 or self._closed:
                        break
                    self._cv.wait(timeout=remaining)
                # final sweep: a member whose deadline passed during the
                # co-ride wait never reaches dispatch
                now = time.perf_counter()
                live: List[_Pending] = []
                for p in batch:
                    if p.deadline is not None and now >= p.deadline:
                        self._expired_c[p.cls].increment()
                        expired.append(p)
                    else:
                        live.append(p)
                if drive_controller:
                    served = {p.cls for p in live}
                    for c in range(1, len(self._qs)):
                        if c in served:
                            self._skips[c] = 0
                        elif self._qs[c]:
                            self._skips[c] += 1
                if live or self._closed:
                    if drive_controller:
                        self._ctl.on_take(self._depth_rows)
                        self._export_ctl_gauges_locked()
                    self._set_depth_gauges_locked()
                    return live, expired
                # every member expired mid-wait — assemble again

    def _export_ctl_gauges_locked(self) -> None:
        REGISTRY.set_gauge(self._g_delay, self._ctl.delay_s * 1e3)
        REGISTRY.set_gauge(self._g_batch, float(self._ctl.batch_rows))

    def _fail_expired(self, expired: List[_Pending]) -> None:
        # outside the lock (done-callbacks run synchronously, G013); the
        # trace records the in-queue death as an instant event
        now = time.perf_counter()
        for p in expired:
            if p.span.recording:
                p.span.event("deadline.expired",
                             queued_ms=round((now - p.enqueued) * 1e3, 3),
                             priority=PRIORITY_NAMES[p.cls])
            if not p.future.cancelled():
                p.future.set_exception(DeadlineExpired(
                    f"batcher {self.name!r}: deadline elapsed after "
                    f"{(now - p.enqueued) * 1e3:.1f} ms in queue "
                    f"(never dispatched)"))

    def _loop(self, classes=None, drive_controller: bool = True) -> None:
        if classes is None:
            classes = tuple(range(len(self._qs)))
        while True:
            batch, expired = self._take_batch(classes, drive_controller)
            self._fail_expired(expired)
            if batch is None:
                return  # shutdown
            if not batch:
                continue  # expiry flush only — nothing to dispatch
            now = time.perf_counter()
            now_ns = time.perf_counter_ns()
            rows: List = []
            for p in batch:
                self._delay.observe(now - p.enqueued,
                                    trace_id=TRACER.exemplar_id(p.span))
                # the enqueue->take wait, recorded retroactively into the
                # request's trace (the hop: submit thread -> this thread)
                TRACER.add_span("queue.wait", p.span,
                                int(p.enqueued * 1e9), now_ns,
                                args={"batcher": self.name,
                                      "rows": len(p.instances),
                                      "priority": PRIORITY_NAMES[p.cls]})
                rows.extend(p.instances)
            self._occupancy.observe(len(rows))
            # the merged device call belongs to ONE trace: the first
            # SAMPLED request of the batch (an unsampled first request
            # would take the device-side spans into a trace that gets
            # dropped, leaving every committed trace stage-less); only
            # when nothing is sampled fall back to the first recording
            # span, whose trace can still commit via the slow_ms escape
            rep = next((p.span for p in batch
                        if p.span.recording and p.span.sampled), None) \
                or next((p.span for p in batch if p.span.recording), None)
            for p in batch:
                if p.span.recording and p.span is not rep:
                    p.span.event("batched", in_trace=rep.trace_id,
                                 batch_rows=len(rows))
            t0 = time.perf_counter()
            try:
                with TRACER.span("batch.predict", parent=rep,
                                 args={"rows": len(rows),
                                       "requests": len(batch)}):
                    preds = self.predict_fn(rows)
            except Exception as e:  # fail the batch, not the process
                for p in batch:
                    if not p.future.cancelled():
                        p.future.set_exception(e)
                continue
            dt = time.perf_counter() - t0
            if dt > 0:
                inst_rate = len(rows) / dt
                with self._cv:
                    # single-writer EWMA (this thread), read under the
                    # same lock by _retry_after_locked/overload_state
                    self._ewma_rows_per_s = inst_rate \
                        if self._ewma_rows_per_s <= 0.0 \
                        else 0.7 * self._ewma_rows_per_s + 0.3 * inst_rate
                    REGISTRY.set_gauge(self._g_rate,
                                       self._ewma_rows_per_s)
            off = 0
            for p in batch:
                k = len(p.instances)
                if not p.future.cancelled():
                    p.future.set_result(list(preds[off:off + k]))
                off += k
