"""Dynamic micro-batching: keep the accelerator hot without unbounded queues.

One request at a time under-fills the device (a [8, K] gather-dot costs the
same dispatch as [512, K]); the batcher merges concurrent requests into one
padded batch — the request-batching layer every production scoring stack
carries (PAPERS.md ads-infra paper). Policy:

- a batch closes when it holds ``max_batch`` rows OR the oldest queued
  request has waited ``max_delay_ms`` (latency ceiling under light load,
  full batches under heavy load);
- admission control is explicit: a queue deeper than ``max_queue_rows``
  REJECTS new work (`QueueFull` -> HTTP 503 in serving/server.py) instead
  of growing an unbounded backlog — shed load early, keep served latency
  bounded;
- every request gets a `concurrent.futures.Future`; a worker failure fails
  the affected requests, never the process.

Metrics (runtime.metrics.REGISTRY): queue-depth gauge, batch-occupancy and
queue-delay histograms, accepted/rejected counters.

Tracing (runtime.tracing.TRACER): the request's span is captured at
submit() and carried ON the queue entry across the thread hop — the worker
parents its spans to it explicitly (contextvars do not cross threads). The
enqueue->dispatch wait is recorded retroactively as a ``queue.wait`` child
span; the merged device call runs under a ``batch.predict`` span parented
to the first traced request of the batch, and every other request in the
batch gets a ``batched`` instant event linking to that trace. A submit with
no ambient span (direct batcher users) opens its own ``serving.request``
root, ended by the future's done-callback.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, List, Sequence

from ..runtime.metrics import REGISTRY
from ..runtime.tracing import TRACER

OCCUPANCY_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)
DELAY_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.05, 0.1, 0.25, 1.0)


class QueueFull(RuntimeError):
    """Admission control: queue at capacity — caller should shed (503)."""


class BatcherClosed(RuntimeError):
    """submit() after close()."""


class _Pending:
    # span/owns_span publish immutably in __init__ BEFORE the entry is
    # visible to the worker thread (set post-append would race the take)
    __slots__ = ("instances", "future", "enqueued", "span", "owns_span")

    def __init__(self, instances, span, owns_span: bool) -> None:
        self.instances = instances
        self.future: Future = Future()
        self.enqueued = time.perf_counter()
        self.span = span  # the request's trace span (maybe NULL_SPAN)
        self.owns_span = owns_span  # True: we opened it, done-cb ends it


class DynamicBatcher:
    """Micro-batching front of one ServingEngine (or any ``predict_fn``
    taking a list of instances and returning an indexable of results)."""

    def __init__(self, predict_fn: Callable[[List], Sequence], *,
                 max_batch: int = 256, max_delay_ms: float = 2.0,
                 max_queue_rows: int = 4096, name: str = "default") -> None:
        self.predict_fn = predict_fn
        self.max_batch = int(max_batch)
        self.max_delay = float(max_delay_ms) / 1000.0
        self.max_queue_rows = int(max_queue_rows)
        self.name = name
        self._cv = threading.Condition()
        self._q: deque = deque()
        self._depth_rows = 0
        self._closed = False
        self._accepted = REGISTRY.counter("serving", f"{name}.batcher.accepted")
        self._rejected = REGISTRY.counter("serving", f"{name}.batcher.rejected")
        self._occupancy = REGISTRY.histogram(
            f"serving.{name}.batch_occupancy", OCCUPANCY_BUCKETS)
        self._delay = REGISTRY.histogram(
            f"serving.{name}.queue_delay_seconds", DELAY_BUCKETS)
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"hivemall-batcher-{name}")
        self._thread.start()

    # -- producer side -------------------------------------------------------

    def submit(self, instances: Sequence) -> Future:
        """Enqueue one request (a list of instances); the Future resolves to
        the list of predictions for exactly those instances, in order."""
        if not instances:
            f: Future = Future()
            f.set_result([])
            return f
        # capture the caller's span for the thread hop; with no ambient
        # span open our own request root (ended by the done-callback). A
        # rejected submit abandons an owned span un-ended — it is never
        # committed, which is the point: 503s don't fill the ring.
        cur = TRACER.current()
        if cur is not None:
            span, owns = cur, False
        else:
            span = TRACER.begin("serving.request", parent=None,
                                args={"batcher": self.name,
                                      "rows": len(instances)})
            owns = span.recording
        p = _Pending(list(instances), span, owns)
        with self._cv:
            if self._closed:
                raise BatcherClosed(f"batcher {self.name!r} is closed")
            if self._depth_rows + len(p.instances) > self.max_queue_rows:
                self._rejected.increment()
                raise QueueFull(
                    f"batcher {self.name!r}: queue holds {self._depth_rows} "
                    f"rows (cap {self.max_queue_rows}) — shed load")
            self._q.append(p)
            self._depth_rows += len(p.instances)
            REGISTRY.set_gauge(f"serving.{self.name}.queue_depth_rows",
                               float(self._depth_rows))
            self._cv.notify()
        self._accepted.increment()
        if owns:
            p.future.add_done_callback(lambda f, s=span: TRACER.end(s))
        return p.future

    def close(self, drain: bool = True) -> None:
        """Stop accepting work. ``drain=True`` (the hot-swap path) lets the
        worker finish everything already queued before the thread exits, so
        an in-flight version swap fails zero requests."""
        dropped: List[_Pending] = []
        with self._cv:
            if self._closed:
                return
            self._closed = True
            if not drain:
                while self._q:
                    dropped.append(self._q.popleft())
                self._depth_rows = 0
            self._cv.notify_all()
        # outside the lock: set_exception runs done-callbacks synchronously,
        # and arbitrary callback code must never execute while _cv is held
        # (a callback that needs the lock would stall every producer — the
        # G013 blocking-under-lock hazard)
        for p in dropped:
            p.future.set_exception(
                BatcherClosed(f"batcher {self.name!r} closed"))
        self._thread.join(timeout=30.0)

    # -- worker side ---------------------------------------------------------

    def _take_batch(self):
        """Block for the first request, then gather more until max_batch or
        the first request's max_delay deadline. Returns [] at shutdown."""
        with self._cv:
            while not self._q:
                if self._closed:
                    return []
                self._cv.wait()
            batch = [self._q.popleft()]
            rows = len(batch[0].instances)
            deadline = batch[0].enqueued + self.max_delay
            while rows < self.max_batch:
                if self._q:
                    nxt = self._q[0]
                    if rows + len(nxt.instances) > self.max_batch:
                        break
                    batch.append(self._q.popleft())
                    rows += len(nxt.instances)
                    continue
                remaining = deadline - time.perf_counter()
                if remaining <= 0 or self._closed:
                    break
                self._cv.wait(timeout=remaining)
            self._depth_rows -= rows
            REGISTRY.set_gauge(f"serving.{self.name}.queue_depth_rows",
                               float(self._depth_rows))
        return batch

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if not batch:
                return
            now = time.perf_counter()
            now_ns = time.perf_counter_ns()
            rows: List = []
            for p in batch:
                self._delay.observe(now - p.enqueued,
                                    trace_id=TRACER.exemplar_id(p.span))
                # the enqueue->take wait, recorded retroactively into the
                # request's trace (the hop: submit thread -> this thread)
                TRACER.add_span("queue.wait", p.span,
                                int(p.enqueued * 1e9), now_ns,
                                args={"batcher": self.name,
                                      "rows": len(p.instances)})
                rows.extend(p.instances)
            self._occupancy.observe(len(rows))
            # the merged device call belongs to ONE trace: the first
            # SAMPLED request of the batch (an unsampled first request
            # would take the device-side spans into a trace that gets
            # dropped, leaving every committed trace stage-less); only
            # when nothing is sampled fall back to the first recording
            # span, whose trace can still commit via the slow_ms escape
            rep = next((p.span for p in batch
                        if p.span.recording and p.span.sampled), None) \
                or next((p.span for p in batch if p.span.recording), None)
            for p in batch:
                if p.span.recording and p.span is not rep:
                    p.span.event("batched", in_trace=rep.trace_id,
                                 batch_rows=len(rows))
            try:
                with TRACER.span("batch.predict", parent=rep,
                                 args={"rows": len(rows),
                                       "requests": len(batch)}):
                    preds = self.predict_fn(rows)
            except Exception as e:  # fail the batch, not the process
                for p in batch:
                    if not p.future.cancelled():
                        p.future.set_exception(e)
                continue
            off = 0
            for p in batch:
                k = len(p.instances)
                if not p.future.cancelled():
                    p.future.set_result(list(preds[off:off + k]))
                off += k
