"""Framework-wide constants.

Mirrors hivemall.HivemallConstants (ref: core/.../HivemallConstants.java:21-48).
"""

VERSION = "0.4.2-rc.1+tpu0"

# The bias feature key. The reference appends feature "0" with value 1.0
# (ref: HivemallConstants.java:25, ftvec/AddBiasUDF.java).
BIAS_CLAUSE = "0"
BIAS_CLAUSE_INT = 0

# Default dense model dimensionality: 2^24 hashed feature space
# (ref: LearnerBaseUDTF.java:90, utils/hashing/MurmurHash3.java:27).
DEFAULT_NUM_FEATURES = 1 << 24

# JobConf keys kept for API parity (ref: HivemallConstants.java:26).
CONFKEY_RAND_AMPLIFY_SEED = "hivemall.amplify.seed"
