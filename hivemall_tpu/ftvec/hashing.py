"""`feature_hashing` — hash feature names in "name[:value]" strings into the
2^24 space, keeping values (ref: ftvec/hashing/FeatureHashingUDF.java:45-190).
The bias feature "0" passes through unhashed (ref: :150-158 keeps int names)."""

from __future__ import annotations

from typing import List, Sequence

from ..utils.hashing import DEFAULT_NUM_FEATURES, mhash, murmurhash3_bytes_batch


def _is_int(s: str) -> bool:
    try:
        int(s)
        return True
    except ValueError:
        return False


def feature_hashing(features: Sequence[str],
                    num_features: int = DEFAULT_NUM_FEATURES) -> List[str]:
    out: List[str] = []
    names, slots = [], []
    for k, fv in enumerate(features):
        pos = fv.find(":")
        name = fv if pos < 0 else fv[:pos]
        rest = "" if pos < 0 else fv[pos:]
        if _is_int(name):
            # int features index the space directly (kept as-is like the ref)
            out.append(fv)
        else:
            out.append(None)  # backfilled below
            names.append(name)
            slots.append((k, rest))
    if names:
        hashed = murmurhash3_bytes_batch(names, num_features)
        for (k, rest), h in zip(slots, hashed):
            out[k] = f"{h}{rest}"
    return out
