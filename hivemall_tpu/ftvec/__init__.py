"""Feature-engineering function library (ref layer L4, SURVEY.md §2.9).

Host-side preprocessing utilities mirroring `hivemall.ftvec.*`; the bulk paths
(feature_hashing over many rows) are numpy-vectorized and feed the TPU block
builder (core/batch.py).
"""

from ..utils.feature import (  # noqa: F401  (ref: ftvec/*.java top-level UDFs)
    add_bias,
    extract_feature,
    extract_weight,
    feature,
    feature_index,
    sort_by_feature,
)
from .amplify import amplify, rand_amplify  # noqa: F401
from .hashing import feature_hashing  # noqa: F401
from .pairing import polynomial_features, powered_features  # noqa: F401
from .scaling import l2_normalize, rescale, zscore  # noqa: F401
from .trans import (  # noqa: F401
    Quantifier,
    binarize_label,
    categorical_features,
    ffm_features,
    indexed_features,
    quantified_features,
    quantitative_features,
    vectorize_features,
)
from .conv import conv2dense, to_dense_features, to_sparse_features, quantify  # noqa: F401
from .ranking import bpr_sampling, item_pairs_sampling, populate_not_in  # noqa: F401
from .text import tf  # noqa: F401
