"""Scaling UDFs (ref: ftvec/scaling/{RescaleUDF,ZScoreUDF,L2NormalizationUDF}.java)."""

from __future__ import annotations

from typing import List, Sequence, Union

import numpy as np


def rescale(value: Union[float, str], min_: float, max_: float):
    """min-max normalization; on "name:value" strings rescales the value part
    (ref: RescaleUDF.java:39-75). min == max maps to 0.5."""
    if isinstance(value, str):
        name, _, v = value.partition(":")
        if not v:
            raise ValueError(f"Invalid feature value representation: {value}")
        return f"{name}:{rescale(float(v), min_, max_)}"
    if min_ == max_:
        return 0.5
    v = (float(value) - min_) / (max_ - min_)
    return float(min(1.0, max(0.0, v)))


def zscore(value: Union[float, str], mean: float, stddev: float):
    """(value - mean) / stddev, 0 when stddev == 0 (ref: ZScoreUDF.java:34-48)."""
    if isinstance(value, str):
        name, _, v = value.partition(":")
        return f"{name}:{zscore(float(v), mean, stddev)}"
    if stddev == 0.0:
        return 0.0
    return float((float(value) - mean) / stddev)


def l2_normalize(ftvecs: Sequence[str]) -> List[str]:
    """Scale a "name:value" vector to unit L2 norm (ref: L2NormalizationUDF.java:38-70)."""
    if ftvecs is None:
        return None
    names, weights = [], []
    for fv in ftvecs:
        name, _, v = fv.partition(":")
        names.append(name)
        weights.append(float(v) if v else 1.0)
    w = np.asarray(weights, dtype=np.float64)
    norm = float(np.sqrt(np.sum(w * w)))
    if norm == 0.0:
        norm = 1.0
    return [f"{n}:{float(x / norm)}" for n, x in zip(names, w)]
