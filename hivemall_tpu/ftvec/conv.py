"""Conversion UDFs (ref: ftvec/conv/*.java)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.feature import parse_feature
from .trans import Quantifier


def conv2dense(feature_rows: Sequence[Tuple[int, float]], nDims: int) -> np.ndarray:
    """`conv2dense(feature, weight, nDims)` UDAF — collect (feature, weight)
    rows into one dense float vector (ref: ftvec/conv/ConvertToDenseModelUDAF.java:33)."""
    out = np.zeros(nDims, dtype=np.float32)
    for f, w in feature_rows:
        if f >= nDims:
            raise ValueError(f"feature {f} outside dims {nDims}")
        out[f] = w
    return out


def to_dense_features(ftvec: Sequence[str], dimensions: int) -> np.ndarray:
    """"idx:value" strings -> dense float[dimensions] (1-based indices kept
    as-is like the reference) (ref: ftvec/conv/ToDenseFeaturesUDF.java)."""
    out = np.zeros(dimensions + 1, dtype=np.float32)
    for fv in ftvec:
        name, v = parse_feature(fv)
        idx = int(name)
        if idx > dimensions:
            raise ValueError(f"feature index {idx} > dimensions {dimensions}")
        out[idx] = v
    return out


def to_sparse_features(dense: Sequence[float]) -> List[str]:
    """dense vector -> "idx:value" strings, skipping zeros
    (ref: ftvec/conv/ToSparseFeaturesUDF.java)."""
    return [f"{i}:{float(v)}" for i, v in enumerate(dense) if v is not None and v != 0.0]


def quantify(quantifier: Optional[Quantifier], *values) -> List[float]:
    """`quantify(output_row, col1, col2, ...)` — assign dense int ids to
    non-numeric columns (ref: ftvec/conv/QuantifyColumnsUDTF.java)."""
    q = quantifier if quantifier is not None else Quantifier()
    return [q.quantify(i, v) for i, v in enumerate(values)]
