"""Transformation UDFs (ref: ftvec/trans/*.java)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union


def vectorize_features(feature_names: Sequence[str], *values) -> List[str]:
    """`vectorize_features(array('a','b'), a_val, b_val)` -> ["a:va", "b:vb"];
    zero/null values are skipped; value 1 emits the bare name — categorical
    convention (ref: ftvec/trans/VectorizeFeaturesUDF.java)."""
    if len(feature_names) != len(values):
        raise ValueError("feature names and values must align")
    out: List[str] = []
    for name, v in zip(feature_names, values):
        if v is None:
            continue
        if isinstance(v, str):
            if v == "":
                continue
            try:
                f = float(v)
            except ValueError:
                out.append(f"{name}#{v}")  # categorical string value
                continue
            v = f
        if v == 0:
            continue
        if v == 1:
            out.append(str(name))
        else:
            out.append(f"{name}:{v}")
    return out


def categorical_features(feature_names: Sequence[str], *values) -> List[str]:
    """`categorical_features(array('a','b'), v1, v2)` -> ["a#v1", "b#v2"]
    (ref: ftvec/trans/CategoricalFeaturesUDF.java)."""
    if len(feature_names) != len(values):
        raise ValueError("feature names and values must align")
    return [f"{n}#{v}" for n, v in zip(feature_names, values) if v is not None]


def quantitative_features(feature_names: Sequence[str], *values) -> List[str]:
    """`quantitative_features(array('a','b'), v1, v2)` -> ["a:v1", "b:v2"]
    (ref: ftvec/trans/QuantitativeFeaturesUDF.java); null/zero skipped."""
    if len(feature_names) != len(values):
        raise ValueError("feature names and values must align")
    out = []
    for n, v in zip(feature_names, values):
        if v is None:
            continue
        v = float(v)
        if v != 0.0:
            out.append(f"{n}:{v}")
    return out


def ffm_features(feature_names: Sequence[str], *values,
                 num_features: Optional[int] = None,
                 num_fields: int = 1024) -> List[str]:
    """`ffm_features(array('a','b'), v1, v2)` -> ["<field>:<index>:1", ...]
    hashing field names and feature#value pairs
    (ref: ftvec/trans/FFMFeaturesUDF.java)."""
    from ..utils.hashing import DEFAULT_NUM_FEATURES, mhash

    nf = num_features or DEFAULT_NUM_FEATURES
    out = []
    for field_idx, (name, v) in enumerate(zip(feature_names, values)):
        if v is None:
            continue
        feat = f"{name}#{v}"
        idx = mhash(feat, nf)
        out.append(f"{field_idx}:{idx}:1")
    return out


def indexed_features(*values) -> List[str]:
    """`indexed_features(v1, v2, ...)` -> ["1:v1", "2:v2", ...] (1-based)
    (ref: ftvec/trans/IndexedFeatures.java)."""
    return [f"{i + 1}:{float(v)}" for i, v in enumerate(values) if v is not None]


class Quantifier:
    """`quantified_features` stateful identifier assignment: maps each distinct
    non-numeric column value to a dense int id in first-seen order
    (ref: ftvec/trans/QuantifiedFeaturesUDTF.java, ftvec/conv/QuantifyColumnsUDTF.java)."""

    def __init__(self) -> None:
        self.maps: Dict[int, Dict[object, int]] = {}

    def quantify(self, col: int, value) -> float:
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            return float(value)
        m = self.maps.setdefault(col, {})
        if value not in m:
            m[value] = len(m)
        return float(m[value])


def quantified_features(quantifier: Quantifier, *values) -> List[float]:
    return [quantifier.quantify(i, v) for i, v in enumerate(values)]


def binarize_label(pos: int, neg: int, *features) -> List[Tuple]:
    """`binarize_label(pos_cnt, neg_cnt, features...)` — emit `pos` rows with
    label 1 and `neg` rows with label 0 (ref: ftvec/trans/BinarizeLabelUDTF.java)."""
    if pos < 0 or neg < 0:
        raise ValueError("pos/neg must be non-negative")
    out = []
    for _ in range(pos):
        out.append(tuple(features) + (1,))
    for _ in range(neg):
        out.append(tuple(features) + (0,))
    return out


def onehot_encode(quantifier: Quantifier, *values) -> List[str]:
    """Categorical one-hot via the quantifier: value v of column i becomes
    feature "i#v"."""
    return [f"{i}#{v}" for i, v in enumerate(values) if v is not None]
