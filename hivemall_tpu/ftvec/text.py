"""Text feature UDAFs (ref: ftvec/text/TermFrequencyUDAF.java:34)."""

from __future__ import annotations

from collections import Counter
from typing import Dict, Iterable


def tf(words: Iterable[str]) -> Dict[str, float]:
    """`tf(word)` aggregate — relative term frequency over the group."""
    counts = Counter(words)
    total = sum(counts.values())
    if total == 0:
        return {}
    return {w: c / total for w, c in counts.items()}
