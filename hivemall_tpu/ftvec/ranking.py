"""Ranking samplers (ref: ftvec/ranking/*.java)."""

from __future__ import annotations

from typing import Dict, Iterator, List, Sequence, Set, Tuple

import numpy as np


def bpr_sampling(user_items: Dict[int, Sequence[int]], max_item_id: int,
                 sampling_rate: float = 1.0, with_replacement: bool = True,
                 seed: int = 31) -> Iterator[Tuple[int, int, int]]:
    """Emit (user, pos_item, neg_item) BPR triples: for each user's positive
    item, sample a negative item uniformly from items the user has NOT
    interacted with (ref: ftvec/ranking/BprSamplingUDTF.java:51-205).
    `sampling_rate` scales how many triples per positive; without replacement
    each negative is used at most once per user."""
    rng = np.random.RandomState(seed)
    for u, items in user_items.items():
        pos = list(items)
        pos_set = set(pos)
        if len(pos_set) >= max_item_id + 1:
            continue  # no negatives exist
        n_samples = max(1, int(round(len(pos) * sampling_rate)))
        used: Set[int] = set()
        for _ in range(n_samples):
            i = pos[rng.randint(len(pos))]
            j = int(rng.randint(max_item_id + 1))
            tries = 0
            while j in pos_set or (not with_replacement and j in used):
                j = int(rng.randint(max_item_id + 1))
                tries += 1
                if tries > 100 * (max_item_id + 1):
                    break
            else:
                if not with_replacement:
                    used.add(j)
                yield u, i, j


def item_pairs_sampling(pos_items: Sequence[int], max_item_id: int,
                        sampling_rate: float = 1.0,
                        seed: int = 31) -> Iterator[Tuple[int, int]]:
    """Emit (pos_item, neg_item) pairs (ref: ftvec/ranking/ItemPairsSamplingUDTF.java)."""
    rng = np.random.RandomState(seed)
    pos_set = set(int(i) for i in pos_items)
    if len(pos_set) >= max_item_id + 1:
        return
    n = max(1, int(round(len(pos_items) * sampling_rate)))
    for _ in range(n):
        i = int(pos_items[rng.randint(len(pos_items))])
        j = int(rng.randint(max_item_id + 1))
        while j in pos_set:
            j = int(rng.randint(max_item_id + 1))
        yield i, j


def populate_not_in(items: Sequence[int], max_item_id: int) -> Iterator[int]:
    """Emit every item id in [0, max_item_id] not in `items`
    (ref: ftvec/ranking/PopulateNotInUDTF.java)."""
    have = set(int(i) for i in items)
    for j in range(max_item_id + 1):
        if j not in have:
            yield j
