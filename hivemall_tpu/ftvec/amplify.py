"""Row amplification — the reference's substitute for multi-epoch training
(ref: ftvec/amplify/{AmplifierUDTF,RandomAmplifierUDTF}.java,
common/RandomizedAmplifier.java:27-120)."""

from __future__ import annotations

from typing import Iterable, Iterator, List, Sequence, TypeVar

import numpy as np

T = TypeVar("T")


def amplify(xtimes: int, rows: Iterable[T]) -> Iterator[T]:
    """`amplify(xtimes, *)` — emit each row xtimes (ref: AmplifierUDTF.java:35-70)."""
    if xtimes < 1:
        raise ValueError(f"Illegal xtimes value: {xtimes}")
    for row in rows:
        for _ in range(xtimes):
            yield row


def rand_amplify(xtimes: int, num_buffers: int, rows: Iterable[T],
                 seed: int = 31) -> Iterator[T]:
    """`rand_amplify(xtimes, num_buffers, *)` — duplicate each row xtimes and
    shuffle through N reservoir buffers, emitting one random victim per insert
    once buffers fill (ref: RandomizedAmplifier.java:27-120; seed from jobconf
    `hivemall.amplify.seed`, RandomAmplifierUDTF.java:43-66)."""
    if xtimes < 1:
        raise ValueError(f"Illegal xtimes value: {xtimes}")
    rng = np.random.RandomState(seed)
    buffers: List[List[T]] = [[] for _ in range(max(1, num_buffers))]
    capacity = 1024
    for row in rows:
        for _ in range(xtimes):
            b = buffers[rng.randint(len(buffers))]
            if len(b) >= capacity:
                victim = rng.randint(len(b))
                yield b[victim]
                b[victim] = row
            else:
                b.append(row)
    for b in buffers:
        order = rng.permutation(len(b))
        for i in order:
            yield b[i]
