"""Pairing UDFs (ref: ftvec/pairing/{PolynomialFeaturesUDF,PoweredFeaturesUDF}.java)."""

from __future__ import annotations

from typing import List, Sequence

from ..utils.feature import parse_feature


def polynomial_features(ftvec: Sequence[str], degree: int,
                        interaction_only: bool = False,
                        truncate: bool = True) -> List[str]:
    """Degree-d polynomial feature expansion over "name:value" strings
    (ref: PolynomialFeaturesUDF.java:44-130). With truncate, features valued
    0/1 are not self-powered; interaction_only skips self-products."""
    if ftvec is None:
        return None
    if degree < 2:
        raise ValueError(f"degree must be >= 2: {degree}")
    parsed = [parse_feature(fv) for fv in ftvec]
    dst: List[str] = []

    def add_poly(feat: str, value: float, cur_degree: int, start: int):
        if cur_degree > degree:
            return
        for j in range(start, len(parsed)):
            name_j, v_j = parsed[j]
            if interaction_only and feat.endswith(str(name_j)):
                pass  # self-product guard handled via start index below
            new_feat = f"{feat}^{name_j}"
            new_val = value * v_j
            dst.append(f"{new_feat}:{new_val}")
            next_start = j + 1 if interaction_only else j
            add_poly(new_feat, new_val, cur_degree + 1, next_start)

    for i, fv in enumerate(ftvec):
        dst.append(fv)  # x^1
        name, v = parsed[i]
        if truncate and (v == 0.0 or v == 1.0):
            # powers of 0/1 are redundant; still pair with *other* features
            start = i + 1
        else:
            start = i + 1 if interaction_only else i
        feat = str(name)
        for j in range(start, len(parsed)):
            name_j, v_j = parsed[j]
            if truncate and i == j and (v == 0.0 or v == 1.0):
                continue
            new_feat = f"{feat}^{name_j}"
            new_val = v * v_j
            dst.append(f"{new_feat}:{new_val}")
            add_poly(new_feat, new_val, 3, j + 1 if interaction_only else j)
    return dst


def powered_features(ftvec: Sequence[str], degree: int,
                     truncate: bool = True) -> List[str]:
    """x, x^2, ..., x^degree per feature (ref: PoweredFeaturesUDF.java)."""
    if ftvec is None:
        return None
    if degree < 2:
        raise ValueError(f"degree must be >= 2: {degree}")
    dst: List[str] = []
    for fv in ftvec:
        name, v = parse_feature(fv)
        dst.append(fv)
        if truncate and (v == 0.0 or v == 1.0):
            continue
        p = v
        for d in range(2, degree + 1):
            p *= v
            dst.append(f"{name}^{d}:{p}")
    return dst
