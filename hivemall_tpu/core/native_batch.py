"""The native batched-apply execution backend (`-batch B -native_apply`).

The segment-sum batch backend (core/batch_update.py) removed the sort and
compacted the scatter, but its last scatter still runs through XLA:CPU's
element-at-a-time scatter engine (~15 M elt/s measured on the bench
host) — a per-element cost the hardware doesn't require. This backend
hands the SAME `StagedDedupPlan` (verbatim — the frozen ctypes ABI in
ops/scatter.py::plan_abi_arrays) to one vectorized C++ pass per block
(native/hivemall_native.cpp::hm_batch_apply_block): gather the U unique
rows from host-resident f32 tables, evaluate the rule's batch closed form
with margin/violation masks computed natively, segment-reduce the B*K
lanes, and scatter-add back — plain contiguous loops the compiler
vectorizes, with the table walk sequential (plan reps ascend). This is
the terascale-system play (PAPERS.md, Agarwal et al.): eliminate
per-element host overhead on the sparse-update hot loop.

Semantics are the batch backend's exactly (the engine's minibatch
accumulate-then-apply, count-averaged): float tables equal up to
reduction order (tolerance-pinned by tests/test_native_batch.py),
touched EXACT. Supported rule families are the native closed forms —
perceptron / CW / AROW / AROWh (native.BATCH_APPLY_RULES); everything
else, a missing .so, or bf16 table storage falls back LOUDLY to the XLA
batch path (models/base.py warns with the reason — never silently).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .. import native
from .batch_update import BlockPlans
from .engine import Rule

# rule capabilities the native pass implements; anything beyond
# (optimizer slots, derive_w recomputation, scalar globals, DELTA_SLOT
# tracking) has no native form and must fall back to the XLA batch path
_NATIVE_RULE_NAMES = frozenset(native.BATCH_APPLY_RULES)


def native_batch_unsupported_reason(rule: Rule,
                                    table_dtype_is_f32: bool = True,
                                    track_deltas: bool = False
                                    ) -> Optional[str]:
    """Why `-native_apply` cannot serve this configuration, or None when
    it can. The reason string is what models/base.py puts in its fallback
    warning — a mismatch is always REPORTED, never swallowed."""
    if not native.available():
        err = native.load_error()
        return ("native library unavailable"
                + (f" ({err})" if err else " (not built)")
                + " — bash scripts/build_native.sh")
    if not native.has_batch_apply():
        return ("libhivemall_native.so predates hm_batch_apply_block — "
                "rebuild with scripts/build_native.sh")
    if rule.name not in _NATIVE_RULE_NAMES:
        return (f"rule {rule.name!r} has no native batch closed form "
                f"(supported: {sorted(_NATIVE_RULE_NAMES)})")
    if rule.slot_names or rule.derive_w is not None or rule.global_names \
            or rule.pre_batch is not None or rule.pre_row is not None:
        return (f"rule {rule.name!r} carries optimizer slots/globals the "
                "native pass does not implement")
    if track_deltas:
        return "DELTA_SLOT tracking has no native form"
    if not table_dtype_is_f32:
        return ("bf16 table storage (dims > 2^24 without "
                "-disable_halffloat) has no native form; tables must be "
                "f32")
    return None


def init_native_tables(dims: int, use_covariance: bool,
                       initial_weights: Optional[np.ndarray] = None,
                       initial_covars: Optional[np.ndarray] = None) -> dict:
    """Host-resident f32 tables the native pass mutates in place — the
    LinearState analog (weights 0, covars 1, touched 0; warm starts seed
    touched from nonzero weights like init_linear_state)."""
    t = {
        "w": (np.ascontiguousarray(initial_weights, np.float32).copy()
              if initial_weights is not None
              else np.zeros(dims, np.float32)),
        "cov": None,
        "touched": np.zeros(dims, np.int8),
    }
    if initial_weights is not None:
        t["touched"][np.asarray(initial_weights) != 0] = 1
    if use_covariance:
        t["cov"] = (np.ascontiguousarray(initial_covars, np.float32).copy()
                    if initial_covars is not None
                    else np.ones(dims, np.float32))
    return t


def make_native_batch_step(rule: Rule, hyper: dict,
                           mini_batch_average: bool = True):
    """`step(tables, values, labels, plans) -> loss_sum` applying one
    staged block through the native pass. `plans` is the block's
    stage_block_plans output, HOST-side (the plan ABI forbids device
    arrays); `tables` is init_native_tables' dict, mutated in place.
    Raises RuntimeError when the backend is unavailable — callers decide
    support FIRST via native_batch_unsupported_reason (the loud-fallback
    contract)."""
    reason = native_batch_unsupported_reason(rule)
    if reason is not None:
        raise RuntimeError(f"-native_apply unavailable: {reason}")

    def step(tables: dict, values, labels, plans: BlockPlans) -> float:
        loss = native.batch_apply_block(
            rule.name, hyper, values, labels, plans.main, plans.tail,
            tables["w"].shape[0], tables["w"], tables["cov"],
            tables["touched"], mini_batch_average=mini_batch_average)
        if loss is None:  # the .so vanished between probe and call
            raise RuntimeError("native batch apply became unavailable "
                               f"mid-run: {native.load_error()}")
        return loss

    return step


def native_tables_to_state(tables: dict, rule: Rule, n_examples: int):
    """Collapse the host tables into a LinearState (the fit_linear return
    convention — model emission reads touched, serving freezes weights)."""
    import jax.numpy as jnp

    from .state import init_linear_state

    state = init_linear_state(
        tables["w"].shape[0], use_covariance=rule.use_covariance,
        initial_weights=tables["w"], initial_covars=tables["cov"])
    return state.replace(
        touched=jnp.asarray(tables["touched"]),
        step=jnp.asarray(np.int32(n_examples)))
