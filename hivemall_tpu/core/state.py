"""Model state: the TPU-resident "parameter store".

Mirrors the reference model layer (ref: core/.../model/DenseModel.java:36-52):
a dense weight table plus optional covariance and optimizer slot arrays, all
fixed-shape HBM-resident device arrays in a pytree — DenseModel's
struct-of-arrays layout maps 1:1. The `touched` bitmap reproduces the close()
behavior of emitting only weights actually updated
(ref: BinaryOnlineClassifierUDTF.java:249-298).

Sparse/string models (SparseModel, SpaceEfficientDenseModel) are subsumed by
feature hashing into this dense space (the reference's own default is hashed
2^24 dims) plus optional bf16 storage in place of the half-float codec.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np
from flax import struct


@struct.dataclass
class LinearState:
    """State for all hashed-feature linear learners (binary + regression)."""

    weights: jnp.ndarray  # [D] float32
    covars: Optional[jnp.ndarray]  # [D] float32, init 1.0 (covariance learners)
    slots: Dict[str, jnp.ndarray]  # per-feature optimizer aux, init 0.0
    touched: jnp.ndarray  # [D] int8 — 1 where an update landed
    step: jnp.ndarray  # [] int32 — 1-based processed-example counter
    globals: Dict[str, jnp.ndarray]  # scalar running stats (e.g. target stddev,
    # ref: common/OnlineVariance.java used by PA1a/PA2a/AROWe2 regressors)

    @property
    def dims(self) -> int:
        return self.weights.shape[0]


def init_linear_state(
    dims: int,
    use_covariance: bool = False,
    slot_names: tuple = (),
    global_names: tuple = (),
    dtype=jnp.float32,
    initial_weights: Optional[np.ndarray] = None,
    initial_covars: Optional[np.ndarray] = None,
) -> LinearState:
    """Create a zeroed model (covariance initialized to 1.0, the implicit
    default for absent entries in the reference, ref: AROWClassifierUDTF.java:140).

    `initial_weights`/`initial_covars` support warm start, mirroring
    `-loadmodel` (ref: LearnerBaseUDTF.java:215-333).
    """
    weights = (
        jnp.asarray(initial_weights, dtype=dtype)
        if initial_weights is not None
        else jnp.zeros((dims,), dtype=dtype)
    )
    covars = None
    if use_covariance:
        covars = (
            jnp.asarray(initial_covars, dtype=dtype)
            if initial_covars is not None
            else jnp.ones((dims,), dtype=dtype)
        )
    slots = {name: jnp.zeros((dims,), dtype=jnp.float32) for name in slot_names}
    touched = jnp.zeros((dims,), dtype=jnp.int8)
    if initial_weights is not None:
        touched = (jnp.asarray(initial_weights) != 0).astype(jnp.int8)
    return LinearState(
        weights=weights,
        covars=covars,
        slots=slots,
        touched=touched,
        step=jnp.zeros((), dtype=jnp.int32),
        globals={name: jnp.zeros((), dtype=jnp.float32) for name in global_names},
    )


def model_rows(state: LinearState, filter_zero: bool = False):
    """Dump the model as (feature, weight[, covar]) arrays over touched
    entries — the close() model emission (ref: BinaryOnlineClassifierUDTF.java:254-291).
    """
    touched = np.asarray(state.touched) != 0
    if filter_zero:
        touched &= np.asarray(state.weights) != 0.0
    feats = np.nonzero(touched)[0].astype(np.int64)
    weights = np.asarray(state.weights)[feats]
    if state.covars is not None:
        covars = np.asarray(state.covars)[feats]
        return feats, weights, covars
    return feats, weights
