from .batch import FeatureBlock, pack_rows, pad_to_bucket  # noqa: F401
