"""The segment-sum batched update backend — the CPU hot path.

The engine's `minibatch` mode computes every row of a block against the
batch-start tables (the reference's FloatAccumulator semantics,
RegressionBaseUDTF.java:236-295) but APPLIES the block through three
full-[D] temporaries (counts, dw sums, dcov sums) plus duplicate-index
scatters — on XLA:CPU, where scatter executes element-at-a-time (~15 M
elt/s measured on this host, vs 400-800 M elt/s for gathers), that
application is the whole step: BENCH r03-r05 sat at ~1.0 M rows/sec while
the transliterated C row loop did 2.4 M on the same machine.

This module promotes the ops/scatter.py sort->segment-reduce->unique-
scatter pattern from a TPU workaround to the primary CPU execution
backend, with the sort moved OUT of the step entirely:

- staging builds ONE StagedDedupPlan per minibatch of B rows on the host
  (numpy radix argsort, 4x faster than XLA:CPU's comparator sort, and
  replayed free every epoch — the kernels/linear_scan.py chunking
  discipline: host-side shaping once, fixed-shape device replay after);
- the jitted step scans the staged block in B-row chunks; each chunk
  gathers every table ONCE at the plan's unique slots (ascending ids — a
  sequential table walk), fans values out to lanes with a take, runs the
  rule batch-aware (`core.engine.make_batch_update`), reduces all delta
  columns with ONE chunk-local cumsum, and writes each table back with a
  single compact unique+sorted scatter — U unique lanes instead of B*K
  update lanes, no full-[D] temporaries anywhere;
- B is the AdaBatch dial (PAPERS.md): batch size trades throughput
  against update staleness, and bench.py sweeps it with a pinned
  holdout-logloss parity tolerance so the chosen default is measured,
  not assumed.

Semantics are the engine's minibatch mode exactly (same sums, f32
accumulation, per-feature count averaging) up to float reduction order;
B=1 reproduces minibatch B=1. Integer tables (touched, DELTA_SLOT
counts) are EXACT: the 0/1 count column's chunk-local cumsum only ever
forms integers below 2^24, all representable in f32.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.scatter import (StagedDedupPlan, broadcast_lanes,
                           build_staged_plan, pad_plan, staged_gather,
                           staged_scatter_add, staged_scatter_set,
                           staged_segment_totals, staged_touch_max)
from .engine import DELTA_SLOT, Rule, make_batch_update
from .state import LinearState


class BlockPlans(NamedTuple):
    """Staged plans for one block: `main` stacks the block's full B-row
    chunks ([nb, ...] leading axis, shared U bucket so one lax.scan body
    serves them all); `tail` covers the remainder rows (its own shapes —
    no sentinel rows, so the example counter and scalar globals stay
    exact)."""

    main: Optional[StagedDedupPlan]
    tail: Optional[StagedDedupPlan]

    @property
    def slot_bucket(self) -> int:
        return int(self.main.rep.shape[-1]) if self.main is not None else 0


def _chunk_plans(indices, batch_size: int, dims: int):
    """Host-side: one UNSTACKED dedup plan per B-row minibatch of a
    staged block [N, K], plus the remainder chunk's plan. The expensive
    part (numpy argsort + segment pass per chunk) happens exactly once
    here — stacking to a common U bucket is pad_plan, not a re-sort."""
    n = int(indices.shape[0])
    b = min(batch_size, n)
    nb = n // b
    chunks: List[StagedDedupPlan] = [
        build_staged_plan(np.asarray(indices[c * b:(c + 1) * b]).reshape(-1),
                          dims)
        for c in range(nb)]
    tail = None
    if n - nb * b:
        tail = build_staged_plan(
            np.asarray(indices[nb * b:]).reshape(-1), dims)
    return chunks, tail


def _stack_chunks(chunks: List[StagedDedupPlan], slots: int,
                  dims: int) -> StagedDedupPlan:
    widened = [pad_plan(p, slots, dims) for p in chunks]
    return StagedDedupPlan(*[np.stack([getattr(p, f) for p in widened])
                             for f in StagedDedupPlan._fields])


def stage_block_plans(indices, batch_size: int, dims: int,
                      slots: Optional[int] = None) -> BlockPlans:
    """Host-side: build one dedup plan per B-row minibatch of a staged
    block [N, K]. `slots` pins the main chunks' U bucket (epoch stacking
    passes a common bucket so every block compiles to one shape)."""
    chunks, tail = _chunk_plans(indices, batch_size, dims)
    main = None
    if chunks:
        u = max(p.rep.shape[0] for p in chunks)
        if slots is not None:
            u = max(u, slots)
        main = _stack_chunks(chunks, u, dims)
    return BlockPlans(main=main, tail=tail)


def stage_epoch_plans(indices, batch_size: int, dims: int) -> BlockPlans:
    """Plans for an epoch's stacked blocks [n_blocks, N, K] (the bench /
    make_epoch deployment shape): every block's chunks share one U bucket
    so the whole epoch replays through a single compiled scan. Blocks
    below the epoch-wide bucket are WIDENED with pad_plan — their sorts
    are never redone."""
    n_blocks = int(indices.shape[0])
    per_block = [_chunk_plans(indices[i], batch_size, dims)
                 for i in range(n_blocks)]
    if any(t is not None for _, t in per_block):
        raise ValueError("epoch staging requires block rows divisible by "
                         "the batch size (blocks are operator-shaped; pad "
                         "or trim the trailing rows at the caller)")
    u = max(p.rep.shape[0] for chunks, _ in per_block for p in chunks)
    stacked = [_stack_chunks(chunks, u, dims) for chunks, _ in per_block]
    main = StagedDedupPlan(*[np.stack([getattr(sb, f) for sb in stacked])
                             for f in StagedDedupPlan._fields])
    return BlockPlans(main=main, tail=None)


def make_batch_train_fn(
    rule: Rule,
    hyper: dict,
    batch_size: int,
    mini_batch_average: bool = True,
    track_deltas: bool = False,
):
    """Raw (unjitted) `step(state, indices, values, labels, plans) ->
    (state, loss_sum)` — the batched execution backend's step. `plans`
    must be `stage_block_plans(indices, batch_size, dims)` for the same
    indices (the plan IS the block's sort, staged host-side)."""
    use_cov = rule.use_covariance
    apply_update = make_batch_update(rule, hyper)

    def chunk_update(tables, idx, val, y, plan, t0, gl):
        weights, covars, slots, touched = tables
        bsz = idx.shape[0]
        ts = (t0 + 1 + jnp.arange(bsz)).astype(jnp.float32)
        if rule.pre_batch is not None:
            gl = rule.pre_batch(gl, y)

        # one gather per table at the unique slots (ascending feature ids:
        # a sequential walk of the table), fanned out to lanes by a take.
        # Pad lanes belong to dropped slots whose gather reads the fill,
        # so no mask tensors appear anywhere (the core/batch.py protocol).
        # bf16 tables widen per-[U]-window only, G021 accumulation in f32.
        uw = staged_gather(weights, plan).astype(jnp.float32)
        w_l = broadcast_lanes(uw, plan).reshape(idx.shape)
        cov_l = None
        ucov = None
        if use_cov:
            ucov = staged_gather(covars, plan, fill=1.0).astype(jnp.float32)
            cov_l = broadcast_lanes(ucov, plan).reshape(idx.shape)
        sl_u = {k: staged_gather(slots[k], plan).astype(jnp.float32)
                for k in rule.slot_names}
        sl_l = {k: broadcast_lanes(v, plan).reshape(idx.shape)
                for k, v in sl_u.items()}

        out = apply_update(w_l, cov_l, sl_l, val, y, ts, gl)
        upd = out.updated.astype(jnp.float32)  # [B]
        lane_upd = upd[:, None] * jnp.ones_like(val)  # [B, K]

        # ALL delta columns reduce under the one plan: dw [+ dcov]
        # [+ dslots] + the update counts, one permute + one cumsum total
        cols = [out.dw]
        if use_cov and out.dcov is not None:
            cols.append(out.dcov)
        scat_slots = [k for k in rule.slot_names if k in out.dslots]
        cols += [out.dslots[k] for k in scat_slots]
        cols.append(lane_upd)
        nd = len(cols)
        stack = jnp.stack([c.astype(jnp.float32).reshape(-1) for c in cols],
                          axis=-1)
        sums = staged_segment_totals(plan, stack)  # [U, nd]
        counts = sums[:, nd - 1]
        denom = counts if mini_batch_average else None

        weights = staged_scatter_add(weights, plan, sums[:, 0], denom)
        pos = 1
        if use_cov and out.dcov is not None:
            covars = staged_scatter_add(covars, plan, sums[:, pos], denom)
            pos += 1
        new_slots = dict(slots)
        slot_sums = {}
        for k in scat_slots:
            slot_sums[k] = sums[:, pos]
            new_slots[k] = staged_scatter_add(slots[k], plan, slot_sums[k])
            pos += 1
        if rule.derive_w is not None:
            # dual-averaging weights are a pure per-feature function of the
            # post-update slots — computed per UNIQUE slot, so the dense
            # gather-after-scatter round trip disappears entirely
            tf_end = (t0 + bsz).astype(jnp.float32)
            sl_new = {k: sl_u[k] + slot_sums[k] if k in slot_sums
                      else sl_u[k] for k in rule.slot_names}
            w_new = rule.derive_w(sl_new, tf_end, hyper)  # [U]
            weights = staged_scatter_set(weights, plan, w_new, counts > 0)
        touched = staged_touch_max(touched, plan, counts)
        if track_deltas:
            delta_tab = new_slots.get(DELTA_SLOT, slots[DELTA_SLOT])
            new_slots[DELTA_SLOT] = staged_scatter_add(delta_tab, plan,
                                                       counts)
        return (weights, covars, new_slots, touched), gl, jnp.sum(out.loss)

    def step(state: LinearState, indices, values, labels,
             plans: BlockPlans):
        n = indices.shape[0]
        tables = (state.weights, state.covars, state.slots, state.touched)
        gl = state.globals
        t = state.step
        loss_total = jnp.zeros(())
        if plans.main is not None:
            nb = plans.main.order.shape[0]
            b = (n // nb) if plans.tail is None else batch_size
            n_main = nb * b
            xs = (indices[:n_main].reshape(nb, b, -1),
                  values[:n_main].reshape(nb, b, -1),
                  labels[:n_main].reshape(nb, b), plans.main)

            def body(carry, x):
                tables, gl, t = carry
                idx, val, y, plan = x
                tables, gl, loss = chunk_update(tables, idx, val, y, plan,
                                                t, gl)
                return (tables, gl, t + b), loss

            (tables, gl, t), losses = jax.lax.scan(body, (tables, gl, t),
                                                   xs)
            loss_total = jnp.sum(losses)
        if plans.tail is not None:
            n_tail = n - (plans.main.order.shape[0] * batch_size
                          if plans.main is not None else 0)
            tables, gl, loss_t = chunk_update(
                tables, indices[n - n_tail:], values[n - n_tail:],
                labels[n - n_tail:], plans.tail, t, gl)
            loss_total = loss_total + loss_t
        weights, covars, slots, touched = tables
        new_state = state.replace(weights=weights, covars=covars,
                                  slots=slots, touched=touched,
                                  step=state.step + n, globals=gl)
        return new_state, loss_total

    return step


def make_batch_train_step(
    rule: Rule,
    hyper: dict,
    batch_size: int,
    mini_batch_average: bool = True,
    track_deltas: bool = False,
    donate: bool = True,
):
    """Jitted wrapper over make_batch_train_fn (the single-replica path)."""
    fn = make_batch_train_fn(rule, hyper, batch_size,
                             mini_batch_average=mini_batch_average,
                             track_deltas=track_deltas)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())
