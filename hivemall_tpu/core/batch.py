"""Feature blocks: the on-device batch format for all hashed-feature learners.

The reference processes one Hive row at a time (`process(Object[])`,
BinaryOnlineClassifierUDTF.java:111). TPU-first, rows are staged into HBM as
fixed-shape padded blocks:

    indices [B, K] int32  — hashed feature ids, padded with `dims` (out of range)
    values  [B, K] f32    — feature values, padded with 0
    labels  [B]    f32    — ±1 for classifiers, y for regressors

Padding with an OUT-OF-RANGE index (== dims) instead of a mask array lets every
gather use mode='fill' (reads 0 / neutral) and every scatter use mode='drop'
(padding lanes vanish), so kernels never multiply by a mask and XLA sees static
shapes. K is bucketed to powers of two to bound recompilation
(SURVEY.md §7 hard part (b)).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence

import numpy as np


class FeatureBlock(NamedTuple):
    indices: np.ndarray  # [B, K] int32 (device or host)
    values: np.ndarray  # [B, K] float32
    labels: np.ndarray  # [B] float32
    nnz: np.ndarray  # [B] int32 — true row lengths (for norms the pad lanes
    # already contribute 0, so this is informational/debug)

    @property
    def batch_size(self) -> int:
        return self.indices.shape[0]

    @property
    def width(self) -> int:
        return self.indices.shape[1]


def pad_to_bucket(k: int, min_width: int = 8) -> int:
    """Round row width up to a power of two >= min_width (bounds the number of
    distinct compiled shapes)."""
    w = min_width
    while w < k:
        w <<= 1
    return w


def bucket_rows(x, min_rows: int = 8):
    """Pad an array's leading (row) axis up to the bucket ladder
    (``pad_to_bucket``): the shape canonicalizer for feeding a
    variable-length batch to a jitted callable without forking one compile
    per novel length (graftcheck G034 rewrites unrouted dispatch sites to
    ``scorer(bucket_rows(batch))[:batch.shape[0]]``). Pad rows are zeros —
    callers slice the result back to the true row count."""
    n = x.shape[0]
    b = pad_to_bucket(max(n, 1), min_width=min_rows)
    if b == n:
        return x
    pad_shape = (b - n,) + tuple(x.shape[1:])
    return np.concatenate([np.asarray(x), np.zeros(pad_shape, x.dtype)])


def pack_rows(
    idx_rows: Sequence[np.ndarray],
    val_rows: Sequence[np.ndarray],
    labels: Sequence[float],
    dims: int,
    width: Optional[int] = None,
    batch_size: Optional[int] = None,
) -> FeatureBlock:
    """Pack variable-length hashed rows into one padded FeatureBlock.

    Rows longer than `width` are truncated (callers should pick width >= max
    nnz; `pad_to_bucket(max_nnz)` is the default). If `batch_size` is given,
    the block is padded with empty rows up to it (their labels are 0 and all
    lanes are dropped, so they are true no-ops in every learner).
    """
    n = len(idx_rows)
    max_nnz = max((len(r) for r in idx_rows), default=1)
    if width is None:
        width = pad_to_bucket(max_nnz)
    b = batch_size if batch_size is not None else n
    if b == n and n > 0:
        from .. import native

        packed = native.pack_block(idx_rows, val_rows, width, dims)
        if packed is not None:
            out_idx, out_val, out_nnz = packed
            return FeatureBlock(out_idx, out_val,
                                np.asarray(labels, dtype=np.float32), out_nnz)
    indices = np.full((b, width), dims, dtype=np.int32)
    values = np.zeros((b, width), dtype=np.float32)
    labs = np.zeros((b,), dtype=np.float32)
    nnz = np.zeros((b,), dtype=np.int32)
    for i in range(n):
        k = min(len(idx_rows[i]), width)
        indices[i, :k] = idx_rows[i][:k]
        values[i, :k] = val_rows[i][:k]
        labs[i] = labels[i]
        nnz[i] = k
    return FeatureBlock(indices, values, labs, nnz)


def iter_blocks(
    idx_rows: Sequence[np.ndarray],
    val_rows: Sequence[np.ndarray],
    labels: Sequence[float],
    dims: int,
    batch_size: int,
    width: Optional[int] = None,
):
    """Yield fixed-shape FeatureBlocks over a dataset.

    The final partial block is emitted at its true size (one extra compiled
    shape) rather than padded with fake rows — fake rows would corrupt global
    scalars (w0, running target stats) and the example counter `t`.
    """
    n = len(idx_rows)
    if width is None:
        max_nnz = max((len(r) for r in idx_rows), default=1)
        width = pad_to_bucket(max_nnz)
    for start in range(0, n, batch_size):
        end = min(start + batch_size, n)
        yield pack_rows(
            idx_rows[start:end],
            val_rows[start:end],
            labels[start:end],
            dims,
            width=width,
            batch_size=end - start,
        )


def pad_rows_to_multiple(indices, values, labels, multiple: int, dims: int):
    """Pad a staged block's rows up to a multiple of `multiple` with
    sentinel rows (every lane the out-of-range pad index ``dims``, value 0,
    label 0) — the fixed-chunk scan shape shared by the chunked device
    backends (kernels/linear_scan.py's SMEM chunking; the batch backend
    stages a tail plan instead, core/batch_update.py). Sentinel rows are
    dead weight only: backends that carry global scalars or the example
    counter must mask by the TRUE row count (linear_scan's live_rows
    meta) — a sentinel row is not a no-op for running scalar stats."""
    import jax.numpy as jnp

    b, k = indices.shape
    b_pad = (b + multiple - 1) // multiple * multiple
    if b_pad == b:
        return indices, values, labels
    pad = b_pad - b
    return (
        jnp.concatenate([indices, jnp.full((pad, k), dims, indices.dtype)]),
        jnp.concatenate([values, jnp.zeros((pad, k), values.dtype)]),
        jnp.concatenate([labels, jnp.zeros((pad,), labels.dtype)]),
    )


def shuffle_rows(
    idx_rows: List[np.ndarray],
    val_rows: List[np.ndarray],
    labels: np.ndarray,
    seed: int,
):
    """Host-side shuffle between epochs (the reference's rand_amplify /
    epoch-replay analog, ref: ftvec/amplify/RandomAmplifierUDTF.java:43-66)."""
    rng = np.random.RandomState(seed)
    perm = rng.permutation(len(idx_rows))
    return (
        [idx_rows[i] for i in perm],
        [val_rows[i] for i in perm],
        np.asarray(labels)[perm],
    )
