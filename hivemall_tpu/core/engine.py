"""The batched update engine shared by every hashed-feature linear learner.

The reference's hot loop is `process(row) -> train -> model.set(feature, ...)`
(ref: BinaryOnlineClassifierUDTF.java:111-247). On TPU that becomes, per
FeatureBlock [B, K]:

- **scan mode** — `lax.scan` over the B rows; each row gathers its K touched
  slots, computes the rule's closed-form update, scatter-adds the deltas.
  Bit-faithful to the reference's sequential semantics (used for parity tests
  and small models).
- **minibatch mode** — one vectorized gather [B, K], the rule vmapped over
  rows against the *stale* batch-start weights, deltas scatter-added (averaged
  per feature when `mini_batch_average`). This is exactly the reference's own
  documented mini-batch semantic (ref: RegressionBaseUDTF.java:236-295 +
  utils/lang/FloatAccumulator.java:38-41: accumulate per-feature deltas over
  the batch, apply sum/count once), and is the TPU hot path: one big gather +
  vectorized math + one big scatter. The reference only routes weight-only
  regressors through its mini-batch path (covariance learners override
  train() around it); here every rule supports it — a documented superset,
  with batch size 1 exactly equal to scan mode.

Padding protocol (see core/batch.py): pad index == dims is out-of-range, so
gathers use mode='fill' and scatters mode='drop' — no mask tensors anywhere.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from .state import LinearState


@struct.dataclass
class RowContext:
    """Everything a rule sees for one row (gathered, padded lanes are 0)."""

    w: jnp.ndarray  # [K] current weights
    cov: Optional[jnp.ndarray]  # [K] current covariance (None if unused)
    slots: Dict[str, jnp.ndarray]  # [K] optimizer aux
    val: jnp.ndarray  # [K] feature values (0 on padding)
    y: jnp.ndarray  # [] label (+-1 or target)
    score: jnp.ndarray  # [] sum(w * val)
    sq_norm: jnp.ndarray  # [] sum(val^2)
    variance: jnp.ndarray  # [] sum(cov * val^2) (0 if no covariance)
    t: jnp.ndarray  # [] float 1-based example counter
    globals: Dict[str, jnp.ndarray] = struct.field(default_factory=dict)  # scalar running stats


@struct.dataclass
class RuleOutput:
    dw: jnp.ndarray  # [K] additive weight delta
    loss: jnp.ndarray  # [] per-row loss contribution
    updated: jnp.ndarray  # [] bool/float — did the rule fire (for touched/deltas)
    dcov: Optional[jnp.ndarray] = None  # [K] additive covariance delta
    dslots: Dict[str, jnp.ndarray] = struct.field(default_factory=dict)


@dataclass(frozen=True)
class Rule:
    """A learner's closed-form per-row update.

    `update(ctx, hyper) -> RuleOutput`. If `derive_w` is set, weights are a
    pure function of the slots (dual-averaging learners like AdaGradRDA):
    after slot deltas are applied the engine recomputes w at touched lanes
    (ref: AdaGradRDAUDTF.java:112-142 where w is rebuilt from u, G, t).
    """

    name: str
    update: Callable[[RowContext, dict], RuleOutput]
    use_covariance: bool = False
    slot_names: Tuple[str, ...] = ()
    derive_w: Optional[Callable[[Dict[str, jnp.ndarray], jnp.ndarray, dict], jnp.ndarray]] = None
    # Scalar running stats threaded through training (e.g. Welford target
    # variance, ref: regression/PassiveAggressiveRegressionUDTF.java preTrain).
    # `pre_row(globals, y) -> globals` runs before each row in scan mode;
    # `pre_batch(globals, labels) -> globals` merges a whole block in
    # minibatch mode (rules then see the post-merge values).
    global_names: Tuple[str, ...] = ()
    pre_row: Optional[Callable] = None
    pre_batch: Optional[Callable] = None
    # loss used for convergence accounting only
    is_regression: bool = False
    # How each optimizer slot merges across data-parallel replicas when a
    # mixed model is collapsed to one (MixTrainer.final_state): "sum" for
    # additive per-example statistics (AdaGrad G accumulators — replicas saw
    # disjoint shards, so the union stream's sum is the sum of per-shard
    # sums), "mean" for decayed/EMA statistics (AdaDelta). Unlisted slots
    # default to "mean" over the replicas that touched the feature.
    slot_merge: Tuple[Tuple[str, str], ...] = ()
    # Batch-aware variant of `update`: same closed form applied to a whole
    # minibatch context at once (ctx fields carry a leading [B] axis —
    # w/cov/val [B, K], y/score/sq_norm/variance/t [B]) with the row-axis
    # broadcasts written out explicitly. Optional: rules without one run
    # the per-row update under vmap (identical math; the explicit form
    # exists because the batched backend is the CPU hot path and the
    # traced program stays smaller without the vmap batching pass).
    batch_update: Optional[Callable[["RowContext", dict], "RuleOutput"]] = None


def _gather(table: jnp.ndarray, idx: jnp.ndarray, fill: float = 0.0) -> jnp.ndarray:
    return table.at[idx].get(mode="fill", fill_value=fill)


def _row_ctx(state_tables, idx, val, y, t, use_cov, globals_=None, packed=None):
    weights, covars, slots = state_tables
    if packed is not None:
        # w+cov interleaved as a [D,2] table: ONE pair-row gather costs the
        # same as ONE scalar gather on v5e (diag micro2 gather_pair 13.0ms
        # vs scalar gather 12.9ms per 512k ids), so this halves the gather
        # side of every covariance learner. The pair fill is 0.0; cov's
        # fill is 1.0 (fresh variance), restored on the pad lanes.
        pairs = packed.at[idx].get(mode="fill", fill_value=0.0)
        w = pairs[..., 0]
        oob = (idx < 0) | (idx >= weights.shape[0])
        cov = jnp.where(oob, 1.0, pairs[..., 1])
    else:
        w = _gather(weights, idx)
        cov = _gather(covars, idx, fill=1.0) if use_cov else None
    sl = {k: _gather(v, idx) for k, v in slots.items()}
    score = jnp.sum(w * val)
    sq_norm = jnp.sum(val * val)
    variance = jnp.sum(cov * val * val) if use_cov else jnp.zeros(())
    return RowContext(w, cov, sl, val, y, score, sq_norm, variance, t, globals_ or {})


DELTA_SLOT = "__delta_upd"  # per-feature update count since the last mix —
# the TPU analog of DenseModel's deltaUpdates byte array (ref: DenseModel.java:52)


def make_batch_update(rule: Rule, hyper: dict):
    """Batch-aware application of a Rule: one call over a whole minibatch.

    Returns `apply(w, cov, sl, val, y, ts, gl) -> RuleOutput` where w/cov/
    val are [B, K], sl maps slot name -> [B, K], y/ts are [B] and gl is the
    rule's scalar globals dict. Uses `rule.batch_update` when the rule
    ships an explicit batch form, else vmaps the per-row update — the two
    are the same closed form, pinned equal by tests/test_batch_update.py.
    """
    use_cov = rule.use_covariance

    if rule.batch_update is not None:
        def apply(w, cov, sl, val, y, ts, gl):
            score = jnp.sum(w * val, axis=-1)
            sq_norm = jnp.sum(val * val, axis=-1)
            variance = jnp.sum(cov * val * val, axis=-1) if use_cov \
                else jnp.zeros_like(score)
            ctx = RowContext(w, cov, sl, val, y, score, sq_norm, variance,
                             ts, gl)
            return rule.batch_update(ctx, hyper)

        return apply

    def apply(w, cov, sl, val, y, ts, gl):
        def per_row(w_r, cov_r, sl_r, val_r, y_r, t_r):
            score = jnp.sum(w_r * val_r)
            sq_norm = jnp.sum(val_r * val_r)
            variance = jnp.sum(cov_r * val_r * val_r) if use_cov \
                else jnp.zeros(())
            ctx = RowContext(w_r, cov_r, sl_r, val_r, y_r, score, sq_norm,
                             variance, t_r, gl)
            return rule.update(ctx, hyper)

        return jax.vmap(per_row)(w, cov, sl, val, y, ts)

    return apply


def make_train_fn(
    rule: Rule,
    hyper: dict,
    mode: str = "minibatch",
    mini_batch_average: bool = True,
    track_deltas: bool = False,
    feature_shard: Optional[Tuple[str, int]] = None,
    update_backend: str = "xla",
):
    """Build the raw (unjitted) `step(state, indices, values, labels) ->
    (state, loss_sum)` — composable inside shard_map/scan by parallel/mix.py.

    `mode='scan'` replays rows sequentially (reference-exact); `mode='minibatch'`
    applies the whole block against batch-start weights (reference's
    -mini_batch semantics). With `track_deltas`, state.slots[DELTA_SLOT]
    accumulates per-feature update counts (for delta-weighted model averaging,
    ref: PartialAverage.java:43-67).

    `feature_shard=(axis_name, stripe)` runs the same step on a [D/stripe]
    model stripe inside shard_map — the training analog of the reference's
    feature-sharded parameter store (`hash(feature) mod numNodes` routing,
    ref: mix/client/MixRequestRouter.java:56-60): lanes this device doesn't
    own are masked out, per-row score/norm/variance partials psum over the
    axis (so every device sees the global row scalars), and scatters land in
    the local stripe only. Exact, not approximate: every rule's lane update
    is a function of (global row scalars, lane-local state), which is what
    the owning device computes.
    """
    if mode not in ("scan", "minibatch"):
        raise ValueError(f"unknown mode {mode!r}")
    if update_backend not in ("xla", "mxu"):
        raise ValueError(f"unknown update_backend {update_backend!r}")
    if update_backend == "mxu":
        if mode != "minibatch":
            raise ValueError("update_backend='mxu' requires minibatch mode "
                             "(scan mode is sequential per row)")
        if feature_shard is not None:
            raise ValueError("update_backend='mxu' does not compose with "
                             "feature_shard yet; use the xla backend")
    use_cov = rule.use_covariance

    if feature_shard is None:
        def build_ctx(tables, idx, val, y, tf, gl, packed=None):
            return _row_ctx(tables, idx, val, y, tf, use_cov, gl, packed), idx
    else:
        shard_axis, stripe = feature_shard
        from .striping import translate_to_stripe

        def build_ctx(tables, idx, val, y, tf, gl, packed=None):
            local_idx, vmask = translate_to_stripe(idx, val, shard_axis, stripe)
            # same gathers/row scalars as the local path, on the stripe's
            # lanes only — then the scalar partials psum to global values
            ctx = _row_ctx(tables, local_idx, vmask, y, tf, use_cov, gl, packed)
            ctx = ctx.replace(
                score=jax.lax.psum(ctx.score, shard_axis),
                sq_norm=jax.lax.psum(ctx.sq_norm, shard_axis),
                variance=jax.lax.psum(ctx.variance, shard_axis)
                if use_cov else ctx.variance,
            )
            return ctx, local_idx

    def scan_step(state: LinearState, indices, values, labels):
        def body(carry, row):
            weights, covars, slots, touched, t, gl = carry
            idx, val, y = row
            tf = (t + 1).astype(jnp.float32)
            if rule.pre_row is not None:
                gl = rule.pre_row(gl, y)
            ctx, sidx = build_ctx((weights, covars, slots), idx, val, y, tf, gl)
            out = rule.update(ctx, hyper)
            # rule math runs in f32; bf16 tables (the SpaceEfficientDenseModel
            # analog) take the delta cast to their storage dtype
            weights = weights.at[sidx].add(
                out.dw.astype(weights.dtype), mode="drop")
            if use_cov and out.dcov is not None:
                covars = covars.at[sidx].add(
                    out.dcov.astype(covars.dtype), mode="drop")
            new_slots = dict(slots)
            for k, d in out.dslots.items():
                new_slots[k] = slots[k].at[sidx].add(
                    d.astype(slots[k].dtype), mode="drop")
            if rule.derive_w is not None:
                # lane-wise slot values after this row's delta
                sl_new = {k: ctx.slots[k] + out.dslots.get(k, 0.0) for k in slots}
                w_new = rule.derive_w(sl_new, tf, hyper)
                w_new = jnp.where(out.updated, w_new, ctx.w)
                weights = weights.at[sidx].set(
                    w_new.astype(weights.dtype), mode="drop")
            upd = out.updated.astype(jnp.int8)
            touched = touched.at[sidx].max(jnp.broadcast_to(upd, sidx.shape), mode="drop")
            if track_deltas:
                new_slots[DELTA_SLOT] = slots[DELTA_SLOT].at[sidx].add(
                    jnp.broadcast_to(
                        out.updated.astype(slots[DELTA_SLOT].dtype),
                        sidx.shape),
                    mode="drop")
            return (weights, covars, new_slots, touched, t + 1, gl), out.loss

        carry0 = (state.weights, state.covars, state.slots, state.touched, state.step,
                  state.globals)
        (weights, covars, slots, touched, step, gl), losses = jax.lax.scan(
            body, carry0, (indices, values, labels)
        )
        new_state = state.replace(
            weights=weights, covars=covars, slots=slots, touched=touched, step=step,
            globals=gl,
        )
        return new_state, jnp.sum(losses)

    def minibatch_step(state: LinearState, indices, values, labels):
        b = indices.shape[0]
        t0 = state.step
        ts = (t0 + 1 + jnp.arange(b)).astype(jnp.float32)
        gl = state.globals
        if rule.pre_batch is not None:
            gl = rule.pre_batch(gl, labels)

        # pack w+cov once per block so every row's two scalar gathers become
        # one pair-row gather (see _row_ctx; the [D,2] stack is one ~0.1ms
        # full-table pass vs ~13ms saved per 512k-update block on v5e)
        packed = (jnp.stack([state.weights, state.covars], axis=-1)
                  if use_cov else None)

        def per_row(idx, val, y, tf):
            ctx, sidx = build_ctx((state.weights, state.covars, state.slots),
                                  idx, val, y, tf, gl, packed)
            return rule.update(ctx, hyper), sidx

        outs, sidx = jax.vmap(per_row)(indices, values, labels, ts)
        upd = outs.updated.astype(jnp.float32)  # [B]
        lane_upd = upd[:, None] * jnp.ones_like(values)  # [B, K]

        weights, covars, slots = state.weights, state.covars, state.slots
        if mini_batch_average:
            # Per-feature averaged application, exactly the reference's
            # FloatAccumulator semantics (RegressionBaseUDTF.java:236-295).
            # Accumulate in f32 even over bf16 tables, cast once at the
            # table write (the SpaceEfficientDenseModel analog stores
            # compact, never accumulates compact).
            acc = jnp.promote_types(weights.dtype, jnp.float32)
            counts = jnp.zeros(weights.shape, acc).at[sidx].add(
                lane_upd, mode="drop")
            denom = jnp.maximum(counts, 1.0)
            dw_sum = jnp.zeros(weights.shape, acc).at[sidx].add(
                outs.dw.astype(acc), mode="drop")
            weights = (weights.astype(acc) + dw_sum / denom) \
                .astype(weights.dtype)
            if use_cov and outs.dcov is not None:
                dc_sum = jnp.zeros(covars.shape, acc).at[sidx].add(
                    outs.dcov.astype(acc), mode="drop")
                covars = (covars.astype(acc) + dc_sum / denom) \
                    .astype(covars.dtype)
        else:
            weights = weights.at[sidx].add(
                outs.dw.astype(weights.dtype), mode="drop")
            if use_cov and outs.dcov is not None:
                covars = covars.at[sidx].add(
                    outs.dcov.astype(covars.dtype), mode="drop")
        new_slots = dict(slots)
        for k in rule.slot_names:
            if k in outs.dslots:
                new_slots[k] = slots[k].at[sidx].add(
                    outs.dslots[k].astype(slots[k].dtype), mode="drop")
        if rule.derive_w is not None:
            # Dual-averaging weights are a pure function of the *updated*
            # accumulators — gather-after-scatter makes duplicate features
            # across the batch deterministic.
            tf_end = (t0 + b).astype(jnp.float32)
            sl_g = {k: _gather(new_slots[k], sidx) for k in new_slots}
            w_new = rule.derive_w(sl_g, tf_end, hyper)  # [B, K]
            keep = _gather(weights, sidx)
            w_new = jnp.where(lane_upd > 0, w_new, keep)
            weights = weights.at[sidx].set(
                w_new.astype(weights.dtype), mode="drop")
        if mini_batch_average:
            # `counts` is exactly this block's per-feature lane_upd scatter,
            # so touched and the MIX delta clock derive from it with cheap
            # full-table elementwise ops instead of two more scalar
            # scatters (~7ms each per 512k-update block on v5e).
            touched = jnp.maximum(state.touched, (counts > 0).astype(jnp.int8))
            if track_deltas:
                delta_tab = new_slots.get(DELTA_SLOT, state.slots[DELTA_SLOT])
                new_slots[DELTA_SLOT] = delta_tab + counts.astype(
                    delta_tab.dtype)
        else:
            touched = state.touched.at[sidx].max(
                lane_upd.astype(jnp.int8), mode="drop"
            )
            if track_deltas:
                delta_tab = new_slots.get(DELTA_SLOT, state.slots[DELTA_SLOT])
                new_slots[DELTA_SLOT] = delta_tab.at[sidx].add(
                    lane_upd.astype(delta_tab.dtype), mode="drop")
        new_state = state.replace(
            weights=weights,
            covars=covars,
            slots=new_slots,
            touched=touched,
            step=t0 + b,
            globals=gl,
        )
        return new_state, jnp.sum(outs.loss)

    def minibatch_step_mxu(state: LinearState, indices, values, labels):
        """minibatch_step with every random table access routed through
        ops/mxu_scatter (sorted-window one-hot matmuls) instead of XLA's
        scalar gather/scatter engine — same FloatAccumulator semantics, f32
        sums equal up to addition order. One packed gather serves w, cov and
        every optimizer slot; one stacked scatter-add serves every delta
        column plus the update counts; derive_w rules recompute w as a
        full-table elementwise map masked by the counts (no
        gather-after-scatter round trip at all)."""
        from ..ops import mxu_scatter as mxu

        b, k = indices.shape
        t0 = state.step
        ts = (t0 + 1 + jnp.arange(b)).astype(jnp.float32)
        gl = state.globals
        if rule.pre_batch is not None:
            gl = rule.pre_batch(gl, labels)

        d = state.weights.shape[0]
        slot_names = tuple(sorted(state.slots))
        plan = mxu.make_plan(indices.reshape(-1), d)

        # ONE gather for everything: w [+ cov] [+ slots], padded to a
        # power-of-two column count
        cols = [state.weights] + ([state.covars] if use_cov else []) + \
               [state.slots[s] for s in slot_names]
        ncol = len(cols)
        cpad = mxu.pad_cols(ncol)
        packed = jnp.stack(
            cols + [cols[0]] * (cpad - ncol), axis=-1).astype(jnp.float32)
        g = mxu.gather(packed, plan).reshape(b, k, cpad)
        w_g = g[..., 0]
        pos = 1
        cov_g = None
        if use_cov:
            oob = (indices < 0) | (indices >= d)
            cov_g = jnp.where(oob, 1.0, g[..., pos])
            pos += 1
        sl_g = {s: g[..., pos + i] for i, s in enumerate(slot_names)}

        def per_row(w, cov, sl, val, y, tf):
            score = jnp.sum(w * val)
            sq_norm = jnp.sum(val * val)
            variance = jnp.sum(cov * val * val) if use_cov else jnp.zeros(())
            ctx = RowContext(w, cov, sl, val, y, score, sq_norm, variance,
                             tf, gl)
            return rule.update(ctx, hyper)

        outs = jax.vmap(per_row)(w_g, cov_g, sl_g, values, labels, ts)
        upd = outs.updated.astype(jnp.float32)  # [B]
        lane_upd = upd[:, None] * jnp.ones_like(values)  # [B, K]

        # ONE stacked scatter-add into zeros: dw [+ dcov] [+ dslots] + counts
        dcols = [outs.dw]
        if use_cov and outs.dcov is not None:
            dcols.append(outs.dcov)
        scat_slots = [s for s in rule.slot_names if s in outs.dslots]
        dcols += [outs.dslots[s] for s in scat_slots]
        dcols.append(lane_upd)
        nd = len(dcols)
        dpad = mxu.pad_cols(nd)
        dstack = jnp.stack(dcols, axis=-1).reshape(b * k, nd)
        sums = mxu.scatter_add(
            jnp.zeros((d, dpad), jnp.float32), indices.reshape(-1), dstack,
            plan)
        counts = sums[:, nd - 1]

        acc = jnp.promote_types(state.weights.dtype, jnp.float32)
        dw_sum = sums[:, 0].astype(acc)
        denom = jnp.maximum(counts, 1.0).astype(acc) if mini_batch_average \
            else jnp.ones((), acc)
        weights = (state.weights.astype(acc) + dw_sum / denom) \
            .astype(state.weights.dtype)
        covars = state.covars
        pos = 1
        if use_cov and outs.dcov is not None:
            dc_sum = sums[:, pos].astype(acc)
            covars = (state.covars.astype(acc) + dc_sum / denom) \
                .astype(state.covars.dtype)
            pos += 1
        new_slots = dict(state.slots)
        for s in scat_slots:
            new_slots[s] = (state.slots[s].astype(acc) +
                            sums[:, pos].astype(acc)).astype(
                                state.slots[s].dtype)
            pos += 1

        if rule.derive_w is not None:
            # w is a pure elementwise function of the slots, so recompute it
            # over the WHOLE table and keep old values where nothing fired —
            # one fused full-table pass (~0.1ms/100MB on v5e) replaces the
            # xla path's gather-after-scatter + set
            tf_end = (t0 + b).astype(jnp.float32)
            sl_full = {s: new_slots[s].astype(jnp.float32)
                       for s in new_slots}
            w_full = rule.derive_w(sl_full, tf_end, hyper)
            weights = jnp.where(counts > 0,
                                w_full.astype(state.weights.dtype), weights)

        touched = jnp.maximum(state.touched, (counts > 0).astype(jnp.int8))
        if track_deltas:
            delta_tab = new_slots.get(DELTA_SLOT, state.slots[DELTA_SLOT])
            new_slots[DELTA_SLOT] = delta_tab + counts.astype(delta_tab.dtype)

        new_state = state.replace(
            weights=weights, covars=covars, slots=new_slots, touched=touched,
            step=t0 + b, globals=gl)
        return new_state, jnp.sum(outs.loss)

    if mode == "scan":
        return scan_step
    return minibatch_step_mxu if update_backend == "mxu" else minibatch_step


def make_train_step(
    rule: Rule,
    hyper: dict,
    mode: str = "minibatch",
    mini_batch_average: bool = True,
    donate: bool = True,
    update_backend: str = "xla",
):
    """Jitted wrapper over make_train_fn (the single-replica path)."""
    fn = make_train_fn(rule, hyper, mode=mode,
                       mini_batch_average=mini_batch_average,
                       update_backend=update_backend)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def make_epoch(step_fn, donate: bool = True):
    """Whole-epoch driver: ONE jitted `lax.scan` of `step_fn` over a stack of
    HBM-staged blocks — the framework's deployment shape (io/records.py
    prefetches blocks; the epoch replays them device-resident, the TPU analog
    of the reference's buffered epoch replay,
    FactorizationMachineUDTF.java:521-559). Dispatch cost is paid once per
    epoch instead of once per block, which on a relay-attached chip is the
    difference between ~15M and ~880M rows/s (PERF.md methodology table).

    `step_fn(state, *block) -> (state, loss)` is a raw traceable step —
    `make_train_fn(...)`, `make_fm_step(..., jit=False)`,
    `make_ffm_step(..., jit=False)`, or a lambda closing over static extras.
    Returns jitted `epoch(state, *stacked) -> (state, losses)` where each
    element of `stacked` has a leading [n_blocks] axis and `losses` is the
    per-block loss stack.
    """

    def epoch(state, *stacked):
        def body(s, blk):
            s, loss = step_fn(s, *blk)
            return s, loss

        return jax.lax.scan(body, state, stacked)

    return jax.jit(epoch, donate_argnums=(0,) if donate else ())


_PREDICT_CACHE: Dict[bool, Callable] = {}


def make_predict(use_covariance: bool = False):
    if use_covariance in _PREDICT_CACHE:
        return _PREDICT_CACHE[use_covariance]
    _PREDICT_CACHE[use_covariance] = _build_predict(use_covariance)
    return _PREDICT_CACHE[use_covariance]


def _build_predict(use_covariance: bool = False):
    """Jitted batched predict: score [B] (and variance [B] for covariance
    learners) — the reference's calcScoreAndNorm/calcScoreAndVariance
    (ref: BinaryOnlineClassifierUDTF.java:169-229)."""

    @jax.jit
    def predict(state: LinearState, indices, values):
        w = _gather(state.weights, indices)
        score = jnp.sum(w * values, axis=-1)
        if use_covariance and state.covars is not None:
            cov = _gather(state.covars, indices, fill=1.0)
            variance = jnp.sum(cov * values * values, axis=-1)
            return score, variance
        return score

    return predict
