"""THE one copy of the feature-stripe index translation.

Every feature-dim sharded path (linear engine, FM, multiclass, serving)
maps global hashed ids onto a device's [stripe] table slice the same way:

    local = global - device * stripe
    owned = 0 <= local < stripe
    foreign / pad lanes -> index `stripe` (one-past-end), which `.at[...]`
    with mode="drop"/"fill" drops/zeroes, and their values mask to 0 so
    they contribute nothing to partials.

Changing this convention (drop slot, masking, negative handling) in one
place changes it for training AND serving of every sharded model — the
paths cannot drift (core/engine.py build_ctx, models/fm.py
sharded_gather_predict, models/multiclass.py _row_quantities_sharded,
parallel/sharded.py stripe_score all call it).

Reference analog: `hash(feature) mod numNodes` server routing
(mix/client/MixRequestRouter.java:56-60) — here the stripe is contiguous
ranges instead of modulo so each device's slice is one dense block.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def translate_to_stripe(idx, val, shard_axis: str, stripe: int):
    """(local_idx, masked_val): global ids -> this device's stripe-local
    indices (foreign/pad -> the drop slot `stripe`), values masked to 0 on
    lanes this device does not own. Works on any shape of idx/val."""
    dev = jax.lax.axis_index(shard_axis)
    local_idx = idx - dev * stripe
    owned = (local_idx >= 0) & (local_idx < stripe)
    local_idx = jnp.where(owned, local_idx, stripe)
    return local_idx, val * owned.astype(val.dtype)
