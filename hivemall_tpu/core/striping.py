"""THE one copy of the feature-stripe index translation.

Every feature-dim sharded path (linear engine, FM, multiclass, serving)
maps global hashed ids onto a device's [stripe] table slice the same way:

    local = global - device * stripe
    owned = 0 <= local < stripe
    foreign / pad lanes -> index `stripe` (one-past-end), which `.at[...]`
    with mode="drop"/"fill" drops/zeroes, and their values mask to 0 so
    they contribute nothing to partials.

Changing this convention (drop slot, masking, negative handling) in one
place changes it for training AND serving of every sharded model — the
paths cannot drift (core/engine.py build_ctx, models/fm.py
sharded_gather_predict, models/multiclass.py _row_quantities_sharded,
parallel/sharded.py stripe_score all call it).

Reference analog: `hash(feature) mod numNodes` server routing
(mix/client/MixRequestRouter.java:56-60) — here the stripe is contiguous
ranges instead of modulo so each device's slice is one dense block.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding


def stripe_grid(dims: int, n_shards: int, align: int = 1):
    """``(stripe, dims_padded)`` for striping a [dims] feature axis across
    ``n_shards`` devices: the sharded trainers' ceil-pad grid
    (parallel/sharded_train.py derives ``stripe = ceil(dims/n)``,
    ``dims_padded = stripe * n``) as a function, so the SERVING load path
    stripes by the identical arithmetic and a table trained sharded and a
    table loaded sharded can never land on different grids. ``align``
    rounds the stripe up to a multiple (int8 scale blocks must not
    straddle a stripe boundary — serving/sharded.py passes the
    quant block_rows)."""
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    stripe = -(-dims // n_shards)
    if align > 1:
        stripe = -(-stripe // align) * align
    return stripe, stripe * n_shards


def translate_to_stripe(idx, val, shard_axis: str, stripe: int):
    """(local_idx, masked_val): global ids -> this device's stripe-local
    indices (foreign/pad -> the drop slot `stripe`), values masked to 0 on
    lanes this device does not own. Works on any shape of idx/val."""
    dev = jax.lax.axis_index(shard_axis)
    local_idx = idx - dev * stripe
    owned = (local_idx >= 0) & (local_idx < stripe)
    local_idx = jnp.where(owned, local_idx, stripe)
    return local_idx, val * owned.astype(val.dtype)


def restripe_array(arr, axis: int, dims: int, dims_padded: int, fill=0.0):
    """Move ONE striped table axis between stripe grids: unpad at the old
    grid (slice back to the logical ``dims``), re-pad at the new grid
    (``dims_padded = stripe' * M``) with ``fill``. The unpad is safe by the
    engine's padding protocol (parallel/sharded_train.py module doc): no
    data id ever reaches a slot past ``dims``, so slicing them off loses
    nothing; the re-pad fill must match the family's init value for the
    slot (weights 0, covariances 1 — a zero-padded covariance puts inf/NaN
    in the argminKLD mix's 1/cov reads)."""
    a = np.asarray(arr)
    if a.shape[axis] < dims:
        raise ValueError(
            f"striped axis {axis} has {a.shape[axis]} < dims {dims}")
    if a.shape[axis] > dims:
        sl = [slice(None)] * a.ndim
        sl[axis] = slice(0, dims)
        a = a[tuple(sl)]
    if dims_padded > dims:
        widths = [(0, 0)] * a.ndim
        widths[axis] = (0, dims_padded - dims)
        a = np.pad(a, widths, constant_values=fill)
    return a


def restripe(host, specs, mesh, axis_name: str, dims: int, dims_padded: int,
             fills: dict | None = None):
    """Re-stripe a COLLAPSED host pytree onto the CURRENT mesh — the
    elastic-resume N→M placement: every leaf whose PartitionSpec stripes
    ``axis_name`` runs restripe_array over that axis (unpad the old grid,
    re-pad to ``dims_padded``, the new mesh's ``stripe' * M``), then every
    leaf — striped or replicated — device_puts with its
    ``NamedSharding(mesh, spec)``. The striped axis is read from each
    leaf's spec, never guessed from sizes (same discipline as the
    trainers' _unpad_state).

    ``fills`` maps a leaf's field name (the last attribute/dict key on its
    tree path, e.g. ``"covars"``) to its re-pad fill; unnamed leaves pad
    with 0."""
    fills = fills or {}

    def leaf_fill(path) -> float:
        for key in reversed(path):
            name = getattr(key, "name", None)
            if name is None:
                name = getattr(key, "key", None)
            if isinstance(name, str):
                return fills.get(name, 0.0)
        return 0.0

    def place(path, leaf, spec):
        a = np.asarray(jax.device_get(leaf))
        for ax, name in enumerate(tuple(spec)):
            if name == axis_name:
                a = restripe_array(a, ax, dims, dims_padded,
                                   fill=leaf_fill(path))
                break
        return jax.device_put(a, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, host, specs)
