"""The axon-TPU-relay scrub used by every CPU-only entry point.

With the relay down, dialing it during jax backend init hangs the process
(round-1 rc=124). Entry points that are CPU-by-definition (the multichip
dryrun, the test suite, bench's CPU fallback) apply this env before jax's
backend initializes. Kept jax-import-free so bench.py's parent process can
import it without risking the very hang it guards against; scripts/test.sh
encodes the same recipe in shell.
"""

SCRUB_ENV = {"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"}
