"""Dtype & weak-type flow: the abstract interpreter behind G017-G021 (v4).

Hivemall shipped a half-float codec because weight-table bytes are the
serving bandwidth bottleneck; the quantized bf16/int8 artifact path this
repo is heading toward (ROADMAP "raw speed") dies silently the moment a
stray ``astype(jnp.float32)`` or a weak Python scalar re-promotes a reduced
table. This module makes precision discipline *provable at lint time*: a
dtype lattice propagated through ``jnp.*``/``np.*`` constructors,
``astype``/``asarray`` sites, NumPy/JAX promotion semantics,
``.at[...].add/set`` scatter updates, and depth-bounded call-return
summaries over the whole-program model (analysis/program.py) — stdlib-only
and jax-free like every other graftcheck layer.

Abstract values (``DT``):

- concrete dtypes: ``bool_``, ``int8..int64``/``uint8..uint64``,
  ``bfloat16``, ``float16``, ``float32``, ``float64``;
- **weak** Python scalars (``weak=True``): a bare ``2.0`` promotes by the
  *other* operand's dtype under JAX semantics but re-promotes to f64 under
  NumPy — so a weak value only stays provable against a concrete operand
  of the same category;
- ``None`` = unknown (parameters, unresolvable calls). Everything built on
  this model flags only what it can prove; unknown is trusted, exactly
  like G004 trusts dynamic axis names.

Promotion is the *provable intersection* of NumPy and JAX semantics:
where the two disagree (``int32 + float16`` widens to f32 under NumPy but
stays f16 under JAX), the result is unknown — a rule can then never flag
a mixing that one backend would have kept narrow.

Per function, ``DtypeFlow.facts`` runs a flow-sensitive statement walk
(loop bodies twice, If branches joined) and records the event classes the
rules consume:

- **promotions** — a binary op / binary ``jnp`` call whose operands'
  concrete dtypes widen (G017's silent-promotion-in-hot-path evidence);
- **casts** — every ``astype`` site with receiver/target dtypes, loop
  enclosure, and receiver loop-invariance (G019);
- **reductions** — ``sum``/``mean``/``cumsum``/``prod``/``segment_sum``
  sites with the operand dtype and whether an explicit accumulator dtype
  was given (G021);
- **scatter updates** — ``table.at[...].add(...)`` sites with the table's
  inferred dtype (G021's scatter-accumulate case).

Call-return summaries make the walk interprocedural: a call to a
resolvable def is evaluated by binding the caller's argument dtypes to
the callee's parameters and joining the callee's ``return`` expression
dtypes, depth-bounded and cycle-safe — so ``q = _load_quantized()`` three
modules away still proves ``q`` is int8 at the mixing site.
"""

from __future__ import annotations

import ast
from typing import Dict, List, NamedTuple, Optional, Set, Tuple

from .modmodel import _FN_TYPES, ModuleModel, dotted_name, enclosing_loop, \
    walk_scope
from .program import ProgramModel

MAX_SUMMARY_DEPTH = 4


class DT(NamedTuple):
    """One abstract dtype: lattice point + weak (Python-scalar) flag."""

    name: str       # "float32", "bfloat16", "int8", ... (numpy dtype name)
    category: str   # "b" bool, "i" int, "u" uint, "f" float
    bits: int
    weak: bool = False

    @property
    def reduced_float(self) -> bool:
        return self.category == "f" and self.bits < 32 and not self.weak

    @property
    def wide_float(self) -> bool:
        return self.category == "f" and self.bits >= 32 and not self.weak


_CONCRETE: Dict[str, DT] = {}
for _name, _cat, _bits in (
    ("bool_", "b", 8), ("int8", "i", 8), ("int16", "i", 16),
    ("int32", "i", 32), ("int64", "i", 64), ("uint8", "u", 8),
    ("uint16", "u", 16), ("uint32", "u", 32), ("uint64", "u", 64),
    ("bfloat16", "f", 16), ("float16", "f", 16), ("float32", "f", 32),
    ("float64", "f", 64),
):
    _CONCRETE[_name] = DT(_name, _cat, _bits)

WEAK_FLOAT = DT("float64", "f", 64, weak=True)   # a bare Python float
WEAK_INT = DT("int64", "i", 64, weak=True)       # a bare Python int

# spelling aliases accepted wherever a dtype is named (attribute tails and
# string literals): np.double, dtype="half", jnp.float_ ...
_ALIASES = {
    "double": "float64", "float_": "float64", "single": "float32",
    "half": "float16", "bool": "bool_", "int": "int64", "float": "float64",
    "bfloat16": "bfloat16", "intc": "int32", "byte": "int8", "ubyte": "uint8",
}
# module roots whose dtype attributes we trust (np.float32, jnp.bfloat16,
# ml_dtypes.bfloat16)
_DTYPE_ROOTS = ("np", "numpy", "jnp", "jax.numpy", "ml_dtypes")

_NP_ROOTS = ("np", "numpy")
_JNP_ROOTS = ("jnp", "jax.numpy")

# array methods whose result keeps the receiver's dtype
_PRESERVING_METHODS = (
    "copy", "reshape", "ravel", "flatten", "transpose", "squeeze", "clip",
    "round", "conj", "take", "repeat", "swapaxes", "block_until_ready",
)
# elementwise jnp/np calls whose result keeps the (promoted) operand dtype
_ELEMENTWISE_CALLS = (
    "exp", "log", "log1p", "expm1", "sqrt", "abs", "absolute", "tanh",
    "sign", "negative", "square", "maximum", "minimum", "add", "subtract",
    "multiply", "divide", "power", "where", "concatenate", "stack", "tile",
    "pad", "roll", "flip", "sort", "dot", "matmul",
)
# binary calls checked for silent promotion alongside BinOp (G017)
_BINARY_PROMOTING_CALLS = (
    "maximum", "minimum", "add", "subtract", "multiply", "divide", "power",
    "dot", "matmul",
)
# accumulating reductions whose accumulator defaults to the operand dtype
# (the G021 class); matmul/dot are excluded — TPU MXU accumulates f32
# internally regardless of the stored dtype
REDUCTION_TAILS = ("sum", "nansum", "mean", "nanmean", "cumsum", "prod",
                   "cumprod", "segment_sum")


def join(a: Optional[DT], b: Optional[DT]) -> Optional[DT]:
    """Lattice join for control-flow merges: equal or unknown."""
    if a is None or b is None:
        return None
    return a if a == b else None


def promote(a: Optional[DT], b: Optional[DT]) -> Optional[DT]:
    """Result dtype of mixing two abstract values — only where NumPy and
    JAX agree; None where they diverge or an input is unknown."""
    if a is None or b is None:
        return None
    if a.weak and b.weak:
        # float wins between weak scalars
        return a if a.category == "f" or b.category != "f" else b
    if a.weak or b.weak:
        weak, conc = (a, b) if a.weak else (b, a)
        if weak.category == "f" and conc.category in ("i", "u", "b"):
            # np: f64; jax: default float — disagree
            return None
        # weak int + anything concrete, weak float + concrete float:
        # both backends keep the concrete operand's dtype
        return conc
    if a.category == "f" and b.category == "f":
        if a.name == b.name:
            return a
        if {a.name, b.name} == {"bfloat16", "float16"}:
            return _CONCRETE["float32"]
        return a if a.bits > b.bits else b
    if a.category == b.category:
        return a if a.bits >= b.bits else b
    # int/uint/bool vs float: provable only when the float side is >= f32
    # (np widens a reduced float against int32/int64; jax keeps it reduced)
    fl, other = (a, b) if a.category == "f" else (b, a)
    if fl.category != "f" or other.category not in ("i", "u", "b"):
        return None  # int vs uint subtleties: unknown
    if fl.bits >= 32 or other.bits <= 8:
        return fl
    return None


def parse_dtype_name(name: str) -> Optional[DT]:
    name = _ALIASES.get(name, name)
    return _CONCRETE.get(name)


class CastSite(NamedTuple):
    node: ast.Call
    receiver_dt: Optional[DT]
    target_dt: Optional[DT]
    loop: Optional[ast.AST]          # enclosing For/While, if any
    loop_invariant: bool             # receiver not rebound inside that loop


class PromotionSite(NamedTuple):
    node: ast.AST
    left_dt: DT
    right_dt: DT
    out_dt: DT


class ReductionSite(NamedTuple):
    node: ast.Call
    tail: str
    operand_dt: Optional[DT]
    widened: bool                    # explicit dtype=/accumulator given


class ScatterSite(NamedTuple):
    node: ast.Call
    method: str                      # add / set / mul / ...
    table_dt: Optional[DT]


class FnFacts:
    """Everything the dtype rules need to know about one function."""

    __slots__ = ("promotions", "casts", "reductions", "scatters",
                 "return_dt", "_returned")

    def __init__(self):
        self.promotions: List[PromotionSite] = []
        self.casts: List[CastSite] = []
        self.reductions: List[ReductionSite] = []
        self.scatters: List[ScatterSite] = []
        self.return_dt: Optional[DT] = None
        self._returned = False


class DtypeFlow:
    def __init__(self, program: ProgramModel):
        self.program = program
        self._facts: Dict[Tuple[str, int], FnFacts] = {}
        self._returns: Dict[Tuple[str, int, tuple], Optional[DT]] = {}

    # -- public ------------------------------------------------------------

    def facts(self, path: str, fn: ast.AST) -> FnFacts:
        key = (path, id(fn))
        cached = self._facts.get(key)
        if cached is None:
            cached = self._analyze(path, fn, {}, collect=True,
                                   depth=0, stack=set())
            self._facts[key] = cached
        return cached

    # -- dtype-expression parsing ------------------------------------------

    def dtype_of_dtype_expr(self, path: str, expr: ast.expr,
                            env: Dict[str, Optional[DT]]) -> Optional[DT]:
        """A dtype-position expression (astype arg, dtype= kwarg)."""
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return parse_dtype_name(expr.value)
        name = dotted_name(expr)
        if name is not None:
            root, _, tail = name.rpartition(".")
            if root in _DTYPE_ROOTS:
                return parse_dtype_name(tail)
            if root == "" and name in env:
                return env[name]  # dt = jnp.bfloat16; x.astype(dt)
            if root == "" and name == "float":
                return _CONCRETE["float64"]  # astype(float) IS f64
            if tail == "dtype":
                # astype(y.dtype): follow y
                return self._eval(path, expr.value, env, None, 0, set())
        if isinstance(expr, ast.Call):
            # jnp.dtype("bfloat16") / np.dtype(np.float32)
            callee = dotted_name(expr.func) or ""
            if callee.rsplit(".", 1)[-1] == "dtype" and expr.args:
                return self.dtype_of_dtype_expr(path, expr.args[0], env)
        return None

    # -- call-return summaries ---------------------------------------------

    def _return_dtype(self, path: str, fn: ast.AST,
                      arg_dts: Dict[str, Optional[DT]], depth: int,
                      stack: Set[Tuple[str, int]]) -> Optional[DT]:
        key = (path, id(fn),
               tuple(sorted((k, v) for k, v in arg_dts.items()
                            if v is not None)))
        if key in self._returns:
            return self._returns[key]
        if (path, id(fn)) in stack or depth > MAX_SUMMARY_DEPTH:
            return None
        stack = stack | {(path, id(fn))}
        facts = self._analyze(path, fn, arg_dts, collect=False,
                              depth=depth, stack=stack)
        self._returns[key] = facts.return_dt
        return facts.return_dt

    # -- the statement walk -------------------------------------------------

    def _analyze(self, path: str, fn: ast.AST,
                 param_dts: Dict[str, Optional[DT]], collect: bool,
                 depth: int, stack: Set[Tuple[str, int]]) -> FnFacts:
        facts = FnFacts()
        env: Dict[str, Optional[DT]] = {}
        a = fn.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            env[p.arg] = param_dts.get(p.arg)
        sink = facts if collect else None
        self._walk_stmts(path, fn.body, env, sink, facts, depth, stack)
        return facts

    def _walk_stmts(self, path, stmts, env, sink, facts, depth, stack):
        for stmt in stmts:
            if isinstance(stmt, _FN_TYPES + (ast.ClassDef,)):
                continue  # nested scopes get their own facts
            if isinstance(stmt, ast.Assign):
                dt = self._eval(path, stmt.value, env, sink, depth, stack)
                for tgt in stmt.targets:
                    self._bind(tgt, dt, env)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                dt = self._eval(path, stmt.value, env, sink, depth, stack)
                self._bind(stmt.target, dt, env)
            elif isinstance(stmt, ast.AugAssign):
                cur = self._eval(path, stmt.target, env, sink, depth, stack)
                dt = promote(cur, self._eval(path, stmt.value, env, sink,
                                             depth, stack))
                self._bind(stmt.target, dt, env)
            elif isinstance(stmt, ast.Return):
                dt = self._eval(path, stmt.value, env, sink, depth, stack) \
                    if stmt.value is not None else None
                facts.return_dt = dt if not facts._returned \
                    else join(facts.return_dt, dt)
                facts._returned = True
            elif isinstance(stmt, ast.For):
                it = self._eval(path, stmt.iter, env, sink, depth, stack)
                self._bind(stmt.target, it, env)  # iterating keeps dtype
                for _ in range(2):  # loop-carried dtypes converge
                    self._walk_stmts(path, stmt.body, env, sink, facts,
                                     depth, stack)
                self._walk_stmts(path, stmt.orelse, env, sink, facts,
                                 depth, stack)
            elif isinstance(stmt, ast.While):
                self._eval(path, stmt.test, env, sink, depth, stack)
                for _ in range(2):
                    self._walk_stmts(path, stmt.body, env, sink, facts,
                                     depth, stack)
                self._walk_stmts(path, stmt.orelse, env, sink, facts,
                                 depth, stack)
            elif isinstance(stmt, ast.If):
                self._eval(path, stmt.test, env, sink, depth, stack)
                e1, e2 = dict(env), dict(env)
                self._walk_stmts(path, stmt.body, e1, sink, facts, depth,
                                 stack)
                self._walk_stmts(path, stmt.orelse, e2, sink, facts, depth,
                                 stack)
                for k in set(e1) | set(e2):  # branch join
                    env[k] = join(e1.get(k), e2.get(k)) \
                        if k in e1 and k in e2 else None
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    dt = self._eval(path, item.context_expr, env, sink,
                                    depth, stack)
                    if item.optional_vars is not None:
                        self._bind(item.optional_vars, dt, env)
                self._walk_stmts(path, stmt.body, env, sink, facts, depth,
                                 stack)
            elif isinstance(stmt, ast.Try):
                for block in (stmt.body, stmt.orelse, stmt.finalbody):
                    self._walk_stmts(path, block, env, sink, facts, depth,
                                     stack)
                for h in stmt.handlers:
                    self._walk_stmts(path, h.body, env, sink, facts, depth,
                                     stack)
            elif isinstance(stmt, ast.Expr):
                self._eval(path, stmt.value, env, sink, depth, stack)

    def _bind(self, tgt: ast.expr, dt: Optional[DT], env) -> None:
        name = dotted_name(tgt)
        if name is not None:
            env[name] = dt
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._bind(elt, None, env)

    # -- expression evaluation ----------------------------------------------

    def _eval(self, path: str, expr: ast.expr, env, sink: Optional[FnFacts],
              depth: int, stack) -> Optional[DT]:
        if isinstance(expr, ast.Constant):
            v = expr.value
            if isinstance(v, bool):
                return _CONCRETE["bool_"]
            if isinstance(v, float):
                return WEAK_FLOAT
            if isinstance(v, int):
                return WEAK_INT
            return None
        if isinstance(expr, ast.Name):
            return env.get(expr.id)
        if isinstance(expr, ast.Attribute):
            name = dotted_name(expr)
            if name is not None:
                root, _, tail = name.rpartition(".")
                if root in _DTYPE_ROOTS:
                    return parse_dtype_name(tail)
                if name in env:
                    return env[name]  # self.intercept = ... bindings
            if expr.attr in ("T", "real", "dtype"):
                return self._eval(path, expr.value, env, sink, depth, stack)
            return None
        if isinstance(expr, ast.Subscript):
            return self._eval(path, expr.value, env, sink, depth, stack)
        if isinstance(expr, ast.UnaryOp):
            if isinstance(expr.op, ast.Not):
                self._eval(path, expr.operand, env, sink, depth, stack)
                return _CONCRETE["bool_"]
            return self._eval(path, expr.operand, env, sink, depth, stack)
        if isinstance(expr, ast.BinOp):
            left = self._eval(path, expr.left, env, sink, depth, stack)
            right = self._eval(path, expr.right, env, sink, depth, stack)
            if isinstance(expr.op, ast.Div) and (
                    left is not None and left.category != "f"
                    or right is not None and right.category != "f"):
                return None  # true division of ints: np f64 / jax f32
            out = promote(left, right)
            self._note_promotion(sink, expr, left, right, out)
            return out
        if isinstance(expr, ast.Compare):
            self._eval(path, expr.left, env, sink, depth, stack)
            for c in expr.comparators:
                self._eval(path, c, env, sink, depth, stack)
            return _CONCRETE["bool_"]
        if isinstance(expr, ast.BoolOp):
            out: Optional[DT] = None
            for v in expr.values:
                out = join(out, self._eval(path, v, env, sink, depth,
                                           stack)) if out is not None \
                    else self._eval(path, v, env, sink, depth, stack)
            return out
        if isinstance(expr, ast.IfExp):
            self._eval(path, expr.test, env, sink, depth, stack)
            return join(self._eval(path, expr.body, env, sink, depth, stack),
                        self._eval(path, expr.orelse, env, sink, depth,
                                   stack))
        if isinstance(expr, ast.Call):
            return self._eval_call(path, expr, env, sink, depth, stack)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set, ast.Dict,
                             ast.GeneratorExp, ast.ListComp, ast.SetComp,
                             ast.DictComp, ast.Lambda)):
            return None
        return None

    def _note_promotion(self, sink: Optional[FnFacts], node: ast.AST,
                        left: Optional[DT], right: Optional[DT],
                        out: Optional[DT]) -> None:
        """Record a provably-widening mix of a reduced array with a wide
        float (the dequant-free violation G017 reports in hot scopes)."""
        if sink is None or left is None or right is None or out is None:
            return
        if not out.wide_float:
            return
        reduced = [d for d in (left, right)
                   if d.reduced_float
                   or (d.category in ("i", "u") and d.bits <= 8
                       and not d.weak)]
        if reduced and any(d.wide_float for d in (left, right)):
            sink.promotions.append(PromotionSite(node, left, right, out))

    # -- call evaluation ----------------------------------------------------

    def _kwarg(self, call: ast.Call, name: str) -> Optional[ast.expr]:
        for kw in call.keywords:
            if kw.arg == name:
                return kw.value
        return None

    def _explicit_dtype(self, path, call, env, positional: Optional[int]
                        ) -> Tuple[bool, Optional[DT]]:
        """(given, dtype) for a call that takes dtype= (or a positional)."""
        kw = self._kwarg(call, "dtype")
        if kw is not None:
            return True, self.dtype_of_dtype_expr(path, kw, env)
        if positional is not None and len(call.args) > positional:
            dt = self.dtype_of_dtype_expr(path, call.args[positional], env)
            if dt is not None:
                return True, dt
        return False, None

    def _eval_call(self, path, call: ast.Call, env, sink, depth, stack
                   ) -> Optional[DT]:
        for arg in call.args:
            if not isinstance(arg, ast.Starred):
                self._eval(path, arg, env, sink, depth, stack)
        for kw in call.keywords:
            self._eval(path, kw.value, env, sink, depth, stack)

        callee = dotted_name(call.func)

        # x.at[idx].add(u) / .set / .max / .min / .mul / .get
        if isinstance(call.func, ast.Attribute):
            at_table = self._at_table(call.func)
            if at_table is not None:
                table_dt = self._eval(path, at_table, env, sink, depth,
                                      stack)
                if sink is not None and call.func.attr in ("add", "mul"):
                    sink.scatters.append(ScatterSite(call, call.func.attr,
                                                     table_dt))
                return table_dt

        if callee is None:
            return None
        root, _, tail = callee.rpartition(".")

        # dtype constructors used as casts: jnp.float32(x), np.int8(x)
        if root in _DTYPE_ROOTS:
            dt = parse_dtype_name(tail)
            if dt is not None:
                return dt

        if tail == "astype":
            recv = call.func.value if isinstance(call.func, ast.Attribute) \
                else None
            recv_dt = self._eval(path, recv, env, sink, depth, stack) \
                if recv is not None else None
            target = None
            if call.args:
                target = self.dtype_of_dtype_expr(path, call.args[0], env)
            else:
                given, target = self._explicit_dtype(path, call, env, None)
            if sink is not None and recv is not None:
                loop = enclosing_loop(call)
                sink.casts.append(CastSite(
                    call, recv_dt, target, loop,
                    self._loop_invariant(recv, loop)))
            return target

        if tail in ("asarray", "array", "ascontiguousarray"):
            given, dt = self._explicit_dtype(path, call, env, 1)
            if given:
                return dt
            inner = self._eval(path, call.args[0], env, None, depth, stack) \
                if call.args else None
            if inner is not None and inner.weak:
                if root in _NP_ROOTS:
                    return _CONCRETE[inner.name]  # np concretizes weak f64
                if root in _JNP_ROOTS:
                    return _CONCRETE["float32"] if inner.category == "f" \
                        else _CONCRETE["int32"]
                return None
            return inner

        if tail in ("zeros", "ones", "empty", "full"):
            pos = 2 if tail == "full" else 1
            given, dt = self._explicit_dtype(path, call, env, pos)
            if given:
                return dt
            if tail == "full" and len(call.args) > 1:
                fill = self._eval(path, call.args[1], env, None, depth,
                                  stack)
                if fill is None:
                    return None
                if root in _NP_ROOTS:
                    return _CONCRETE[fill.name]
                if root in _JNP_ROOTS and fill.weak:
                    return _CONCRETE["float32"] if fill.category == "f" \
                        else _CONCRETE["int32"]
                return DT(fill.name, fill.category, fill.bits)
            if root in _NP_ROOTS:
                return _CONCRETE["float64"]
            if root in _JNP_ROOTS:
                return _CONCRETE["float32"]
            return None

        if tail in ("zeros_like", "ones_like", "empty_like", "full_like"):
            given, dt = self._explicit_dtype(path, call, env, None)
            if given:
                return dt
            return self._eval(path, call.args[0], env, None, depth, stack) \
                if call.args else None

        if tail == "linspace":
            given, dt = self._explicit_dtype(path, call, env, None)
            if given:
                return dt
            if root in _NP_ROOTS:
                return _CONCRETE["float64"]
            if root in _JNP_ROOTS:
                return _CONCRETE["float32"]
            return None

        if tail in ("float", "int") and root == "":
            return WEAK_FLOAT if tail == "float" else WEAK_INT

        if tail in REDUCTION_TAILS:
            operand = None
            if isinstance(call.func, ast.Attribute) and root not in \
                    _NP_ROOTS + _JNP_ROOTS + ("jax.ops", "jax.lax", "lax"):
                operand = self._eval(path, call.func.value, env, sink,
                                     depth, stack)  # x.sum()
            elif call.args:
                # args were already evaluated (events recorded) above —
                # re-evaluate without the sink to avoid duplicates
                operand = self._eval(path, call.args[0], env, None, depth,
                                     stack)
            given, acc_dt = self._explicit_dtype(path, call, env, None)
            # an explicit dtype= that does not RESOLVE (a threaded
            # parameter) is trusted like every unknown — only an explicit
            # accumulator provably equal to a reduced operand stays
            # flaggable
            widened = given and (
                acc_dt is None or operand is None
                or acc_dt.bits > operand.bits
                or acc_dt.category != operand.category)
            if sink is not None:
                sink.reductions.append(ReductionSite(call, tail, operand,
                                                     widened))
            return acc_dt if given else operand

        if tail in _PRESERVING_METHODS and isinstance(call.func,
                                                      ast.Attribute):
            return self._eval(path, call.func.value, env, sink, depth,
                              stack)

        if tail in _ELEMENTWISE_CALLS and root in _NP_ROOTS + _JNP_ROOTS:
            args = [a for a in call.args
                    if not isinstance(a, ast.Starred)]
            if tail == "where":
                args = args[1:]
            dts = [self._eval(path, a, env, None, depth, stack)
                   for a in args]
            out: Optional[DT] = dts[0] if dts else None
            for d in dts[1:]:
                out = promote(out, d)
            if tail in _BINARY_PROMOTING_CALLS and len(dts) >= 2:
                self._note_promotion(sink, call, dts[0], dts[1], out)
            return out

        # calls to resolvable defs: bind argument dtypes, join return exprs
        if "." not in callee:
            got = self.program.resolve_fn(path, callee, call)
            if got is not None:
                t_path, t_fn = got
                arg_dts = self._arg_dtypes(path, call, t_fn, env, depth,
                                           stack)
                return self._return_dtype(t_path, t_fn, arg_dts, depth + 1,
                                          stack)
        return None

    def _arg_dtypes(self, path, call, callee_fn, env, depth, stack
                    ) -> Dict[str, Optional[DT]]:
        a = callee_fn.args
        params = [p.arg for p in a.posonlyargs + a.args]
        offset = 1 if params[:1] == ["self"] else 0
        out: Dict[str, Optional[DT]] = {}
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break
            j = i + offset
            if j < len(params):
                out[params[j]] = self._eval(path, arg, env, None, depth,
                                            stack)
        for kw in call.keywords:
            if kw.arg is not None:
                out[kw.arg] = self._eval(path, kw.value, env, None, depth,
                                         stack)
        return out

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _at_table(func: ast.Attribute) -> Optional[ast.expr]:
        """table expr of a ``table.at[...].method`` chain, else None."""
        if func.attr not in ("add", "set", "max", "min", "mul", "get",
                             "multiply"):
            return None
        sub = func.value
        if isinstance(sub, ast.Subscript) \
                and isinstance(sub.value, ast.Attribute) \
                and sub.value.attr == "at":
            return sub.value.value
        return None

    @staticmethod
    def _loop_invariant(recv: ast.expr, loop: Optional[ast.AST]) -> bool:
        """True when the astype receiver is a Name that no statement inside
        the enclosing loop rebinds — the cast re-materializes the same
        array every iteration."""
        if loop is None or not isinstance(recv, ast.Name):
            return False
        name = recv.id
        for node in ast.walk(loop):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if any(isinstance(n, ast.Name) and n.id == name
                           for n in ast.walk(tgt)):
                        return False
            elif isinstance(node, ast.AugAssign):
                if any(isinstance(n, ast.Name) and n.id == name
                       for n in ast.walk(node.target)):
                    return False
            elif isinstance(node, ast.For):
                if any(isinstance(n, ast.Name) and n.id == name
                       for n in ast.walk(node.target)):
                    return False
        return True


def get_model(program: ProgramModel) -> DtypeFlow:
    """One DtypeFlow per ProgramModel (all five dtype rules share it)."""
    model = getattr(program, "_graftcheck_dtypeflow", None)
    if model is None:
        model = DtypeFlow(program)
        program._graftcheck_dtypeflow = model
    return model


def in_hot_scope(path: str, model: Optional[ModuleModel],
                 fn: Optional[ast.AST] = None) -> bool:
    """Hot-path scoping for G017/G019: the kernel/op packages and the
    serving score path always; elsewhere in the dtype-sensitive packages
    only traced or step-shaped functions (their math runs per step)."""
    from . import config

    if path.startswith(config.DTYPEFLOW_HOT_PREFIXES) \
            or path in config.DTYPEFLOW_HOT_MODULES:
        return True
    if model is not None and config.HOT_MARKER in model.source:
        return True
    if fn is not None and model is not None \
            and path.startswith(config.DTYPE_MODULE_PREFIXES):
        if model.is_traced(fn) or config.HOT_FN_RE.match(
                getattr(fn, "name", "")):
            return True
    return False
