"""Per-module AST model shared by every graftcheck rule.

Builds, once per file:

- parent links + enclosing-scope resolution;
- the set of *traced* functions: defs decorated with ``@jax.jit`` /
  ``@partial(jax.jit, ...)``, defs wrapped via ``jax.jit(name)`` /
  ``jax.vmap(name)`` / ``jax.shard_map(name, ...)`` / ``jax.lax.scan(name,
  ...)`` and friends, plus every def lexically nested inside a traced def
  (inner defs are executed during the trace);
- per-def static parameter names (from ``static_argnums`` /
  ``static_argnames``) — static args are Python values, not tracers;
- jit aliases: ``name = jax.jit(fn, ...)`` (including ``self._step = ...``)
  with their ``donate_argnums`` for the donation rule;
- a lightweight, intraprocedural *device-value taint* walker: which local
  names hold jax arrays (results of ``jnp.*`` / ``jax.*`` calls, calls to
  jitted functions or jitted-factory products), with explicit host
  boundaries (``jax.device_get``, ``np.asarray``, ``float`` ...) untainting.

Free (closure) variables are deliberately NOT tainted: at trace time they
are Python constants, so branching on them is trace-safe — exactly JAX's
semantics.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import config


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.lax.psum' for Attribute chains / Names; None otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_child_stmts(node: ast.AST):
    for child in ast.iter_child_nodes(node):
        if isinstance(child, ast.stmt):
            yield child


_FN_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _call_kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _literal_ints(node: ast.expr) -> Optional[Tuple[int, ...]]:
    """(3,) / 0 / [0, 1] as a tuple of ints; None when not a literal."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def _literal_strs(node: ast.expr) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def _is_jit_callee(node: ast.expr) -> bool:
    name = dotted_name(node)
    return name in ("jax.jit", "jit")


def _is_partial_jit(call: ast.Call) -> bool:
    """partial(jax.jit, ...) / functools.partial(jax.jit, ...)."""
    name = dotted_name(call.func)
    if name not in ("partial", "functools.partial"):
        return False
    return bool(call.args) and _is_jit_callee(call.args[0])


class JitWrap:
    """One jax.jit(...) site: static/donate info + the wrapped expression."""

    __slots__ = ("call", "static_argnums", "static_argnames",
                 "donate_argnums", "has_donate")

    def __init__(self, call: ast.Call):
        self.call = call
        sn = _call_kwarg(call, "static_argnums")
        self.static_argnums = _literal_ints(sn) if sn is not None else None
        sa = _call_kwarg(call, "static_argnames")
        self.static_argnames = _literal_strs(sa) if sa is not None else None
        dn = _call_kwarg(call, "donate_argnums")
        self.has_donate = dn is not None
        self.donate_argnums = _literal_ints(dn) if dn is not None else None


class ModuleModel:
    def __init__(self, rel_path: str, source: str, tree: ast.Module):
        self.rel_path = rel_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree

        # parent links + scope map
        tree.graftcheck_parent = None  # type: ignore[attr-defined]
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                child.graftcheck_parent = node  # type: ignore[attr-defined]

        self.functions: List[ast.AST] = [
            n for n in ast.walk(tree) if isinstance(n, _FN_TYPES)]
        # (scope, name) -> def node; scope is the enclosing def or None
        self._defs_by_scope: Dict[Tuple[Optional[ast.AST], str], ast.AST] = {}
        for fn in self.functions:
            self._defs_by_scope[(self.enclosing_function(fn), fn.name)] = fn

        self.traced: Set[ast.AST] = set()
        self.static_params: Dict[ast.AST, Set[str]] = {}
        # alias ("step", "self._step") -> JitWrap
        self.jit_aliases: Dict[str, JitWrap] = {}
        # jit call sites wrapping a step-shaped def without donate_argnums
        self.jit_wraps: List[Tuple[JitWrap, Optional[str]]] = []

        self._collect_traced_roots()
        self._propagate_nested_traced()

    # -- scope helpers ------------------------------------------------------

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = getattr(node, "graftcheck_parent", None)
        while cur is not None and not isinstance(cur, _FN_TYPES):
            cur = getattr(cur, "graftcheck_parent", None)
        return cur

    def resolve_def(self, name: str, from_node: ast.AST) -> Optional[ast.AST]:
        scope = self.enclosing_function(from_node)
        while True:
            fn = self._defs_by_scope.get((scope, name))
            if fn is not None:
                return fn
            if scope is None:
                return None
            scope = self.enclosing_function(scope)

    def snippet(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    # -- traced-function detection ------------------------------------------

    def _mark_traced(self, fn: ast.AST, wrap: Optional[JitWrap]) -> None:
        self.traced.add(fn)
        if wrap is None:
            return
        statics = self.static_params.setdefault(fn, set())
        args = fn.args
        pos = [a.arg for a in args.posonlyargs + args.args]
        if wrap.static_argnums:
            for i in wrap.static_argnums:
                if 0 <= i < len(pos):
                    statics.add(pos[i])
        if wrap.static_argnames:
            statics.update(wrap.static_argnames)

    def _collect_traced_roots(self) -> None:
        # decorators
        for fn in self.functions:
            for dec in fn.decorator_list:
                if _is_jit_callee(dec):
                    self._mark_traced(fn, None)
                elif isinstance(dec, ast.Call):
                    if _is_jit_callee(dec.func):
                        self._mark_traced(fn, JitWrap(dec))
                    elif _is_partial_jit(dec):
                        self._mark_traced(fn, JitWrap(dec))
        # call sites: jax.jit(name) / jax.vmap(name) / jax.lax.scan(name, ..)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee is None:
                continue
            tail = callee.rsplit(".", 1)[-1]
            if tail not in config.TRACING_TRANSFORMS:
                continue
            fn_arg: Optional[ast.expr] = node.args[0] if node.args else None
            wrap = JitWrap(node) if tail == "jit" else None
            if isinstance(fn_arg, ast.Name):
                fn = self.resolve_def(fn_arg.id, node)
                if fn is not None:
                    self._mark_traced(fn, wrap)
            if tail == "jit" and wrap is not None:
                self._record_jit_alias(node, wrap, fn_arg)

    def _record_jit_alias(self, call: ast.Call, wrap: JitWrap,
                          fn_arg: Optional[ast.expr]) -> None:
        wrapped_name = dotted_name(fn_arg) if fn_arg is not None else None
        self.jit_wraps.append((wrap, wrapped_name))
        parent = getattr(call, "graftcheck_parent", None)
        if isinstance(parent, ast.Assign) and parent.value is call:
            for tgt in parent.targets:
                name = dotted_name(tgt)
                if name:
                    self.jit_aliases[name] = wrap
        elif isinstance(parent, ast.Return):
            # `return jax.jit(fn, ...)` — the enclosing factory's results
            # are jitted callables; record under the factory's name
            fn = self.enclosing_function(parent)
            if fn is not None:
                self.jit_aliases[fn.name] = wrap

    def _propagate_nested_traced(self) -> None:
        changed = True
        while changed:
            changed = False
            for fn in self.functions:
                if fn in self.traced:
                    continue
                enc = self.enclosing_function(fn)
                if enc is not None and enc in self.traced:
                    self.traced.add(fn)
                    changed = True

    def is_traced(self, fn: ast.AST) -> bool:
        return fn in self.traced

    # -- taint --------------------------------------------------------------

    def taint_function(self, fn: ast.AST, taint_params: bool = False):
        """Best-effort intraprocedural device-value taint for one function.

        Returns (tainted_names, jitted_callables): names currently holding
        device values, and names whose *call* yields device values. Loop
        bodies are walked twice so loop-carried taint converges.
        """
        tainted: Set[str] = set()
        callables: Set[str] = set()
        if taint_params:
            statics = self.static_params.get(fn, set())
            args = fn.args
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                if a.arg != "self" and a.arg not in statics:
                    tainted.add(a.arg)
        for _ in range(2):
            self._taint_stmts(fn.body, tainted, callables, fn)
        return tainted, callables

    def _taint_stmts(self, stmts, tainted, callables, fn) -> None:
        for stmt in stmts:
            if isinstance(stmt, _FN_TYPES + (ast.ClassDef,)):
                continue  # nested scopes analyzed separately
            if isinstance(stmt, ast.Assign):
                self._taint_assign(stmt.targets, stmt.value, tainted,
                                   callables)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                self._taint_assign([stmt.target], stmt.value, tainted,
                                   callables)
            elif isinstance(stmt, ast.AugAssign):
                if (self.expr_tainted(stmt.value, tainted, callables)
                        or self.expr_tainted(stmt.target, tainted, callables)):
                    self._taint_target(stmt.target, tainted, True)
            elif isinstance(stmt, ast.For):
                if self.expr_tainted(stmt.iter, tainted, callables):
                    self._taint_target(stmt.target, tainted, True)
                for _ in range(2):
                    self._taint_stmts(stmt.body, tainted, callables, fn)
                self._taint_stmts(stmt.orelse, tainted, callables, fn)
            elif isinstance(stmt, ast.While):
                for _ in range(2):
                    self._taint_stmts(stmt.body, tainted, callables, fn)
                self._taint_stmts(stmt.orelse, tainted, callables, fn)
            elif isinstance(stmt, ast.If):
                self._taint_stmts(stmt.body, tainted, callables, fn)
                self._taint_stmts(stmt.orelse, tainted, callables, fn)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    if item.optional_vars is not None and self.expr_tainted(
                            item.context_expr, tainted, callables):
                        self._taint_target(item.optional_vars, tainted, True)
                self._taint_stmts(stmt.body, tainted, callables, fn)
            elif isinstance(stmt, ast.Try):
                self._taint_stmts(stmt.body, tainted, callables, fn)
                for h in stmt.handlers:
                    self._taint_stmts(h.body, tainted, callables, fn)
                self._taint_stmts(stmt.orelse, tainted, callables, fn)
                self._taint_stmts(stmt.finalbody, tainted, callables, fn)

    def _taint_assign(self, targets, value, tainted, callables) -> None:
        callee = dotted_name(value.func) if isinstance(value, ast.Call) \
            else None
        if callee is not None:
            tail = callee.rsplit(".", 1)[-1]
            # `step = make_train_step(...)` / `x = jax.jit(f)`:
            # target is a jitted CALLABLE, not a device value
            if (config.JITTED_FACTORY_RE.match(tail)
                    or callee in ("jax.jit", "jit")):
                for tgt in targets:
                    name = dotted_name(tgt)
                    if name:
                        callables.add(name)
                        tainted.discard(name)
                return
        is_tainted = self.expr_tainted(value, tainted, callables)
        for tgt in targets:
            self._taint_target(tgt, tainted, is_tainted)

    def _taint_target(self, tgt, tainted, is_tainted: bool) -> None:
        if isinstance(tgt, ast.Name):
            (tainted.add if is_tainted else tainted.discard)(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._taint_target(elt, tainted, is_tainted)
        elif isinstance(tgt, ast.Starred):
            self._taint_target(tgt.value, tainted, is_tainted)
        # Attribute / Subscript targets: not tracked

    def call_yields_device(self, call: ast.Call, tainted, callables) -> Optional[bool]:
        """True/False when the call's result is known device/host; None when
        unknown (propagate from arguments)."""
        callee = dotted_name(call.func)
        if callee is None:
            return None
        tail = callee.rsplit(".", 1)[-1]
        if tail in config.UNTAINT_CALLS:
            return False
        if callee.startswith(("jnp.", "jax.numpy.")):
            return True
        if callee.startswith("jax.tree"):
            return None  # host pytrees stay host: propagate from args
        if callee.startswith("jax.") or callee in ("jit", "vmap"):
            return True
        if callee in callables or callee in self.jit_aliases:
            return True
        if tail in config.JITTED_ATTR_CALLEES and "." in callee:
            return True  # self._step(...) trainer convention
        if callee.startswith("np.") or callee.startswith("numpy."):
            return False
        if tail in config.SYNC_CALLS or tail in config.SYNC_METHODS:
            return False
        # call to a def jitted in this module
        fn = self.resolve_def(callee, call) if "." not in callee else None
        if fn is not None and fn in self.traced:
            return True
        return None

    def expr_tainted(self, expr: ast.expr, tainted, callables) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if isinstance(expr, ast.Call):
            known = self.call_yields_device(expr, tainted, callables)
            if known is not None:
                return known
            return any(self.expr_tainted(a, tainted, callables)
                       for a in expr.args) or any(
                self.expr_tainted(kw.value, tainted, callables)
                for kw in expr.keywords)
        if isinstance(expr, ast.Attribute):
            return self.expr_tainted(expr.value, tainted, callables)
        if isinstance(expr, ast.Subscript):
            return self.expr_tainted(expr.value, tainted, callables)
        if isinstance(expr, ast.BinOp):
            return (self.expr_tainted(expr.left, tainted, callables)
                    or self.expr_tainted(expr.right, tainted, callables))
        if isinstance(expr, ast.UnaryOp):
            return self.expr_tainted(expr.operand, tainted, callables)
        if isinstance(expr, ast.Compare):
            return self.expr_tainted(expr.left, tainted, callables) or any(
                self.expr_tainted(c, tainted, callables)
                for c in expr.comparators)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr_tainted(e, tainted, callables)
                       for e in expr.elts)
        if isinstance(expr, ast.Dict):
            return any(self.expr_tainted(v, tainted, callables)
                       for v in expr.values)
        if isinstance(expr, ast.IfExp):
            return (self.expr_tainted(expr.body, tainted, callables)
                    or self.expr_tainted(expr.orelse, tainted, callables))
        if isinstance(expr, ast.BoolOp):
            return any(self.expr_tainted(v, tainted, callables)
                       for v in expr.values)
        if isinstance(expr, ast.Starred):
            return self.expr_tainted(expr.value, tainted, callables)
        return False


def walk_scope(fn: ast.AST):
    """Walk a function body WITHOUT descending into nested function/class
    definitions (those are separate trace scopes, analyzed on their own)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _FN_TYPES + (ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def enclosing_loop(node: ast.AST):
    """Nearest For/While ancestor within the same function scope (stops at a
    function boundary), else None."""
    cur = getattr(node, "graftcheck_parent", None)
    while cur is not None and not isinstance(cur, _FN_TYPES):
        if isinstance(cur, (ast.For, ast.While)):
            return cur
        cur = getattr(cur, "graftcheck_parent", None)
    return None


def build_model(rel_path: str, source: str) -> ModuleModel:
    tree = ast.parse(source, filename=rel_path)
    return ModuleModel(rel_path, source, tree)
