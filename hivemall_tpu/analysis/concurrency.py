"""Concurrency model: guarded-by inference + thread entry points (v3).

PR 3 put background threads, condition variables, and hot-swap state on
the serving path; the G012-G016 rules check that code the same way
G007-G011 check SPMD safety — against a whole-program model, flagging
only what they can prove. This module provides, stdlib-only:

- per-class **lock discovery**: ``self._x = threading.Lock()/RLock()/
  Condition()`` fields with their reentrancy kind, plus module- and
  function-local lock names;
- a statement walker that tracks the **held-lock set** through ``with
  self._lock:`` scopes (and linear ``acquire()``/``release()`` pairs),
  recording every ``self.<field>`` access, call, and lock acquisition
  with the locks held at that point;
- **thread entry points**: ``threading.Thread(target=self._loop)``
  spawn targets and ``do_*`` HTTP-handler methods, closed over the
  intra-class call graph, so accesses can be attributed to "runs on the
  spawned thread" vs "runs on a caller thread";
- **context propagation** through helper calls: a private method called
  only under the lock inherits the caller's held set (depth-bounded via
  the held-set lattice), which is how ``self._bump_locked()`` bodies
  count as guarded and how re-acquiring a non-reentrant lock through a
  helper is detected;
- cross-class **lock-ordering edges**: acquiring ``B._cv`` while holding
  ``A._lock`` (resolved through module-level instances and
  ``self.field = ClassName(...)`` assignments) — cycles in that graph
  are the G016 deadlocks.

Everything dynamic (locks passed as parameters, receivers whose type
cannot be resolved) is trusted, exactly like the SPMD rules trust
dynamic axis names.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from . import config
from .modmodel import _FN_TYPES, ModuleModel, dotted_name, walk_scope
from .program import ProgramModel

ClassKey = Tuple[str, str]  # (module rel_path, class name)

_INIT_METHODS = ("__init__", "__new__")


class Access:
    """One ``self.<attr>`` touch with the locks held at that point."""

    __slots__ = ("method", "attr", "write", "line", "held")

    def __init__(self, method: str, attr: str, write: bool, line: int,
                 held: FrozenSet[str]):
        self.method = method
        self.attr = attr
        self.write = write
        self.line = line
        self.held = held


class CallEv:
    """One call with the locks held at the call site."""

    __slots__ = ("method", "node", "dotted", "held", "line")

    def __init__(self, method: str, node: ast.Call, dotted: str,
                 held: FrozenSet[str]):
        self.method = method
        self.node = node
        self.dotted = dotted
        self.held = held
        self.line = node.lineno


class Acquire:
    """One lock acquisition (with-statement or .acquire()) and the locks
    already held when it happens."""

    __slots__ = ("method", "lock", "held", "node")

    def __init__(self, method: str, lock: str, held: FrozenSet[str],
                 node: ast.AST):
        self.method = method
        self.lock = lock
        self.held = held
        self.node = node


class _Events:
    __slots__ = ("accesses", "calls", "acquisitions")

    def __init__(self):
        self.accesses: List[Access] = []
        self.calls: List[CallEv] = []
        self.acquisitions: List[Acquire] = []


class ClassConc:
    """Concurrency summary of one class."""

    __slots__ = ("path", "node", "name", "locks", "methods", "spawn_targets",
                 "thread_side", "thread_entries", "raw", "contexts",
                 "eff_accesses", "eff_calls", "double_acquires")

    def __init__(self, path: str, node: ast.ClassDef):
        self.path = path
        self.node = node
        self.name = node.name
        self.locks: Dict[str, str] = {}  # field -> kind
        self.methods: Dict[str, ast.AST] = {
            n.name: n for n in node.body if isinstance(n, _FN_TYPES)}
        self.spawn_targets: Set[str] = set()
        self.thread_side: Set[str] = set()
        # DIRECT thread entry points only (spawn targets + do_* handlers):
        # the zero-held propagation seeds. thread_side is the CLOSURE over
        # the call graph — right for "which methods run on the worker
        # thread" (G012 cross-thread proof) but wrong as a held-set seed:
        # a helper reached only via `with self._lock:` call sites would be
        # falsely analyzed lock-free (its real contexts flow through the
        # caller's held set).
        self.thread_entries: Set[str] = set()
        self.raw: Dict[str, _Events] = {}
        # method -> {held-at-entry: introducing call node (None for entries)}
        self.contexts: Dict[str, Dict[FrozenSet[str],
                                      Optional[ast.AST]]] = {}
        self.eff_accesses: Dict[str, List[Access]] = {}  # field -> accesses
        self.eff_calls: List[CallEv] = []
        # (site node, lock name) — non-reentrant lock re-acquired
        self.double_acquires: List[Tuple[ast.AST, str]] = []

    @property
    def concurrent(self) -> bool:
        return bool(self.locks or self.spawn_targets or self.thread_side)


class LockEdge:
    """Acquiring `to` while holding `frm` (both (ClassKey, lockname))."""

    __slots__ = ("frm", "to", "site", "path")

    def __init__(self, frm, to, site: ast.AST, path: str):
        self.frm = frm
        self.to = to
        self.site = site
        self.path = path


def _lock_ctor_kind(expr: ast.expr) -> Optional[str]:
    if not isinstance(expr, ast.Call):
        return None
    d = dotted_name(expr.func) or ""
    tail = d.rsplit(".", 1)[-1]
    if tail in config.LOCK_CONSTRUCTOR_KINDS \
            and (d == tail or d.startswith(("threading.",
                                            "multiprocessing."))):
        return config.LOCK_CONSTRUCTOR_KINDS[tail]
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class ConcurrencyModel:
    def __init__(self, program: ProgramModel):
        self.program = program
        self.classes: Dict[ClassKey, ClassConc] = {}
        # (path, enclosing-def name, CallEv) for non-method defs — used by
        # G013 for module-level functions holding local/module locks
        self.fn_calls: List[Tuple[str, str, CallEv]] = []
        self.lock_edges: List[LockEdge] = []
        self._ctor_memo: Dict[Tuple[str, str, str], Optional[str]] = {}
        for path in sorted(program.modules):
            model = program.modules.get(path)
            if model is None:
                continue
            # per-module collection + per-class propagation are pure
            # per-module products — cache them on the ModuleModel, whose
            # lifetime (modelcache's mtime layer) already tracks file changes,
            # so repeated in-process scans (the test suite's _cli runs)
            # pay the walkers once per module version. Only the
            # cross-class lock-ordering edges rebuild per program.
            cached = getattr(model, "_graftcheck_conc", None)
            if cached is None:
                mod_classes: Dict[ClassKey, ClassConc] = {}
                mod_calls: List[Tuple[str, str, CallEv]] = []
                self._build_module(path, model, mod_classes, mod_calls)
                for cls in mod_classes.values():
                    self._propagate(cls)
                cached = (mod_classes, mod_calls)
                model._graftcheck_conc = cached  # type: ignore[attr-defined]
            for key, cls in cached[0].items():
                self.classes.setdefault(key, cls)
            self.fn_calls.extend(cached[1])
        self._build_edges()

    # -- construction ------------------------------------------------------

    def _build_module(self, path: str, model: ModuleModel,
                      out_classes: Dict[ClassKey, ClassConc],
                      out_calls: List[Tuple[str, str, CallEv]]) -> None:
        # cheap pre-filter: nothing lock/thread-shaped, nothing to model
        src = model.source
        if "Lock" not in src and "Condition" not in src \
                and "Thread" not in src and "Semaphore" not in src:
            return
        module_locks = self._module_lock_names(model)
        class_nodes = [n for n in ast.walk(model.tree)
                       if isinstance(n, ast.ClassDef)]
        for cnode in class_nodes:
            cls = ClassConc(path, cnode)
            for m in cls.methods.values():
                for node in walk_scope(m):
                    if isinstance(node, ast.Assign) \
                            and len(node.targets) == 1:
                        attr = _self_attr(node.targets[0])
                        kind = _lock_ctor_kind(node.value)
                        if attr is not None and kind is not None:
                            cls.locks[attr] = kind
                    if isinstance(node, ast.Call):
                        self._note_spawn(cls, node)
            for mname in (n for n in cnode.body if isinstance(n, _FN_TYPES)):
                if mname.name.startswith("do_"):
                    cls.thread_side.add(mname.name)
            for mname, m in cls.methods.items():
                cls.raw[mname] = self._collect(cls, mname, m, model,
                                               module_locks)
            self._close_thread_side(cls)
            out_classes.setdefault((path, cls.name), cls)
        # module-level and nested (non-method) defs: call events only
        for fn in model.functions:
            parent = getattr(fn, "graftcheck_parent", None)
            if isinstance(parent, ast.ClassDef):
                continue  # direct method, covered above
            owner = self._owning_class(fn, path, out_classes)
            ev = self._collect(owner, fn.name, fn, model, module_locks)
            for call in ev.calls:
                out_calls.append((path, fn.name, call))

    def _owning_class(self, fn: ast.AST, path: str,
                      classes: Dict[ClassKey, ClassConc]
                      ) -> Optional[ClassConc]:
        cur = getattr(fn, "graftcheck_parent", None)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return classes.get((path, cur.name))
            cur = getattr(cur, "graftcheck_parent", None)
        return None

    def _module_lock_names(self, model: ModuleModel) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for node in model.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                kind = _lock_ctor_kind(node.value)
                if kind is not None:
                    out["@" + node.targets[0].id] = kind
        return out

    def _note_spawn(self, cls: ClassConc, call: ast.Call) -> None:
        d = dotted_name(call.func) or ""
        if d.rsplit(".", 1)[-1] not in ("Thread", "Timer"):
            return
        for kw in call.keywords:
            if kw.arg == "target":
                attr = _self_attr(kw.value)
                if attr is not None and attr in cls.methods:
                    cls.spawn_targets.add(attr)

    def _close_thread_side(self, cls: ClassConc) -> None:
        cls.thread_entries = set(cls.thread_side) | cls.spawn_targets
        cls.thread_side |= cls.spawn_targets
        changed = True
        while changed:
            changed = False
            for mname in list(cls.thread_side):
                for ev in cls.raw.get(mname, _Events()).calls:
                    parts = ev.dotted.split(".")
                    if parts[0] == "self" and len(parts) == 2 \
                            and parts[1] in cls.methods \
                            and parts[1] not in cls.thread_side:
                        cls.thread_side.add(parts[1])
                        changed = True

    # -- the statement walker ---------------------------------------------

    def _collect(self, cls: Optional[ClassConc], mname: str, fn: ast.AST,
                 model: ModuleModel,
                 module_locks: Dict[str, str]) -> _Events:
        events = _Events()
        name_locks = dict(module_locks)
        # locals assigned a lock constructor anywhere in this def (and its
        # enclosing defs — closures see the outer function's locks)
        scope: Optional[ast.AST] = fn
        while scope is not None:
            for node in walk_scope(scope):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    kind = _lock_ctor_kind(node.value)
                    if kind is not None:
                        name_locks.setdefault(
                            "@" + node.targets[0].id, kind)
            scope = model.enclosing_function(scope)

        def lock_of(expr: ast.expr) -> Optional[str]:
            attr = _self_attr(expr)
            if attr is not None and cls is not None and attr in cls.locks:
                return attr
            if isinstance(expr, ast.Name) and "@" + expr.id in name_locks:
                return "@" + expr.id
            return None

        def record(tree: ast.AST, held: FrozenSet[str]) -> None:
            stack = [tree]
            while stack:
                node = stack.pop()
                if isinstance(node, _FN_TYPES + (ast.Lambda, ast.ClassDef)):
                    continue
                if isinstance(node, ast.Call):
                    d = dotted_name(node.func)
                    if d is not None:
                        events.calls.append(CallEv(mname, node, d, held))
                attr = _self_attr(node)
                if attr is not None and cls is not None \
                        and attr not in cls.locks \
                        and attr not in cls.methods:
                    write = isinstance(node.ctx, (ast.Store, ast.Del))
                    parent = getattr(node, "graftcheck_parent", None)
                    if isinstance(parent, ast.Subscript) \
                            and parent.value is node \
                            and isinstance(parent.ctx,
                                           (ast.Store, ast.Del)):
                        write = True
                    if isinstance(parent, ast.Attribute) \
                            and parent.value is node:
                        gp = getattr(parent, "graftcheck_parent", None)
                        if isinstance(gp, ast.Call) and gp.func is parent \
                                and parent.attr in config.MUTATOR_METHODS:
                            write = True
                    events.accesses.append(
                        Access(mname, attr, write, node.lineno, held))
                stack.extend(ast.iter_child_nodes(node))

        def walk(stmts, held: FrozenSet[str]) -> None:
            held = frozenset(held)
            for stmt in stmts:
                if isinstance(stmt, _FN_TYPES + (ast.ClassDef,)):
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    add: List[str] = []
                    for item in stmt.items:
                        lk = lock_of(item.context_expr)
                        if lk is not None:
                            events.acquisitions.append(Acquire(
                                mname, lk, held | frozenset(add),
                                item.context_expr))
                            add.append(lk)
                        else:
                            record(item.context_expr, held | frozenset(add))
                            if item.optional_vars is not None:
                                record(item.optional_vars,
                                       held | frozenset(add))
                    walk(stmt.body, held | frozenset(add))
                    continue
                if isinstance(stmt, ast.Expr) \
                        and isinstance(stmt.value, ast.Call):
                    d = dotted_name(stmt.value.func) or ""
                    if d.endswith(".acquire") or d.endswith(".release"):
                        func = stmt.value.func
                        lk = lock_of(func.value) \
                            if isinstance(func, ast.Attribute) else None
                        if lk is not None:
                            if d.endswith(".acquire"):
                                events.acquisitions.append(Acquire(
                                    mname, lk, held, stmt.value))
                                held = held | {lk}
                            else:
                                held = held - {lk}
                            continue
                if isinstance(stmt, (ast.If, ast.While)):
                    record(stmt.test, held)
                    walk(stmt.body, held)
                    walk(stmt.orelse, held)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    record(stmt.iter, held)
                    record(stmt.target, held)
                    walk(stmt.body, held)
                    walk(stmt.orelse, held)
                elif isinstance(stmt, ast.Try):
                    walk(stmt.body, held)
                    for h in stmt.handlers:
                        walk(h.body, held)
                    walk(stmt.orelse, held)
                    walk(stmt.finalbody, held)
                else:
                    record(stmt, held)

        walk(fn.body, frozenset())
        return events

    # -- context propagation ----------------------------------------------

    def _propagate(self, cls: ClassConc) -> None:
        callers: Dict[str, List[CallEv]] = {}
        for mname, ev in cls.raw.items():
            for call in ev.calls:
                parts = call.dotted.split(".")
                if parts[0] == "self" and len(parts) == 2 \
                        and parts[1] in cls.methods:
                    callers.setdefault(parts[1], []).append(call)
        entries = set()
        for mname in cls.methods:
            is_dunder = mname.startswith("__") and mname.endswith("__")
            # thread_ENTRIES (direct spawn targets / do_* handlers) seed a
            # zero-held context; thread-side helpers reached only through
            # locked call sites inherit their callers' held sets instead
            # of being falsely seeded lock-free
            if (not mname.startswith("_") or is_dunder
                    or mname in cls.thread_entries
                    or mname not in callers):
                entries.add(mname)
        cls.contexts = {m: {} for m in cls.methods}
        work: List[Tuple[str, FrozenSet[str], Optional[ast.AST]]] = [
            (m, frozenset(), None) for m in sorted(entries)]
        while work:
            mname, ctx, site = work.pop()
            if ctx in cls.contexts[mname]:
                continue
            cls.contexts[mname][ctx] = site
            for call in cls.raw[mname].calls:
                parts = call.dotted.split(".")
                if parts[0] == "self" and len(parts) == 2 \
                        and parts[1] in cls.methods:
                    work.append((parts[1], frozenset(ctx | call.held),
                                 call.node))

        seen_acc: Set[tuple] = set()
        seen_dbl: Set[tuple] = set()
        for mname, contexts in cls.contexts.items():
            ev = cls.raw[mname]
            for ctx, site in sorted(contexts.items(),
                                    key=lambda kv: sorted(kv[0])):
                for a in ev.accesses:
                    eff = frozenset(ctx | a.held)
                    key = (a.method, a.attr, a.write, a.line, eff)
                    if key in seen_acc:
                        continue
                    seen_acc.add(key)
                    cls.eff_accesses.setdefault(a.attr, []).append(
                        Access(a.method, a.attr, a.write, a.line, eff))
                for call in ev.calls:
                    cls.eff_calls.append(CallEv(
                        call.method, call.node, call.dotted,
                        frozenset(ctx | call.held)))
                for acq in ev.acquisitions:
                    before = ctx | acq.held
                    if acq.lock in before \
                            and cls.locks.get(acq.lock) == "lock":
                        # re-acquiring a non-reentrant Lock: report at the
                        # call that carried the lock in (clearer than the
                        # inner with), or locally for with-inside-with
                        at = acq.node if acq.lock in acq.held \
                            else (site or acq.node)
                        key = (at.lineno, acq.lock)
                        if key not in seen_dbl:
                            seen_dbl.add(key)
                            cls.double_acquires.append((at, acq.lock))

    # -- lock-ordering edges -----------------------------------------------

    def _build_edges(self) -> None:
        for (path, cname), cls in sorted(self.classes.items()):
            key = (path, cname)
            lock_names = set(cls.locks)
            # intra-class nesting
            for mname, contexts in cls.contexts.items():
                for ctx in contexts:
                    for acq in cls.raw[mname].acquisitions:
                        if acq.lock not in lock_names:
                            continue
                        for x in sorted((ctx | acq.held) & lock_names):
                            if x != acq.lock:
                                self.lock_edges.append(LockEdge(
                                    (key, x), (key, acq.lock),
                                    acq.node, path))
            # cross-class: a call made while holding one of our locks into
            # a method (of a resolvable instance) that acquires its own
            for call in cls.eff_calls:
                held_self = sorted(call.held & lock_names)
                if not held_self:
                    continue
                target = self._resolve_instance_method(cls, call.dotted)
                if target is None:
                    continue
                t_cls, t_method = target
                for y in self._acquired_locks(t_cls, t_method):
                    for x in held_self:
                        self.lock_edges.append(LockEdge(
                            (key, x), ((t_cls.path, t_cls.name), y),
                            call.node, path))

    def _acquired_locks(self, cls: ClassConc, method: str,
                        depth: int = 0,
                        _seen: Optional[Set[str]] = None) -> List[str]:
        """Self-lock names a method (transitively) acquires."""
        if _seen is None:
            _seen = set()
        if method in _seen or depth > 3:
            return []
        _seen.add(method)
        out: Set[str] = set()
        ev = cls.raw.get(method)
        if ev is None:
            return []
        for acq in ev.acquisitions:
            if acq.lock in cls.locks:
                out.add(acq.lock)
        for call in ev.calls:
            parts = call.dotted.split(".")
            if parts[0] == "self" and len(parts) == 2 \
                    and parts[1] in cls.methods:
                out.update(self._acquired_locks(cls, parts[1], depth + 1,
                                                _seen))
        return sorted(out)

    def _resolve_instance_method(self, cls: ClassConc, dotted: str
                                 ) -> Optional[Tuple[ClassConc, str]]:
        parts = dotted.split(".")
        target_cls: Optional[ClassConc] = None
        method: Optional[str] = None
        if parts[0] == "self" and len(parts) == 3:
            ctor = self._self_field_ctor(cls, parts[1])
            if ctor is not None:
                target_cls = self._resolve_class(cls.path, ctor)
            method = parts[2]
        elif len(parts) == 2:
            ctor = self._module_instance_ctor(cls.path, parts[0])
            if ctor is not None:
                target_cls = self._resolve_class(ctor[0], ctor[1])
            method = parts[1]
        if target_cls is None or method is None \
                or method not in target_cls.methods:
            return None
        return target_cls, method

    def _self_field_ctor(self, cls: ClassConc, field: str) -> Optional[str]:
        key = (cls.path, cls.name, field)
        if key in self._ctor_memo:
            return self._ctor_memo[key]
        got = self._self_field_ctor_uncached(cls, field)
        self._ctor_memo[key] = got
        return got

    def _self_field_ctor_uncached(self, cls: ClassConc,
                                  field: str) -> Optional[str]:
        methods = sorted(cls.methods.values(),
                         key=lambda m: m.name != "__init__")
        for m in methods:
            for node in walk_scope(m):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and _self_attr(node.targets[0]) == field \
                        and isinstance(node.value, ast.Call):
                    d = dotted_name(node.value.func)
                    if d is not None and "." not in d:
                        return d
        return None

    def _module_instance_ctor(self, path: str, name: str,
                              _seen: Optional[Set[Tuple[str, str]]] = None
                              ) -> Optional[Tuple[str, str]]:
        """(module, ctor name) for a module-level ``NAME = Ctor()``,
        following import hops (cycle-safe: circular re-exports resolve
        to None, trusted)."""
        if _seen is None:
            _seen = set()
        if (path, name) in _seen:
            return None
        _seen.add((path, name))
        model = self.program.modules.get(path)
        if model is not None:
            for node in model.tree.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id == name \
                        and isinstance(node.value, ast.Call):
                    d = dotted_name(node.value.func)
                    if d is not None and "." not in d:
                        return path, d
        imp = self.program.imports(path).get(name)
        if imp is not None and imp[0] is not None and imp[1]:
            return self._module_instance_ctor(imp[0], imp[1], _seen)
        return None

    def _resolve_class(self, path: str, name: str) -> Optional[ClassConc]:
        got = self.classes.get((path, name))
        if got is not None:
            return got
        imp = self.program.imports(path).get(name)
        if imp is not None and imp[0] is not None:
            return self.classes.get((imp[0], imp[1]))
        return None


def get_model(program: ProgramModel) -> ConcurrencyModel:
    """One ConcurrencyModel per ProgramModel (the runner builds one program
    per scan; all four concurrency rules share the model)."""
    model = getattr(program, "_graftcheck_concurrency", None)
    if model is None:
        model = ConcurrencyModel(program)
        program._graftcheck_concurrency = model
    return model


def in_g013_scope(path: str, model: Optional[ModuleModel]) -> bool:
    """G013 runs on the serving hot path plus opted-in modules."""
    if path.startswith(config.CONCURRENCY_HOT_PREFIXES):
        return True
    return model is not None and config.CONCURRENCY_MARKER in model.source
