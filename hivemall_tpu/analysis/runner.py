"""File walking + rule dispatch + diagnostics formatting.

Stdlib-only and jax-free by design: a full-tree scan must stay in the ~5s
budget of scripts/lint.sh, and graftcheck must be runnable on hosts
without an accelerator stack.

Two rule tiers run over every scan:

- **module rules** (G001–G006, G009) see one ModuleModel at a time;
- **program rules** (G007/G008/G010/G011) see the whole-program model
  (program.py), which is always built with the full package tree as
  context — a single-file scan resolves cross-module call edges exactly
  like a full scan, but only *emits* findings for the scanned files.
"""

from __future__ import annotations

import ast
import os
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence

from .findings import (Finding, Severity, apply_suppressions,
                       parse_suppressions, sort_findings)
from .modmodel import ModuleModel
from .program import ProgramModel

_SKIP_DIRS = {"__pycache__", ".git", ".mypy_cache", ".pytest_cache"}


def normalize_path(path: str) -> str:
    """Stable repo-relative path: anchored at the `hivemall_tpu` package
    when the file lives inside it (so baselines don't depend on the
    checkout location), else relative to cwd, else absolute."""
    ap = os.path.abspath(path)
    parts = Path(ap).parts
    if "hivemall_tpu" in parts:
        idx = len(parts) - 1 - tuple(reversed(parts)).index("hivemall_tpu")
        return "/".join(parts[idx:])
    rp = os.path.relpath(ap)
    if not rp.startswith(".."):
        return rp.replace(os.sep, "/")
    return ap.replace(os.sep, "/")


def iter_python_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)
        elif p.endswith(".py"):
            yield p


def default_jobs() -> int:
    return min(4, os.cpu_count() or 1)


def _run_rules(models: Dict[str, ModuleModel],
               parse_failures: List[Finding],
               sources: Dict[str, str],
               rules: Optional[Sequence[str]] = None,
               jobs: Optional[int] = None) -> List[Finding]:
    """Module rules per model + program rules over the whole set, then
    per-file suppressions.

    Module rules are independent per file, so with ``jobs > 1`` they run
    on a thread pool (default ``min(4, cpus)``). Results are collected
    per file in the submission order and the whole set goes through
    ``sort_findings`` at the end, so finding order — and therefore
    baseline and SARIF fingerprint stability — is identical to a serial
    run. Program rules share one mutable ProgramModel (memoized
    summaries, lazily-built concurrency/exception models) and stay
    serial."""
    from .rules import ALL_RULES, PROGRAM_RULES

    selected_module_rules = [
        (rule_id, check) for rule_id, check in ALL_RULES.items()
        if rules is None or rule_id in rules]

    def module_findings(model: ModuleModel) -> List[Finding]:
        out: List[Finding] = []
        for _rule_id, check in selected_module_rules:
            out.extend(check(model))
        return out

    findings: List[Finding] = list(parse_failures)
    jobs = default_jobs() if jobs is None else max(1, jobs)
    if jobs > 1 and len(models) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=jobs) as pool:
            for per_file in pool.map(module_findings, models.values()):
                findings.extend(per_file)
    else:
        for model in models.values():
            findings.extend(module_findings(model))
    selected_program_rules = [
        (rule_id, check_program)
        for rule_id, check_program in PROGRAM_RULES.items()
        if rules is None or rule_id in rules]
    if selected_program_rules:  # skip the package parse when filtered out
        program = ProgramModel(models)
        scanned = set(models)
        for rule_id, check_program in selected_program_rules:
            findings.extend(f for f in check_program(program, scanned)
                            if f.path in scanned)
    out: List[Finding] = []
    suppressions = {p: parse_suppressions(src) for p, src in sources.items()}
    by_path: Dict[str, List[Finding]] = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    for p, flist in by_path.items():
        if p in suppressions:
            per_line, whole_file = suppressions[p]
            out.extend(apply_suppressions(flist, per_line, whole_file))
        else:
            out.extend(flist)
    return sort_findings(out)


def analyze_source(source: str, rel_path: str,
                   rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run graftcheck over one module's source. `rel_path` is the
    normalized path used for scope decisions (hot modules, dtype modules)
    and reporting."""
    try:
        model = ModuleModel(rel_path, source, ast.parse(source,
                                                        filename=rel_path))
    except SyntaxError as e:
        return [Finding(rel_path, e.lineno or 0, "G000", Severity.ERROR,
                        f"syntax error: {e.msg}", "")]
    return _run_rules({rel_path: model}, [], {rel_path: source}, rules)


def analyze_paths(paths: Sequence[str],
                  rules: Optional[Sequence[str]] = None,
                  jobs: Optional[int] = None) -> List[Finding]:
    from . import modelcache
    models: Dict[str, ModuleModel] = {}
    sources: Dict[str, str] = {}
    parse_failures: List[Finding] = []
    for path in iter_python_files(paths):
        rel = normalize_path(path)
        model = modelcache.cached_model(path, rel)
        if model is not None:
            # shared cache hit/build: package-context and scanned models
            # are the SAME objects, so per-module analysis memos persist
            # across scans instead of being rebuilt per analyze_paths call
            models[rel] = model
            sources[rel] = model.source
            continue
        # unreadable or unparsable: re-read for the precise G000 message
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
        except OSError as e:
            parse_failures.append(Finding(rel, 0, "G000", Severity.ERROR,
                                          f"unreadable: {e}", ""))
            continue
        try:
            models[rel] = ModuleModel(rel, source,
                                      ast.parse(source, filename=rel))
            sources[rel] = source
        except SyntaxError as e:
            parse_failures.append(Finding(rel, e.lineno or 0, "G000",
                                          Severity.ERROR,
                                          f"syntax error: {e.msg}", ""))
    findings = _run_rules(models, parse_failures, sources, rules, jobs)
    modelcache.save()
    return findings


def expand_to_callers(paths: Sequence[str]) -> List[str]:
    """The scanned set plus every package module that (transitively)
    imports one of the scanned modules — interprocedural rules can fire in
    an unchanged caller when its callee changed, so changed-files scans
    must include the callers. Returns filesystem paths; non-package inputs
    pass through untouched."""
    file_list = list(iter_python_files(paths))
    rel_of = {normalize_path(p): p for p in file_list}
    program = ProgramModel({}, with_package_context=True)
    targets = {r for r in rel_of if r in program.modules}
    if not targets:
        return file_list
    from .program import package_root
    root = os.path.dirname(package_root())
    extra = []
    for rel in sorted(program.importers_of(targets)):
        if rel in rel_of:
            continue
        fs = os.path.join(root, *rel.split("/"))
        if os.path.exists(fs):
            extra.append(fs)
    return file_list + extra
