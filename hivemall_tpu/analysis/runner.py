"""File walking + rule dispatch + diagnostics formatting.

Stdlib-only and jax-free by design: a full-tree scan must stay well under
the 5s budget of scripts/lint.sh, and graftcheck must be runnable on hosts
without an accelerator stack.
"""

from __future__ import annotations

import ast
import os
from pathlib import Path
from typing import Iterable, List, Optional, Sequence

from .findings import (Finding, Severity, apply_suppressions,
                       parse_suppressions, sort_findings)
from .modmodel import ModuleModel

_SKIP_DIRS = {"__pycache__", ".git", ".mypy_cache", ".pytest_cache"}


def normalize_path(path: str) -> str:
    """Stable repo-relative path: anchored at the `hivemall_tpu` package
    when the file lives inside it (so baselines don't depend on the
    checkout location), else relative to cwd, else absolute."""
    ap = os.path.abspath(path)
    parts = Path(ap).parts
    if "hivemall_tpu" in parts:
        idx = len(parts) - 1 - tuple(reversed(parts)).index("hivemall_tpu")
        return "/".join(parts[idx:])
    rp = os.path.relpath(ap)
    if not rp.startswith(".."):
        return rp.replace(os.sep, "/")
    return ap.replace(os.sep, "/")


def iter_python_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)
        elif p.endswith(".py"):
            yield p


def analyze_source(source: str, rel_path: str,
                   rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run graftcheck over one module's source. `rel_path` is the
    normalized path used for scope decisions (hot modules, dtype modules)
    and reporting."""
    from .rules import ALL_RULES

    try:
        model = ModuleModel(rel_path, source, ast.parse(source,
                                                        filename=rel_path))
    except SyntaxError as e:
        return [Finding(rel_path, e.lineno or 0, "G000", Severity.ERROR,
                        f"syntax error: {e.msg}", "")]
    findings: List[Finding] = []
    for rule_id, check in ALL_RULES.items():
        if rules is not None and rule_id not in rules:
            continue
        findings.extend(check(model))
    per_line, whole_file = parse_suppressions(source)
    return sort_findings(apply_suppressions(findings, per_line, whole_file))


def analyze_paths(paths: Sequence[str],
                  rules: Optional[Sequence[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
        except OSError as e:
            findings.append(Finding(normalize_path(path), 0, "G000",
                                    Severity.ERROR, f"unreadable: {e}", ""))
            continue
        findings.extend(analyze_source(source, normalize_path(path),
                                       rules=rules))
    return sort_findings(findings)
