"""FFI-boundary model: ctypes bindings, foreign calls, pointer provenance,
and the lightweight C declaration scanner (v5).

PR 14 put a C++ backend on the training hot path behind a frozen ctypes
ABI — the one boundary the AST rules could not see: a wrong-dtype pointer
or a dropped temporary there is silent memory corruption, not a Python
traceback. This module gives the G022-G026 rules, stdlib-only:

- per-module **foreign-call discovery**: every ``ast.Call`` whose dotted
  callee tail carries a native-symbol prefix (``hm_*``), in modules that
  mention ctypes, with the enclosing function attached;
- the **declaration map**: ``lib.hm_x.argtypes = [...]`` /
  ``lib.hm_x.restype = ...`` assignments anywhere in the module, with the
  argtype list statically evaluated (``[c_void_p] * 3 + [...]`` included)
  into width-class kinds (``ptr``/``i8``..``i64``/``f32``/``f64``);
- **pointer-argument extraction**: ``x.ctypes.data_as(...)`` /
  ``x.ctypes.data`` / local ``as_p = lambda a: a.ctypes.data_as(...)``
  aliases, unwrapped through ``IfExp`` branches, classified by base kind
  (named binding, const-keyed subscript, slice/transpose view,
  expression temporary, inline-validated coercion);
- the **validation engine**: whether a pointer base is dominated by a
  dtype+contiguity proof — ``np.ascontiguousarray(..., dtype=...)``,
  fresh dtype-pinned constructors, ``.astype`` copies, a sanctioning
  validator (``plan_abi_arrays``), an explicit
  ``dtype``+``C_CONTIGUOUS`` guard statement, or (interprocedurally) a
  helper whose every return validates;
- the **C declaration scanner**: the exported ``hm_*`` signatures and the
  ``HM_PLAN_ABI_VERSION`` literal parsed out of
  ``native/hivemall_native.cpp`` (comment-stripped, newline-preserving,
  balanced-paren parameter split) so G025 can cross-check
  arity/pointer-ness/int-width per argument and the version literal —
  the frozen-ABI contract made machine-checkable.

Everything dynamic (pointers from opaque helpers, symbols absent from the
C source) is trusted, exactly like the SPMD rules trust dynamic axis
names.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from . import config
from .modmodel import ModuleModel, _FN_TYPES, dotted_name, walk_scope
from .program import ProgramModel, package_root

_MAX_VALIDATION_DEPTH = 3

# ctypes spelling -> ABI width class ("kind"): pointers collapse to "ptr",
# ints/floats to their width; anything else is "other" (never compared).
CTYPES_KIND = {
    "c_void_p": "ptr", "c_char_p": "ptr", "c_wchar_p": "ptr",
    "c_bool": "i8", "c_int8": "i8", "c_uint8": "i8",
    "c_byte": "i8", "c_ubyte": "i8", "c_char": "i8",
    "c_int16": "i16", "c_uint16": "i16", "c_short": "i16", "c_ushort": "i16",
    "c_int32": "i32", "c_uint32": "i32", "c_int": "i32", "c_uint": "i32",
    "c_int64": "i64", "c_uint64": "i64", "c_longlong": "i64",
    "c_ulonglong": "i64", "c_size_t": "i64", "c_ssize_t": "i64",
    "c_float": "f32", "c_double": "f64",
}

# C scalar type -> the same width classes (LP64: long == 64-bit).
C_KIND = {
    "void": "void",
    "bool": "i8", "char": "i8", "int8_t": "i8", "uint8_t": "i8",
    "int16_t": "i16", "uint16_t": "i16", "short": "i16",
    "int32_t": "i32", "uint32_t": "i32", "int": "i32", "unsigned": "i32",
    "int64_t": "i64", "uint64_t": "i64", "size_t": "i64", "ssize_t": "i64",
    "long": "i64", "intptr_t": "i64", "uintptr_t": "i64",
    "float": "f32", "double": "f64",
}

_KIND_DESC = {"ptr": "a pointer", "void": "void", "i8": "an 8-bit int",
              "i16": "a 16-bit int", "i32": "a 32-bit int",
              "i64": "a 64-bit int", "f32": "a 32-bit float",
              "f64": "a 64-bit float"}


def describe_kind(kind: Optional[str]) -> str:
    return _KIND_DESC.get(kind or "", "an unknown type")


# --------------------------------------------------------------------------
# C declaration scanner
# --------------------------------------------------------------------------

class CParam:
    __slots__ = ("kind", "const", "text")

    def __init__(self, kind: str, const: bool, text: str):
        self.kind = kind
        self.const = const
        self.text = text


class CSig:
    __slots__ = ("name", "line", "ret", "params")

    def __init__(self, name: str, line: int, ret: str,
                 params: List[CParam]):
        self.name = name
        self.line = line
        self.ret = ret
        self.params = params


class CDecls:
    """What G025 needs from the C side: exported signatures + the plan ABI
    version literal, with display-path and per-item line numbers for the
    cross-file SARIF locations."""

    __slots__ = ("display_path", "lines", "sigs", "abi_version",
                 "abi_version_line")

    def __init__(self, display_path: str, lines: List[str]):
        self.display_path = display_path
        self.lines = lines
        self.sigs: Dict[str, CSig] = {}
        self.abi_version: Optional[int] = None
        self.abi_version_line: int = 0

    def snippet(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


def native_cpp_path() -> Optional[str]:
    """Filesystem path of the native C++ source, or None when absent.
    ``GRAFTCHECK_NATIVE_CPP`` overrides the repo-root default (the seeded
    ABI-drift tests point it at a tempdir copy)."""
    override = os.environ.get(config.FFI_NATIVE_CPP_ENV)
    if override:
        return override if os.path.isfile(override) else None
    cand = os.path.join(os.path.dirname(package_root()),
                        *config.FFI_NATIVE_CPP_DEFAULT.split("/"))
    return cand if os.path.isfile(cand) else None


def _display_path(path: str) -> str:
    repo = os.path.dirname(package_root())
    ap = os.path.abspath(path)
    if ap.startswith(repo + os.sep):
        return os.path.relpath(ap, repo).replace(os.sep, "/")
    return path.replace(os.sep, "/")


def _strip_comments(text: str) -> str:
    """Blank out ``//`` and ``/* */`` comments and string literals,
    preserving every newline so line numbers survive."""
    out: List[str] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "/" and i + 1 < n and text[i + 1] == "/":
            while i < n and text[i] != "\n":
                i += 1
        elif c == "/" and i + 1 < n and text[i + 1] == "*":
            i += 2
            while i + 1 < n and not (text[i] == "*" and text[i + 1] == "/"):
                if text[i] == "\n":
                    out.append("\n")
                i += 1
            i += 2
        elif c in "\"'":
            quote = c
            i += 1
            while i < n and text[i] != quote:
                if text[i] == "\\":
                    i += 1
                i += 1
            i += 1
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _c_param(text: str) -> Optional[CParam]:
    toks = text.replace("*", " * ").split()
    if not toks or toks == ["void"]:
        return None
    if "*" in toks:
        return CParam("ptr", "const" in toks, text.strip())
    base = next((t for t in toks if t not in ("const", "unsigned", "signed",
                                              "struct", "enum")), "")
    if base == "" and "unsigned" in toks:
        base = "unsigned"
    return CParam(C_KIND.get(base, "other"), "const" in toks, text.strip())


def _split_params(src: str) -> List[str]:
    parts: List[str] = []
    depth, start = 0, 0
    for i, c in enumerate(src):
        if c in "(<[":
            depth += 1
        elif c in ")>]":
            depth -= 1
        elif c == "," and depth == 0:
            parts.append(src[start:i])
            start = i + 1
    parts.append(src[start:])
    return [p for p in parts if p.strip()]


_VERSION_RE = re.compile(r"HM_PLAN_ABI_VERSION\s*=\s*(\d+)")

_CPP_CACHE: Dict[str, Tuple[float, int, Optional[CDecls]]] = {}


def scan_native_decls(path: Optional[str] = None) -> Optional[CDecls]:
    """Parse the exported ``hm_*`` function definitions (and the plan ABI
    version literal) out of the C++ source. Definitions only: a matched
    name must be followed by a balanced parameter list and an opening
    brace, so call sites inside other bodies never register. mtime-cached
    per path."""
    if path is None:
        path = native_cpp_path()
    if path is None:
        return None
    ap = os.path.abspath(path)
    try:
        st = os.stat(ap)
    except OSError:
        return None
    cached = _CPP_CACHE.get(ap)
    if cached is not None and cached[0] == st.st_mtime \
            and cached[1] == st.st_size:
        return cached[2]
    try:
        with open(ap, "r", encoding="utf-8", errors="replace") as fh:
            text = fh.read()
    except OSError:
        _CPP_CACHE[ap] = (st.st_mtime, st.st_size, None)
        return None
    decls = CDecls(_display_path(path), text.splitlines())
    stripped = _strip_comments(text)
    vm = _VERSION_RE.search(stripped)
    if vm:
        decls.abi_version = int(vm.group(1))
        decls.abi_version_line = stripped[:vm.start()].count("\n") + 1
    prefixes = tuple(config.FFI_SYMBOL_PREFIXES)
    for m in re.finditer(r"\b([A-Za-z_][A-Za-z0-9_]*)\s*\(", stripped):
        name = m.group(1)
        if not name.startswith(prefixes):
            continue
        depth, i = 0, m.end() - 1
        while i < len(stripped):
            if stripped[i] == "(":
                depth += 1
            elif stripped[i] == ")":
                depth -= 1
                if depth == 0:
                    break
            i += 1
        if i >= len(stripped):
            continue
        j = i + 1
        while j < len(stripped) and stripped[j] in " \t\r\n":
            j += 1
        if j >= len(stripped) or stripped[j] != "{":
            continue  # a call site or a bare prototype, not the definition
        head = re.split(r"[;{}()]", stripped[:m.start()])[-1]
        ret_toks = head.replace("*", " * ").split()
        if not ret_toks:
            continue
        ret = "ptr" if "*" in ret_toks else C_KIND.get(
            next((t for t in ret_toks
                  if t not in ("const", "unsigned", "signed", "static",
                               "inline", "extern")), ""), "other")
        params = []
        for p in _split_params(stripped[m.end():i]):
            cp = _c_param(p)
            if cp is not None:
                params.append(cp)
        line = stripped[:m.start()].count("\n") + 1
        decls.sigs[name] = CSig(name, line, ret, params)
    _CPP_CACHE[ap] = (st.st_mtime, st.st_size, decls)
    return decls


# --------------------------------------------------------------------------
# Python-side binding model
# --------------------------------------------------------------------------

class PyDecl:
    """argtypes/restype declarations observed for one symbol in one
    module."""

    __slots__ = ("symbol", "argtypes_node", "argtypes_line", "argtypes_src",
                 "argtypes_kinds", "restype_node", "restype_line",
                 "restype_src", "restype_kind")

    def __init__(self, symbol: str):
        self.symbol = symbol
        self.argtypes_node: Optional[ast.Assign] = None
        self.argtypes_line = 0
        self.argtypes_src = ""
        self.argtypes_kinds: Optional[List[str]] = None
        self.restype_node: Optional[ast.Assign] = None
        self.restype_line = 0
        self.restype_src = ""
        self.restype_kind: Optional[str] = None


class ForeignCall:
    """One call crossing the FFI: ``lib.hm_x(...)`` with its enclosing
    function (None at module level)."""

    __slots__ = ("node", "symbol", "fn")

    def __init__(self, node: ast.Call, symbol: str, fn: Optional[ast.AST]):
        self.node = node
        self.symbol = symbol
        self.fn = fn


class PtrArg:
    """One pointer-valued argument of a foreign call: the base array
    expression under ``.ctypes.data_as`` / ``.ctypes.data`` / an ``as_p``
    alias, plus its classification (see base_kind)."""

    __slots__ = ("index", "arg", "base", "via", "kind")

    def __init__(self, index: int, arg: ast.expr, base: ast.expr, via: str,
                 kind: str):
        self.index = index
        self.arg = arg
        self.base = base
        self.via = via
        self.kind = kind


class ModuleFFI:
    __slots__ = ("decls", "calls", "asp_names")

    def __init__(self):
        self.decls: Dict[str, PyDecl] = {}
        self.calls: List[ForeignCall] = []
        # (enclosing fn or None, name) of `as_p = lambda a: a.ctypes...`
        self.asp_names: Set[Tuple[Optional[ast.AST], str]] = set()


class FFIModel:
    __slots__ = ("modules",)

    def __init__(self):
        self.modules: Dict[str, ModuleFFI] = {}

    def all_decls(self) -> Dict[str, PyDecl]:
        out: Dict[str, PyDecl] = {}
        for mod in self.modules.values():
            out.update(mod.decls)
        return out


def foreign_symbol(dotted: Optional[str]) -> Optional[str]:
    """The native symbol name of a dotted callee (``lib.hm_x`` ->
    ``hm_x``), or None when the tail carries no native prefix."""
    if not dotted:
        return None
    tail = dotted.rsplit(".", 1)[-1]
    if tail.startswith(tuple(config.FFI_SYMBOL_PREFIXES)):
        return tail
    return None


def _eval_argtypes(expr: ast.expr) -> Optional[List[str]]:
    """[c_void_p] * 3 + [c_int64, POINTER(c_float)] -> kinds; None when the
    expression is not statically a list."""
    if isinstance(expr, ast.List):
        return [_elt_kind(e) for e in expr.elts]
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left = _eval_argtypes(expr.left)
        right = _eval_argtypes(expr.right)
        if left is not None and right is not None:
            return left + right
        return None
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Mult):
        for lst, num in ((expr.left, expr.right), (expr.right, expr.left)):
            kinds = _eval_argtypes(lst)
            if kinds is not None and isinstance(num, ast.Constant) \
                    and isinstance(num.value, int):
                return kinds * num.value
        return None
    return None


def _elt_kind(expr: ast.expr) -> str:
    if isinstance(expr, ast.Call):
        callee = dotted_name(expr.func) or ""
        if callee.rsplit(".", 1)[-1] == "POINTER":
            return "ptr"
        return "other"
    d = dotted_name(expr) or ""
    return CTYPES_KIND.get(d.rsplit(".", 1)[-1], "other")


def _restype_kind(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Constant) and expr.value is None:
        return "void"
    d = dotted_name(expr) or ""
    return CTYPES_KIND.get(d.rsplit(".", 1)[-1])


def _is_asp_lambda(value: ast.expr) -> bool:
    """``lambda a: a.ctypes.data_as(...)`` (optionally behind an IfExp
    None-guard) — the repo's pointer-shorthand idiom."""
    if not isinstance(value, ast.Lambda) or not value.args.args:
        return False
    param = value.args.args[0].arg
    body = value.body
    if isinstance(body, ast.IfExp):
        body = body.body
    got = _match_pointer_expr(body, set(), None)
    return got is not None and isinstance(got[0], ast.Name) \
        and got[0].id == param


def get_ffi(program: ProgramModel) -> FFIModel:
    cached = getattr(program, "_graftcheck_ffi", None)
    if cached is not None:
        return cached
    ffi = FFIModel()
    for path, model in program.modules.items():
        # the ModuleFFI is a pure per-module product: cache it on the
        # ModuleModel (False = scanned, nothing foreign) so repeated
        # in-process scans skip the AST walk entirely
        mod = getattr(model, "_graftcheck_ffi_mod", None)
        if mod is not None:
            if mod is not False:
                ffi.modules[path] = mod
            continue
        mod = _build_module_ffi(model)
        model._graftcheck_ffi_mod = mod if mod is not None else False  # type: ignore[attr-defined]
        if mod is not None:
            ffi.modules[path] = mod
    program._graftcheck_ffi = ffi  # type: ignore[attr-defined]
    return ffi


def _build_module_ffi(model) -> Optional[ModuleFFI]:
    if "ctypes" not in model.source:
        return None
    mod = ModuleFFI()
    for node in ast.walk(model.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and _is_asp_lambda(node.value):
            mod.asp_names.add((model.enclosing_function(node),
                               node.targets[0].id))
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Attribute):
            tgt = node.targets[0]
            if tgt.attr not in ("argtypes", "restype"):
                continue
            sym = None
            if isinstance(tgt.value, ast.Attribute):
                if tgt.value.attr.startswith(
                        tuple(config.FFI_SYMBOL_PREFIXES)):
                    sym = tgt.value.attr
            if sym is None:
                continue
            decl = mod.decls.setdefault(sym, PyDecl(sym))
            src = ast.get_source_segment(model.source, tgt) or ""
            if tgt.attr == "argtypes":
                decl.argtypes_node = node
                decl.argtypes_line = node.lineno
                decl.argtypes_src = src
                decl.argtypes_kinds = _eval_argtypes(node.value)
            else:
                decl.restype_node = node
                decl.restype_line = node.lineno
                decl.restype_src = src
                decl.restype_kind = _restype_kind(node.value)
        elif isinstance(node, ast.Call):
            sym = foreign_symbol(dotted_name(node.func))
            if sym is not None:
                mod.calls.append(ForeignCall(
                    node, sym, model.enclosing_function(node)))
    return mod if (mod.decls or mod.calls) else None


# --------------------------------------------------------------------------
# pointer-argument extraction + base classification
# --------------------------------------------------------------------------

def _match_pointer_expr(expr: ast.expr,
                        asp_names: Set[Tuple[Optional[ast.AST], str]],
                        fn: Optional[ast.AST]
                        ) -> Optional[Tuple[ast.expr, str]]:
    """(base array expr, via) when `expr` produces a raw pointer/address
    from a numpy array; None otherwise."""
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute) \
            and expr.func.attr == "data_as" \
            and isinstance(expr.func.value, ast.Attribute) \
            and expr.func.value.attr == "ctypes":
        return expr.func.value.value, "data_as"
    if isinstance(expr, ast.Attribute) and expr.attr == "data" \
            and isinstance(expr.value, ast.Attribute) \
            and expr.value.attr == "ctypes":
        return expr.value.value, "data"
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name) \
            and len(expr.args) == 1 and not expr.keywords:
        scope: Optional[ast.AST] = fn
        while True:
            if (scope, expr.func.id) in asp_names:
                return expr.args[0], "as_p"
            if scope is None:
                return None
            scope = getattr(scope, "graftcheck_parent", None)
            while scope is not None and not isinstance(scope, _FN_TYPES):
                scope = getattr(scope, "graftcheck_parent", None)
    return None


def _unwrap_ifexp(expr: ast.expr) -> List[ast.expr]:
    if isinstance(expr, ast.IfExp):
        return _unwrap_ifexp(expr.body) + _unwrap_ifexp(expr.orelse)
    return [expr]


def pointer_args(program: ProgramModel, path: str, mod: ModuleFFI,
                 fc: ForeignCall) -> List[PtrArg]:
    model = program.modules[path]
    out: List[PtrArg] = []
    exprs = [(i, a) for i, a in enumerate(fc.node.args)]
    exprs += [(-1, kw.value) for kw in fc.node.keywords]
    for i, arg in exprs:
        for branch in _unwrap_ifexp(arg):
            got = _match_pointer_expr(branch, mod.asp_names, fc.fn)
            if got is None:
                continue
            base, via = got
            kind = base_kind(program, path, model, fc.fn, base,
                             fc.node.lineno)
            out.append(PtrArg(i, arg, base, via, kind))
    return out


def _is_view_expr(expr: ast.expr) -> bool:
    if isinstance(expr, ast.Subscript):
        return any(isinstance(n, ast.Slice) for n in ast.walk(expr.slice))
    if isinstance(expr, ast.Attribute) and expr.attr == "T":
        return True
    if isinstance(expr, ast.Call):
        callee = dotted_name(expr.func) or ""
        return callee.rsplit(".", 1)[-1] in ("transpose", "swapaxes")
    return False


def base_kind(program: ProgramModel, path: str, model: ModuleModel,
              fn: Optional[ast.AST], base: ast.expr, before_line: int
              ) -> str:
    """Classify a pointer base expression:

    - ``name``: a plain named binding (lifetime held; G022 checks its
      validation);
    - ``namedsub``: a const-string-keyed subscript like ``state["w"]``
      (same treatment as a name, matched by source text);
    - ``view``: a slice / ``.T`` / ``transpose`` — non-owning,
      possibly non-contiguous (G023), including a name assigned one;
    - ``inline_ok``: a validated coercion built inline in the call
      argument (``np.ascontiguousarray(x, dtype=...)``) — safe;
    - ``temp``: any other expression temporary (G023).
    """
    if _is_view_expr(base):
        return "view"
    if isinstance(base, ast.Name):
        if fn is not None:
            rhs = _last_assignment(model, fn, base.id, before_line)
            if rhs is not None and _is_view_expr(rhs):
                return "view"
        return "name"
    if isinstance(base, ast.Subscript) and isinstance(base.value, ast.Name) \
            and isinstance(base.slice, ast.Constant) \
            and isinstance(base.slice.value, str):
        return "namedsub"
    if expr_validated(program, path, model, base, fn):
        return "inline_ok"
    return "temp"


def _last_assignment(model: ModuleModel, fn: ast.AST, name: str,
                     before_line: int) -> Optional[ast.expr]:
    found = None
    for node in walk_scope(fn):
        if isinstance(node, ast.Assign) and node.lineno < before_line:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    found = node.value
    return found


# --------------------------------------------------------------------------
# validation engine (G022)
# --------------------------------------------------------------------------

def _dotted_parts(expr: ast.expr) -> Tuple[str, str]:
    """(root, tail) of a callee. The tail falls back to the attribute name
    when the base is not a plain dotted chain (``np.concatenate(x)
    .astype(...)``: dotted_name can't render the call base, but the
    method tail is still ``astype``)."""
    d = dotted_name(expr) or ""
    root, tail = d.split(".", 1)[0], d.rsplit(".", 1)[-1]
    if not tail and isinstance(expr, ast.Attribute):
        tail = expr.attr
    return root, tail


def _has_kwarg(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


def _kwarg_is_false(call: ast.Call, name: str) -> bool:
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    return False


def _contains_astype(expr: ast.expr) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute) \
                and node.func.attr == "astype" and node.args:
            return True
    return False


def expr_validated(program: ProgramModel, path: str, model: ModuleModel,
                   expr: ast.expr, fn: Optional[ast.AST],
                   depth: int = 0) -> bool:
    """Does this expression produce a dtype-pinned, C-contiguous, freshly
    owned (or sanctioned) array?"""
    if depth > _MAX_VALIDATION_DEPTH:
        return False
    if isinstance(expr, ast.IfExp):
        return (expr_validated(program, path, model, expr.body, fn,
                               depth + 1)
                and expr_validated(program, path, model, expr.orelse, fn,
                                   depth + 1))
    if not isinstance(expr, ast.Call):
        return False
    root, tail = _dotted_parts(expr.func)
    if tail in config.FFI_SANCTIONING_VALIDATORS:
        return True
    if tail == "ascontiguousarray":
        if len(expr.args) >= 2 or _has_kwarg(expr, "dtype"):
            return True
        # ascontiguousarray(x.astype(dt, ...)): dtype pinned by the inner
        # astype, contiguity by the wrapper — validated even with
        # copy=False inside (astype always returns the requested dtype)
        return bool(expr.args) and _contains_astype(expr.args[0])
    if tail == "astype" and expr.args:
        # a fresh C-order copy with the requested dtype — unless
        # copy=False allowed the (possibly non-contiguous) original through
        return not _kwarg_is_false(expr, "copy")
    if root in ("np", "numpy"):
        if tail in config.FFI_FRESH_CTORS:
            return len(expr.args) >= 2 or _has_kwarg(expr, "dtype")
        if tail == "full":
            return len(expr.args) >= 3 or _has_kwarg(expr, "dtype")
        if tail == "array":
            return (len(expr.args) >= 2 or _has_kwarg(expr, "dtype")) \
                and not _kwarg_is_false(expr, "copy")
    if "." not in (dotted_name(expr.func) or "."):
        got = program.resolve_fn(path, tail, expr)
        if got is not None:
            return _returns_validated(program, got[0], got[1], None,
                                      depth + 1)
    return False


def _returns_validated(program: ProgramModel, path: str, fn: ast.AST,
                       pos: Optional[int], depth: int) -> bool:
    """Every return of `fn` (at tuple position `pos` when given) is a
    validated expression — the interprocedural hop that lets
    ``offsets`` from ``_pack_bytes()`` count as proven."""
    model = program.modules.get(path)
    if model is None or depth > _MAX_VALIDATION_DEPTH:
        return False
    returns = [n for n in walk_scope(fn) if isinstance(n, ast.Return)]
    if not returns:
        return False
    for ret in returns:
        value = ret.value
        if value is None:
            return False
        if pos is not None:
            if not isinstance(value, ast.Tuple) or pos >= len(value.elts):
                return False
            value = value.elts[pos]
        if isinstance(value, ast.Name):
            if not name_validated(program, path, model, fn, value.id,
                                  ret.lineno, depth + 1):
                return False
        elif not expr_validated(program, path, model, value, fn, depth):
            return False
    return True


def name_validated(program: ProgramModel, path: str, model: ModuleModel,
                   fn: Optional[ast.AST], name: str, before_line: int,
                   depth: int = 0) -> bool:
    """A named binding is validated when some statement before the use
    proves dtype+contiguity: a validating assignment (direct, through an
    IfExp, or unpacked from a sanctioning validator / an all-validating
    helper), or an explicit guard statement that mentions both ``dtype``
    and ``C_CONTIGUOUS`` and the name."""
    if fn is None or depth > _MAX_VALIDATION_DEPTH:
        return False
    for node in walk_scope(fn):
        if not isinstance(node, ast.stmt) or node.lineno >= before_line:
            continue
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    if expr_validated(program, path, model, node.value, fn,
                                      depth):
                        return True
                elif isinstance(tgt, ast.Tuple):
                    for i, elt in enumerate(tgt.elts):
                        if isinstance(elt, ast.Name) and elt.id == name:
                            if _unpack_validated(program, path, model, fn,
                                                 node.value, i, depth):
                                return True
        if _guard_statement_validates(model, node, name):
            return True
    return False


def _unpack_validated(program: ProgramModel, path: str, model: ModuleModel,
                      fn: ast.AST, value: ast.expr, pos: int,
                      depth: int) -> bool:
    if isinstance(value, ast.Tuple) and pos < len(value.elts):
        return expr_validated(program, path, model, value.elts[pos], fn,
                              depth)
    if not isinstance(value, ast.Call):
        return False
    root, tail = _dotted_parts(value.func)
    if tail in config.FFI_SANCTIONING_VALIDATORS:
        return True
    if "." not in (dotted_name(value.func) or "."):
        got = program.resolve_fn(path, tail, value)
        if got is not None:
            return _returns_validated(program, got[0], got[1], pos,
                                      depth + 1)
    return False


def _guard_statement_validates(model: ModuleModel, stmt: ast.stmt,
                               name: str) -> bool:
    """An explicit runtime guard — any statement whose source mentions both
    ``dtype`` and ``C_CONTIGUOUS`` and the name (the
    ``if t.dtype != dt or not t.flags["C_CONTIGUOUS"]: raise`` idiom,
    including table-driven loops over several arrays)."""
    end = getattr(stmt, "end_lineno", stmt.lineno)
    text = "\n".join(model.lines[stmt.lineno - 1:end])
    if "dtype" not in text or "C_CONTIGUOUS" not in text:
        return False
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(stmt))


def subscript_validated(model: ModuleModel, fn: Optional[ast.AST],
                        base: ast.expr, before_line: int) -> bool:
    """``state["w"]`` provenance: a prior subscript-target assignment with
    the same source text whose RHS is a validating expression — matched
    textually because subscript keys have no binding structure."""
    if fn is None:
        return False
    want = ast.get_source_segment(model.source, base)
    if not want:
        return False
    for node in walk_scope(fn):
        if isinstance(node, ast.Assign) and node.lineno < before_line:
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    src = ast.get_source_segment(model.source, tgt)
                    if src == want:
                        return True
    return False
