"""CLI: ``python -m hivemall_tpu.analysis [paths] [options]``.

Exit codes: 0 = clean against the baseline; 1 = new findings; 2 = usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from .baseline import (DEFAULT_BASELINE, diff_against_baseline,
                       load_baseline, write_baseline)
from .findings import Finding, Severity
from .runner import analyze_paths, iter_python_files, normalize_path


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(DEFAULT_BASELINE))))


def _new_findings(findings, paths, args):
    """Findings the baseline gate would report (all of them under
    --no-baseline) — the set --fix/--fix-check operates on, so
    baseline-accepted debt never fails the fix gate."""
    if args.no_baseline:
        return list(findings)
    scanned = [normalize_path(p) for p in iter_python_files(paths)]
    new, _ = diff_against_baseline(findings, load_baseline(args.baseline),
                                   scanned_paths=scanned)
    return new


def _run_fixes(findings, rules, check_only: bool, args) -> int:
    """--fix / --fix-check: plan every attached fix, show the diff, then
    (fix mode) write and re-scan the touched files to confirm the repairs
    landed. Idempotent by construction: applied fixes remove their own
    findings, so a second run plans nothing."""
    from .fixer import plan_fixes, render_diffs, write_fixes

    root = _repo_root()
    planned, notes = plan_fixes(findings, root=root)
    for note in notes:
        print(f"note: {note}")
    if not planned:
        print("graftcheck: no applicable fixes"
              + (" (clean)" if check_only else ""))
        return 0
    diff = render_diffs(planned)
    print(diff, end="" if diff.endswith("\n") else "\n")
    if check_only:
        print(f"graftcheck: --fix would modify {len(planned)} file(s) — "
              f"run `python -m hivemall_tpu.analysis --fix`")
        return 1
    written = write_fixes(planned, root=root)
    print(f"graftcheck: fixed {len(written)} file(s): "
          + ", ".join(written))
    from .fixer import finding_fs_path
    fixed_paths = [finding_fs_path(p, root) for p in written]
    rescanned = _new_findings(analyze_paths(fixed_paths, rules=rules),
                              fixed_paths, args)
    refixable = [f for f in rescanned if f.fix is not None]
    if refixable:
        print("graftcheck: WARNING — findings with fixes remain after "
              "applying:")
        for f in refixable:
            print("  " + f.format())
        return 1
    print("graftcheck: re-scan of fixed files reports no remaining "
          "fixable findings")
    return 0


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hivemall_tpu.analysis",
        description="graftcheck: JAX/TPU-aware static analysis "
                    "(recompile / host-sync / dtype / axis / donation / "
                    "side-effect hazards, interprocedural SPMD/collective "
                    "safety G007-G011, concurrency/serving safety "
                    "G012-G016, and dtype/precision flow G017-G021 — "
                    "silent hot-path promotion, f64 serving leaks, "
                    "cast-in-loop dequant, artifact dtype round-trips, "
                    "low-precision accumulation — FFI boundary safety "
                    "G022-G026, and exception-flow / failure-path safety "
                    "G027-G031: future leaks, silent fallbacks, swallowed "
                    "exceptions, unwind-unsafe locking, unbounded retries "
                    "— jit-cache / retrace-hazard traceflow G032-G036: "
                    "cache-entry churn, host branches on traced values, "
                    "unbucketed shape dispatch, donated-buffer reuse, "
                    "hot-loop host syncs — with a --fix autofix engine "
                    "and SARIF output)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories (default: hivemall_tpu)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline json (default: packaged baseline)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="accept the current findings as the new baseline")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (e.g. G001,G002)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text",
                    help="sarif emits SARIF 2.1.0 of the non-baselined "
                         "findings for CI annotations")
    ap.add_argument("--output", default=None, metavar="FILE",
                    help="write the --format payload to FILE instead of "
                         "stdout; stdout then keeps the human-readable "
                         "text rendering (so the CI gate can archive a "
                         "SARIF artifact without losing the console "
                         "summary)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--fix", action="store_true",
                    help="apply machine-applicable fixes (with a unified-"
                         "diff preview), then re-scan the fixed files")
    ap.add_argument("--fix-check", action="store_true",
                    help="exit 1 if --fix would change anything (CI guard);"
                         " prints the would-be diff, writes nothing")
    ap.add_argument("--with-callers", action="store_true",
                    help="also scan package modules that (transitively) "
                         "import the given paths — interprocedural rules "
                         "can fire in an unchanged caller")
    ap.add_argument("--jobs", type=int, default=None, metavar="N",
                    help="thread-pool width for per-file module rules "
                         "(default min(4, cpus); 1 forces serial); "
                         "finding order is deterministic either way")
    args = ap.parse_args(argv)

    if args.output is not None:
        # a silently-unwritten artifact is worse than a usage error: a CI
        # step would upload a stale file from a previous run
        if args.format == "text":
            ap.error("--output requires --format sarif or --format json")
        if args.fix or args.fix_check or args.update_baseline:
            ap.error("--output applies to report runs only, not "
                     "--fix/--fix-check/--update-baseline")

    if args.list_rules:
        from .rules import RULE_DOCS
        for rule_id in sorted(RULE_DOCS):
            print(f"{rule_id}  {RULE_DOCS[rule_id]}")
        return 0

    paths = args.paths or ["hivemall_tpu"]
    # a typo'd path must be a loud usage error, not a silent 'clean' exit —
    # a CI gate pointed at nothing would otherwise check nothing
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print("graftcheck: no such path(s): " + ", ".join(missing),
              file=sys.stderr)
        return 2
    if not any(True for _ in iter_python_files(paths)):
        print("graftcheck: no python files under: " + ", ".join(paths),
              file=sys.stderr)
        return 2
    rules = [r.strip().upper() for r in args.rules.split(",")] \
        if args.rules else None
    if args.with_callers:
        from .runner import expand_to_callers
        paths = expand_to_callers(paths)
    findings = analyze_paths(paths, rules=rules, jobs=args.jobs)

    if args.fix or args.fix_check:
        # fix only what the baseline gate would report: baseline-accepted
        # debt must not fail --fix-check (the documented --update-baseline
        # workflow has to unblock CI)
        return _run_fixes(_new_findings(findings, paths, args), rules,
                          check_only=args.fix_check, args=args)

    if args.update_baseline:
        # a partial scan refreshes only the scanned files' entries; accepted
        # debt in unscanned (still-existing) files is carried over so
        # `lint.sh <file> --update-baseline`-style runs can't clobber it
        scanned = {normalize_path(p) for p in iter_python_files(paths)}
        repo_root = _repo_root()
        carried = [b for b in load_baseline(args.baseline)
                   if b.path not in scanned
                   and os.path.exists(os.path.join(repo_root,
                                                   *b.path.split("/")))]
        merged = sorted(carried + list(findings),
                        key=lambda f: (f.path, f.line, f.rule, f.message))
        out = write_baseline(merged, args.baseline)
        print(f"graftcheck: baseline updated: {out} ({len(findings)} "
              f"scanned + {len(carried)} carried finding(s))")
        return 0

    if args.no_baseline:
        new, stale = list(findings), []
    else:
        scanned = [normalize_path(p) for p in iter_python_files(paths)]
        new, stale = diff_against_baseline(findings, load_baseline(
            args.baseline), scanned_paths=scanned)

    payload = None
    if args.format == "sarif":
        from .sarif import render_sarif
        payload = json.dumps(render_sarif(new), indent=1)
    elif args.format == "json":
        payload = json.dumps({
            "new": [f.to_dict() for f in new],
            "stale": [f.to_dict() for f in stale],
            "total": len(findings),
        }, indent=1)
    if payload is not None and args.output is not None:
        # archive the machine payload, keep the console human-readable —
        # the CI gate uploads the file as an annotation artifact while the
        # log still shows the findings
        with open(args.output, "w", encoding="utf-8") as fh:
            fh.write(payload + "\n")
        _print_text(new, stale, findings)
        print(f"graftcheck: {args.format} written to {args.output}")
    elif payload is not None:
        print(payload)
    else:
        _print_text(new, stale, findings)
    return 1 if new else 0


def _print_text(new, stale, findings) -> None:
    for f in new:
        print(f.format())
    for b in stale:
        print(f"note: stale baseline entry ({b.rule} {b.path}: "
              f"{b.snippet!r}) — refresh with --update-baseline")
    n_err = sum(1 for f in new if f.severity == Severity.ERROR)
    n_warn = len(new) - n_err
    if new:
        print(f"graftcheck: {n_err} error(s), {n_warn} warning(s) not "
              f"in baseline ({len(findings)} total findings)")
    else:
        print(f"graftcheck: clean ({len(findings)} baselined finding(s)"
              f", {len(stale)} stale)" if (findings or stale)
              else "graftcheck: clean")


if __name__ == "__main__":
    sys.exit(main())
