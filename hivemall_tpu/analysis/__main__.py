"""CLI: ``python -m hivemall_tpu.analysis [paths] [options]``.

Exit codes: 0 = clean against the baseline; 1 = new findings; 2 = usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from .baseline import (DEFAULT_BASELINE, diff_against_baseline,
                       load_baseline, write_baseline)
from .findings import Finding, Severity
from .runner import analyze_paths, iter_python_files, normalize_path


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hivemall_tpu.analysis",
        description="graftcheck: JAX/TPU-aware static analysis "
                    "(recompile / host-sync / dtype / axis / donation / "
                    "side-effect hazards)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories (default: hivemall_tpu)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline json (default: packaged baseline)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="accept the current findings as the new baseline")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (e.g. G001,G002)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        from .rules import RULE_DOCS
        for rule_id in sorted(RULE_DOCS):
            print(f"{rule_id}  {RULE_DOCS[rule_id]}")
        return 0

    paths = args.paths or ["hivemall_tpu"]
    rules = [r.strip().upper() for r in args.rules.split(",")] \
        if args.rules else None
    findings = analyze_paths(paths, rules=rules)

    if args.update_baseline:
        # a partial scan refreshes only the scanned files' entries; accepted
        # debt in unscanned (still-existing) files is carried over so
        # `lint.sh <file> --update-baseline`-style runs can't clobber it
        scanned = {normalize_path(p) for p in iter_python_files(paths)}
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(DEFAULT_BASELINE))))
        carried = [b for b in load_baseline(args.baseline)
                   if b.path not in scanned
                   and os.path.exists(os.path.join(repo_root,
                                                   *b.path.split("/")))]
        merged = sorted(carried + list(findings),
                        key=lambda f: (f.path, f.line, f.rule, f.message))
        out = write_baseline(merged, args.baseline)
        print(f"graftcheck: baseline updated: {out} ({len(findings)} "
              f"scanned + {len(carried)} carried finding(s))")
        return 0

    if args.no_baseline:
        new, stale = list(findings), []
    else:
        scanned = [normalize_path(p) for p in iter_python_files(paths)]
        new, stale = diff_against_baseline(findings, load_baseline(
            args.baseline), scanned_paths=scanned)

    if args.format == "json":
        print(json.dumps({
            "new": [f.to_dict() for f in new],
            "stale": [f.to_dict() for f in stale],
            "total": len(findings),
        }, indent=1))
    else:
        for f in new:
            print(f.format())
        for b in stale:
            print(f"note: stale baseline entry ({b.rule} {b.path}: "
                  f"{b.snippet!r}) — refresh with --update-baseline")
        n_err = sum(1 for f in new if f.severity == Severity.ERROR)
        n_warn = len(new) - n_err
        if new:
            print(f"graftcheck: {n_err} error(s), {n_warn} warning(s) not "
                  f"in baseline ({len(findings)} total findings)")
        else:
            print(f"graftcheck: clean ({len(findings)} baselined finding(s)"
                  f", {len(stale)} stale)" if (findings or stale)
                  else "graftcheck: clean")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
