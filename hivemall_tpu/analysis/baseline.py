"""Checked-in baseline: accepted pre-existing findings.

The baseline is a multiset of finding keys ``(rule, path, snippet)`` — line
numbers are carried for display but NOT matched, so edits elsewhere in a
file don't churn the baseline while any edit to a flagged line resurfaces
it. CI semantics:

- a current finding whose key is not covered by the baseline is NEW ->
  exit 1 (fix it or, for accepted debt outside the hot paths, refresh with
  ``--update-baseline`` in the same review);
- a baseline entry with no current finding is STALE -> reported as a note;
  the tier-1 test (tests/test_graftcheck.py) asserts exact equality in
  both directions so the baseline can never drift silently.
"""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

from .findings import Finding

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


def load_baseline(path: Optional[str] = None) -> List[Finding]:
    path = path or DEFAULT_BASELINE
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return [Finding.from_dict(d) for d in data.get("findings", [])]


def write_baseline(findings: Sequence[Finding],
                   path: Optional[str] = None) -> str:
    path = path or DEFAULT_BASELINE
    payload = {
        "version": 1,
        "note": "accepted pre-existing graftcheck findings; refresh with "
                "`python -m hivemall_tpu.analysis --update-baseline`",
        "findings": [f.to_dict() for f in findings],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=False)
        fh.write("\n")
    return path


def diff_against_baseline(current: Sequence[Finding],
                          baseline: Sequence[Finding],
                          scanned_paths: Optional[Sequence[str]] = None,
                          ) -> Tuple[List[Finding], List[Finding]]:
    """(new, stale). When `scanned_paths` is given (changed-files mode),
    baseline entries for files outside the scanned set are ignored — a
    partial scan can never report stale entries for files it didn't read."""
    if scanned_paths is not None:
        scanned = set(scanned_paths)
        baseline = [b for b in baseline if b.path in scanned]
    base_counts: Dict[tuple, int] = Counter(b.key for b in baseline)
    new: List[Finding] = []
    for f in current:
        if base_counts.get(f.key, 0) > 0:
            base_counts[f.key] -= 1
        else:
            new.append(f)
    cur_counts = Counter(f.key for f in current)
    stale: List[Finding] = []
    for b in baseline:
        if cur_counts.get(b.key, 0) > 0:
            cur_counts[b.key] -= 1
        else:
            stale.append(b)
    return new, stale
