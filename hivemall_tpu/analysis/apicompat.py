"""JAX API compatibility table for rule G009.

A small, declarative registry of APIs whose spelling moved across the jax
versions this repo targets, keyed on dotted callee names. Each entry knows
the version window in which the raw API exists and the
``runtime/jax_compat.py`` export that is portable across the whole window,
so G009 can both *grade* a use (error when the installed jax lacks the
API, warning when it merely harms portability) and *repair* it (the
autofix rewrites the callee and routes the import through the compat
module).

The installed jax version is read from package metadata — graftcheck must
stay importable (and fast) on hosts with no accelerator stack, so jax
itself is never imported. ``GRAFTCHECK_JAX_VERSION`` overrides for tests
and cross-version audits.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Tuple

Version = Tuple[int, ...]

# The one module allowed to touch the raw APIs (it IS the portability
# layer), and the import the autofix routes callers through.
COMPAT_MODULE_PATH = "hivemall_tpu/runtime/jax_compat.py"
COMPAT_MODULE = "hivemall_tpu.runtime.jax_compat"


@dataclass(frozen=True)
class ApiEntry:
    dotted: str                    # callee as written (dotted_name match)
    introduced: Optional[Version]  # first jax version carrying the API
    removed: Optional[Version]     # first jax version without it
    compat_name: str               # portable export in jax_compat
    note: str                      # one-line context for the message


API_TABLE: Tuple[ApiEntry, ...] = (
    ApiEntry(
        dotted="jax.shard_map",
        introduced=(0, 6, 0),
        removed=None,
        compat_name="shard_map",
        note="jax<0.6 only ships jax.experimental.shard_map (check_rep=, "
             "no check_vma=)",
    ),
    ApiEntry(
        dotted="jax.experimental.shard_map.shard_map",
        introduced=None,
        removed=(0, 8, 0),
        compat_name="shard_map",
        note="the experimental spelling is removed once jax.shard_map is "
             "stable",
    ),
    ApiEntry(
        dotted="jax.lax.pcast",
        introduced=(0, 7, 0),
        removed=None,
        compat_name="pcast",
        note="pcast belongs to the vma system; jax<0.7 has no varying/"
             "invariant tags at all",
    ),
)

API_BY_DOTTED = {e.dotted: e for e in API_TABLE}

# import modules whose *presence* G009 flags (version-fragile spelling)
LEGACY_IMPORT_MODULES = {
    "jax.experimental.shard_map": API_BY_DOTTED[
        "jax.experimental.shard_map.shard_map"],
}


def parse_version(text: str) -> Optional[Version]:
    parts = []
    for piece in text.split(".")[:3]:
        digits = "".join(ch for ch in piece if ch.isdigit())
        if not digits:
            return tuple(parts) if parts else None
        parts.append(int(digits))
    return tuple(parts) if parts else None


def installed_jax_version() -> Optional[Version]:
    """Installed jax version without importing jax; None when undetectable
    (G009 then grades everything as a portability warning)."""
    override = os.environ.get("GRAFTCHECK_JAX_VERSION")
    if override:
        return parse_version(override)
    try:
        from importlib import metadata
        return parse_version(metadata.version("jax"))
    except Exception:
        return None


def available_in(entry: ApiEntry, version: Optional[Version]
                 ) -> Optional[bool]:
    """Does `version` carry the raw API? None when the version is unknown."""
    if version is None:
        return None
    if entry.introduced is not None and version < entry.introduced:
        return False
    if entry.removed is not None and version >= entry.removed:
        return False
    return True


def compat_import_module(rel_path: str) -> str:
    """The import-from module string a file should use to reach jax_compat:
    relative inside the hivemall_tpu package (matching the house style),
    absolute elsewhere."""
    parts = rel_path.split("/")
    if parts[0] != "hivemall_tpu" or len(parts) < 2:
        return COMPAT_MODULE
    # depth below the package root: parallel/x.py -> 1, models/trees/x.py -> 2
    depth = len(parts) - 2
    if parts[1] == "runtime":
        # sibling module: from .jax_compat import ... (runtime/x.py only)
        if depth == 1:
            return ".jax_compat"
    return "." * (depth + 1) + "runtime.jax_compat"
