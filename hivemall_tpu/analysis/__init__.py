"""graftcheck — a JAX/TPU-aware static analysis pass for this codebase.

Five bench rounds in a row (BENCH_r01-r05, VERDICT.md) lost throughput to
*silent* Python-side hazards — retracing, implicit device->host syncs,
accidental float64 promotion — never to kernel bugs. graftcheck is the gate:
an AST analyzer purpose-built for the repo's JAX idioms, runnable as

    python -m hivemall_tpu.analysis [paths]

and wired into tier-1 CI (scripts/lint.sh, tests/test_graftcheck.py).

Rules (see docs/static_analysis.md for the full contract):

- G001 recompile-hazard     — Python control flow on traced values,
                              shape-derived f-strings/keys in jitted fns,
                              jax.jit built inside hot loops, non-literal
                              static_argnums.
- G002 host-sync-in-hot-loop — .item()/float()/int()/np.asarray/.tolist()
                              on device values inside the per-step loops of
                              the hot-path modules; per-element device_get.
- G003 dtype-drift          — np.float64 and bare float literals in update
                              math (the bf16-above-2^24 policy of
                              models/base.py must not silently upcast).
- G004 axis-name-mismatch   — psum/pmean/all_gather axis names checked
                              against the mesh axes of parallel/mesh.py.
- G005 donation-misuse      — step-shaped jit wrappers missing
                              donate_argnums; reads of a donated argument
                              after the donating call.
- G006 untraced-side-effect — print/metrics/time/np.random and free-variable
                              mutation inside traced functions.

Suppress a single line with `# graftcheck: disable=G00X[,G00Y]` (or
`disable=all`); accepted pre-existing findings live in
``hivemall_tpu/analysis/baseline.json`` and are refreshed with
``python -m hivemall_tpu.analysis --update-baseline``.

Runtime companion: ``hivemall_tpu.runtime.metrics.recompile_guard`` counts
jit cache misses per named step function and exports them on ``/metrics``,
so G001 claims are verifiable on hardware.
"""

from .findings import Finding, Severity
from .runner import analyze_paths, analyze_source
from .baseline import load_baseline, diff_against_baseline, write_baseline

__all__ = [
    "Finding",
    "Severity",
    "analyze_paths",
    "analyze_source",
    "load_baseline",
    "diff_against_baseline",
    "write_baseline",
]
