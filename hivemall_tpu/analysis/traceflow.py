"""Trace-time / jit-cache model: the v7 layer behind G032-G036.

The repo's one dynamic invariant — zero steady-state recompiles, witnessed
after the fact by ``recompile_guard`` counters — rests on three static
properties this module makes provable, stdlib-only and jax-free, on top of
the per-module models (modmodel.py) and the whole-program layer
(program.py):

- **jit cache identity**: which ``jax.jit(...)`` call sites produce a
  wrapper whose compile cache survives across calls. A module-level def
  wrapped once shares one cache forever; a fresh lambda / closure (nested
  def) / ``partial`` object reaching ``jax.jit`` per call never hits its
  own cache again (measured: three ``jax.jit(nested_def)`` wrappers at one
  shape compile three times, while a cache-size probe on any *named*
  wrapper stays flat — the counter blind spot the dynamic attribution in
  runtime/metrics.py closes). Every site is classified by the wrapped
  expression's identity class and by its construction context;
- **sanctioned memo plumbing**: the ``_SHARDED_JIT`` / ``_RETRIEVAL_JIT``
  / ``_QUANT_JIT`` get-or-build idiom — a module-level dict named like a
  jit memo, both read and written by a helper function — bounds wrapper
  construction to once per key. Jit sites under a memo helper, under a
  ``make_*``/``build_*`` factory, under ``__init__``, at module level, or
  in a decorator position are construction-once by convention and never
  churn findings;
- **shape canonicalization**: which call-site arguments are routed through
  the bucket ladder (``pad_to_bucket`` widths, ``bucket_rows`` /
  ``pad_rows_to_multiple`` array padding) before reaching a jitted
  callable — the recompile-per-novel-shape hazard the serving warmup
  matrix exists to prevent;
- **donation flow**: jit aliases with ``donate_argnums`` resolved
  *interprocedurally* — through ``self._step = self._build_block_step()``
  factory assignments and through memo-helper build thunks — so
  use-after-donate is provable beyond the single-module straight-line scan
  G005 already does (loop-carried donations are the live case:
  retrieval.py's top-K carries donate the running best buffers every
  block).

Resolution is deliberately conservative, like every layer before it: the
rules flag only what the model proves (a fresh-identity object reaching a
jit site outside every sanctioned context; a slice with a non-literal
bound reaching a provably-jitted callee), and anything dynamic is trusted.

Per-module facts are memoized as ``model._graftcheck_traceflow`` (the
``_graftcheck_*`` prefix is stripped by modelcache before pickling); the
program-level handle follows the exceptionflow/concurrency pattern via
``get_info``/``program._graftcheck_traceflow``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import config
from .modmodel import (_FN_TYPES, JitWrap, ModuleModel, dotted_name,
                       enclosing_loop, walk_scope)
from .program import ProgramModel

SYNC_WALK_DEPTH = 3


# --------------------------------------------------------------------------
# jit call-site classification
# --------------------------------------------------------------------------

class JitSite:
    """One ``jax.jit(...)`` call: what identity class the wrapped
    expression has, and whether the construction context is sanctioned."""

    __slots__ = ("call", "wrap", "arg_kind", "wrapped_name", "in_loop",
                 "sanctioned", "eta_target")

    def __init__(self, call: ast.Call):
        self.call = call
        self.wrap = JitWrap(call)
        self.in_loop = enclosing_loop(call) is not None
        self.sanctioned = False
        self.arg_kind = "none"          # none|lambda|closure|partial|named
        self.wrapped_name: Optional[str] = None
        self.eta_target: Optional[ast.expr] = None


def _eta_target(lam: ast.Lambda) -> Optional[ast.expr]:
    """``lambda x, y: f(x, y)`` -> the ``f`` expression; None when the
    lambda is not a pure eta-expansion (defaults, kwargs, reordered or
    transformed arguments all disqualify)."""
    a = lam.args
    if a.defaults or a.kw_defaults or a.kwonlyargs or a.vararg or a.kwarg:
        return None
    params = [p.arg for p in a.posonlyargs + a.args]
    body = lam.body
    if not isinstance(body, ast.Call) or body.keywords:
        return None
    if not isinstance(body.func, (ast.Name, ast.Attribute)):
        return None
    if isinstance(body.func, ast.Name) and body.func.id in params:
        return None
    if len(body.args) != len(params):
        return None
    for arg, param in zip(body.args, params):
        if not (isinstance(arg, ast.Name) and arg.id == param):
            return None
    return body.func


class ModuleTraceInfo:
    """Per-module trace-time facts, memoized on the ModuleModel."""

    __slots__ = ("memo_dicts", "memo_helper_fns", "memo_helper_names",
                 "sites", "donating")

    def __init__(self, model: ModuleModel):
        self.memo_dicts = _memo_dicts(model)
        self.memo_helper_fns: Set[ast.AST] = set()
        self.memo_helper_names: Set[str] = set()
        for fn in model.functions:
            if _touches_memo(fn, self.memo_dicts) or _is_cached(fn):
                self.memo_helper_fns.add(fn)
                self.memo_helper_names.add(fn.name)
        # one tree walk feeds both site classification and donating-alias
        # resolution — this constructor runs for every module in the
        # program context, so the walk count is the scan's hot dimension
        jit_calls: List[ast.Call] = []
        call_assigns: List[ast.Assign] = []
        for node in ast.walk(model.tree):
            if isinstance(node, ast.Call):
                if dotted_name(node.func) in ("jax.jit", "jit"):
                    jit_calls.append(node)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.value, ast.Call):
                call_assigns.append(node)
        self.sites = _collect_sites(model, jit_calls, self.memo_helper_fns,
                                    self.memo_helper_names)
        self.donating = _donating_map(model, call_assigns,
                                      self.memo_helper_names)


def _memo_dicts(model: ModuleModel) -> Set[str]:
    out: Set[str] = set()
    for node in model.tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) \
                        and config.TRACEFLOW_MEMO_NAME_RE.match(tgt.id):
                    out.add(tgt.id)
    return out


def _touches_memo(fn: ast.AST, memo_names: Set[str]) -> bool:
    """A memo helper both reads (get/subscript-load/truth-test/``in``) and
    writes (subscript-store/setdefault/update) a module-level jit memo —
    the get-or-build contract that bounds wrappers to one per key."""
    if not memo_names:
        return False
    reads: Set[str] = set()
    writes: Set[str] = set()
    for node in walk_scope(fn):
        if isinstance(node, ast.Subscript) \
                and isinstance(node.value, ast.Name) \
                and node.value.id in memo_names:
            bucket = writes if isinstance(node.ctx, ast.Store) else reads
            bucket.add(node.value.id)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in memo_names:
            if node.func.attr in ("setdefault", "update"):
                writes.add(node.func.value.id)
            elif node.func.attr in ("get", "pop"):
                reads.add(node.func.value.id)
        elif isinstance(node, (ast.If, ast.While)):
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Name) and sub.id in memo_names:
                    reads.add(sub.id)
        elif isinstance(node, ast.Compare):
            for op, comp in zip(node.ops, node.comparators):
                if isinstance(op, (ast.In, ast.NotIn)) \
                        and isinstance(comp, ast.Name) \
                        and comp.id in memo_names:
                    reads.add(comp.id)
    return bool(reads & writes)


def _is_cached(fn: ast.AST) -> bool:
    """``functools.lru_cache`` / ``functools.cache`` decorated functions
    are memo helpers by construction — one return value per distinct key,
    forever — so a jit wrapper built inside one is construction-once
    (grow.py's ``_sharded_hist_fn`` is the live case)."""
    for dec in getattr(fn, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(target)
        if name is not None \
                and name.rsplit(".", 1)[-1] in ("lru_cache", "cache"):
            return True
    return False


def local_rebinds(fn: ast.AST) -> Set[str]:
    """Names (re)bound by assignment or loop target inside ``fn``. A local
    binding shadows any same-named def, so a bare call to one of these
    must not be resolved lexically (``predict = make_predict(...)`` inside
    a ``predict`` method is the live case)."""
    out: Set[str] = set()
    for node in walk_scope(fn):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.For)):
            targets = [node.target]
        elif isinstance(node, ast.withitem) and node.optional_vars:
            targets = [node.optional_vars]
        else:
            continue
        for tgt in targets:
            for sub in ast.walk(tgt):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
    return out


def _is_decorator_of(call: ast.Call, fn: Optional[ast.AST]) -> bool:
    if fn is None:
        return False
    for dec in getattr(fn, "decorator_list", ()):
        for node in ast.walk(dec):
            if node is call:
                return True
    return False


def _context_sanctioned(model: ModuleModel, call: ast.Call,
                        memo_helper_fns: Set[ast.AST],
                        memo_helper_names: Set[str]) -> bool:
    """Construction-once contexts: module level, decorators, __init__,
    make_*/build_* factories, memo helpers (at any enclosing depth), and
    build thunks passed as arguments to a memo-helper call."""
    fn = model.enclosing_function(call)
    if fn is None or _is_decorator_of(call, fn):
        return True
    cur = fn
    while cur is not None:
        if cur.name == "__init__" \
                or config.TRACEFLOW_FACTORY_RE.match(cur.name) \
                or cur in memo_helper_fns:
            return True
        cur = model.enclosing_function(cur)
    # lexically inside an argument of a memo-helper call (the
    # `_retrieval_jit(key, lambda: jax.jit(...))` thunk shape)
    node: ast.AST = call
    while node is not None and not isinstance(node, _FN_TYPES):
        parent = getattr(node, "graftcheck_parent", None)
        if isinstance(parent, ast.Call) and parent is not call:
            callee = dotted_name(parent.func)
            if callee is not None \
                    and callee.rsplit(".", 1)[-1] in memo_helper_names:
                return True
        node = parent
    return False


def _collect_sites(model: ModuleModel, jit_calls: List[ast.Call],
                   memo_helper_fns: Set[ast.AST],
                   memo_helper_names: Set[str]) -> List[JitSite]:
    sites: List[JitSite] = []
    for node in jit_calls:
        site = JitSite(node)
        site.sanctioned = _context_sanctioned(model, node, memo_helper_fns,
                                              memo_helper_names)
        fn_arg = node.args[0] if node.args else None
        if fn_arg is None:
            site.arg_kind = "none"
        elif isinstance(fn_arg, ast.Lambda):
            site.arg_kind = "lambda"
            site.eta_target = _eta_target(fn_arg)
        elif isinstance(fn_arg, ast.Call):
            callee = dotted_name(fn_arg.func)
            site.arg_kind = "partial" \
                if callee in ("partial", "functools.partial") else "named"
            site.wrapped_name = callee
        elif isinstance(fn_arg, ast.Name):
            site.wrapped_name = fn_arg.id
            target = model.resolve_def(fn_arg.id, node)
            if target is not None \
                    and model.enclosing_function(target) is not None:
                # a nested def is a fresh closure object per enclosing call
                site.arg_kind = "closure"
            else:
                site.arg_kind = "named"
        else:
            site.wrapped_name = dotted_name(fn_arg)
            site.arg_kind = "named"
        sites.append(site)
    return sites


# --------------------------------------------------------------------------
# interprocedural donating-alias resolution
# --------------------------------------------------------------------------

def _thunk_factory_name(value: ast.Call,
                        memo_helper_names: Set[str]) -> Optional[str]:
    """``_retrieval_jit(key, lambda: self._build_x(...))`` -> "_build_x"
    when the callee is a memo helper and an argument is a build thunk."""
    callee = dotted_name(value.func)
    if callee is None or callee.rsplit(".", 1)[-1] not in memo_helper_names:
        return None
    for arg in list(value.args) + [kw.value for kw in value.keywords]:
        if isinstance(arg, ast.Lambda) and isinstance(arg.body, ast.Call):
            inner = dotted_name(arg.body.func)
            if inner is not None:
                return inner.rsplit(".", 1)[-1]
        elif isinstance(arg, ast.Name):
            return arg.id
    return None


def _donating_map(model: ModuleModel, call_assigns: List[ast.Assign],
                  memo_helper_names: Set[str]) -> Dict[str, JitWrap]:
    """Donating callables G005's module-local alias map cannot see:
    ``self.X = <factory>()`` / ``self.X = <memo helper>(key, thunk)``
    where the factory's returned jit (recorded in jit_aliases under the
    factory's name) has donate_argnums."""
    out: Dict[str, JitWrap] = {}
    for node in call_assigns:
        tgt = node.targets[0]
        tgt_name = dotted_name(tgt)
        if tgt_name is None or tgt_name in model.jit_aliases:
            continue
        value = node.value
        callee = dotted_name(value.func)
        factory = None
        if callee is not None:
            tail = callee.rsplit(".", 1)[-1]
            if tail in memo_helper_names:
                factory = _thunk_factory_name(value, memo_helper_names)
            elif tail in model.jit_aliases:
                factory = tail
        if factory is None:
            continue
        wrap = model.jit_aliases.get(factory)
        if wrap is not None and wrap.donate_argnums:
            out[tgt_name] = wrap
    return out


# --------------------------------------------------------------------------
# memoized accessors
# --------------------------------------------------------------------------

def module_info(model: ModuleModel) -> ModuleTraceInfo:
    info = getattr(model, "_graftcheck_traceflow", None)
    if info is None:
        info = ModuleTraceInfo(model)
        model._graftcheck_traceflow = info  # type: ignore[attr-defined]
    return info


class TraceflowModel:
    """Program-level handle shared by the five v7 rules."""

    def __init__(self, program: ProgramModel):
        self.program = program

    def info(self, path: str) -> Optional[ModuleTraceInfo]:
        model = self.program.modules.get(path)
        return module_info(model) if model is not None else None

    # -- G032c: does a resolvable callee construct jit wrappers? ----------

    def jit_site_in(self, path: str, fn: ast.AST) -> Optional[JitSite]:
        """First jit site lexically within ``fn`` (nested defs included) —
        the evidence that calling ``fn`` per iteration churns wrappers."""
        model = self.program.modules.get(path)
        info = self.info(path)
        if model is None or info is None:
            return None
        for site in info.sites:
            cur = model.enclosing_function(site.call)
            while cur is not None:
                if cur is fn:
                    return site
                cur = model.enclosing_function(cur)
        return None

    # -- G036: depth-bounded callee sync summaries ------------------------

    def sync_site(self, path: str, fn: ast.AST, depth: int = 0
                  ) -> Optional[Tuple[str, int, str]]:
        """(module, line, call tail) of the first provable device sync a
        call to ``fn`` performs — ``jax.device_get`` /
        ``.block_until_ready()`` in its own scope or in a resolvable bare
        callee, depth-bounded. Taint-free by design: only calls that block
        *by name* count, so already-host values can never false-positive."""
        model = self.program.modules.get(path)
        if model is None or depth > SYNC_WALK_DEPTH:
            return None
        memo: Dict[int, object] = getattr(model, "_graftcheck_syncs", None)
        if memo is None:
            memo = {}
            model._graftcheck_syncs = memo  # type: ignore[attr-defined]
        key = id(fn)
        if key in memo:
            cached = memo[key]
            return cached if cached != () else None  # type: ignore[return-value]
        memo[key] = ()  # cycle guard: in-progress reads as "no sync"
        result: Optional[Tuple[str, int, str]] = None
        for node in walk_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee is None:
                continue
            tail = callee.rsplit(".", 1)[-1]
            if tail in config.TRACEFLOW_SYNC_CALL_TAILS:
                result = (path, node.lineno, tail)
                break
            if "." not in callee:
                got = self.program.resolve_fn(path, callee, node)
                if got is not None:
                    deeper = self.sync_site(got[0], got[1], depth + 1)
                    if deeper is not None:
                        result = deeper
                        break
        memo[key] = result if result is not None else ()
        return result


def get_model(program: ProgramModel) -> TraceflowModel:
    model = getattr(program, "_graftcheck_traceflow", None)
    if model is None:
        model = TraceflowModel(program)
        program._graftcheck_traceflow = model  # type: ignore[attr-defined]
    return model


def in_traceflow_scope(path: str, model: Optional[ModuleModel]) -> bool:
    """G034/G036 sweep the jit-hot scope: the kernel/op layers, the
    serving dispatch modules, and anything opting in with the marker."""
    if path.startswith(config.TRACEFLOW_HOT_PREFIXES) \
            or path in config.TRACEFLOW_HOT_MODULES:
        return True
    return model is not None and config.TRACEFLOW_MARKER in model.source
