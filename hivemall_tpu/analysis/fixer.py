"""Autofix engine: apply the machine-applicable edits findings carry.

A fix is deliberately tiny — within-line substring replacements plus an
optional "make sure this import exists" request (findings.Fix) — which
buys two properties the rules rely on:

- **one-pass safety**: within-line edits never shift line numbers, so
  every fix collected in a single scan applies against the same line
  numbering; import insertion (which does add a line) runs last, per
  file, against the already-edited source;
- **ordered multi-line wraps**: the G030 try/finally wrap DOES insert
  lines, so wraps apply after every within-line edit, bottom-up by
  start line (lower wraps first never shift an upper wrap's numbering),
  each re-validated against the release line's current text;
- **idempotence**: an applied fix removes its own finding, so a second
  ``--fix`` run collects no edits and writes nothing — the property
  ``scripts/lint.sh --fix-check`` (and the round-trip test) locks in.

Import requests are merged per target module: three findings that each
want a name from ``..runtime.jax_compat`` produce one import statement
(or extend an existing one) with the union of names, inserted after the
module's last top-level import (falling back to after the docstring).
"""

from __future__ import annotations

import ast
import difflib
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .findings import Finding

Result = Dict[str, Tuple[str, str]]  # path -> (old_source, new_source)


def finding_fs_path(path: str, root: str) -> str:
    """Filesystem location of a finding's (normalized) path. normalize_path
    emits cwd-relative paths for files outside the hivemall_tpu package, so
    try the cwd interpretation first, then anchor package paths at the repo
    root (covers scans launched from other directories)."""
    if os.path.isabs(path):
        return path
    cand = os.path.abspath(path)
    if os.path.exists(cand):
        return cand
    return os.path.join(root, *path.split("/"))


def _insertion_line(tree: ast.Module) -> int:
    """1-based line AFTER which a new import goes: the last top-level
    import's end, else the docstring's end, else line 0 (file start)."""
    last = 0
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            last = max(last, node.end_lineno or node.lineno)
    if last:
        return last
    if tree.body and isinstance(tree.body[0], ast.Expr) \
            and isinstance(tree.body[0].value, ast.Constant) \
            and isinstance(tree.body[0].value.value, str):
        return tree.body[0].end_lineno or tree.body[0].lineno
    return 0


def _existing_from_import(tree: ast.Module, module: str
                          ) -> Optional[ast.ImportFrom]:
    """A top-level single-line `from <module> import ...` to extend."""
    for node in tree.body:
        if isinstance(node, ast.ImportFrom):
            rendered = "." * node.level + (node.module or "")
            if rendered == module \
                    and (node.end_lineno or node.lineno) == node.lineno:
                return node
    return None


def _ensure_imports(source: str, wanted: Dict[str, Set[str]]) -> str:
    """Insert/extend `from <module> import <names>` for each requested
    module, skipping names already imported from it."""
    if not wanted:
        return source
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return source
    lines = source.splitlines(keepends=True)
    inserts: List[Tuple[int, str]] = []  # (after-line, text)
    replaces: Dict[int, str] = {}  # lineno -> new text
    for module in sorted(wanted):
        names = set(wanted[module])
        existing = _existing_from_import(tree, module)
        if existing is not None:
            # only an UNALIASED import satisfies a request for the bare
            # name (`import shard_map as smap` does not bind `shard_map`)
            have_bare = {a.name for a in existing.names
                         if a.asname is None}
            missing = sorted(names - have_bare)
            if not missing:
                continue
            # preserve `as` aliases on the names already there, and any
            # trailing comment (it may be a lint suppression)
            kept = [f"{a.name} as {a.asname}" if a.asname else a.name
                    for a in existing.names]
            entries = sorted(set(kept) | set(missing))
            old_line = lines[existing.lineno - 1]
            comment = ""
            if "#" in old_line:
                comment = "  #" + old_line.split("#", 1)[1].rstrip("\n")
            replaces[existing.lineno] = "from {} import {}{}\n".format(
                module, ", ".join(entries), comment)
        else:
            inserts.append((
                _insertion_line(tree),
                f"from {module} import {', '.join(sorted(names))}\n"))
    for lineno, text in replaces.items():
        lines[lineno - 1] = text
    for after, text in sorted(inserts, reverse=True):
        lines.insert(after, text)
    return "".join(lines)


def _apply_wraps(lines: List[str], wraps, path: str, notes: List[str],
                 rules: Dict[int, str]) -> bool:
    """Apply WrapFinally repairs bottom-up (highest start first), so an
    applied wrap's inserted lines never shift a pending wrap above it."""
    applied = False
    for w in sorted(wraps, key=lambda w: -w.start):
        if not (1 <= w.start <= w.release_line <= len(lines)):
            notes.append(f"{path}:{w.start}: wrap for "
                         f"{rules.get(w.start, '?')} skipped — lines out "
                         f"of range (stale finding?)")
            continue
        release_raw = lines[w.release_line - 1]
        if release_raw.strip() != w.release_text:
            notes.append(
                f"{path}:{w.release_line}: wrap skipped — expected "
                f"release {w.release_text!r}, found "
                f"{release_raw.strip()!r} (stale finding?)")
            continue
        indent = release_raw[:len(release_raw) - len(release_raw.lstrip())]
        body = [("    " + ln if ln.strip() else ln)
                for ln in lines[w.start - 1:w.release_line - 1]]
        lines[w.start - 1:w.release_line] = (
            [indent + "try:\n"] + body +
            [indent + "finally:\n", indent + "    " + w.release_text + "\n"])
        applied = True
    return applied


def plan_fixes(findings: Sequence[Finding], root: str = "."
               ) -> Tuple[Result, List[str]]:
    """Compute the post-fix sources for every file a fixable finding
    points at. Returns ({path: (old, new)}, notes) — notes record edits
    that no longer matched their line (stale finding, manual edit since
    the scan) and were skipped."""
    notes: List[str] = []
    by_path: Dict[str, List[Finding]] = {}
    for f in findings:
        if f.fix is not None:
            by_path.setdefault(f.path, []).append(f)
    out: Result = {}
    for path, flist in sorted(by_path.items()):
        fs_path = finding_fs_path(path, root)
        try:
            with open(fs_path, "r", encoding="utf-8") as fh:
                old_source = fh.read()
        except OSError as e:
            notes.append(f"{path}: unreadable, fixes skipped ({e})")
            continue
        lines = old_source.splitlines(keepends=True)
        wanted_imports: Dict[str, Set[str]] = {}
        wraps = []
        wrap_rules: Dict[int, str] = {}
        applied_any = False
        for f in flist:
            ok = True
            for edit in f.fix.edits:
                if not (1 <= edit.line <= len(lines)) \
                        or edit.old not in lines[edit.line - 1]:
                    notes.append(
                        f"{path}:{edit.line}: fix for {f.rule} skipped — "
                        f"expected text {edit.old!r} not found (stale "
                        f"finding?)")
                    ok = False
                    break
            if not ok:
                continue
            for edit in f.fix.edits:
                lines[edit.line - 1] = lines[edit.line - 1].replace(
                    edit.old, edit.new, 1)
            if f.fix.add_import is not None:
                module, name = f.fix.add_import
                wanted_imports.setdefault(module, set()).add(name)
            if f.fix.wrap is not None:
                wraps.append(f.fix.wrap)
                wrap_rules[f.fix.wrap.start] = f.rule
            applied_any = bool(f.fix.edits) or f.fix.add_import is not None \
                or applied_any
        if _apply_wraps(lines, wraps, path, notes, wrap_rules):
            applied_any = True
        if not applied_any:
            continue
        new_source = _ensure_imports("".join(lines), wanted_imports)
        if new_source != old_source:
            out[path] = (old_source, new_source)
    return out, notes


def render_diffs(result: Result) -> str:
    chunks = []
    for path, (old, new) in sorted(result.items()):
        chunks.append("".join(difflib.unified_diff(
            old.splitlines(keepends=True), new.splitlines(keepends=True),
            fromfile=f"a/{path}", tofile=f"b/{path}")))
    return "".join(chunks)


def write_fixes(result: Result, root: str = ".") -> List[str]:
    written = []
    for path, (_, new) in sorted(result.items()):
        fs_path = finding_fs_path(path, root)
        with open(fs_path, "w", encoding="utf-8") as fh:
            fh.write(new)
        written.append(path)
    return written
