"""G019 cast-inside-loop / materializing-dequant: full-array casts per step.

Two advisory shapes of the same waste, scoped to the hot-path modules
(analysis/dtypeflow.in_hot_scope):

- **cast-inside-loop**: an ``x.astype(...)`` whose receiver no statement
  in the enclosing Python loop rebinds — the cast re-materializes the
  same array every iteration. Hoist it above the loop, or reuse a
  precomputed plan the way ``ops/scatter.py`` builds its sort/segment
  structure once per block and amortizes it over every table.
- **materializing dequant**: an ``astype`` whose receiver is *provably*
  reduced-precision (bf16/f16/int8) and whose target is f32/f64 — a
  full widened copy of a quantized array. The dequant-free serving
  contract wants the cast fused per-tile/per-window inside the consuming
  loop (the ``ops/mxu_scatter.py`` window pattern), not a whole-table
  materialization that erases the bandwidth the quantization bought.

Both are warnings: widening can be the right call (an f32 accumulator),
and the fix is structural — suppress with a rationale where the copy is
deliberate.
"""

from __future__ import annotations

from typing import List, Set

from ..dtypeflow import get_model, in_hot_scope
from ..findings import Finding, Severity
from ..program import ProgramModel

RULE_ID = "G019"


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    flow = get_model(program)
    for path in sorted(scanned):
        model = program.modules.get(path)
        if model is None:
            continue
        seen: Set[int] = set()
        for fn in model.functions:
            if not in_hot_scope(path, model, fn):
                continue
            for site in flow.facts(path, fn).casts:
                if site.node.lineno in seen:
                    continue
                if site.loop is not None and site.loop_invariant:
                    seen.add(site.node.lineno)
                    findings.append(Finding(
                        path, site.node.lineno, RULE_ID, Severity.WARNING,
                        "astype of a loop-invariant array inside a Python "
                        "loop — the cast re-materializes the full array "
                        "every iteration; hoist it, or build a reusable "
                        "plan once per block (ops/scatter.py amortizes its "
                        "sort/segment plan over every table exactly this "
                        "way)",
                        model.snippet(site.node.lineno)))
                elif site.receiver_dt is not None \
                        and site.receiver_dt.reduced_float \
                        and site.target_dt is not None \
                        and site.target_dt.wide_float:
                    seen.add(site.node.lineno)
                    findings.append(Finding(
                        path, site.node.lineno, RULE_ID, Severity.WARNING,
                        f"materializing dequant: astype("
                        f"{site.target_dt.name}) of a "
                        f"{site.receiver_dt.name} array copies the whole "
                        f"table widened — cast per-tile/per-window inside "
                        f"the consuming loop (the ops/mxu_scatter.py "
                        f"window pattern) to keep the bandwidth the "
                        f"reduced dtype bought",
                        model.snippet(site.node.lineno)))
    return findings
