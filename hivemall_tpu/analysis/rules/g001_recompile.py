"""G001 recompile-hazard: code shapes that force XLA retracing.

Four patterns:

(a) Python ``if``/``while`` whose test reads a *traced* value inside a
    traced function — every distinct concrete value retraces (or raises
    ConcretizationError). ``is (not) None`` / ``isinstance`` / containment
    tests are pruned: pytree *structure* is static at trace time.
(b) ``jax.jit(...)`` constructed inside a ``for``/``while`` body — a fresh
    jit wrapper per iteration never hits its own cache (the
    production-metric class of the ads-infra paper: recompilation count).
(c) f-strings / dict-or-format keys derived from ``.shape`` or traced
    values inside traced functions — shape-keyed Python caches silently
    fork one compilation per shape.
(d) non-literal ``static_argnums``/``static_argnames`` — data-dependent
    static args hash per value and retrace per batch.
"""

from __future__ import annotations

import ast
from typing import List

from ..findings import Finding, Severity
from ..modmodel import ModuleModel, dotted_name, enclosing_loop, walk_scope

RULE_ID = "G001"


def _prune_static_tests(test: ast.expr) -> List[ast.expr]:
    """Drop subtrees whose truth is static at trace time, return the rest."""
    if isinstance(test, ast.BoolOp):
        out: List[ast.expr] = []
        for v in test.values:
            out.extend(_prune_static_tests(v))
        return out
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _prune_static_tests(test.operand)
    if isinstance(test, ast.Compare):
        # x is None / x is not None — structure checks, static under trace
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return []
        # k in outs.dslots — dict/tuple membership is Python-level structure
        if all(isinstance(op, (ast.In, ast.NotIn)) for op in test.ops):
            return []
    if isinstance(test, ast.Call):
        callee = dotted_name(test.func)
        if callee in ("isinstance", "hasattr", "len", "callable"):
            return []
    return [test]


def _names_in(expr: ast.expr):
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            yield node.id


def _has_shape_access(expr: ast.expr) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in ("shape", "dtype",
                                                             "ndim"):
            return True
    return False


def check(model: ModuleModel) -> List[Finding]:
    findings: List[Finding] = []

    def emit(node: ast.AST, msg: str, sev: str = Severity.ERROR) -> None:
        findings.append(Finding(model.rel_path, node.lineno, RULE_ID, sev,
                                msg, model.snippet(node.lineno)))

    # (a) + (c): per traced function
    for fn in model.functions:
        if not model.is_traced(fn):
            continue
        tainted, callables = model.taint_function(fn, taint_params=True)
        for node in walk_scope(fn):
            if isinstance(node, (ast.If, ast.While)):
                for sub in _prune_static_tests(node.test):
                    hot = sorted(n for n in _names_in(sub) if n in tainted)
                    if hot:
                        kind = "while" if isinstance(node, ast.While) else "if"
                        emit(node, f"Python `{kind}` on traced value(s) "
                                   f"{', '.join(hot)} inside jitted "
                                   f"`{fn.name}` — use jnp.where/lax.cond or "
                                   f"hoist to a static arg")
                        break
            elif isinstance(node, ast.JoinedStr):
                for fv in node.values:
                    if not isinstance(fv, ast.FormattedValue):
                        continue
                    if _has_shape_access(fv.value) or any(
                            n in tainted for n in _names_in(fv.value)):
                        emit(node, f"f-string over traced/shape value inside "
                                   f"jitted `{fn.name}` — shape-keyed strings "
                                   f"fork one compile per shape",
                             Severity.WARNING)
                        break

    # (b): jax.jit under a loop (within one function scope — a jit inside a
    # def that is merely *defined* in a loop runs once per call, not per
    # iteration, so the ancestor walk stops at function boundaries)
    for node in ast.walk(model.tree):
        if isinstance(node, ast.Call) and dotted_name(node.func) in (
                "jax.jit", "jit") and enclosing_loop(node) is not None:
            emit(node, "jax.jit(...) constructed inside a loop — a fresh "
                       "wrapper per iteration never hits its own compile "
                       "cache; hoist the jit out of the loop")

    # (d): non-literal static_argnums/static_argnames
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func)
        is_jit = callee in ("jax.jit", "jit")
        is_partial_jit = callee in ("partial", "functools.partial") and \
            node.args and dotted_name(node.args[0]) in ("jax.jit", "jit")
        if not (is_jit or is_partial_jit):
            continue
        for kw in node.keywords:
            if kw.arg not in ("static_argnums", "static_argnames"):
                continue
            v = kw.value
            ok = isinstance(v, ast.Constant) or (
                isinstance(v, (ast.Tuple, ast.List))
                and all(isinstance(e, ast.Constant) for e in v.elts))
            if not ok:
                emit(kw.value, f"non-literal {kw.arg} — data-dependent "
                               f"static args retrace per distinct value; "
                               f"use a literal tuple")

    return findings
