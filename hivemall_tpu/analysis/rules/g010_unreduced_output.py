"""G010 unreduced-output-escapes-shard_map: per-shard value declared
replicated.

``out_specs=P()`` promises XLA the body's output is identical on every
device. Returning a per-shard value there — a sharded input passed
through, or any output of a body that performs no cross-device reduction
at all — hands each consumer device whichever shard it happens to hold:
under the legacy ``check_rep=False`` shim (and ``check_vma=False`` sites)
nothing catches it and the training result silently depends on device
count. This is the checker's static analog for exactly the sites where
the runtime checker is off.

Two provable patterns are flagged, both interprocedural-resolution
gated (see program.py), anything unresolvable is trusted:

- a return element at a ``P()`` position is a body *parameter* whose
  matching ``in_specs`` entry shards an axis (direct passthrough);
- the body and every transitively resolvable callee contain **no**
  reducing collective (psum/pmean/pmax/pmin/all_gather/psum_scatter) yet
  an output position is declared replicated — claimed only when the
  returned value at that position provably *derives from a sharded
  input* (local-assignment taint) and every call edge resolved, so a
  single opaque helper — or an output computed purely from replicated
  inputs — suppresses the claim. Method calls on *local values*
  (``st.replace(...)``, ``x.sum()``) are assumed collective-free — the
  deliberate trade-off that keeps the rule usable on idiomatic pytree
  code; module-attribute calls are treated as opaque.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from ..findings import Finding, Severity
from ..modmodel import dotted_name, walk_scope
from ..program import ProgramModel

RULE_ID = "G010"

_REDUCING = ("psum", "pmean", "pmax", "pmin", "all_gather", "psum_scatter")
_BENIGN_ROOTS = ("jax", "jnp", "np", "numpy", "math", "functools")
_BENIGN_BARE = {"len", "range", "tuple", "list", "dict", "zip", "enumerate",
                "sorted", "min", "max", "sum", "abs", "float", "int", "bool",
                "isinstance", "getattr", "print", "P", "PartitionSpec",
                "partial"}


def _spec_elements(expr: Optional[ast.expr]) -> Optional[List[ast.expr]]:
    """out_specs/in_specs as a positional list; None when not literal."""
    if expr is None:
        return None
    if isinstance(expr, (ast.Tuple, ast.List)):
        return list(expr.elts)
    return [expr]


def _is_replicated_spec(expr: ast.expr) -> bool:
    return isinstance(expr, ast.Call) and not expr.args \
        and not expr.keywords \
        and (dotted_name(expr.func) or "").rsplit(".", 1)[-1] \
        in ("P", "PartitionSpec")


def _is_sharded_spec(expr: ast.expr) -> bool:
    if not isinstance(expr, ast.Call):
        return False
    if (dotted_name(expr.func) or "").rsplit(".", 1)[-1] \
            not in ("P", "PartitionSpec"):
        return False
    for arg in expr.args:
        if not (isinstance(arg, ast.Constant) and arg.value is None):
            return True
    return False


def _returns(fn: ast.AST) -> List[ast.Return]:
    return [n for n in walk_scope(fn)
            if isinstance(n, ast.Return) and n.value is not None]


def _sharded_taint(fn: ast.AST, sharded_params: Set[str]) -> Set[str]:
    """Names (transitively, through local assignments) derived from the
    sharded parameters — two passes so loop-carried taint converges."""
    tainted = set(sharded_params)
    for _ in range(2):
        for node in walk_scope(fn):
            if isinstance(node, ast.Assign):
                if any(isinstance(n, ast.Name) and n.id in tainted
                       for n in ast.walk(node.value)):
                    for tgt in node.targets:
                        for n in ast.walk(tgt):
                            if isinstance(n, ast.Name):
                                tainted.add(n.id)
    return tainted


def _reduction_scan(program: ProgramModel, path: str, fn: ast.AST,
                    env) -> Tuple[bool, bool]:
    """(found_reduction, fully_resolved) over fn's transitive call graph."""
    found = False
    resolved = True
    for f_path, f_fn, summ, f_env in program.walk_calls(path, fn, env):
        for _, tail, _, _ in summ.collectives:
            if tail in _REDUCING:
                found = True
        for call, callee in summ.calls:
            root = callee.split(".", 1)[0]
            if "." in callee:
                if root in _BENIGN_ROOTS:
                    continue
                if program.imports(f_path).get(root) is None:
                    continue  # method call on a local value: benign
                # module-attribute call (internal or external import):
                # not walked, so it could reduce — suppress the claim
                resolved = False
                continue
            if callee in _BENIGN_BARE:
                continue
            bound = f_env.get(callee)
            if bound is not None and bound[0] == "fn":
                continue  # walked via walk_calls
            if program.resolve_fn(f_path, callee, call) is None:
                resolved = False
    return found, resolved


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    for site in program.shard_map_sites():
        out_specs = _spec_elements(site.out_specs_expr)
        if out_specs is None:
            continue
        replicated = [i for i, s in enumerate(out_specs)
                      if _is_replicated_spec(s)]
        if not replicated:
            continue
        body = program.resolve_callable(site.module, site.fn_expr)
        if body is None:
            continue
        b_path, b_fn, b_env = body
        if b_path not in scanned and site.module not in scanned:
            continue
        model = program.modules[b_path]
        in_specs = _spec_elements(site.in_specs_expr)
        params = [a.arg for a in b_fn.args.posonlyargs + b_fn.args.args]
        sharded_params = set()
        if in_specs is not None and len(in_specs) == len(params):
            sharded_params = {p for p, s in zip(params, in_specs)
                              if _is_sharded_spec(s)}

        flagged_passthrough = False
        for ret in _returns(b_fn):
            elts = ret.value.elts if isinstance(ret.value, ast.Tuple) \
                else [ret.value]
            if len(elts) != len(out_specs):
                continue
            for i in replicated:
                e = elts[i]
                if isinstance(e, ast.Name) and e.id in sharded_params \
                        and b_path in scanned:
                    flagged_passthrough = True
                    findings.append(Finding(
                        b_path, ret.lineno, RULE_ID, Severity.ERROR,
                        f"per-shard input `{e.id}` (sharded by in_specs) "
                        f"returned at out_specs position {i} declared "
                        f"replicated (P()) by the shard_map at "
                        f"{site.module}:{site.call.lineno} — each consumer "
                        f"device sees a different shard",
                        model.snippet(ret.lineno)))
        if flagged_passthrough:
            continue
        if not sharded_params:
            # no provably-sharded input: a collective-free body may be
            # legitimately replicated (all-P() inputs), so no claim
            continue

        # the no-reduction claim also needs data flow: the value at the
        # replicated position must actually DERIVE from a sharded input
        # (a replicated output computed purely from replicated inputs is
        # legitimately identical on every device)
        tainted = _sharded_taint(b_fn, sharded_params)
        tainted_return = None
        for ret in _returns(b_fn):
            elts = ret.value.elts if isinstance(ret.value, ast.Tuple) \
                else [ret.value]
            if len(elts) != len(out_specs):
                continue
            for i in replicated:
                if any(isinstance(n, ast.Name) and n.id in tainted
                       for n in ast.walk(elts[i])):
                    tainted_return = ret
                    break
            if tainted_return is not None:
                break
        if tainted_return is None:
            continue

        found, resolved = _reduction_scan(program, b_path, b_fn, b_env)
        if not found and resolved and b_path in scanned:
            line = tainted_return.lineno
            findings.append(Finding(
                b_path, line, RULE_ID, Severity.ERROR,
                f"shard_map body `{getattr(b_fn, 'name', '<fn>')}` declares "
                f"a replicated output (out_specs P() at the site "
                f"{site.module}:{site.call.lineno}) but performs no "
                f"cross-device reduction anywhere in its call graph — the "
                f"'replicated' value is whatever shard each device computed",
                model.snippet(line)))
    return findings
