"""G008 partition-spec/mesh mismatch: P(...) axes the mesh does not have.

A ``PartitionSpec`` names mesh axes; naming one the mesh lacks —
``P(SHARD_AXIS)`` under a 1-D ``make_mesh()`` that only binds ``workers``,
or a typo'd literal in ``NamedSharding(mesh, P("model"))`` — is accepted
at trace time on some paths and explodes (or silently replicates) at
placement time. The declarations live in ``parallel/mesh.py``; the uses
are spread over every trainer, so the check is cross-module: resolve the
mesh expression at each ``shard_map`` and ``NamedSharding(mesh, spec)``
site to its axis-name set (program.py), then validate every axis literal
(or constant resolvable to one) inside the specs against it.

Both ends must be provable; specs built dynamically (``jax.tree.map``
lambdas, computed tuples) are trusted.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from ..findings import Finding, Severity
from ..modmodel import dotted_name
from ..program import ProgramModel

RULE_ID = "G008"

_SPEC_CALLEES = ("P", "PartitionSpec")


def _spec_axis_literals(program: ProgramModel, path: str,
                        expr: Optional[ast.expr]
                        ) -> Iterator[Tuple[ast.AST, str]]:
    """(node, axis string) for every provable axis name inside P(...) calls
    of a spec expression (tuples of specs, nested axis tuples)."""
    if expr is None:
        return
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func) or ""
        if callee.rsplit(".", 1)[-1] not in _SPEC_CALLEES:
            continue
        stack = list(node.args)
        while stack:
            arg = stack.pop()
            if isinstance(arg, (ast.Tuple, ast.List)):
                stack.extend(arg.elts)
            elif isinstance(arg, ast.Constant) \
                    and isinstance(arg.value, str):
                yield node, arg.value
            elif isinstance(arg, ast.Name):
                s = program.resolve_str(path, arg.id)
                if s is not None:
                    yield node, s


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    seen = set()

    def flag(path: str, node: ast.AST, axis: str, axes: Set[str],
             where: str) -> None:
        if path not in scanned:
            return
        key = (path, node.lineno, axis, where)
        if key in seen:
            return
        seen.add(key)
        model = program.modules[path]
        findings.append(Finding(
            path, node.lineno, RULE_ID, Severity.ERROR,
            f"PartitionSpec names axis '{axis}' but the {where} mesh only "
            f"binds ({', '.join(sorted(axes))}) — the spec cannot be "
            f"honored and fails (or silently replicates) at placement "
            f"time", model.snippet(node.lineno)))

    # shard_map sites: in_specs/out_specs vs the site's mesh
    for site in program.shard_map_sites():
        model = program.modules.get(site.module)
        if model is None or site.module not in scanned:
            continue
        scope = model.enclosing_function(site.call)
        axes = program.mesh_axes(site.module, site.mesh_expr, scope)
        if not axes:
            continue
        for spec_expr in (site.in_specs_expr, site.out_specs_expr):
            for node, axis in _spec_axis_literals(program, site.module,
                                                  spec_expr):
                if axis not in axes:
                    flag(site.module, node, axis, axes, "shard_map")

    # NamedSharding(mesh, spec) / pjit(..., in_shardings=...) style sites
    for path in scanned:
        model = program.modules.get(path)
        if model is None:
            continue
        for node in ast.walk(model.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func) or ""
            tail = callee.rsplit(".", 1)[-1]
            if tail == "NamedSharding" and len(node.args) >= 2:
                scope = model.enclosing_function(node)
                axes = program.mesh_axes(path, node.args[0], scope)
                if not axes:
                    continue
                for spec_node, axis in _spec_axis_literals(
                        program, path, node.args[1]):
                    if axis not in axes:
                        flag(path, spec_node, axis, axes, "NamedSharding")
    return findings
