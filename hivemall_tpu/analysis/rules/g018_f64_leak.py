"""G018 weak-scalar/float64-leak: f64 defaults entering the serving path.

The serving tables are f32 (bf16 in the quantized manifests); a request
payload or intermediate staged as float64 doubles host staging bandwidth
and — when it reaches a device array — HBM traffic, for zero precision
the score math ever uses. Three provable channels, scoped to the
serving/IO modules (``serving/``, ``io/``, plus ``# graftcheck:
serving-module`` opt-ins):

- an explicit ``np.float64`` / ``np.double`` dtype (the
  ``serving/engine.py`` request-payload/intercept hits this rule was
  dogfooded on) — machine-fixable: ``--fix`` rewrites the token to
  ``np.float32``, matching the table dtype;
- ``astype(float)`` / ``dtype=float`` — Python's ``float`` IS float64;
- a float64-*by-default* numpy constructor: ``np.zeros/ones/empty``
  without a dtype (and ``np.full`` with a float fill) — the "weak Python
  scalar becomes a wide array" channel; single-line sites carry a fix
  appending ``dtype=np.float32``.

``jnp.*`` constructors default to f32 and are never flagged;
``np.asarray`` without a dtype follows its input and is trusted.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .. import config
from ..findings import Edit, Finding, Fix, Severity
from ..modmodel import ModuleModel, dotted_name

RULE_ID = "G018"

_F64_NAMES = ("np.float64", "numpy.float64", "np.double", "numpy.double",
              "np.float_", "numpy.float_")
_DEFAULT_F64_CTORS = ("zeros", "ones", "empty")


def _in_scope(model: ModuleModel) -> bool:
    return (model.rel_path.startswith(config.DTYPEFLOW_SERVING_PREFIXES)
            or config.CONCURRENCY_MARKER in model.source)


def _token_fix(model: ModuleModel, lineno: int, old: str, new: str
               ) -> Optional[Fix]:
    line = model.lines[lineno - 1] if 1 <= lineno <= len(model.lines) else ""
    if old in line:
        return Fix(edits=(Edit(lineno, old, new),))
    return None


def _pin_dtype_fix(model: ModuleModel, call: ast.Call) -> Optional[Fix]:
    """Append ``dtype=np.float32`` to a single-line constructor call."""
    if (call.end_lineno or call.lineno) != call.lineno:
        return None
    line = model.lines[call.lineno - 1] if call.lineno <= len(model.lines) \
        else ""
    seg = line[call.col_offset:call.end_col_offset]
    if not seg.endswith(")") or line.count(seg) != 1:
        return None
    return Fix(edits=(Edit(call.lineno, seg,
                           seg[:-1] + ", dtype=np.float32)"),))


def _has_dtype(call: ast.Call, positional: int) -> bool:
    if any(kw.arg == "dtype" for kw in call.keywords):
        return True
    if any(isinstance(a, ast.Starred) for a in call.args) \
            or any(kw.arg is None for kw in call.keywords):
        return True  # *args / **kwargs may carry the dtype: trusted
    return len(call.args) > positional


def _float_fill(node: ast.expr) -> bool:
    if isinstance(node, ast.UnaryOp):
        node = node.operand
    return isinstance(node, ast.Constant) and isinstance(node.value, float)


def check(model: ModuleModel) -> List[Finding]:
    if not _in_scope(model):
        return []
    findings: List[Finding] = []

    def emit(node: ast.AST, msg: str, fix: Optional[Fix]) -> None:
        findings.append(Finding(model.rel_path, node.lineno, RULE_ID,
                                Severity.ERROR, msg,
                                model.snippet(node.lineno), fix=fix))

    for node in ast.walk(model.tree):
        name = dotted_name(node) if isinstance(node, (ast.Attribute,
                                                      ast.Name)) else None
        if name in _F64_NAMES:
            parent = getattr(node, "graftcheck_parent", None)
            if isinstance(parent, ast.Attribute):
                continue  # attribute inside a longer dotted chain
            fix = _token_fix(model, node.lineno, "np.float64",
                             "np.float32") if name == "np.float64" else None
            emit(node, f"{name} on the serving path — request payloads and "
                       f"intermediates should match the f32 table dtype "
                       f"(f64 doubles host and HBM bandwidth for precision "
                       f"the score math never uses); use np.float32", fix)
            continue
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func) or ""
        root, _, tail = callee.rpartition(".")
        if tail == "astype" and node.args:
            a = node.args[0]
            if isinstance(a, ast.Name) and a.id == "float":
                emit(node, "astype(float) is float64 on the serving path — "
                           "pin np.float32 (the table dtype)", None)
        elif any(kw.arg == "dtype" and isinstance(kw.value, ast.Name)
                 and kw.value.id == "float" for kw in node.keywords):
            emit(node, "dtype=float is float64 on the serving path — pin "
                       "np.float32 (the table dtype)", None)
        elif root in ("np", "numpy") and tail in _DEFAULT_F64_CTORS:
            if not _has_dtype(node, 1):
                emit(node, f"np.{tail} without a dtype allocates float64 — "
                           f"the weak-scalar default leak; pin "
                           f"dtype=np.float32 to match the serving tables",
                     _pin_dtype_fix(model, node))
        elif root in ("np", "numpy") and tail == "full":
            if not _has_dtype(node, 2) and len(node.args) > 1 \
                    and _float_fill(node.args[1]):
                emit(node, "np.full with a Python-float fill allocates "
                           "float64 — pin dtype=np.float32 to match the "
                           "serving tables", _pin_dtype_fix(model, node))
    return findings
