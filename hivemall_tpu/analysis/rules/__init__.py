"""Rule registry.

Module rules export ``RULE_ID`` and ``check(model)``; program rules export
``RULE_ID`` and ``check_program(program, scanned)`` — the runner dispatches
each tier (runner._run_rules)."""

from __future__ import annotations

from typing import Callable, Dict, List

from ..findings import Finding
from ..modmodel import ModuleModel
from . import (g001_recompile, g002_host_sync, g003_dtype, g004_axis,
               g005_donation, g006_side_effect, g007_collective_axis,
               g008_spec_mesh, g009_api_compat, g010_unreduced_output,
               g011_divergent_collective, g012_unguarded_shared_field,
               g013_blocking_under_lock, g014_cv_misuse, g015_thread_leak,
               g016_lock_order_cycle, g017_hot_promotion, g018_f64_leak,
               g019_cast_in_loop, g020_artifact_dtype,
               g021_low_precision_accum, g022_ffi_unvalidated_pointer,
               g023_ffi_borrowed_buffer, g024_ffi_missing_prototype,
               g025_ffi_abi_drift, g026_ffi_unchecked_return,
               g027_future_leak, g028_silent_fallback,
               g029_swallowed_exception, g030_unwind_under_lock,
               g031_unbounded_retry, g032_jit_cache_churn,
               g033_host_branch_traced, g034_unbucketed_shape,
               g035_donated_reuse, g036_hot_loop_sync)

_MODULE_RULES = (g001_recompile, g002_host_sync, g003_dtype, g004_axis,
                 g005_donation, g006_side_effect, g009_api_compat,
                 g015_thread_leak, g018_f64_leak)
_PROGRAM_RULES = (g007_collective_axis, g008_spec_mesh,
                  g010_unreduced_output, g011_divergent_collective,
                  g012_unguarded_shared_field, g013_blocking_under_lock,
                  g014_cv_misuse, g016_lock_order_cycle,
                  g017_hot_promotion, g019_cast_in_loop,
                  g020_artifact_dtype, g021_low_precision_accum,
                  g022_ffi_unvalidated_pointer, g023_ffi_borrowed_buffer,
                  g024_ffi_missing_prototype, g025_ffi_abi_drift,
                  g026_ffi_unchecked_return, g027_future_leak,
                  g028_silent_fallback, g029_swallowed_exception,
                  g030_unwind_under_lock, g031_unbounded_retry,
                  g032_jit_cache_churn, g033_host_branch_traced,
                  g034_unbucketed_shape, g035_donated_reuse,
                  g036_hot_loop_sync)

ALL_RULES: Dict[str, Callable[[ModuleModel], List[Finding]]] = {
    m.RULE_ID: m.check for m in _MODULE_RULES
}

PROGRAM_RULES: Dict[str, Callable] = {
    m.RULE_ID: m.check_program for m in _PROGRAM_RULES
}

RULE_DOCS: Dict[str, str] = {
    m.RULE_ID: (m.__doc__ or "").strip().splitlines()[0]
    for m in _MODULE_RULES + _PROGRAM_RULES
}
