"""Rule registry: each rule module exports RULE_ID and check(model)."""

from __future__ import annotations

from typing import Callable, Dict, List

from ..findings import Finding
from ..modmodel import ModuleModel
from . import (g001_recompile, g002_host_sync, g003_dtype, g004_axis,
               g005_donation, g006_side_effect)

_MODULES = (g001_recompile, g002_host_sync, g003_dtype, g004_axis,
            g005_donation, g006_side_effect)

ALL_RULES: Dict[str, Callable[[ModuleModel], List[Finding]]] = {
    m.RULE_ID: m.check for m in _MODULES
}

RULE_DOCS: Dict[str, str] = {
    m.RULE_ID: (m.__doc__ or "").strip().splitlines()[0] for m in _MODULES
}
