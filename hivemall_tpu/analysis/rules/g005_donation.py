"""G005 donation-misuse: hot-loop jits that copy state, or reuse donated.

(a) A jit wrapper around a step-shaped function (name matches
    step/epoch/train) without ``donate_argnums`` forces XLA to keep the
    input model tables alive across the step — at 2^24-dim tables that is
    a full extra HBM copy per step (warning; predict-shaped wrappers are
    exempt: their inputs are reused by design).
(b) Reading a variable after passing it at a donated position of a known
    donating jit (``name = jax.jit(fn, donate_argnums=(0,))``) — the
    buffer was handed to XLA; the read sees a deleted array at run time,
    but only on paths that actually execute (error).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .. import config
from ..findings import Finding, Severity
from ..modmodel import ModuleModel, dotted_name, walk_scope

RULE_ID = "G005"


def check(model: ModuleModel) -> List[Finding]:
    findings: List[Finding] = []

    def emit(node: ast.AST, msg: str, sev: str) -> None:
        findings.append(Finding(model.rel_path, node.lineno, RULE_ID, sev,
                                msg, model.snippet(node.lineno)))

    # (a) step-shaped jit wrappers without donate_argnums
    for wrap, wrapped_name in model.jit_wraps:
        if wrap.has_donate:
            continue
        name = wrapped_name or ""
        tail = name.rsplit(".", 1)[-1]
        if config.STEP_NAME_RE.search(tail):
            emit(wrap.call,
                 f"jax.jit({tail}) without donate_argnums — a hot-loop step "
                 f"keeps an extra copy of the model tables alive in HBM; "
                 f"donate the state argument", Severity.WARNING)

    # (b) read-after-donate, linear scan per function body
    donating = {name: wrap for name, wrap in model.jit_aliases.items()
                if wrap.donate_argnums}
    if not donating:
        return findings
    for fn in model.functions:
        if model.is_traced(fn):
            continue
        stmts = list(fn.body)
        _scan_block(model, fn, stmts, donating, emit)
    return findings


def _assigned_names(stmt: ast.stmt):
    """Every name (re)bound anywhere within `stmt`, including inside
    compound-statement bodies — a rebind on any path clears the donation."""
    for node in ast.walk(stmt):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                yield from _target_names(tgt)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            yield from _target_names(node.target)
        elif isinstance(node, ast.For):
            yield from _target_names(node.target)


def _target_names(tgt):
    if isinstance(tgt, ast.Name):
        yield tgt.id
    elif isinstance(tgt, (ast.Tuple, ast.List)):
        for elt in tgt.elts:
            yield from _target_names(elt)
    elif isinstance(tgt, ast.Starred):
        yield from _target_names(tgt.value)


def _donated_name(call: ast.Call, donating) -> Optional[str]:
    callee = dotted_name(call.func)
    wrap = donating.get(callee) if callee else None
    if wrap is None:
        return None
    for pos in wrap.donate_argnums or ():
        if pos < len(call.args) and isinstance(call.args[pos], ast.Name):
            return call.args[pos].id
    return None


def _scan_block(model, fn, stmts, donating, emit) -> None:
    """Flag reads of a donated Name after the donating call, stopping at
    reassignment. Straight-line approximation: nested blocks are scanned
    in statement order."""
    pending = {}  # var name -> lineno of donation
    for stmt in stmts:
        # reads in this statement of still-pending donated names
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                    and node.id in pending:
                emit(node, f"`{node.id}` read after being donated to a "
                           f"jitted step at line {pending[node.id]} — the "
                           f"buffer belongs to XLA now; rebind the result "
                           f"(`{node.id} = step({node.id}, ...)`) or drop "
                           f"donation", Severity.ERROR)
                del pending[node.id]
        # reassignment clears the pending flag
        for name in _assigned_names(stmt):
            pending.pop(name, None)
        # new donations introduced by this statement
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                victim = _donated_name(node, donating)
                if victim is not None:
                    # `state = step(state, ...)` rebinds: not pending
                    if victim in set(_assigned_names(stmt)):
                        continue
                    pending[victim] = node.lineno
