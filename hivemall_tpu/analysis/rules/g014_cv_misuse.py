"""G014 condition-variable misuse: wait outside a loop, notify unheld, re-acquire.

Three provable misuses of the ``threading.Condition`` protocol:

- **wait() not in a predicate loop**: spurious wakeups and notify races
  mean a woken waiter must re-check its predicate; ``if pred:
  cv.wait()`` proceeds on a stale condition. The single-statement form
  carries a machine fix (``--fix`` rewrites the ``if`` to ``while``).
- **notify()/notify_all() without the CV held**: raises RuntimeError at
  run time on the stdlib Condition — but only on the code path that
  reaches it, which a lightly-loaded test may never do.
- **re-acquiring a non-reentrant Lock through a helper**: ``with
  self._lock:`` then ``self._helper()`` whose body takes ``self._lock``
  again self-deadlocks; found through the same context propagation that
  powers the guarded-by inference (analysis/concurrency.py).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..concurrency import get_model
from ..findings import Edit, Finding, Fix, Severity
from ..modmodel import _FN_TYPES
from ..program import ProgramModel

RULE_ID = "G014"


def _enclosing_while(node: ast.AST) -> Optional[ast.While]:
    cur = getattr(node, "graftcheck_parent", None)
    while cur is not None and not isinstance(cur, _FN_TYPES):
        if isinstance(cur, ast.While):
            return cur
        cur = getattr(cur, "graftcheck_parent", None)
    return None


def _wait_loop_fix(call: ast.Call, model) -> Optional[Fix]:
    """``if <pred>:`` directly wrapping a lone ``cv.wait()`` statement
    rewrites to ``while <pred>:`` — a within-line, semantics-preserving
    repair (the predicate is simply re-checked after wakeup)."""
    stmt = getattr(call, "graftcheck_parent", None)
    if not isinstance(stmt, ast.Expr):
        return None
    branch = getattr(stmt, "graftcheck_parent", None)
    if not isinstance(branch, ast.If) or branch.orelse \
            or branch.body != [stmt]:
        return None
    if (branch.test.end_lineno or branch.lineno) != branch.lineno:
        return None  # multi-line test: hand repair
    line = model.snippet(branch.lineno)
    if not line.startswith("if "):
        return None  # elif arms can't become while
    return Fix(edits=(Edit(branch.lineno, "if ", "while "),))


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    cm = get_model(program)
    for (path, _cname), cls in sorted(cm.classes.items()):
        if path not in scanned:
            continue
        model = program.modules[path]
        conds = {name for name, kind in cls.locks.items()
                 if kind == "condition"}

        # (a) wait() outside a predicate loop — structural, per call site
        for mname in sorted(cls.raw):
            for ev in cls.raw[mname].calls:
                parts = ev.dotted.split(".")
                if len(parts) == 3 and parts[0] == "self" \
                        and parts[1] in conds and parts[2] == "wait" \
                        and _enclosing_while(ev.node) is None:
                    findings.append(Finding(
                        path, ev.line, RULE_ID, Severity.ERROR,
                        f"`self.{parts[1]}.wait()` is not inside a "
                        f"`while <predicate>` loop — spurious wakeups and "
                        f"notify races hand control back with the "
                        f"predicate still false; loop until it holds",
                        model.snippet(ev.line),
                        fix=_wait_loop_fix(ev.node, model)))

        # (b) notify()/notify_all() with the CV not held — context-aware
        seen_notify: Set[int] = set()
        for ev in cls.eff_calls:
            parts = ev.dotted.split(".")
            if len(parts) == 3 and parts[0] == "self" \
                    and parts[1] in conds \
                    and parts[2] in ("notify", "notify_all") \
                    and parts[1] not in ev.held \
                    and ev.line not in seen_notify:
                seen_notify.add(ev.line)
                findings.append(Finding(
                    path, ev.line, RULE_ID, Severity.ERROR,
                    f"`self.{parts[1]}.{parts[2]}()` without holding the "
                    f"condition variable — raises RuntimeError on the "
                    f"stdlib Condition, but only on the path that reaches "
                    f"it; wrap in `with self.{parts[1]}:`",
                    model.snippet(ev.line)))

        # (c) non-reentrant lock re-acquired through a helper chain
        for node, lock in sorted(cls.double_acquires,
                                 key=lambda t: t[0].lineno):
            findings.append(Finding(
                path, node.lineno, RULE_ID, Severity.ERROR,
                f"`self.{lock}` (a non-reentrant threading.Lock) is "
                f"re-acquired through this call chain — the thread "
                f"deadlocks on itself; use an RLock or split the locked "
                f"helper out of the locked region",
                model.snippet(node.lineno)))
    return findings
