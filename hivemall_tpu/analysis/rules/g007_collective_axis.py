"""G007 collective-axis-not-bound: psum over an axis the shard_map lacks.

A collective names a mesh axis; that axis must be bound by the enclosing
``shard_map``'s mesh or the program dies at run time (on hardware, inside
the compiled step) with an unbound-axis error — or worse, silently reduces
over the wrong axis on a 2-D mesh. The hazard hides *interprocedurally*:
the psum usually sits in a helper (``mix_average`` in ``parallel/mix.py``,
the histogram bodies in ``models/trees/grow.py``) several calls below the
``shard_map`` site that binds the axes.

For every shard_map site whose mesh expression resolves to a literal
axis-name set, the rule walks the body's call graph (through factory
returns, function-valued arguments, and string arguments propagated edge
by edge — see program.py) and checks every collective whose axis resolves
to a literal. Both ends must be provable: unknown meshes and dynamic axis
names are trusted, exactly like G004.
"""

from __future__ import annotations

from typing import List, Set

from ..findings import Finding, Severity
from ..program import ProgramModel

RULE_ID = "G007"


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    seen = set()
    for site in program.shard_map_sites():
        model = program.modules.get(site.module)
        if model is None:
            continue
        scope = model.enclosing_function(site.call)
        axes = program.mesh_axes(site.module, site.mesh_expr, scope)
        if not axes:
            continue
        body = program.resolve_callable(site.module, site.fn_expr)
        if body is None:
            continue
        b_path, b_fn, b_env = body
        for f_path, f_fn, summ, env in program.walk_calls(
                b_path, b_fn, b_env):
            for call, tail, kind, value in summ.collectives:
                axis = program.resolve_axis(f_path, f_fn, kind, value, env)
                if axis is None or axis in axes:
                    continue
                if f_path not in scanned:
                    continue
                f_model = program.modules[f_path]
                key = (f_path, call.lineno, tail, axis, site.module,
                       site.call.lineno)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    f_path, call.lineno, RULE_ID, Severity.ERROR,
                    f"collective `{tail}` over axis '{axis}' which is not "
                    f"bound by the enclosing shard_map at "
                    f"{site.module}:{site.call.lineno} (mesh axes: "
                    f"{', '.join(sorted(axes))}) — unbound collective axes "
                    f"fail only at run time inside the compiled step",
                    f_model.snippet(call.lineno)))
    return findings
