"""G003 dtype-drift: float64 and unpinned literals in update math.

The storage policy (models/base.py, LearnerBaseUDTF.java:172-175 analog)
stores tables bf16 above 2^24 dims; rule math deliberately runs f32 and
casts once at the table write. Two drift channels break that silently:

(a) ``np.float64`` / ``np.double`` / ``dtype=float`` / ``astype(float)``
    anywhere in the dtype-sensitive packages (ops/, core/, models/,
    kernels/) — f64 propagates through every downstream op and doubles
    both HBM and VPU cost (error);
(b) bare Python float literals as arithmetic operands inside traced
    functions and inside the update-math modules (ops/eta.py,
    ops/losses.py) — under ``jax_enable_x64`` (or numpy-scalar mixing) a
    bare literal promotes the whole expression; pin with
    ``jnp.asarray(lit, x.dtype)`` so the expression follows the array's
    dtype (warning).

Literals passed as *call arguments* (``jnp.maximum(x, 1.0)``) follow JAX
weak-type promotion against an explicit array and are not flagged;
comparison thresholds (``p > -100.0``) are likewise safe.
"""

from __future__ import annotations

import ast
from typing import List

from .. import config
from ..findings import Finding, Severity
from ..modmodel import ModuleModel, dotted_name, walk_scope

RULE_ID = "G003"

_F64_NAMES = ("np.float64", "numpy.float64", "np.double", "numpy.double",
              "np.float_", "numpy.float_", "jnp.float64")


def _in_dtype_modules(model: ModuleModel) -> bool:
    return (model.rel_path.startswith(config.DTYPE_MODULE_PREFIXES)
            or "# graftcheck: dtype-module" in model.source)


def _is_math_module(model: ModuleModel) -> bool:
    return (model.rel_path in config.DTYPE_MATH_MODULES
            or "# graftcheck: dtype-module" in model.source)


def _float_literal_operands(binop: ast.BinOp):
    for side in (binop.left, binop.right):
        node = side
        if isinstance(node, ast.UnaryOp):
            node = node.operand
        if isinstance(node, ast.Constant) and isinstance(node.value, float):
            yield side


def check(model: ModuleModel) -> List[Finding]:
    if not _in_dtype_modules(model):
        return []
    findings: List[Finding] = []

    def emit(node: ast.AST, msg: str, sev: str) -> None:
        findings.append(Finding(model.rel_path, node.lineno, RULE_ID, sev,
                                msg, model.snippet(node.lineno)))

    # (a) float64 anywhere in dtype-sensitive modules
    for node in ast.walk(model.tree):
        name = dotted_name(node) if isinstance(node, (ast.Attribute,
                                                      ast.Name)) else None
        if name in _F64_NAMES:
            # only flag *loads* (np.float64(x), dtype=np.float64), not the
            # attribute inside a larger dotted chain
            parent = getattr(node, "graftcheck_parent", None)
            if isinstance(parent, ast.Attribute):
                continue
            emit(node, f"{name} in update math — f64 doubles HBM traffic "
                       f"and silently upcasts the bf16 storage policy "
                       f"(models/base.py); use float32/bfloat16",
                 Severity.ERROR)
        elif isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "astype" and node.args:
                a = node.args[0]
                if isinstance(a, ast.Name) and a.id == "float":
                    emit(node, "astype(float) is float64 — pin an explicit "
                               "32-bit (or table) dtype", Severity.ERROR)

    # (b) unpinned float literals in arithmetic
    for fn in model.functions:
        scan = model.is_traced(fn) or _is_math_module(model)
        if not scan:
            continue
        for node in walk_scope(fn):
            if not isinstance(node, ast.BinOp):
                continue
            if not isinstance(node.op, (ast.Add, ast.Sub, ast.Mult, ast.Div,
                                        ast.Pow, ast.Mod, ast.FloorDiv)):
                continue
            for lit in _float_literal_operands(node):
                emit(lit, f"bare float literal {ast.unparse(lit)} in update "
                          f"arithmetic — pin with jnp.asarray(lit, x.dtype) "
                          f"so x64/np-scalar mixing cannot promote the "
                          f"update dtype", Severity.WARNING)
    return findings
