"""G020 dtype-unstable-artifact-round-trip: reloads that don't pin dtype.

The artifact save path widens bf16 tables to f32 at rest (np.savez cannot
round-trip ml_dtypes reliably — serving/artifact._host) and records the
training dtype in the manifest. A load-side ``jnp.asarray(pack[...])``
WITHOUT a dtype therefore resurrects the table *wide*: a bf16-trained
model silently serves at 2x the HBM traffic forever after one
freeze->load cycle, and a future int8 manifest would dequantize at load.
This rule flags exactly that shape, in the artifact/checkpoint modules
(``io/checkpoint.py``, ``serving/artifact.py``, ``serving/engine.py``,
plus ``# graftcheck: artifact-io`` opt-ins):

- a name bound from ``np.load(...)`` (assignment or ``with ... as z``) or
  from an ``.arrays`` attribute (the Artifact pack) is a **pack**;
- ``jnp.asarray(pack[...])`` / ``jnp.array(pack[...])`` with no dtype
  argument is a finding — pin the dtype from the manifest
  (``meta["weights_dtype"]``, see serving/artifact.manifest_dtype) or
  suppress with a rationale where the stored dtype is authoritative.

Host-side ``np`` uses of pack entries are fine (numpy round-trips its own
concrete dtypes bit-exactly); only the host->device rebuild can widen.
"""

from __future__ import annotations

import ast
from typing import List, Set

from .. import config
from ..findings import Finding, Severity
from ..modmodel import _FN_TYPES, ModuleModel, dotted_name, walk_scope
from ..program import ProgramModel

RULE_ID = "G020"

_ASARRAY_TAILS = ("asarray", "array")
_JNP_ROOTS = ("jnp", "jax.numpy")


def _in_scope(model: ModuleModel) -> bool:
    return (model.rel_path in config.ARTIFACT_IO_MODULES
            or config.ARTIFACT_MARKER in model.source)


def _is_pack_source(expr: ast.expr) -> bool:
    """np.load(...) or <x>.arrays — the two pack producers."""
    if isinstance(expr, ast.Call):
        callee = dotted_name(expr.func) or ""
        if callee.rsplit(".", 1)[-1] == "load" \
                and callee.split(".", 1)[0] in ("np", "numpy"):
            return True
    if isinstance(expr, ast.Attribute) and expr.attr == "arrays":
        return True
    return False


def _pack_names(fn: ast.AST) -> Set[str]:
    packs: Set[str] = set()
    for node in walk_scope(fn):
        if isinstance(node, ast.Assign):
            targets = node.targets
            values = [node.value]
            if len(targets) == 1 and isinstance(targets[0], ast.Tuple) \
                    and isinstance(node.value, ast.Tuple) \
                    and len(targets[0].elts) == len(node.value.elts):
                targets, values = targets[0].elts, node.value.elts
            for tgt, val in zip(targets, values * len(targets)
                                if len(values) == 1 else values):
                if isinstance(tgt, ast.Name) and _is_pack_source(val):
                    packs.add(tgt.id)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None \
                        and isinstance(item.optional_vars, ast.Name) \
                        and _is_pack_source(item.context_expr):
                    packs.add(item.optional_vars.id)
    return packs


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    for path in sorted(scanned):
        model = program.modules.get(path)
        if model is None or not _in_scope(model):
            continue
        for fn in model.functions:
            packs = _pack_names(fn)
            if not packs:
                continue
            for node in walk_scope(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = dotted_name(node.func) or ""
                root, _, tail = callee.rpartition(".")
                if tail not in _ASARRAY_TAILS or root not in _JNP_ROOTS:
                    continue
                args = [a for a in node.args
                        if not isinstance(a, ast.Starred)]
                if len(args) != len(node.args) or not args:
                    continue
                first = args[0]
                if not (isinstance(first, ast.Subscript)
                        and isinstance(first.value, ast.Name)
                        and first.value.id in packs):
                    continue
                if len(args) > 1 or any(kw.arg == "dtype"
                                        for kw in node.keywords):
                    continue  # dtype pinned: stable round-trip
                findings.append(Finding(
                    path, node.lineno, RULE_ID, Severity.WARNING,
                    f"dtype-unstable artifact round-trip: "
                    f"{callee}({ast.unparse(first)}) reloads whatever "
                    f"width the pack holds — the save path widens bf16 to "
                    f"f32 at rest, so a reduced-precision table silently "
                    f"serves wide after one freeze->load; pin the dtype "
                    f"from the manifest (meta['weights_dtype'] via "
                    f"serving/artifact.manifest_dtype)",
                    model.snippet(node.lineno)))
    return findings
