"""G017 silent-dtype-promotion-in-hot-path: a reduced array widens implicitly.

The dequant-free violation: a bf16/f16/int8 array meets an f32/f64 operand
in a hot-path scope (ops/, kernels/, the serving score path, traced or
step-shaped functions in the dtype-sensitive packages) and the result
widens — from that op on, every downstream read/write moves 2-4x the
bytes the quantized table was sized for. The dtype-flow model
(analysis/dtypeflow.py) proves both operand dtypes through constructors,
astype sites, and call-return summaries; mixes involving unknown or weak
(Python-scalar) operands are trusted, exactly like G004 trusts dynamic
axis names. Intentional widening (an f32 accumulator fed by a bf16 table)
is declared with an explicit ``astype``/``dtype=`` — explicit casts never
fire this rule (G019/G021 police those separately).
"""

from __future__ import annotations

from typing import List, Set

from ..dtypeflow import get_model, in_hot_scope
from ..findings import Finding, Severity
from ..program import ProgramModel

RULE_ID = "G017"


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    flow = get_model(program)
    for path in sorted(scanned):
        model = program.modules.get(path)
        if model is None:
            continue
        seen: Set[int] = set()
        for fn in model.functions:
            if not in_hot_scope(path, model, fn):
                continue
            for site in flow.facts(path, fn).promotions:
                if site.node.lineno in seen:
                    continue
                seen.add(site.node.lineno)
                findings.append(Finding(
                    path, site.node.lineno, RULE_ID, Severity.ERROR,
                    f"silent dtype promotion in hot path: "
                    f"{site.left_dt.name} x {site.right_dt.name} widens to "
                    f"{site.out_dt.name} — every downstream op now moves "
                    f"{site.out_dt.bits // 8} bytes/elt where the reduced "
                    f"table was sized for "
                    f"{min(site.left_dt.bits, site.right_dt.bits) // 8}; "
                    f"cast the wide operand down (or widen explicitly with "
                    f"astype and a rationale if accumulation requires it)",
                    model.snippet(site.node.lineno)))
    return findings
