"""G011 collective-under-divergent-control-flow: a psum only some devices
reach.

Collectives are rendezvous points: every device in the mesh must execute
the same collective in the same order. A collective guarded by control
flow that can *diverge across devices* — a Python ``if`` on
``jax.lax.axis_index`` (each device sees a different value at trace time
under shard_map, and the branch bakes device-dependent programs), or a
collective inside a ``jax.lax.cond``/``switch`` branch whose predicate is
per-shard data — deadlocks on hardware or returns garbage, and does so
only at scale, never in single-device tests.

Flagged patterns:

- a collective lexically inside an ``if``/``while`` whose test involves
  ``axis_index`` (directly or through a local name bound to it);
- a collective inside a function passed as a *branch* to
  ``jax.lax.cond``/``jax.lax.switch`` (resolved through the program call
  graph, so a psum two helpers below the branch is still found). Branches
  must be collective-free regardless of the predicate: under vma
  semantics both branches trace, but the hardware schedule only
  rendezvous when *every* device takes the same path, which a per-shard
  predicate cannot guarantee.
"""

from __future__ import annotations

import ast
from typing import List, Set

from .. import config
from ..findings import Finding, Severity
from ..modmodel import _FN_TYPES, dotted_name, walk_scope
from ..program import ProgramModel

RULE_ID = "G011"

_BRANCH_TRANSFORMS = ("cond", "switch")


def _axis_index_names(fn: ast.AST) -> Set[str]:
    """Local names bound to jax.lax.axis_index(...) results."""
    names: Set[str] = set()
    for node in walk_scope(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            callee = dotted_name(node.value.func) or ""
            if callee.rsplit(".", 1)[-1] == "axis_index":
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        names.add(tgt.id)
    return names


def _test_is_device_varying(test: ast.expr, idx_names: Set[str]) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func) or ""
            if callee.rsplit(".", 1)[-1] == "axis_index":
                return True
        if isinstance(node, ast.Name) and node.id in idx_names:
            return True
    return False


def _collectives_under(stmt_body, model) -> List[ast.Call]:
    out = []
    # scope-pruned walk: a def/lambda nested under the branch is a
    # separate trace scope — it only diverges if *called* there, which the
    # call-site analysis covers
    stack = list(stmt_body)
    while stack:
        node = stack.pop()
        if isinstance(node, _FN_TYPES + (ast.Lambda,)):
            continue
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func) or ""
            tail = callee.rsplit(".", 1)[-1]
            if tail in config.COLLECTIVE_CALLS and tail != "axis_index":
                out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    seen = set()

    def flag(path: str, call: ast.Call, why: str) -> None:
        if path not in scanned:
            return
        key = (path, call.lineno, why)
        if key in seen:
            return
        seen.add(key)
        model = program.modules[path]
        tail = (dotted_name(call.func) or "").rsplit(".", 1)[-1]
        findings.append(Finding(
            path, call.lineno, RULE_ID, Severity.ERROR,
            f"collective `{tail}` under device-divergent control flow "
            f"({why}) — collectives are rendezvous points; devices that "
            f"skip the branch deadlock the mesh (or corrupt the reduction) "
            f"at run time", model.snippet(call.lineno)))

    for path in scanned:
        model = program.modules.get(path)
        if model is None:
            continue
        # pattern 1: if/while on axis_index around a collective
        for fn in model.functions:
            idx_names = _axis_index_names(fn)
            for node in walk_scope(fn):
                if isinstance(node, (ast.If, ast.While)) \
                        and _test_is_device_varying(node.test, idx_names):
                    for call in _collectives_under(node.body + node.orelse,
                                                   model):
                        flag(path, call,
                             "a Python `if`/`while` on jax.lax.axis_index")

    # pattern 2: collectives reachable from lax.cond/switch branches
    for path, model in program.modules.items():
        if "cond" not in model.source and "switch" not in model.source:
            continue  # cheap pre-filter before the full AST walk
        for node in ast.walk(model.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func) or ""
            tail = callee.rsplit(".", 1)[-1]
            if tail not in _BRANCH_TRANSFORMS or not callee.startswith(
                    ("jax.lax.", "lax.")):
                continue
            if tail == "cond":
                branches = node.args[1:3]
            else:
                # switch(index, branches_sequence, *operands): only a
                # literal branch list resolves; operands are data, never
                # branches
                seq = node.args[1] if len(node.args) > 1 else None
                branches = list(seq.elts) \
                    if isinstance(seq, (ast.Tuple, ast.List)) else []
            for br in branches:
                body = program.resolve_callable(path, br)
                if body is None:
                    continue
                b_path, b_fn, b_env = body
                for f_path, f_fn, summ, _ in program.walk_calls(
                        b_path, b_fn, b_env):
                    for call, c_tail, _, _ in summ.collectives:
                        if c_tail == "axis_index":
                            continue
                        flag(f_path, call,
                             f"a `jax.lax.{tail}` branch at "
                             f"{path}:{node.lineno}")
    return findings
