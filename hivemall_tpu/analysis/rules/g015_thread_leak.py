"""G015 thread-leak: a non-daemon thread with no join on any shutdown path.

A ``threading.Thread`` that is neither ``daemon=True`` nor ``join()``ed
anywhere outlives its owner: process exit hangs waiting for it, test
runs accumulate workers, and a serving hot-swap that forgets to join
the old worker leaks one thread per deploy. The repo convention
(metrics/serving servers, the batcher worker) is daemon threads plus an
explicit ``join`` on the close path.

Resolution is conservative: a thread object that escapes the analyzed
scope (returned, yielded, passed as an argument, stored into an
untracked structure) is trusted, as is a dynamic ``daemon=<expr>``.
Joins are recognized directly (``t.join()``, ``self._t.join()``) and
through the collect-then-join idiom (``threads.append(t)`` /
comprehension into ``threads``, then ``for t in threads: t.join()``).

Single-line constructor calls carry a machine fix appending
``daemon=True``.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..findings import Edit, Finding, Fix, Severity
from ..modmodel import _FN_TYPES, ModuleModel, dotted_name, walk_scope

RULE_ID = "G015"


def _daemon_state(call: ast.Call) -> Optional[bool]:
    """True = daemon, False = explicitly/implicitly non-daemon,
    None = dynamic (trusted)."""
    for kw in call.keywords:
        if kw.arg == "daemon":
            if isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
            return None
    return False


def _scope_of(model: ModuleModel, node: ast.AST) -> ast.AST:
    return model.enclosing_function(node) or model.tree


def _joins_name(scope: ast.AST, name: str) -> bool:
    for node in walk_scope(scope):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join" \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == name:
            return True
    return False


def _escapes(scope: ast.AST, name: str, assign: ast.Assign) -> bool:
    """The thread object leaves this scope: returned, yielded, passed as an
    argument, or stored somewhere we don't track."""
    for node in walk_scope(scope):
        if isinstance(node, ast.Name) and node.id == name \
                and isinstance(node.ctx, ast.Load):
            parent = getattr(node, "graftcheck_parent", None)
            if isinstance(parent, ast.Attribute):
                continue  # t.start() / t.join() — method use
            if isinstance(parent, ast.Call) and node in parent.args:
                fn = dotted_name(parent.func) or ""
                if fn.endswith(".append"):
                    continue  # collect-then-join, checked by the caller
                return True
            if isinstance(parent, (ast.Return, ast.Yield, ast.keyword,
                                   ast.Tuple, ast.List, ast.Dict,
                                   ast.Subscript, ast.Starred)):
                return True
            if isinstance(parent, ast.Assign) and parent is not assign:
                return True
    return False


def _collected_list(scope: ast.AST, name: str) -> Optional[str]:
    """List variable `name` is appended to: `L.append(t)` -> "L"."""
    for node in walk_scope(scope):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "append" \
                and isinstance(node.func.value, ast.Name) \
                and any(isinstance(a, ast.Name) and a.id == name
                        for a in node.args):
            return node.func.value.id
    return None


def _list_joined(scope: ast.AST, list_name: str) -> bool:
    """``for t in L: t.join()`` (or join on an element of L)."""
    for node in walk_scope(scope):
        if isinstance(node, ast.For) and isinstance(node.iter, ast.Name) \
                and node.iter.id == list_name \
                and isinstance(node.target, ast.Name):
            if _joins_name(node, node.target.id):
                return True
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join" \
                and isinstance(node.func.value, ast.Subscript) \
                and isinstance(node.func.value.value, ast.Name) \
                and node.func.value.value.id == list_name:
            return True
    return False


def _self_attr_joined(model: ModuleModel, node: ast.AST, attr: str) -> bool:
    """Any ``self.<attr>.join(`` (or escape of self.<attr>) in the class."""
    cls = getattr(node, "graftcheck_parent", None)
    while cls is not None and not isinstance(cls, ast.ClassDef):
        cls = getattr(cls, "graftcheck_parent", None)
    if cls is None:
        return False
    for n in ast.walk(cls):
        if isinstance(n, ast.Attribute) \
                and isinstance(n.value, ast.Attribute) \
                and isinstance(n.value.value, ast.Name) \
                and n.value.value.id == "self" and n.value.attr == attr \
                and n.attr == "join":
            return True
        # self._t passed somewhere: escapes, trusted
        if isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name) \
                and n.value.id == "self" and n.attr == attr \
                and isinstance(n.ctx, ast.Load):
            parent = getattr(n, "graftcheck_parent", None)
            if isinstance(parent, ast.Call) and n in parent.args:
                return True
    return False


def _comprehension_target(call: ast.Call) -> Optional[ast.AST]:
    """The comprehension node the Thread(...) call sits in, if any."""
    cur = getattr(call, "graftcheck_parent", None)
    while cur is not None and not isinstance(cur, _FN_TYPES):
        if isinstance(cur, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return cur
        cur = getattr(cur, "graftcheck_parent", None)
    return None


def _daemon_fix(model: ModuleModel, call: ast.Call) -> Optional[Fix]:
    if call.end_lineno != call.lineno:
        return None  # multi-line constructor: hand repair
    if any(kw.arg == "daemon" for kw in call.keywords):
        return None  # daemon=False/None present: appending would repeat
        # the kwarg (SyntaxError) — the intent needs a human
    segment = ast.get_source_segment(model.source, call)
    if not segment or not segment.endswith(")"):
        return None
    sep = ", " if (call.args or call.keywords) else ""
    return Fix(edits=(Edit(call.lineno, segment,
                           segment[:-1] + f"{sep}daemon=True)"),))


def check(model: ModuleModel) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted_name(node.func) or ""
        if d not in ("threading.Thread", "Thread"):
            continue
        if _daemon_state(node) is not False:
            continue  # daemon, or dynamic (trusted)
        scope = _scope_of(model, node)
        parent = getattr(node, "graftcheck_parent", None)
        joined = False
        trusted = False
        comp = _comprehension_target(node)
        if comp is not None:
            comp_parent = getattr(comp, "graftcheck_parent", None)
            if isinstance(comp_parent, ast.Assign) \
                    and len(comp_parent.targets) == 1 \
                    and isinstance(comp_parent.targets[0], ast.Name):
                joined = _list_joined(scope,
                                      comp_parent.targets[0].id)
            else:
                trusted = True  # comprehension result escapes
        elif isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            tgt = parent.targets[0]
            if isinstance(tgt, ast.Name):
                if _joins_name(scope, tgt.id):
                    joined = True
                elif _escapes(scope, tgt.id, parent):
                    trusted = True
                else:
                    lst = _collected_list(scope, tgt.id)
                    if lst is not None:
                        joined = _list_joined(scope, lst)
            elif isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self":
                joined = _self_attr_joined(model, node, tgt.attr)
            else:
                trusted = True
        elif isinstance(parent, ast.Attribute):
            joined = False  # threading.Thread(...).start(): anonymous leak
        else:
            trusted = True  # passed/returned/stored: escapes this scope
        if joined or trusted:
            continue
        findings.append(Finding(
            model.rel_path, node.lineno, RULE_ID, Severity.WARNING,
            "non-daemon thread is never joined — it outlives its owner, "
            "hangs interpreter exit, and leaks one worker per start; pass "
            "daemon=True or join() it on the shutdown path",
            model.snippet(node.lineno),
            fix=_daemon_fix(model, node)))
    return findings
