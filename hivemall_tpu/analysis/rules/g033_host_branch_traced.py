"""G033 host-branch-on-traced-value: concretization errors across call edges.

G001(a) flags ``if``/``while`` on traced values *inside* a jitted function.
The interprocedural gap: a plain helper that branches on (or ``float()``s)
its parameter is fine on its own, but called from a traced function with a
traced argument it raises TracerBoolConversionError — or silently retraces
— at run time. Two patterns:

(a) a traced function passes a provably-traced argument to a resolvable
    untraced callee whose body branches on (``if``/``while``, after G001's
    static-test pruning) or host-converts (``bool()``/``float()``/``int()``
    /``np.asarray()``/``.item()``) a value derived from that parameter.
    Flagged at the callee's offending line, with the traced call site as a
    related location. Tests over ``.shape``/``.dtype``/``.ndim`` are
    static at trace time and never flagged.
(b) the silent-retrace variant: a call to a jit alias declared with
    ``static_argnums`` passing a provably device-valued expression at a
    static position — hashes per *value*, so every batch retraces without
    an error ever surfacing.
"""

from __future__ import annotations

import ast
from typing import List, Set, Tuple

from ..findings import Finding, Severity
from ..modmodel import dotted_name, walk_scope
from ..program import ProgramModel
from .g001_recompile import _has_shape_access, _names_in, _prune_static_tests
from .g002_host_sync import _sync_call_kind

RULE_ID = "G033"


def _seeded_taint(model, fn, seed):
    """The module taint walker, seeded with specific parameters instead of
    all of them — the callee-side view of one call edge."""
    tainted = set(seed)
    callables: Set[str] = set()
    for _ in range(2):
        model._taint_stmts(fn.body, tainted, callables, fn)
    return tainted, callables


def _shape_static_names(fn) -> Set[str]:
    """Names assigned from shape-bearing expressions (``e, k =
    table.shape``, ``n = x.shape[0]``, ``r = len(xs)``) — concrete at
    trace time even when the source array is traced, so branching on them
    never concretizes a tracer."""
    static: Set[str] = set()
    for node in walk_scope(fn):
        if isinstance(node, ast.Assign):
            value, targets = node.value, node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            value, targets = node.value, [node.target]
        else:
            continue
        if not (_has_shape_access(value) or _has_len_call(value)):
            continue
        for tgt in targets:
            for sub in ast.walk(tgt):
                if isinstance(sub, ast.Name):
                    static.add(sub.id)
    return static


def _has_len_call(expr) -> bool:
    return any(isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name)
               and sub.func.id == "len" for sub in ast.walk(expr))


def _callee_params(fn) -> List[str]:
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args]


def _tainted_params(model, call, fn, tainted, callables) -> List[str]:
    """Callee parameter names receiving provably-traced caller arguments."""
    params = _callee_params(fn)
    offset = 1 if params[:1] == ["self"] else 0
    out: List[str] = []
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            break
        j = i + offset
        if j < len(params) and model.expr_tainted(arg, tainted, callables) \
                and not _has_shape_access(arg):
            out.append(params[j])
    for kw in call.keywords:
        if kw.arg in params \
                and model.expr_tainted(kw.value, tainted, callables) \
                and not _has_shape_access(kw.value):
            out.append(kw.arg)
    return out


def check_program(program: ProgramModel, scanned: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()

    def emit(path: str, line: int, msg: str, related=()) -> None:
        if (path, line) in seen:
            return
        seen.add((path, line))
        model = program.modules[path]
        findings.append(Finding(path, line, RULE_ID, Severity.ERROR, msg,
                                model.snippet(line), related=tuple(related)))

    for path in sorted(scanned):
        model = program.modules.get(path)
        if model is None:
            continue

        # (a) traced caller -> untraced callee receiving traced args
        for fn in model.functions:
            if not model.is_traced(fn):
                continue
            tainted, callables = model.taint_function(fn, taint_params=True)
            for call in walk_scope(fn):
                if not isinstance(call, ast.Call):
                    continue
                callee = dotted_name(call.func)
                if callee is None or "." in callee:
                    continue
                got = program.resolve_fn(path, callee, call)
                if got is None:
                    continue
                t_path, t_fn = got
                t_model = program.modules.get(t_path)
                if t_model is None or t_fn in t_model.traced:
                    continue  # traced callees are G001(a)'s subject
                seeds = _tainted_params(model, call, t_fn, tainted,
                                        callables)
                if not seeds:
                    continue
                related = ((path, call.lineno, model.snippet(call.lineno)),)
                _flag_callee(program, t_path, t_model, t_fn, seeds, callee,
                             fn.name, related, emit)

        # (b) device value at a static_argnums position of a jit alias
        for fn in model.functions:
            if model.is_traced(fn):
                continue
            tainted, callables = model.taint_function(fn)
            for call in walk_scope(fn):
                if not isinstance(call, ast.Call):
                    continue
                callee = dotted_name(call.func)
                wrap = model.jit_aliases.get(callee) if callee else None
                if wrap is None or not wrap.static_argnums:
                    continue
                for i in wrap.static_argnums:
                    if i < len(call.args) \
                            and model.expr_tainted(call.args[i], tainted,
                                                   callables) \
                            and not _has_shape_access(call.args[i]):
                        emit(path, call.lineno,
                             f"device-valued argument at static_argnums "
                             f"position {i} of `{callee}` — static args "
                             f"hash per VALUE, so every distinct array "
                             f"silently retraces; pass it as a traced "
                             f"argument or fetch a host scalar first")
                        break
    return findings


def _flag_callee(program, t_path, t_model, t_fn, seeds, callee, caller_name,
                 related, emit) -> None:
    tainted, callables = _seeded_taint(t_model, t_fn, seeds)
    static = _shape_static_names(t_fn)
    for node in walk_scope(t_fn):
        if isinstance(node, (ast.If, ast.While)):
            for sub in _prune_static_tests(node.test):
                if _has_shape_access(sub):
                    continue  # shapes are static under trace
                hot = sorted(n for n in _names_in(sub)
                             if n in tainted and n not in static)
                if hot:
                    kind = "while" if isinstance(node, ast.While) else "if"
                    emit(t_path, node.lineno,
                         f"`{callee}` branches (`{kind}`) on "
                         f"{', '.join(f'`{h}`' for h in hot)}, which is "
                         f"traced when `{caller_name}` calls it from a jit "
                         f"— TracerBoolConversionError at run time; use "
                         f"jnp.where/lax.cond or keep the branch out of "
                         f"the traced path", related=related)
                    break
        elif isinstance(node, ast.Call):
            sync = _sync_call_kind(node)
            if sync is None:
                continue
            kind, arg = sync
            if _has_shape_access(arg):
                continue
            t_names = [n for n in _names_in(arg) if n in tainted]
            if t_names and all(n in static for n in t_names):
                continue  # shape-derived scalars concretize for free
            if t_model.expr_tainted(arg, tainted, callables):
                emit(t_path, node.lineno,
                     f"`{callee}` applies `{kind}` to a value that is "
                     f"traced when `{caller_name}` calls it from a jit — "
                     f"concretization error at run time; return the array "
                     f"and convert outside the trace", related=related)
