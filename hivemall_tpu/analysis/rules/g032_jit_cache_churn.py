"""G032 jit-cache-entry-churn: fresh wrapper identities that never hit cache.

``jax.jit``'s compile cache lives on the *wrapper object*, and the wrapper
is keyed by the identity of the function it wraps. A module-level def
wrapped once compiles once per shape forever; a fresh lambda, closure
(nested def), or ``functools.partial`` object reaching ``jax.jit`` on
every call builds a wrapper whose cache starts empty — every invocation
retraces and recompiles, silently (measured: three ``jax.jit(nested_def)``
wrappers at a single shape compile three times while a cache-size probe on
a named wrapper stays flat, which is why the counter-based
``recompile_guard`` alone cannot see this class; its compile-log
attribution can, and names the same function this rule flags).

Three patterns, all skipping the sanctioned construction-once contexts
(module level, decorators, ``__init__``, ``make_*``/``build_*`` factories,
``_SHARDED_JIT``-style memo helpers and their build thunks —
traceflow.py):

(a) ``jax.jit(lambda x: f(x))`` — a pure eta-expansion; the lambda adds a
    fresh identity around a stable function for nothing. Machine fix:
    ``jax.jit(f)``.
(b) a lambda / closure / ``partial`` reaching ``jax.jit`` in a per-call
    context — every call of the enclosing function churns a cache entry.
(c) a loop calling a function that constructs a jit wrapper without a
    recognized memo — one fresh wrapper (and one compile) per iteration,
    attributed to the caller's line. Jit sites lexically inside a loop are
    G001's subject (pattern b there) and are not re-flagged here.
"""

from __future__ import annotations

import ast
from typing import List, Set, Tuple

from ..findings import Edit, Finding, Fix, Severity
from ..modmodel import dotted_name, enclosing_loop, walk_scope
from ..program import ProgramModel
from ..traceflow import get_model, local_rebinds, module_info

RULE_ID = "G032"

_KIND_NOUN = {"lambda": "a fresh lambda", "closure": "a fresh closure "
              "(nested def)", "partial": "a fresh functools.partial object"}


def _eta_fix(model, site) -> Fix | None:
    """``jax.jit(lambda x: f(x))`` -> ``jax.jit(f)`` when the lambda and
    its target render on one line (within-line Edit vocabulary)."""
    lam = site.call.args[0]
    if lam.lineno != getattr(lam, "end_lineno", lam.lineno):
        return None
    old = ast.get_source_segment(model.source, lam)
    new = ast.get_source_segment(model.source, site.eta_target)
    if not old or not new or old not in model.lines[lam.lineno - 1]:
        return None
    return Fix(edits=(Edit(lam.lineno, old, new),))


def check_program(program: ProgramModel, scanned: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    tf = get_model(program)
    seen: Set[Tuple[str, int]] = set()

    def emit(path: str, line: int, msg: str, fix=None, related=()) -> None:
        if (path, line) in seen:
            return
        seen.add((path, line))
        model = program.modules[path]
        findings.append(Finding(path, line, RULE_ID, Severity.ERROR, msg,
                                model.snippet(line), fix=fix,
                                related=tuple(related)))

    for path in sorted(scanned):
        model = program.modules.get(path)
        if model is None:
            continue
        info = module_info(model)

        # (a) + (b): fresh-identity objects reaching jax.jit per call
        for site in info.sites:
            if site.sanctioned or site.in_loop:  # loops are G001b's subject
                continue
            if site.arg_kind not in _KIND_NOUN:
                continue
            fn = model.enclosing_function(site.call)
            where = f"`{fn.name}`" if fn is not None else "module scope"
            if site.eta_target is not None:
                target = dotted_name(site.eta_target) or "the wrapped fn"
                emit(path, site.call.lineno,
                     f"jax.jit over an eta-expanded lambda in {where} — the "
                     f"lambda is a fresh cache identity around `{target}` on "
                     f"every call; jit the function directly",
                     fix=_eta_fix(model, site))
            else:
                emit(path, site.call.lineno,
                     f"jax.jit over {_KIND_NOUN[site.arg_kind]} in {where} — "
                     f"a per-call wrapper never hits its own compile cache; "
                     f"hoist the jit to module scope, a make_*/build_* "
                     f"factory called once, or a jit memo dict")

        # (c): loop-driven calls into unmemoized jit constructors
        for fn in model.functions:
            if model.is_traced(fn):
                continue
            rebound = None  # computed on first candidate: most fns loop-free
            for node in walk_scope(fn):
                if not isinstance(node, ast.Call) \
                        or enclosing_loop(node) is None:
                    continue
                callee = dotted_name(node.func)
                if callee is None or "." in callee:
                    continue
                if rebound is None:
                    rebound = local_rebinds(fn)
                if callee in rebound:
                    continue  # a local binding shadows any same-named def
                got = program.resolve_fn(path, callee, node)
                if got is None:
                    continue
                t_path, t_fn = got
                if t_fn is fn:
                    continue
                t_info = tf.info(t_path)
                if t_info is None or t_fn in t_info.memo_helper_fns:
                    continue
                site = tf.jit_site_in(t_path, t_fn)
                if site is None:
                    continue
                t_model = program.modules[t_path]
                emit(path, node.lineno,
                     f"`{callee}()` constructs a jax.jit wrapper (at "
                     f"{t_path}:{site.call.lineno}) and is called here once "
                     f"per loop iteration — one fresh compile cache per "
                     f"iteration; hoist the call out of the loop or memoize "
                     f"the wrapper in a jit memo dict",
                     related=((t_path, site.call.lineno,
                               t_model.snippet(site.call.lineno)),))
    return findings
