"""G023 FFI borrowed buffer: a temporary or view's pointer crosses the ABI with no owner live across the call.

``(a + b).ctypes.data_as(...)`` takes the address of an array that
nothing references once the argument expression is evaluated — CPython
is free to collect it mid-call (and with ``.ctypes.data`` there is not
even a ctypes object keeping it pinned), so the C side reads freed
memory. Slices, ``.T`` and ``transpose()`` results are worse in a
second way: they borrow the parent's buffer with *strides*, while the
ABI assumes dense C order — and when the C side writes through the
pointer, a strided view means it scribbles over unrelated elements of
the parent.

The safe idiom is two steps: bind a validated, C-contiguous copy to a
name (``tmp = np.ascontiguousarray(v, dtype=...)``), pass ``tmp``'s
pointer, and keep ``tmp`` alive past the call. Inline
``np.ascontiguousarray(..., dtype=...)`` in the argument itself is
accepted for ``data_as`` (the returned ctypes pointer keeps the fresh
array alive for the duration of the call).

No autofix: the repair moves an expression onto its own line, which is
a structural edit the within-line fixer does not do.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..ffi import (FFIModel, _match_pointer_expr, get_ffi, pointer_args,
                   scan_native_decls)
from ..findings import Finding, Severity
from ..program import ProgramModel

RULE_ID = "G023"


def _writes_through(symbol: str, index: int, cdecls) -> bool:
    """True when the C signature shows a non-const pointer at this
    positional index (the view-scribble case)."""
    if cdecls is None or index < 0:
        return False
    sig = cdecls.sigs.get(symbol)
    if sig is None or index >= len(sig.params):
        return False
    p = sig.params[index]
    return p.kind == "ptr" and not p.const


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    ffi = get_ffi(program)
    cdecls = scan_native_decls()
    for path in sorted(scanned):
        mod = ffi.modules.get(path)
        if mod is None:
            continue
        model = program.modules[path]
        seen = set()
        for fc in mod.calls:
            for pa in pointer_args(program, path, mod, fc):
                if pa.kind not in ("view", "temp"):
                    continue
                src = ast.get_source_segment(model.source, pa.base) or "?"
                if pa.kind == "view":
                    detail = ("a slice/transpose view — it borrows the "
                              "parent's buffer with strides while the ABI "
                              "assumes dense C order")
                    if _writes_through(fc.symbol, pa.index, cdecls):
                        detail += (", and the C side writes through this "
                                   "parameter, scribbling over unrelated "
                                   "parent elements")
                else:
                    detail = ("an expression temporary with no named "
                              "binding live across the call — the buffer "
                              "can be collected while the C side still "
                              "reads it")
                key = (fc.node.lineno, src)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    path, fc.node.lineno, RULE_ID, Severity.ERROR,
                    f"pointer of `{src}` passed to native `{fc.symbol}` "
                    f"is {detail}; bind a validated copy first "
                    f"(tmp = np.ascontiguousarray({src}, dtype=...)) and "
                    f"pass tmp, keeping it alive past the call",
                    model.snippet(fc.node.lineno)))
        # module-wide: raw addresses stashed from temporaries/views even
        # outside a foreign call (`p = (a+b).ctypes.data_as(...)`), and
        # bare integer addresses (.ctypes.data) taken off non-names —
        # nothing pins the buffer once the expression dies
        _sweep_stashed(program, path, model, mod, seen, findings)
    return findings


def _sweep_stashed(program: ProgramModel, path: str, model, mod,
                   seen: Set, findings: List[Finding]) -> None:
    from ..ffi import base_kind
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Assign):
            continue
        got = _match_pointer_expr(node.value, mod.asp_names,
                                  model.enclosing_function(node))
        if got is None:
            continue
        base, via = got
        fn = model.enclosing_function(node)
        kind = base_kind(program, path, model, fn, base, node.lineno)
        if via == "data" and kind not in ("name", "namedsub"):
            pass  # integer address of a dying buffer: always flag
        elif kind not in ("view", "temp"):
            continue
        src = ast.get_source_segment(model.source, base) or "?"
        key = (node.lineno, src)
        if key in seen:
            continue
        seen.add(key)
        what = ("slice/transpose view" if kind == "view"
                else "expression temporary")
        findings.append(Finding(
            path, node.lineno, RULE_ID, Severity.ERROR,
            f"raw pointer taken from {what} `{src}` and stored — the "
            f"underlying buffer is not owned by the stored pointer and "
            f"can be freed or reflect strided layout by the time it is "
            f"used; bind a validated C-contiguous copy to a name and "
            f"take the pointer from that",
            model.snippet(node.lineno)))
