"""G024 FFI missing prototype: a CDLL symbol is invoked without both argtypes and restype declared.

Without ``argtypes`` ctypes guesses the C signature from the Python
values at every call — an ``int`` that should be ``int64_t`` truncates
on 32-bit promotion, a float silently becomes a double — and without
``restype`` every return is assumed ``int`` (32-bit), so a 64-bit
status or count comes back sign-mangled. Both must be declared once at
load time so every later call is type-checked; the declarations are
also what G025 cross-checks against the C source and what G026 uses to
know a status code exists.

Fix: when ``argtypes`` is declared but ``restype`` is missing, a
``restype = ctypes.c_int64`` assignment is splicable onto the argtypes
line (the repo ABI returns int64 status everywhere). The reverse is not
auto-fixable — argtypes require the real parameter list.

Second half (extends G013's held-lock machinery): a native call made
while a serving-path lock is held stalls every thread behind it for the
full native runtime — native code never yields the GIL back to waiters
of *our* lock. Flagged in the G013 scope (``serving/``, ``pipeline/``,
``runtime/metrics`` or the ``# graftcheck: serving-module`` marker).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .. import config
from ..concurrency import get_model, in_g013_scope
from ..ffi import foreign_symbol, get_ffi
from ..findings import Edit, Finding, Fix, Severity
from ..program import ProgramModel

RULE_ID = "G024"


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    ffi = get_ffi(program)
    all_decls = ffi.all_decls()
    fixed_symbols: Set[str] = set()
    for path in sorted(scanned):
        mod = ffi.modules.get(path)
        if mod is None:
            continue
        model = program.modules[path]
        seen: Set[Tuple[int, str]] = set()
        for fc in sorted(mod.calls, key=lambda c: c.node.lineno):
            decl = mod.decls.get(fc.symbol) or all_decls.get(fc.symbol)
            has_arg = decl is not None and decl.argtypes_node is not None
            has_res = decl is not None and decl.restype_node is not None
            if has_arg and has_res:
                continue
            key = (fc.node.lineno, fc.symbol)
            if key in seen:
                continue
            seen.add(key)
            missing = [n for n, ok in (("argtypes", has_arg),
                                       ("restype", has_res)) if not ok]
            fix = None
            if has_arg and not has_res and decl is not None \
                    and decl.argtypes_src \
                    and fc.symbol not in fixed_symbols:
                # splice `X.restype = ctypes.c_int64; ` ahead of the
                # existing argtypes assignment target — one edit per
                # symbol (a second identical edit would re-match the old
                # text still present after the first application)
                target = decl.argtypes_src
                base = target[:-len(".argtypes")]
                fix = Fix(edits=(Edit(
                    decl.argtypes_line, target,
                    f"{base}.restype = ctypes.c_int64; {target}"),))
                fixed_symbols.add(fc.symbol)
            findings.append(Finding(
                path, fc.node.lineno, RULE_ID, Severity.ERROR,
                f"native `{fc.symbol}` is called without "
                f"{' or '.join(missing)} declared — ctypes falls back to "
                f"guessing the C signature per call (ints promote to "
                f"32-bit, returns are assumed 32-bit int); declare both "
                f"once at load time",
                model.snippet(fc.node.lineno), fix=fix))
    findings.extend(_under_lock(program, scanned))
    return findings


def _under_lock(program: ProgramModel, scanned: Set[str]) -> List[Finding]:
    """Native calls made while a serving-path lock is held (rides the
    G013 concurrency model: eff_calls carry the held-lock set)."""
    findings: List[Finding] = []
    cm = get_model(program)
    prefixes = tuple(config.FFI_SYMBOL_PREFIXES)
    seen: Set[Tuple[str, int]] = set()

    def sweep(path: str, events) -> None:
        model = program.modules[path]
        for ev in events:
            if not ev.held:
                continue
            sym = foreign_symbol(ev.dotted)
            if sym is None or not sym.startswith(prefixes):
                continue
            key = (path, ev.line)
            if key in seen:
                continue
            seen.add(key)
            locks = sorted(lk.lstrip("@") for lk in ev.held)
            findings.append(Finding(
                path, ev.line, RULE_ID, Severity.ERROR,
                f"native `{sym}` called while holding "
                f"`{'`, `'.join(locks)}` — the full native runtime "
                f"executes under the lock and never yields it, stalling "
                f"every waiting thread; marshal under the lock, call "
                f"after releasing",
                model.snippet(ev.line)))

    for path in sorted(scanned):
        model = program.modules.get(path)
        if model is None or not in_g013_scope(path, model):
            continue
        for (c_path, _), cls in sorted(cm.classes.items()):
            if c_path == path:
                sweep(path, cls.eff_calls)
        sweep(path, (ev for f_path, _, ev in cm.fn_calls
                     if f_path == path))
    return findings
