"""G036 blocking-host-sync-in-hot-loop: the sync your callee performs.

G002 flags ``float(x)`` / ``device_get`` syncs written *directly* inside a
hot loop. The interprocedural gap: the loop body calls a helper, and the
helper blocks — ``jax.device_get(...)`` or ``.block_until_ready()`` three
frames down still serializes the dispatch stream once per iteration, with
nothing at the call site to see.

Scope: the step/dispatch loops — ``config.HOT_LOOP_MODULES`` (G002's
scope) plus the jit-hot serving/kernels scope
(``traceflow.in_traceflow_scope``). For every call inside a loop whose
callee resolves through the program layer, a depth-bounded summary walk
(``traceflow.sync_site``) finds the first provable device sync the callee
performs; the finding lands on the caller's line with the sync's location
related. Taint-free by design — only calls that block *by name* count —
and callees that *declare* themselves sync boundaries
(``config.TRACEFLOW_SYNC_NAME_RE``: fetch/sync/to_host/...) are the
sanctioned whole-value boundary read and never flagged.
"""

from __future__ import annotations

import ast
from typing import List, Set, Tuple

from .. import config
from ..findings import Finding, Severity
from ..modmodel import dotted_name, enclosing_loop, walk_scope
from ..program import ProgramModel
from ..traceflow import get_model, in_traceflow_scope, local_rebinds

RULE_ID = "G036"


def _in_scope(path: str, model) -> bool:
    if path in config.HOT_LOOP_MODULES \
            or "# graftcheck: hot-module" in model.source:
        return True
    return in_traceflow_scope(path, model)


def check_program(program: ProgramModel, scanned: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    tf = get_model(program)
    seen: Set[Tuple[str, int]] = set()

    for path in sorted(scanned):
        model = program.modules.get(path)
        if model is None or not _in_scope(path, model):
            continue
        for fn in model.functions:
            if model.is_traced(fn):
                continue
            rebound = None  # computed on first candidate: most fns loop-free
            for call in walk_scope(fn):
                if not isinstance(call, ast.Call) \
                        or enclosing_loop(call) is None:
                    continue
                callee = dotted_name(call.func)
                if callee is None or "." in callee:
                    continue
                if rebound is None:
                    rebound = local_rebinds(fn)
                if callee in rebound:
                    continue  # a local binding shadows any same-named def
                tail = callee.rsplit(".", 1)[-1]
                if config.TRACEFLOW_SYNC_NAME_RE.search(tail):
                    continue  # a self-declared sync boundary: the idiom
                got = program.resolve_fn(path, callee, call)
                if got is None:
                    continue
                t_path, t_fn = got
                if t_fn is fn:
                    continue
                if config.TRACEFLOW_SYNC_NAME_RE.search(t_fn.name):
                    continue
                sync = tf.sync_site(t_path, t_fn)
                if sync is None:
                    continue
                if (path, call.lineno) in seen:
                    continue
                seen.add((path, call.lineno))
                s_path, s_line, s_tail = sync
                s_model = program.modules.get(s_path)
                snippet = s_model.snippet(s_line) if s_model else ""
                findings.append(Finding(
                    path, call.lineno, RULE_ID, Severity.ERROR,
                    f"`{callee}()` blocks on the device ({s_tail} at "
                    f"{s_path}:{s_line}) and is called once per hot-loop "
                    f"iteration — the dispatch stream stalls behind it "
                    f"every pass; batch the read to the loop boundary or "
                    f"rename the helper to declare the sync "
                    f"(*_fetch/*_sync)", model.snippet(call.lineno),
                    related=((s_path, s_line, snippet),)))
    return findings
