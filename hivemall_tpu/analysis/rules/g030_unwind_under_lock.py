"""G030 unsafe-unwind-under-lock: an exception leaves a lock held or state torn.

Two unwind hazards the G012-G016 held-set machinery deliberately does
not model (``_collect`` walks ``Try`` with the same held set — it
assumes every unwind releases):

1. **Manual acquire without finally** — ``X.acquire()`` ... work ...
   ``X.release()`` in the same suite: any statement in between that
   unwinds skips the release and every other thread deadlocks on X
   forever. The with-statement (or ``try/finally``) is the only
   exception-safe shape. Machine fix: wrap the region in
   ``try:``/``finally: X.release()``.

2. **Half-updated state** — inside a ``with <lock>:`` suite, a call
   that provably raises (non-empty raise summary in the exception-flow
   model) *between two writes to self state*: the unwind releases the
   lock with the invariant the lock guards half-applied, and the next
   reader sees torn state. The fix is ordering (compute first, then
   write) or a handler that rolls back — a judgement call, so no
   machine fix.

Scope: serving/pipeline/runtime plus ``# graftcheck: failure-path-module``.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from ..exceptionflow import get_model, in_exception_scope
from ..findings import Finding, Fix, Severity, WrapFinally
from ..modmodel import _FN_TYPES, dotted_name, walk_scope
from ..program import ProgramModel

RULE_ID = "G030"


def _protocol_call(stmt: ast.stmt, tail: str) -> Optional[str]:
    """Receiver dotted prefix when stmt is ``<recv>.<tail>()``."""
    if not (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)):
        return None
    d = dotted_name(stmt.value.func)
    if d is None or not d.endswith("." + tail):
        return None
    return d[:-(len(tail) + 1)]


def _suites(fn: ast.AST) -> Iterator[List[ast.stmt]]:
    """Every statement list in the function scope."""
    yield fn.body
    for node in walk_scope(fn):
        for attr in ("body", "orelse", "finalbody"):
            suite = getattr(node, attr, None)
            if isinstance(suite, list) and suite \
                    and isinstance(suite[0], ast.stmt) \
                    and not isinstance(node, _FN_TYPES + (ast.ClassDef,)):
                yield suite


def _wrap_fix(model, region: List[ast.stmt], release: ast.stmt
              ) -> Optional[Fix]:
    """try/finally wrap when the region lines are contiguous single-suite
    lines right up to a single-line release statement."""
    start = region[0].lineno
    end = release.lineno
    if release.end_lineno != end or region[-1].end_lineno >= end:
        return None
    if region[0].col_offset != release.col_offset:
        return None
    return Fix(wrap=WrapFinally(start=start, release_line=end,
                                release_text=model.snippet(end)))


def _check_manual_acquire(model, path: str, fn: ast.AST,
                          findings: List[Finding]) -> None:
    for suite in _suites(fn):
        for i, stmt in enumerate(suite):
            recv = _protocol_call(stmt, "acquire")
            if recv is None:
                continue
            for j in range(i + 1, len(suite)):
                if _protocol_call(suite[j], "release") == recv:
                    region = suite[i + 1:j]
                    if not region:
                        break
                    findings.append(Finding(
                        path, stmt.lineno, RULE_ID, Severity.ERROR,
                        f"manual `{recv}.acquire()` with the release "
                        f"{suite[j].lineno - stmt.lineno} lines below in "
                        f"the same suite: any unwind in between leaves "
                        f"`{recv}` held forever — use `with {recv}:` or "
                        f"wrap the region in try/finally",
                        model.snippet(stmt.lineno),
                        fix=_wrap_fix(model, region, suite[j])))
                    break


def _self_writes(stmt: ast.stmt) -> bool:
    if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        for tgt in targets:
            d = dotted_name(tgt)
            if d is not None and d.startswith("self."):
                return True
            if isinstance(tgt, ast.Subscript):
                d = dotted_name(tgt.value)
                if d is not None and d.startswith("self."):
                    return True
    return False


def _is_lock_ctx(item: ast.withitem) -> bool:
    d = dotted_name(item.context_expr)
    if d is None:
        return False
    tail = d.rsplit(".", 1)[-1]
    return tail.lstrip("_").startswith(("lock", "cv", "cond", "mutex")) \
        or d.startswith(("self._lock", "self._cv"))


def _raising_call(ef, path: str, stmt: ast.stmt) -> Optional[Tuple[str, int]]:
    """(exception, line) when a top-level call in the statement provably
    raises per the interprocedural summaries."""
    for call, dotted in ef._stmt_calls(stmt):
        got = ef.resolve_callee(path, call, dotted)
        if got is None:
            continue
        excs = ef.raises(got[0], got[1], 1)
        if excs:
            return sorted(excs)[0], call.lineno
    return None


def _check_torn_state(ef, model, path: str, fn: ast.AST,
                      findings: List[Finding]) -> None:
    for node in walk_scope(fn):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        if not any(_is_lock_ctx(item) for item in node.items):
            continue
        lock = next(dotted_name(i.context_expr) for i in node.items
                    if _is_lock_ctx(i))
        wrote = False
        for stmt in node.body:
            if isinstance(stmt, ast.Try):
                wrote = False  # guarded region: trust the handler
                continue
            raising = _raising_call(ef, path, stmt) \
                if wrote and not _self_writes(stmt) else None
            if raising is not None and any(
                    _self_writes(later) for later in
                    node.body[node.body.index(stmt) + 1:]):
                exc, line = raising
                findings.append(Finding(
                    path, line, RULE_ID, Severity.ERROR,
                    f"this call can raise {exc} between two writes to "
                    f"self state under `{lock}` — the unwind releases "
                    f"the lock with the guarded invariant half-applied; "
                    f"compute before the first write or roll back in a "
                    f"handler", model.snippet(line)))
                break
            if _self_writes(stmt):
                wrote = True


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    ef = get_model(program)
    for path in sorted(scanned):
        model = program.modules.get(path)
        if model is None or not in_exception_scope(path, model):
            continue
        for fn in model.functions:
            _check_manual_acquire(model, path, fn, findings)
            _check_torn_state(ef, model, path, fn, findings)
    return findings
