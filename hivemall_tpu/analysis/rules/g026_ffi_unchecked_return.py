"""G026 FFI unchecked return: a native status code is dropped on the floor.

The repo ABI returns ``int64_t`` status/count from every fallible
export (negative = refusal/error, else rows processed). A bare
``lib.hm_x(...)`` statement — or an assignment to ``_`` — discards
that code, so a native-side refusal (bad magic, overflow guard,
version check) silently becomes "worked fine" and the caller consumes
garbage output buffers. Only symbols whose declared ``restype`` is an
integer width are checked: ``restype = None`` marks a genuinely
void export (``hm_murmur3_bulk``), and undeclared symbols are G024's
subject, not this rule's.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..ffi import get_ffi
from ..findings import Finding, Severity
from ..program import ProgramModel

RULE_ID = "G026"

_INT_KINDS = ("i8", "i16", "i32", "i64")


def _discards(node: ast.Call) -> bool:
    parent = getattr(node, "graftcheck_parent", None)
    if isinstance(parent, ast.Expr):
        return True
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
        tgt = parent.targets[0]
        return isinstance(tgt, ast.Name) and tgt.id == "_"
    return False


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    ffi = get_ffi(program)
    all_decls = ffi.all_decls()
    for path in sorted(scanned):
        mod = ffi.modules.get(path)
        if mod is None:
            continue
        model = program.modules[path]
        seen = set()
        for fc in mod.calls:
            decl = mod.decls.get(fc.symbol) or all_decls.get(fc.symbol)
            if decl is None or decl.restype_kind not in _INT_KINDS:
                continue
            if not _discards(fc.node):
                continue
            if fc.node.lineno in seen:
                continue
            seen.add(fc.node.lineno)
            findings.append(Finding(
                path, fc.node.lineno, RULE_ID, Severity.ERROR,
                f"status code of native `{fc.symbol}` is discarded — the "
                f"ABI returns a negative value on refusal/error and this "
                f"call treats failure as success; capture the return and "
                f"check it (rc = ...; if rc < 0: raise/fallback)",
                model.snippet(fc.node.lineno)))
    return findings
