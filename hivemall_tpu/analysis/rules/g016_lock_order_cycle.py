"""G016 lock-ordering-cycle: two locks acquired in opposite orders.

Thread 1 holds the registry lock and calls into the batcher (which takes
its CV); thread 2 holds the batcher CV and calls into the registry.
Under contention each holds what the other needs — the classic ABBA
deadlock, invisible in single-threaded tests and fatal under load.

The concurrency model (analysis/concurrency.py) records every
acquisition edge "acquired Y while holding X", intra-class (nested
``with`` scopes, helpers reached through context propagation) and
cross-class (calls into methods of resolvable instances — module-level
singletons like ``REGISTRY`` and ``self.field = ClassName(...)``
fields). A cycle in that graph is reported at every participating
acquisition site in the scanned set; receivers whose type cannot be
resolved are trusted.
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple

from ..concurrency import get_model
from ..findings import Finding, Severity
from ..program import ProgramModel

RULE_ID = "G016"

Node = Tuple[Tuple[str, str], str]  # ((module, class), lock field)


def _sccs(adj: Dict[Node, Set[Node]]) -> List[Set[Node]]:
    """Tarjan strongly-connected components, iterative."""
    index: Dict[Node, int] = {}
    low: Dict[Node, int] = {}
    on_stack: Set[Node] = set()
    stack: List[Node] = []
    out: List[Set[Node]] = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(adj.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp: Set[Node] = set()
                while True:
                    top = stack.pop()
                    on_stack.discard(top)
                    comp.add(top)
                    if top == node:
                        break
                out.append(comp)
    return out


def _label(node: Node) -> str:
    (_path, cls), lock = node
    return f"{cls}.{lock}"


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    cm = get_model(program)
    adj: Dict[Node, Set[Node]] = {}
    for e in cm.lock_edges:
        if e.frm == e.to:
            continue  # same-lock re-acquisition is G014's subject
        adj.setdefault(e.frm, set()).add(e.to)
        adj.setdefault(e.to, set())
    comp_of: Dict[Node, int] = {}
    comps: List[Set[Node]] = []
    for comp in _sccs(adj):
        if len(comp) > 1:
            for n in comp:
                comp_of[n] = len(comps)
            comps.append(comp)
    if not comps:
        return []
    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for e in sorted(cm.lock_edges,
                    key=lambda e: (e.path, e.site.lineno)):
        if e.frm == e.to or e.frm not in comp_of \
                or comp_of.get(e.to) != comp_of[e.frm]:
            continue
        members = ", ".join(sorted(_label(n)
                                   for n in comps[comp_of[e.frm]]))
        if e.path not in scanned:
            continue
        key = (e.path, e.site.lineno)
        if key in seen:
            continue
        seen.add(key)
        model = program.modules[e.path]
        findings.append(Finding(
            e.path, e.site.lineno, RULE_ID, Severity.ERROR,
            f"lock-ordering cycle: `{_label(e.to)}` is acquired here while "
            f"holding `{_label(e.frm)}`, and the reverse order exists "
            f"elsewhere (cycle: {members}) — under contention each thread "
            f"holds what the other needs (ABBA deadlock); pick one global "
            f"order or release before calling across",
            model.snippet(e.site.lineno)))
    return findings
