"""G025 FFI ABI drift: Python ctypes declarations disagree with the exported C signatures or the plan ABI version.

The ctypes bindings and ``native/hivemall_native.cpp`` are two
hand-maintained copies of one contract. When they drift — an argument
added on one side only, an ``int32_t`` widened to ``int64_t``, a bumped
``HM_PLAN_ABI_VERSION`` without the matching Python
``PLAN_ABI_VERSION`` — every call still "works": ctypes happily
marshals the declared types and the C side reinterprets the bytes.
This rule parses the exported ``hm_*`` definitions (and the version
literal) out of the C source with a lightweight declaration scanner and
cross-checks, per symbol declared on both sides: arity,
pointer-vs-scalar per argument, int/float width per argument, and the
return width — plus the version literals. Width classes only (``ptr``,
``i8``..``i64``, ``f32``/``f64``): signedness mismatches are benign at
the ABI level and ``c_void_p`` vs a typed pointer is the bindings'
established idiom.

Findings anchor on the Python declaration line (the side you edit to
fix them) and carry the C declaration as a second SARIF location, so CI
annotates both files. Symbols present on only one side are skipped —
absence is a link-time/AttributeError problem the loader already
surfaces loudly, not silent drift.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from .. import config
from ..ffi import describe_kind, get_ffi, scan_native_decls
from ..findings import Finding, Severity
from ..program import ProgramModel

RULE_ID = "G025"


def _py_abi_version(model) -> Optional[Tuple[int, int]]:
    """(value, line) of a module-level ``PLAN_ABI_VERSION = <int>``."""
    for node in model.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == config.FFI_ABI_VERSION_CONSTANT \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, int) \
                and not isinstance(node.value.value, bool):
            return node.value.value, node.lineno
    return None


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    cdecls = scan_native_decls()
    if cdecls is None:
        return []  # no C source reachable: nothing to cross-check
    findings: List[Finding] = []
    ffi = get_ffi(program)
    for path in sorted(scanned):
        model = program.modules.get(path)
        if model is None:
            continue
        got = _py_abi_version(model)
        if got is not None and cdecls.abi_version is not None \
                and got[0] != cdecls.abi_version:
            value, line = got
            findings.append(Finding(
                path, line, RULE_ID, Severity.ERROR,
                f"{config.FFI_ABI_VERSION_CONSTANT} = {value} but the C "
                f"side compiles HM_PLAN_ABI_VERSION = "
                f"{cdecls.abi_version} ({cdecls.display_path}:"
                f"{cdecls.abi_version_line}) — the frozen plan ABI "
                f"changed on one side only; bump both literals in the "
                f"same commit",
                model.snippet(line),
                related=((cdecls.display_path, cdecls.abi_version_line,
                          cdecls.snippet(cdecls.abi_version_line)),)))
        mod = ffi.modules.get(path)
        if mod is None:
            continue
        for sym in sorted(mod.decls):
            decl = mod.decls[sym]
            sig = cdecls.sigs.get(sym)
            if sig is None:
                continue  # Python-only symbol: loader surfaces that
            rel = ((cdecls.display_path, sig.line,
                    cdecls.snippet(sig.line)),)
            if decl.argtypes_kinds is not None:
                kinds = decl.argtypes_kinds
                if len(kinds) != len(sig.params):
                    findings.append(Finding(
                        path, decl.argtypes_line, RULE_ID, Severity.ERROR,
                        f"`{sym}` declares {len(kinds)} argtypes but the "
                        f"C definition takes {len(sig.params)} parameters "
                        f"({cdecls.display_path}:{sig.line}) — every call "
                        f"marshals a mis-sized frame",
                        model.snippet(decl.argtypes_line), related=rel))
                else:
                    for i, (pk, cp) in enumerate(zip(kinds, sig.params)):
                        if pk == "other" or cp.kind == "other":
                            continue
                        if pk != cp.kind:
                            findings.append(Finding(
                                path, decl.argtypes_line, RULE_ID,
                                Severity.ERROR,
                                f"`{sym}` argument {i} is declared as "
                                f"{describe_kind(pk)} in Python but the C "
                                f"definition takes {describe_kind(cp.kind)}"
                                f" (`{cp.text}`, {cdecls.display_path}:"
                                f"{sig.line}) — the marshalled bytes are "
                                f"reinterpreted at the wrong width",
                                model.snippet(decl.argtypes_line),
                                related=rel))
            if decl.restype_kind is not None \
                    and decl.restype_kind != "other" \
                    and sig.ret != "other" \
                    and decl.restype_kind != sig.ret:
                findings.append(Finding(
                    path, decl.restype_line, RULE_ID, Severity.ERROR,
                    f"`{sym}` restype is {describe_kind(decl.restype_kind)}"
                    f" in Python but the C definition returns "
                    f"{describe_kind(sig.ret)} ({cdecls.display_path}:"
                    f"{sig.line}) — the returned value is truncated or "
                    f"reinterpreted",
                    model.snippet(decl.restype_line), related=rel))
    return findings
