"""G029 swallowed-exception-in-hot-path: broad except that discards the error.

``except Exception: pass`` (or a bare ``except:``) in the serving /
pipeline / runtime scopes erases the only evidence a failure happened —
no re-raise, no log, no counter, nothing. On the failure-path fronts
(replica death, elastic process loss) these are exactly the sites that
turn a diagnosable crash into a silent wedge. A *narrow* swallow
(``except KeyError: pass`` on a best-effort cache probe) is a
deliberate, reviewable choice and stays legal; swallowing *everything*
needs an inline rationale:

    except Exception:  # graftcheck: disable=G029 (best-effort unlink)
        pass

No machine fix — the repair is a judgement call (re-raise, surface, or
justify), so the rule only forces the judgement to be written down.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..exceptionflow import classify_handler, in_exception_scope
from ..findings import Finding, Severity
from ..program import ProgramModel

RULE_ID = "G029"


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    for path in sorted(scanned):
        model = program.modules.get(path)
        if model is None or not in_exception_scope(path, model):
            continue
        for node in ast.walk(model.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            info = classify_handler(node)
            if not info.swallow_only or not info.broad:
                continue
            what = "a bare except" if info.bare else \
                f"except {'/'.join(info.names or ())}"
            findings.append(Finding(
                path, node.lineno, RULE_ID, Severity.WARNING,
                f"{what} swallows every failure on the hot path with no "
                f"trace — re-raise, surface the reason, or suppress with "
                f"an inline rationale "
                f"(# graftcheck: disable=G029 (why))",
                model.snippet(node.lineno)))
    return findings
