"""G012 unguarded-shared-field: a field two threads touch with no common lock.

The guarded-by inference (analysis/concurrency.py) computes, per class,
which ``self._x`` fields are touched under which locks — through ``with
self._lock:`` scopes and helper calls. A class that declares concurrency
(owns a lock, spawns a thread, or serves HTTP ``do_*`` handlers) must
then be consistent about it; two provable failure modes are flagged:

- **inconsistent discipline**: the field is guarded by a lock at some
  accesses but read/written bare at others — the unlocked access races
  with the locked writers (``registry.get()`` reading ``_entries`` while
  ``deploy()`` publishes under ``_lock``). Designed lock-free reads
  (GIL-atomic dict reads) are suppressed inline with a justification.
- **cross-thread, no lock at all**: the field is written on a spawned
  thread (``threading.Thread(target=self._loop)``) and accessed from
  caller-side methods, with no lock anywhere.

Fields written only in ``__init__`` are immutable-after-publish and
skipped; purely dynamic receivers are trusted.
"""

from __future__ import annotations

from typing import List, Set

from ..concurrency import get_model
from ..findings import Finding, Severity
from ..program import ProgramModel

RULE_ID = "G012"


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    cm = get_model(program)
    for (path, _cname), cls in sorted(cm.classes.items()):
        if path not in scanned or not cls.concurrent:
            continue
        model = program.modules[path]
        for field in sorted(cls.eff_accesses):
            accesses = [a for a in cls.eff_accesses[field]
                        if a.method not in ("__init__", "__new__")]
            if not accesses:
                continue
            writes = [a for a in accesses if a.write]
            if not writes:
                continue  # written only at construction: publish-immutable
            lock_names = set(cls.locks)
            guards = [frozenset(a.held) & lock_names for a in accesses]
            common = frozenset.intersection(*guards) if guards else frozenset()
            if common:
                continue  # consistently guarded
            guarded = [a for a, g in zip(accesses, guards) if g]
            unguarded = [a for a, g in zip(accesses, guards) if not g]
            if guarded and unguarded:
                locks = sorted({lk for g in guards for lk in g})
                ex = min(guarded, key=lambda a: a.line)
                seen_lines: Set[int] = set()
                for a in sorted(unguarded, key=lambda a: a.line):
                    if a.line in seen_lines:
                        continue
                    seen_lines.add(a.line)
                    verb = "written" if a.write else "read"
                    findings.append(Finding(
                        path, a.line, RULE_ID, Severity.ERROR,
                        f"field `self.{field}` of {cls.name} is guarded by "
                        f"`self.{'`/`self.'.join(locks)}` elsewhere "
                        f"({ex.method}(), line {ex.line}) but {verb} here "
                        f"with no lock held — inconsistent lock discipline "
                        f"is a data race under concurrent load",
                        model.snippet(a.line)))
            elif guarded:
                # every access is locked, but by DISJOINT locks: two locks
                # that never coincide don't exclude each other
                w = min(writes, key=lambda a: a.line)
                w_guard = frozenset(w.held) & lock_names
                seen_lines = set()
                for a in sorted(accesses, key=lambda a: a.line):
                    g = frozenset(a.held) & lock_names
                    if a.line == w.line or (g & w_guard) \
                            or a.line in seen_lines:
                        continue
                    seen_lines.add(a.line)
                    verb = "written" if a.write else "read"
                    findings.append(Finding(
                        path, a.line, RULE_ID, Severity.ERROR,
                        f"field `self.{field}` of {cls.name} is {verb} "
                        f"here under `self.{'`/`self.'.join(sorted(g))}` "
                        f"but written under "
                        f"`self.{'`/`self.'.join(sorted(w_guard))}` "
                        f"({w.method}(), line {w.line}) — disjoint locks "
                        f"do not exclude each other; guard every access "
                        f"with one common lock",
                        model.snippet(a.line)))
            else:
                # no lock anywhere: flag only when cross-thread is proven
                t_side = [a for a in accesses
                          if a.method in cls.thread_side]
                c_side = [a for a in accesses
                          if a.method not in cls.thread_side]
                if not (t_side and c_side):
                    continue
                other = min(c_side if writes[0] in t_side else t_side,
                            key=lambda a: a.line)
                seen_lines = set()
                for a in sorted(writes, key=lambda a: a.line):
                    if a.line in seen_lines:
                        continue
                    seen_lines.add(a.line)
                    findings.append(Finding(
                        path, a.line, RULE_ID, Severity.ERROR,
                        f"field `self.{field}` of {cls.name} is written "
                        f"here and accessed from {other.method}() (line "
                        f"{other.line}) on a different thread with no lock "
                        f"— guard both sides with one "
                        f"threading.Lock/Condition",
                        model.snippet(a.line)))
    return findings
