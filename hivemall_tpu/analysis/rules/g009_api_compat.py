"""G009 version-incompatible-jax-api: raw shard_map/pcast spellings.

The shard_map API surface moved across jax versions (``jax.shard_map`` +
``check_vma=`` vs ``jax.experimental.shard_map`` + ``check_rep=``; the
vma-era ``jax.lax.pcast`` does not exist before it). A direct call to
either spelling pins the module to one side of the fence and dies with an
``AttributeError``/``TypeError`` on the other — exactly how this repo's
entire distributed subsystem (48 tier-1 tests) was dead against the
installed jax. The portable surface is ``runtime/jax_compat.py``; every
finding carries a machine-applicable fix (``--fix``) that rewrites the
callee to the compat export and routes the import through it.

Severity: error when the installed jax (package metadata or
``GRAFTCHECK_JAX_VERSION``) provably lacks the API — the code cannot run
here; warning otherwise — it runs today but breaks on the other side of
the version fence.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..apicompat import (API_BY_DOTTED, COMPAT_MODULE_PATH,
                         LEGACY_IMPORT_MODULES, available_in,
                         compat_import_module, installed_jax_version)
from ..findings import Edit, Finding, Fix, Severity
from ..modmodel import ModuleModel, dotted_name

RULE_ID = "G009"


def _grade(entry, version) -> str:
    avail = available_in(entry, version)
    return Severity.ERROR if avail is False else Severity.WARNING


def _version_clause(entry, version) -> str:
    if available_in(entry, version) is False:
        v = ".".join(str(p) for p in version)
        return f"not available in the installed jax {v}"
    return "version-fragile (exists only on one side of the shard_map " \
           "API migration)"


def check(model: ModuleModel) -> List[Finding]:
    if model.rel_path == COMPAT_MODULE_PATH:
        return []  # the portability layer itself touches both spellings
    version = installed_jax_version()
    compat_mod = compat_import_module(model.rel_path)
    findings: List[Finding] = []

    for node in ast.walk(model.tree):
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func)
            entry = API_BY_DOTTED.get(callee) if callee else None
            if entry is None:
                continue
            fix: Optional[Fix] = Fix(
                edits=(Edit(node.lineno, callee + "(",
                            entry.compat_name + "("),),
                add_import=(compat_mod, entry.compat_name),
            )
            # only fix when the callee text sits on the call's first line
            line_text = model.snippet(node.lineno)
            if callee + "(" not in line_text:
                fix = None
            findings.append(Finding(
                model.rel_path, node.lineno, RULE_ID, _grade(entry, version),
                f"`{callee}` is {_version_clause(entry, version)}: "
                f"{entry.note}; call `{entry.compat_name}` from "
                f"runtime/jax_compat.py instead (machine-fixable: --fix)",
                line_text, fix=fix))
        elif isinstance(node, ast.Import):
            # `import jax.experimental.shard_map [as x]`
            for alias in node.names:
                entry = LEGACY_IMPORT_MODULES.get(alias.name)
                if entry is None:
                    continue
                findings.append(Finding(
                    model.rel_path, node.lineno, RULE_ID,
                    _grade(entry, version),
                    f"import of `{alias.name}` is "
                    f"{_version_clause(entry, version)}: {entry.note}; "
                    f"import from runtime/jax_compat.py instead",
                    model.snippet(node.lineno)))
        elif isinstance(node, ast.ImportFrom):
            entry = LEGACY_IMPORT_MODULES.get(node.module or "")
            if entry is None:
                # `from jax.experimental import shard_map [as x]`
                for alias in node.names:
                    full = f"{node.module}.{alias.name}" if node.module \
                        else alias.name
                    sub_entry = LEGACY_IMPORT_MODULES.get(full)
                    if sub_entry is None:
                        continue
                    findings.append(Finding(
                        model.rel_path, node.lineno, RULE_ID,
                        _grade(sub_entry, version),
                        f"import of `{full}` is "
                        f"{_version_clause(sub_entry, version)}: "
                        f"{sub_entry.note}; import from "
                        f"runtime/jax_compat.py instead",
                        model.snippet(node.lineno)))
                continue
            fix = None
            names = [a.name for a in node.names]
            aliased = [a for a in node.names if a.asname]
            line_text = model.snippet(node.lineno)
            legacy_import = "from jax.experimental.shard_map import shard_map"
            if names == ["shard_map"] and not aliased \
                    and legacy_import in line_text:
                fix = Fix(edits=(Edit(
                    node.lineno, legacy_import,
                    f"from {compat_mod} import shard_map"),))
            findings.append(Finding(
                model.rel_path, node.lineno, RULE_ID, _grade(entry, version),
                f"import from `{node.module}` is "
                f"{_version_clause(entry, version)}: {entry.note}; import "
                f"from runtime/jax_compat.py instead"
                + (" (machine-fixable: --fix)" if fix else ""),
                line_text, fix=fix))
    return findings
